"""Static graph auditor: lower a jitted step, never run it, name defects.

Three check families, all read off artifacts that exist *before* any
step executes:

* **Collective census** (post-SPMD HLO, ``analysis/hlo.py``): every
  all-gather / all-reduce / reduce-scatter / collective-permute /
  all-to-all with wire dtype and modeled wire bytes, diffed against the
  :class:`AuditIntent` derived from the config — a GSPMD-inserted
  resharding nobody declared or an fp32 wire on a quantized path is a
  named high-severity finding.
* **Donation audit** (the module header's ``input_output_alias`` map vs
  the ``donate_argnums`` the caller declared): a donated buffer XLA
  could not alias stays live across the step and inflates peak HBM by
  its full footprint.
* **Hot-path hygiene** (the jaxpr + args signature): host callbacks
  inside the step, bf16→fp32 promotions in low-precision compute, and
  recompile hazards (python scalars / weak-type constants) that make the
  jit cache miss on value instead of shape.

The auditor costs one AOT ``lower().compile()`` — the same one-time
price ``profiling/flops_profiler.profile_compiled`` already pays — and
zero step executions, so it runs on the virtual 8-device CPU mesh in CI
against every bench-row step config (``analysis/targets.py``).
"""

from __future__ import annotations

import re
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.hlo import (aggregate_census,
                                        entry_parameters, has_infeed,
                                        parse_collectives,
                                        parse_input_output_alias)
from deepspeed_tpu.analysis.report import (Finding, GraphAuditReport)

# jaxpr primitives that round-trip through the host mid-step.  A step
# containing one serializes device execution behind python; only
# debug_callback (jax.debug.print) degrades to a warning — it is at
# least async — everything else is a high finding.
HOST_CALLBACK_PRIMS = ("callback", "debug_callback", "io_callback",
                       "outside_call", "pure_callback")

# post-lowering spellings of the same defect
_CALLBACK_CUSTOM_CALLS = ("xla_python_cpu_callback",
                          "xla_python_gpu_callback",
                          "xla_ffi_python_cpu_callback")

_LOW_PRECISION = ("bfloat16", "float16")


@dataclass
class AuditIntent:
    """Declared communication/compute intent the census is diffed against.

    ``expected``: collective kinds the config explains — any OTHER kind
    carrying ≥ ``min_unexpected_bytes`` is an ``implicit_resharding``.
    ``required``: ``{kind: (wire dtypes,)}`` that MUST appear (empty
    tuple = any dtype) — e.g. a quantized grad reduce must surface an
    int8 ``all-to-all``; absence is a ``collective_mismatch``.
    ``banned``: ``{kind: (wire dtypes,)}`` that must NOT appear at
    volume — an fp32 ``all-reduce`` on a path whose reduce was declared
    quantized is a ``wire_dtype_mismatch``.
    """
    expected: frozenset = frozenset()
    required: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    banned: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    compute_dtype: str = "fp32"
    min_unexpected_bytes: int = 1 << 16
    allow_callbacks: bool = False


# ----------------------------------------------------------------------
# jaxpr-level checks
# ----------------------------------------------------------------------
def _iter_eqns(jaxpr):
    """Depth-first over every equation, descending into sub-jaxprs
    (scan bodies, cond branches, custom_vjp calls, pjit) duck-typed —
    no jax-internal imports (the seam lint applies to this file too)."""
    stack = [jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            for val in eqn.params.values():
                stack.extend(_subjaxprs(val))


def _subjaxprs(val):
    out = []
    if hasattr(val, "eqns"):
        out.append(val)
    elif hasattr(val, "jaxpr") and hasattr(val.jaxpr, "eqns"):
        out.append(val.jaxpr)
    elif isinstance(val, (list, tuple)):
        for v in val:
            out.extend(_subjaxprs(v))
    return out


def _callback_findings(jaxpr, label: str) -> List[Finding]:
    hits: Dict[str, int] = {}
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in HOST_CALLBACK_PRIMS or name in ("infeed", "outfeed"):
            hits[name] = hits.get(name, 0) + 1
    return [
        Finding(
            kind="host_callback",
            severity="warning" if prim == "debug_callback" else "high",
            message=f"{count}× `{prim}` inside the compiled step — every "
                    "call is a device→host→device round trip on the hot "
                    "path",
            where=label, detail={"key": prim, "count": count})
        for prim, count in sorted(hits.items())
    ]


def _promotion_findings(jaxpr, label: str, compute_dtype: str,
                        min_bytes: int = 1 << 12) -> List[Finding]:
    """bf16/fp16 → fp32 ``convert_element_type`` volume inside a
    low-precision step.  fp32 accumulation is often deliberate (softmax,
    loss, grad accumulators), so this aggregates to ONE finding and only
    escalates info→warning above 16 MiB of promoted output."""
    if compute_dtype not in ("bf16", "fp16"):
        return []
    count, total = 0, 0
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        try:
            src = str(eqn.invars[0].aval.dtype)
            out = eqn.outvars[0].aval
        except (AttributeError, IndexError):
            continue
        if src in _LOW_PRECISION and str(out.dtype) == "float32":
            nbytes = int(out.size) * 4
            if nbytes >= min_bytes:
                count += 1
                total += nbytes
    if not count:
        return []
    return [Finding(
        kind="dtype_promotion",
        severity="warning" if total >= (1 << 24) else "info",
        message=f"{count} fp32 promotions of {_LOW_PRECISION[0]}/"
                f"{_LOW_PRECISION[1]} tensors ({total} output bytes) in a "
                f"{compute_dtype} step — check each is a deliberate "
                "accumulator, not a leaked upcast",
        where=label, detail={"key": "bf16->f32", "count": count,
                             "bytes": total})]


def _signature_findings(args, label: str) -> List[Finding]:
    """Recompile hazards in the example arguments: python scalars trace
    as weak-type *constants* (a new value = a new program), and
    weak-type arrays re-specialize the jit cache the same way."""
    import jax

    hazards: List[Tuple[str, str]] = []

    def visit(path, leaf):
        if isinstance(leaf, (bool, int, float)):
            hazards.append((jax.tree_util.keystr(path),
                            f"python {type(leaf).__name__}"))
        elif getattr(leaf, "weak_type", False):
            hazards.append((jax.tree_util.keystr(path), "weak-type array"))

    jax.tree_util.tree_map_with_path(visit, args)
    return [Finding(
        kind="recompile_hazard", severity="warning",
        message=f"step argument {path or '<root>'} is a {what}: its "
                "VALUE is baked into the trace, so every new value "
                "recompiles the step",
        where=label, detail={"key": path, "what": what})
        for path, what in hazards]


# ----------------------------------------------------------------------
# HLO-level checks
# ----------------------------------------------------------------------
def _census_findings(census, intent: AuditIntent,
                     label: str) -> List[Finding]:
    findings: List[Finding] = []
    present: Dict[str, set] = {}
    for row in census:
        present.setdefault(row.kind, set()).update(
            row.dtype.split("+"))
        key = f"{row.kind}:{row.dtype}"
        if (row.kind not in intent.expected
                and row.payload_bytes >= intent.min_unexpected_bytes):
            findings.append(Finding(
                kind="implicit_resharding", severity="high",
                message=f"{row.count}× {row.kind} ({row.dtype}, "
                        f"{row.payload_bytes} payload bytes) in the "
                        "lowered step but the config declares no source "
                        "for it — GSPMD inserted a resharding nobody "
                        "asked for",
                where=label, detail={"key": key, "count": row.count,
                                     "payload_bytes": row.payload_bytes,
                                     "wire_bytes": row.wire_bytes}))
        banned = intent.banned.get(row.kind)
        if (banned and row.payload_bytes >= intent.min_unexpected_bytes
                and any(d in banned for d in row.dtype.split("+"))):
            findings.append(Finding(
                kind="wire_dtype_mismatch", severity="high",
                message=f"{row.kind} moves {row.dtype} "
                        f"({row.payload_bytes} payload bytes) on a path "
                        "the config declares quantized — the wire dtype "
                        "never narrowed",
                where=label, detail={"key": f"banned:{key}",
                                     "payload_bytes": row.payload_bytes}))
    for kind, dtypes in sorted(intent.required.items()):
        have = present.get(kind, set())
        if not have or (dtypes and not have.intersection(dtypes)):
            findings.append(Finding(
                kind="collective_mismatch", severity="warning",
                message=f"config declares a {kind} "
                        f"({'/'.join(dtypes) or 'any dtype'}) but the "
                        f"lowered step contains "
                        f"{'none' if not have else 'only ' + '/'.join(sorted(have))}"
                        " — the declared comm path did not materialize",
                where=label, detail={"key": f"required:{kind}"}))
    return findings


def _donation_audit(flat_args_info, hlo_text: str, label: str,
                    min_high_bytes: int = 1 << 16
                    ) -> Tuple[Dict[str, Any], List[Finding]]:
    donated = [i for i, a in enumerate(flat_args_info)
               if getattr(a, "donated", False)]
    alias = parse_input_output_alias(hlo_text)
    entry = entry_parameters(hlo_text)
    reliable = len(entry) == len(flat_args_info)
    aliased = [i for i in donated if i in alias] if reliable \
        else sorted(alias)
    block: Dict[str, Any] = {"declared": len(donated),
                             "aliased": len(aliased), "missed": [],
                             "missed_bytes": 0}
    findings: List[Finding] = []
    if not donated:
        return block, findings
    if not reliable:
        # unused args were dropped from the executable: indices no longer
        # line up, so report counts only (never a phantom per-buffer miss)
        gap = max(0, len(donated) - len(alias))
        block["missed_bytes"] = -1 if gap else 0
        if gap:
            findings.append(Finding(
                kind="donation_miss", severity="warning",
                message=f"{gap} of {len(donated)} donated buffers have no "
                        "output alias (parameter indices unmappable: the "
                        "executable dropped unused args)",
                where=label, detail={"key": "unmapped", "gap": gap}))
        return block, findings
    groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for i in donated:
        if i in alias:
            continue
        a = flat_args_info[i]
        shape = tuple(getattr(a, "shape", ()))
        dtype = str(getattr(a, "dtype", "?"))
        try:
            import numpy as np
            nbytes = int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
        except Exception:
            nbytes = 0
        block["missed"].append({"param_index": i, "shape": list(shape),
                                "dtype": dtype, "bytes": nbytes})
        block["missed_bytes"] += nbytes
        g = groups.setdefault((str(shape), dtype),
                              {"count": 0, "bytes": 0, "indices": []})
        g["count"] += 1
        g["bytes"] += nbytes
        g["indices"].append(i)
    for (shape, dtype), g in sorted(groups.items(),
                                    key=lambda kv: -kv[1]["bytes"]):
        sev = ("high" if g["bytes"] >= min_high_bytes
               else "warning" if g["bytes"] >= 1024 else "info")
        findings.append(Finding(
            kind="donation_miss", severity=sev,
            message=f"{g['count']}× donated {dtype}{shape} "
                    f"({g['bytes']} bytes) not aliased to any output — "
                    "the buffer stays live across the step and inflates "
                    "peak HBM by its full footprint",
            where=label,
            detail={"key": f"{shape}:{dtype}", "count": g["count"],
                    "bytes": g["bytes"],
                    "param_indices": g["indices"][:8]}))
    return block, findings


# ----------------------------------------------------------------------
# the auditor
# ----------------------------------------------------------------------
@dataclass
class LoweredStep:
    """One AOT lowering's reusable artifacts.

    Every audit family (collective census, donation, memory plan) reads
    off the same trio — jaxpr, lowered, compiled — so a caller auditing
    one target several ways pays the ~2s trace+lower+compile ONCE
    (``analysis/targets.py`` / ``graft_lint --rows --memory``) instead of
    once per audit.  The artifacts stay valid after the owning engine is
    destroyed: they are standalone AOT objects, and the audits only read
    text/metadata off them."""
    label: str
    jaxpr: Any
    lowered: Any
    compiled: Any
    hlo: str
    args: Tuple[Any, ...]
    backend: str
    num_partitions: int


def lower_step(fn, *args, label: str = "step",
               static_kwargs: Optional[Dict[str, Any]] = None
               ) -> LoweredStep:
    """Trace + lower + AOT-compile one jitted function (shapes only —
    NEVER executed, so zero-filled arrays are fine and donated example
    buffers are not consumed) into a reusable :class:`LoweredStep`."""
    import jax

    kw = static_kwargs or {}
    if not hasattr(fn, "lower"):
        raise TypeError(f"audit needs a jax.jit-wrapped callable, got "
                        f"{type(fn).__name__} (wrap it in jax.jit first)")
    with warnings.catch_warnings():
        # jax's donated-buffers-not-usable warning (raised at lowering)
        # is OUR report — do not also print it
        warnings.simplefilter("ignore")
        if hasattr(fn, "trace"):
            traced = fn.trace(*args, **kw)
            jaxpr = traced.jaxpr
            lowered = traced.lower()   # one trace serves both artifacts
        else:  # pragma: no cover - older jax without AOT trace()
            jaxpr = jax.make_jaxpr(fn)(*args, **kw).jaxpr
            lowered = fn.lower(*args, **kw)
        compiled = lowered.compile()
    hlo = compiled.as_text()
    # SPMD modules always carry num_partitions= in the header; absence
    # means a single-partition program, so the fallback is 1 (never the
    # host's device count — a single-device jit on an 8-device host
    # must not have its wire model scaled by 8)
    m = re.search(r"num_partitions=(\d+)", hlo)
    return LoweredStep(label=label, jaxpr=jaxpr, lowered=lowered,
                       compiled=compiled, hlo=hlo, args=tuple(args),
                       backend=jax.default_backend(),
                       num_partitions=int(m.group(1)) if m else 1)


def audit(fn, *args, label: str = "step", intent: Optional[AuditIntent] = None,
          static_kwargs: Optional[Dict[str, Any]] = None
          ) -> GraphAuditReport:
    """Audit one jitted function against example ``args`` (lower + audit
    in one call; use :func:`lower_step` + :func:`audit_artifacts` to
    share the lowering with the memory auditor)."""
    return audit_artifacts(lower_step(fn, *args, label=label,
                                      static_kwargs=static_kwargs),
                           intent=intent)


def audit_artifacts(art: LoweredStep,
                    intent: Optional[AuditIntent] = None
                    ) -> GraphAuditReport:
    """The graph audit proper, off pre-lowered artifacts."""
    import jax

    intent = intent or AuditIntent()
    label = art.label
    jaxpr, lowered, hlo = art.jaxpr, art.lowered, art.hlo
    num_partitions = art.num_partitions
    args = art.args
    findings: List[Finding] = []
    if not intent.allow_callbacks:
        findings.extend(_callback_findings(jaxpr, label))
    findings.extend(_promotion_findings(jaxpr, label,
                                        intent.compute_dtype))
    findings.extend(_signature_findings(args, label))

    ops = parse_collectives(hlo, num_partitions=num_partitions)
    census = aggregate_census(ops)
    findings.extend(_census_findings(census, intent, label))

    flat_info, _ = jax.tree_util.tree_flatten(lowered.args_info)
    donation, don_findings = _donation_audit(flat_info, hlo, label)
    findings.extend(don_findings)

    if not intent.allow_callbacks:
        # post-lowering catch for callbacks the jaxpr walk missed (e.g.
        # injected by a custom lowering rule).  Every jaxpr callback
        # prim (debug_callback included) lowers to the same custom-call
        # targets, so attribution is by COUNT: more callback sites in
        # the HLO than jaxpr hits means lowering added some.  Warning,
        # not high — loop unrolling can legitimately duplicate one
        # jaxpr-level site into several HLO sites.
        jaxpr_cb = sum(int(f.detail.get("count", 1)) for f in findings
                       if f.kind == "host_callback")
        hlo_cb = sum(hlo.count(f'custom_call_target="{t}"')
                     for t in _CALLBACK_CUSTOM_CALLS)
        if hlo_cb > jaxpr_cb:
            findings.append(Finding(
                kind="host_callback",
                severity="high" if jaxpr_cb == 0 else "warning",
                message=f"{hlo_cb} callback custom-call(s) in the "
                        f"optimized HLO vs {jaxpr_cb} jaxpr-level "
                        "callback(s) — a host round trip was injected "
                        "below the jaxpr (custom lowering rule?)",
                where=label, detail={"key": "lowered_callback",
                                     "hlo_sites": hlo_cb,
                                     "jaxpr_sites": jaxpr_cb}))
        known = {f.detail.get("key") for f in findings
                 if f.kind == "host_callback"}
        if has_infeed(hlo) and "infeed" not in known:
            findings.append(Finding(
                kind="host_callback", severity="high",
                message="infeed op in the optimized HLO",
                where=label, detail={"key": "infeed"}))

    order = {"high": 0, "warning": 1, "info": 2}
    findings.sort(key=lambda f: (order[f.severity], f.kind,
                                 str(f.detail.get("key", ""))))
    return GraphAuditReport(
        label=label, backend=art.backend,
        num_partitions=max(1, num_partitions), census=census,
        donation=donation, findings=findings)


# ----------------------------------------------------------------------
# config → intent, engine adapters
# ----------------------------------------------------------------------
def intent_for_engine(engine) -> AuditIntent:
    """Derive the declared comm/compute intent from a built
    ``GraftEngine``: mesh axes + ZeRO stage + ``comm_quantization`` +
    ``step_schedule`` explain which collective kinds may appear."""
    topo = engine.topology
    cfg = engine.config
    stage = engine.zero_stage
    dp = getattr(topo, "dp_size", 1)
    tp = getattr(topo, "tp_size", 1)
    pp = getattr(topo, "pp_size", 1)
    sp = getattr(topo, "sp_size", 1)
    ep = getattr(topo, "ep_size", 1)

    expected = set()
    required: Dict[str, Tuple[str, ...]] = {}
    banned: Dict[str, Tuple[str, ...]] = {}
    if dp > 1:
        expected.add("all-reduce")
        if stage >= 1 or cfg.step_schedule.weight_update == "decomposed":
            # sharded optimizer state makes XLA free to express the
            # reduce as reduce-scatter + re-gather of updated params,
            # and the declared grad-accumulator sharding constraint
            # legitimately reshards batch-parallel gradients into the
            # ZeRO layout (an all-to-all per GSPMD) — those layout
            # transitions are the config's own intent, not implicit
            expected.update(("all-gather", "reduce-scatter",
                             "all-to-all"))
    if tp > 1:
        expected.update(("all-reduce", "all-gather", "reduce-scatter"))
        if dp > 1 and stage >= 1:
            # 2-D dp×tp mesh with a sharded optimizer: the layout
            # transition between batch-parallel gradients and the
            # (data, tensor)-factored ZeRO state legitimately lowers as
            # collective-permutes (GSPMD routes the cross-axis reshard
            # point-to-point; observed on the train_resumed target's
            # data×tensor resume mesh — identical on a from-scratch
            # engine with the same mesh, so it is the config's own
            # intent, not a resume artifact)
            expected.add("collective-permute")
    if pp > 1:
        expected.update(("collective-permute", "all-reduce", "all-gather"))
    if sp > 1:
        expected.update(("all-gather", "all-reduce", "reduce-scatter"))
        seq_impl = getattr(engine.model_config, "seq_impl", "") \
            if engine.model_config is not None else ""
        if seq_impl == "ring":
            expected.add("collective-permute")
            required.setdefault("collective-permute", ())
            ring_wire = getattr(engine.model_config, "ring_wire_dtype",
                                "fp32")
            if ring_wire != "fp32":
                # quantized ring rotation (comm_quantization.ring_rotation):
                # the K/V payload moves s8 (int8) or u8 (fp8 bitcast) —
                # a DECLARED narrow wire, not a wire_dtype_mismatch; the
                # fp32-wire rotation's u32 word-packing must be gone
                # (the small fp32 scale messages stay legitimate)
                required["collective-permute"] = ("s8", "u8")
                banned["collective-permute"] = ("u32",)
        else:   # ulysses/alst head<->seq exchanges
            expected.add("all-to-all")
    if ep > 1:
        expected.add("all-to-all")

    cq = getattr(cfg, "comm_quantization", None)
    if cq is not None and getattr(cq, "enabled", False) \
            and getattr(engine, "_comm_quant", None) is not None:
        wire = getattr(cq, "grad_reduce", "fp32")
        if wire in ("int8", "fp8"):
            # quantized reduce = quantize → all-to-all → dequant-reduce;
            # fp8 bitcasts to u8 so every backend moves plain bytes
            expected.add("all-to-all")
            required["all-to-all"] = ("s8", "u8")
            # the GSPMD fp32 grad reduce this path replaces must be gone
            banned["all-reduce"] = ("f32",)
    compute = "bf16" if getattr(cfg, "bf16_enabled", False) else (
        "fp16" if getattr(cfg, "fp16_enabled", False) else "fp32")
    return AuditIntent(expected=frozenset(expected), required=required,
                       banned=banned, compute_dtype=compute)


def audit_engine(engine, data=None, label: str = "train_step"
                 ) -> GraphAuditReport:
    """Audit a built train engine's compiled step without running it."""
    fn, args = engine.audit_step_args(data)
    return audit(fn, *args, label=label, intent=intent_for_engine(engine))


def intent_for_v2(v2) -> AuditIntent:
    """The serving engine's declared collective/dtype intent — shared
    by :func:`audit_v2_engine` and the bench-row target preparer so the
    CLI/tier-1 audits can never drift from the API audit."""
    expected = set()
    if getattr(v2.topology, "tp_size", 1) > 1:
        expected.update(("all-reduce", "all-gather", "reduce-scatter"))
    if getattr(v2.topology, "ep_size", 1) > 1:
        expected.add("all-to-all")
    compute = "bf16" if "bf" in str(v2.cfg.dtype) else "fp32"
    return AuditIntent(expected=frozenset(expected), compute_dtype=compute)


def audit_v2_engine(v2, phase: str = "decode",
                    label: Optional[str] = None) -> GraphAuditReport:
    """Audit the serving engine's ragged prefill/decode step."""
    fn, args = v2.audit_step_args(phase)
    return audit(fn, *args, label=label or f"v2_{phase}",
                 intent=intent_for_v2(v2))


def fused_collective_intent(engine) -> Dict[str, Dict[str, Any]]:
    """Which compute-collective FUSIONS the engine's gates declare —
    the hops that are no longer scheduled around but folded into their
    producing/consuming compute (docs/STATIC_ANALYSIS.md):

    * ``ring_rotation`` — quantized ring wire
      (comm_quantization.ring_rotation; sequence/ring.py): the
      collective-permute payload narrowed + dequant in the flash
      epilogue.
    * ``gather_matmul`` — step_schedule.fused_gather_matmul
      (ops/pallas/gather_matmul.py): MLP param all-gathers issued from
      the matmul region.
    * ``reduce_scatter_epilogue`` — step_schedule.fused_reduce_scatter:
      explicit per-leaf psum_scatter in the grad-accumulator epilogue.
    """
    out: Dict[str, Dict[str, Any]] = {}
    mc = getattr(engine, "model_config", None)
    sp = getattr(engine.topology, "sp_size", 1)
    if (mc is not None and sp > 1
            and getattr(mc, "seq_impl", "") == "ring"
            and getattr(mc, "ring_wire_dtype", "fp32") != "fp32"):
        out["ring_rotation"] = {"kind": "collective-permute",
                                "wire": mc.ring_wire_dtype}
    if mc is not None and getattr(mc, "fused_gather_matmul", False):
        out["gather_matmul"] = {"kind": "all-gather",
                                "axes": list(mc.fused_gather_axes)}
    if getattr(engine, "_fused_rs", False):
        out["reduce_scatter_epilogue"] = {"kind": "reduce-scatter"}
    return out


def collective_census_engine(engine) -> Dict[str, Dict[str, Any]]:
    """Compact census for the overlap scheduler's pinned evidence.

    On top of the per-kind rollup, a ``fused_collective`` entry records
    which hops are FUSED (gate-declared) vs merely scheduled, each with
    ``present`` = whether a matching collective kind materialized in the
    lowered step — so pinned ``static_census`` evidence distinguishes a
    fused wire from a scheduled one."""
    return census_and_memory_engine(engine)[0]


def census_and_memory_engine(engine) -> Tuple[Dict[str, Any],
                                              Optional[Dict[str, Any]]]:
    """Both pinned-evidence blocks off ONE lowering: the collective
    census rollup (``static_census``) and the memory-plan rollup
    (``static_memory``) — the probe pays the AOT trace+lower+compile
    once.  The memory half degrades to None (with a warning) rather than
    costing the probe its census."""
    fn, args = engine.audit_step_args()
    art = lower_step(fn, *args, label="census_probe")
    report = audit_artifacts(art, intent=intent_for_engine(engine))
    summary = report.census_summary()
    fused = fused_collective_intent(engine)
    summary["fused_collective"] = {
        name: {**info, "present": info["kind"] in summary}
        for name, info in sorted(fused.items())}
    static_memory = None
    try:
        from deepspeed_tpu.analysis.memory import (audit_memory,
                                                   memory_intent_for_engine)

        static_memory = audit_memory(
            art, intent=memory_intent_for_engine(engine)).summary()
    except Exception as e:  # census evidence must survive a memory miss
        warnings.warn(f"static memory audit unavailable: {e}")
    return summary, static_memory
