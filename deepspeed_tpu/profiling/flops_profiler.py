"""FLOPS profiler — measured XLA costs + analytic model breakdown.

Analog of ``deepspeed/profiling/flops_profiler/profiler.py`` (module-hook
MAC counting :30, per-op formulas :518+, ``print_model_profile`` :286).
The reference installs nn.Module hooks and counts MACs op-by-op in eager
mode.  Under XLA the compiler already knows the graph's cost:
:func:`profile_compiled` reads ``cost_analysis()`` (flops / bytes accessed)
off a lowered+compiled jit function — exact for whatever fusion XLA
actually performed — and :func:`get_model_profile` gives the analytic
per-component breakdown (attention / MLP / logits) the reference prints,
computed from the model config.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger


def profile_compiled(jit_fn, *args, **kwargs) -> Dict[str, float]:
    """Lower+compile a jitted fn on concrete/abstract args and read XLA's
    cost model: {'flops', 'bytes_accessed', 'peak_memory_bytes'} (keys
    present when the backend reports them)."""
    compiled = jit_fn.lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):  # some backends return one dict per computation
        ca = ca[0] if ca else {}
    out: Dict[str, float] = {}
    for src, dst in (("flops", "flops"),
                     ("bytes accessed", "bytes_accessed"),
                     ("optimal_seconds", "optimal_seconds")):
        if ca and src in ca:
            out[dst] = float(ca[src])
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            # one shared peak derivation with analysis/memory.audit_memory
            from deepspeed_tpu.analysis.report import \
                memory_totals_from_analysis

            totals = memory_totals_from_analysis(mem)
            out["memory"] = totals
            out["peak_memory_bytes"] = float(
                totals["temp_bytes"] + totals["argument_bytes"]
                + totals["output_bytes"])
    except Exception:  # backend without memory analysis
        pass
    return out


# ----------------------------------------------------------------------
# Analytic model profile (ref per-op flop formulas, profiler.py:518+)
# ----------------------------------------------------------------------

def get_model_profile(model_cfg, batch_size: int, seq_len: int,
                      include_backward: bool = True,
                      recompute_fwd_factor: float = 0.0) -> Dict[str, Any]:
    """Per-component flops/params for one step of a TransformerConfig.

    backward ≈ 2× forward; activation recompute adds
    ``recompute_fwd_factor`` extra forwards (ref recompute_fwd_factor).
    """
    c = model_cfg
    b, s = batch_size, seq_len
    h = c.hidden_size
    nh, nkv, hd = c.num_heads, c.kv_heads, c.dim_per_head
    ffn = c.intermediate_size
    n_mlp_mats = 3 if c.activation == "swiglu" else 2

    qkv = 2 * b * s * h * (nh * hd + 2 * nkv * hd)
    attn_scores = 2 * b * nh * s * s * hd * 2  # QK^T + PV
    attn_out = 2 * b * s * (nh * hd) * h
    attn = qkv + attn_scores + attn_out
    mlp = 2 * b * s * h * ffn * n_mlp_mats
    if getattr(c, "num_experts", 0):
        mlp *= getattr(c, "top_k", 2)  # routed expert compute per token
    per_layer = attn + mlp
    logits = 2 * b * s * h * c.vocab_size
    fwd = per_layer * c.num_layers + logits

    factor = 1.0
    if include_backward:
        factor += 2.0 + recompute_fwd_factor
    total = fwd * factor

    from deepspeed_tpu.models.transformer import count_params, init_params  # noqa: F401

    # param count analytically (avoid building arrays)
    attn_p = h * (nh * hd) + 2 * h * (nkv * hd) + (nh * hd) * h
    mlp_p = n_mlp_mats * h * ffn
    if getattr(c, "num_experts", 0):
        mlp_p = mlp_p * c.num_experts + h * c.num_experts
    norm_p = 2 * h * (2 if c.norm == "layernorm" else 1)
    params = c.num_layers * (attn_p + mlp_p + norm_p) + c.vocab_size * h + h

    return {
        "params": int(params),
        "fwd_flops": float(fwd),
        "total_flops_per_step": float(total),
        "breakdown_per_layer": {
            "attention_qkv": float(qkv), "attention_scores": float(attn_scores),
            "attention_out": float(attn_out), "mlp": float(mlp)},
        "logits_flops": float(logits),
        "macs": float(total / 2),
    }


def mfu(flops_per_step: float, step_seconds: float,
        peak_flops_per_sec: float) -> float:
    """Model-flops-utilisation given a hardware peak (e.g. v5p bf16)."""
    if step_seconds <= 0 or peak_flops_per_sec <= 0:
        return 0.0
    return flops_per_step / step_seconds / peak_flops_per_sec


class FlopsProfiler:
    """Engine-facing wrapper (ref FlopsProfiler, profiler.py:30).

    ``start()``/``stop()`` bracket a step; ``profile(engine, batch)``
    measures the engine's compiled train step via XLA cost analysis and
    merges the analytic breakdown.
    """

    def __init__(self, config=None):
        self.config = config
        self.profile_done = False

    def profile_engine_step(self, engine, *step_args) -> Dict[str, Any]:
        out = profile_compiled(engine._train_step_jit, *step_args)
        mc = getattr(engine, "model_config", None)
        if mc is not None:
            bs = engine.config.train_micro_batch_size_per_gpu or 1
            seq = getattr(mc, "max_seq_len", 0)
            out["analytic"] = get_model_profile(mc, bs, seq)
        self.profile_done = True
        return out

    def print_profile(self, prof: Dict[str, Any]) -> None:
        logger.info("flops profile: " + ", ".join(
            f"{k}={v:.3e}" for k, v in prof.items() if isinstance(v, float)))
        if "analytic" in prof:
            a = prof["analytic"]
            logger.info(f"  params={a['params']:,} "
                        f"fwd_flops={a['fwd_flops']:.3e} "
                        f"step_flops={a['total_flops_per_step']:.3e}")
