"""ZeRO user-facing API surface.

Analogs of ``deepspeed.zero``:
* :class:`Init` — construct params already partitioned (ref ``zero.Init``,
  runtime/zero/partition_parameters.py:878).  The reference patches
  nn.Module constructors to scatter tensors at creation; functionally, the
  same contract is "init functions evaluated shape-only, then materialised
  directly into ZeRO-3 shardings" — no full replica ever exists.
* :func:`GatheredParameters` — temporarily materialise full params (ref
  partition_parameters.py GatheredParameters ctx) for host-side surgery.
* Memory estimators (ref runtime/zero/stage3.py
  ``estimate_zero3_model_states_mem_needs_all_live``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from deepspeed_tpu.resilience.oracle import PartitionOracle
from deepspeed_tpu.parallel.topology import MeshTopology, get_topology


class Init:
    """Sharded model construction context (ref zero.Init).

    Usage::

        with deepspeed_tpu.zero.Init(zero_stage=3) as zinit:
            params = zinit.materialize(init_fn, rng)

    ``materialize`` evaluates ``init_fn`` abstractly (shapes only), plans
    ZeRO shardings for the current mesh, and jits the initializer with
    those out-shardings — each device materialises only its shard, the
    functional equivalent of the reference's scatter-at-construction.
    """

    def __init__(self, zero_stage: int = 3,
                 topology: Optional[MeshTopology] = None,
                 dtype=None):
        self.zero_stage = zero_stage
        self.topology = topology
        self.dtype = dtype
        self._rules: Optional[PartitionOracle] = None

    def __enter__(self) -> "Init":
        topo = self.topology or get_topology()
        if topo is None:
            from deepspeed_tpu.comm.comm import init_distributed

            topo = init_distributed()
        self.topology = topo
        self._rules = PartitionOracle(topo, zero_stage=self.zero_stage)
        return self

    def __exit__(self, *exc) -> None:
        return None

    def materialize(self, init_fn: Callable, *args) -> Any:
        if self._rules is None:
            raise RuntimeError("zero.Init used outside its context")
        shapes = jax.eval_shape(init_fn, *args)
        shardings = self._rules.tree_shardings(shapes, param_style=True)
        fn = init_fn
        if self.dtype is not None:
            base = init_fn

            def fn(*a):
                return jax.tree.map(lambda x: x.astype(self.dtype), base(*a))

        return jax.jit(fn, out_shardings=shardings)(*args)

    def shardings_for(self, params_or_shapes) -> Any:
        if self._rules is None:
            raise RuntimeError("zero.Init used outside its context")
        return self._rules.tree_shardings(params_or_shapes, param_style=True)


class GatheredParameters:
    """Materialise full host copies of sharded params inside the context
    (ref GatheredParameters, partition_parameters.py): ``ctx.params`` is a
    mutable numpy tree; after exit ``ctx.updated`` holds the edited tree
    re-scattered to the original shardings.

    Functional arrays can't be mutated in place, so the reference's
    "modifications write back into the module" becomes "read
    ``ctx.updated`` after the block" (or use :func:`gathered_update`).
    """

    def __init__(self, params, modifier_rank: Optional[int] = 0):
        self._orig = params
        self.params = None
        self.updated = None

    def __enter__(self):
        self.params = jax.tree.map(
            lambda x: np.array(jax.device_get(x)), self._orig)
        return self.params

    def __exit__(self, *exc):
        def put_back(orig, new):
            if hasattr(orig, "sharding"):
                return jax.device_put(np.asarray(new, dtype=orig.dtype),
                                      orig.sharding)
            return new

        self.updated = jax.tree.map(put_back, self._orig, self.params)
        return None


def gathered_update(params, edit_fn: Callable) -> Any:
    """Functional form of GatheredParameters: gather → edit on host →
    re-scatter; returns the updated sharded tree."""
    full = jax.tree.map(lambda x: np.array(jax.device_get(x)), params)
    edited = edit_fn(full)

    def put_back(orig, new):
        if hasattr(orig, "sharding"):
            return jax.device_put(np.asarray(new, dtype=orig.dtype),
                                  orig.sharding)
        return new

    return jax.tree.map(put_back, params, edited)


# ----------------------------------------------------------------------
def estimate_zero3_model_states_mem_needs(total_params: int,
                                          num_gpus_per_node: int = 1,
                                          num_nodes: int = 1,
                                          cpu_offload: bool = True,
                                          cpu_offload_params: bool = False,
                                          additional_buffer_factor: float = 1.5):
    """Per-device + host bytes for ZeRO-3 (ref stage3.py estimator)."""
    world = num_gpus_per_node * num_nodes
    gpu = 2 * total_params / world  # bf16 shard
    if not cpu_offload:
        gpu += 16 * total_params / world  # fp32 master + adam moments
        host = additional_buffer_factor * 4 * total_params
    elif not cpu_offload_params:
        host = additional_buffer_factor * 16 * total_params
    else:
        gpu = 2 * total_params / world
        host = additional_buffer_factor * 18 * total_params
    return int(gpu), int(host)


def estimate_zero2_model_states_mem_needs(total_params: int,
                                          num_gpus_per_node: int = 1,
                                          num_nodes: int = 1,
                                          cpu_offload: bool = True,
                                          additional_buffer_factor: float = 1.5):
    """Ref stage_1_and_2.py estimator."""
    world = num_gpus_per_node * num_nodes
    gpu = 4 * total_params  # bf16 params + grads replicated
    if cpu_offload:
        host = additional_buffer_factor * 12 * total_params
    else:
        gpu += 12 * total_params / world
        host = 0
    return int(gpu), int(host)
