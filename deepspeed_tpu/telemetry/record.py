"""StepRecord — the one machine-readable per-step telemetry record.

Assembled once per train (or serving) step and fanned out everywhere:
the JSONL step log, the Prometheus registry, MonitorMaster backends, and
the auto-capture report all read THIS object, so "what MFU did step 500
get" has exactly one answer.

Schema stability: ``SCHEMA_VERSION`` is embedded in every record and the
key set is linted by ``tools/telemetry_check.py`` — change either in the
same commit as the docs table in ``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

SCHEMA_VERSION = 3

# bf16 peak FLOP/s by TPU device kind (matmul peak; the MFU denominator).
# Sources: public TPU spec sheets; v5e figure matches bench.py's 197e12.
_PEAK_FLOPS_BY_KIND = {
    "tpu v2": 45e12,
    "tpu v3": 123e12,
    "tpu v4": 275e12,
    "tpu v5": 197e12,       # v5e / v5 litepod
    "tpu v5e": 197e12,
    "tpu v5 lite": 197e12,
    "tpu v5p": 459e12,
    "tpu v6": 918e12,       # Trillium
    "tpu v6e": 918e12,
}

# Non-TPU fallback (CPU test meshes, unknown PJRT devices): generous
# enough that a host backend can never exceed it, so MFU stays a
# meaningful (0, 1] fraction instead of clamping at 1.
_FALLBACK_PEAK_FLOPS = 1e13


def detect_peak_flops_per_sec() -> float:
    """Per-device peak FLOP/s from the JAX device kind; fallback for
    backends without a known spec (MFU then reads as a lower bound)."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return _FALLBACK_PEAK_FLOPS
    for key in sorted(_PEAK_FLOPS_BY_KIND, key=len, reverse=True):
        if key in kind:
            return _PEAK_FLOPS_BY_KIND[key]
    return _FALLBACK_PEAK_FLOPS


def collect_hbm_stats(max_devices: int = 64) -> Dict[str, Dict[str, int]]:
    """Per-device HBM watermarks via the accelerator ``memory_stats()``
    (PJRT on TPU; /proc RSS on the CPU fallback).  Keys are
    ``device_<i>``; values carry whatever of bytes_in_use /
    peak_bytes_in_use / bytes_limit the backend reports."""
    try:
        from deepspeed_tpu.accelerator import get_accelerator

        acc = get_accelerator()
        n = min(acc.device_count(), max_devices)
    except Exception:
        return {}
    out: Dict[str, Dict[str, int]] = {}
    for i in range(n):
        stats = acc.memory_stats(i)
        if not stats:
            continue
        out[f"device_{i}"] = {
            k: int(stats[k])
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
            if k in stats}
    return out


@dataclass
class StepRecord:
    """Typed per-step telemetry record (see docs/OBSERVABILITY.md)."""

    step: int
    kind: str = "train"                    # train | serving
    schema: int = SCHEMA_VERSION
    # the run this record belongs to (one bench row = one run_id, shared
    # with Tracer metadata and FleetSampler rows; "" = unstitched)
    run_id: str = ""
    # timing / throughput
    wall_time_s: float = 0.0
    tokens: int = 0
    tokens_per_sec: float = 0.0
    # flops / MFU (per-chip denominators)
    flops_per_step: float = 0.0            # whole train batch, one device
    achieved_flops_per_sec: float = 0.0
    peak_flops_per_sec: float = 0.0
    mfu: float = 0.0                       # clamped to [0, 1]
    flops_source: str = "none"             # measured | analytic | none
    # goodput: fraction of optimizer steps so far that actually applied
    # (1.0 - skipped/total); per-step productivity is `not skipped`
    goodput: float = 1.0
    skipped: bool = False
    # training scalars
    loss: Optional[float] = None
    grad_norm: Optional[float] = None
    lr: Optional[float] = None
    loss_scale: Optional[float] = None
    # chunked offload pipeline: fraction of the d2h/h2d transfer time the
    # host optimizer step hid this step (None off the chunked path)
    offload_overlap_fraction: Optional[float] = None
    # memory watermarks: {"device_0": {"bytes_in_use": ..,
    #                                  "peak_bytes_in_use": ..}, ...}
    hbm: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # cumulative comm volume by collective (trace-time exact counts):
    # {"all_reduce": {"count": n, "bytes": b}, ...}
    comm: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # serving-only stats (queue/preemption/KV), empty for train records
    serving: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        if self.wall_time_s > 0 and self.tokens and not self.tokens_per_sec:
            self.tokens_per_sec = self.tokens / self.wall_time_s
        if self.wall_time_s > 0 and self.flops_per_step \
                and not self.achieved_flops_per_sec:
            self.achieved_flops_per_sec = \
                self.flops_per_step / self.wall_time_s
        if self.peak_flops_per_sec > 0 and self.achieved_flops_per_sec \
                and not self.mfu:
            self.mfu = min(
                1.0, self.achieved_flops_per_sec / self.peak_flops_per_sec)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        """One JSONL line: keys sorted (schema-lint relies on this)."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"), default=float)


def record_keys() -> list:
    """The stable top-level key set (consumed by tools/telemetry_check)."""
    return sorted(f.name for f in dataclasses.fields(StepRecord))
