"""SLO specs and ledgers: latency targets → attainment evidence.

The serving control plane (ROADMAP item 4) scales tiers against
*objectives*, not raw percentiles — "decode TTFT p95 under 200 ms for
99% of windows" is an autoscaler input, a bare p95 is not.  This module
pins that contract:

* :class:`SLOSpec` parses the ``serving.slo`` config block (per-metric
  p95 targets with per-scenario overrides; the runtime twin is
  ``runtime.config.SLOServingConfig``, which round-trips through this
  class under the PR 9 drift tripwire) and evaluates a batch of
  per-request measurements into a frozen-key ``slo`` block — the bench
  rows (``serve_disagg``, ``serve_load_multi``) emit it so the
  shifting-mix scenario schedule doubles as the autoscaler's validation
  set, with per-scenario-phase attainment.
* :class:`SLOLedger` is the streaming per-tier form: each fleet-sampler
  cadence tick feeds one windowed percentile set per tier, and the
  ledger accumulates attainment / violations / error-budget burn — the
  numbers a scale-up decision cites.

Key sets are frozen vocabularies linted by ``tools/telemetry_check.py``
(``check_fleet``) against docs/OBSERVABILITY.md, the same contract as
the StepRecord schema.  Pure stdlib — serving/ and telemetry/ stay
jax-free.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence

#: the three targeted latencies (ms); 0 in a spec means "no target"
SLO_TARGET_KEYS = ("queue_wait_p95_ms", "tpot_p95_ms", "ttft_p95_ms")

#: frozen key set of the ``slo`` block bench rows emit (SLOSpec.evaluate)
SLO_BLOCK_KEYS = ("attainment", "by_scenario", "error_budget_burn",
                  "objective", "targets", "violations")

#: frozen key set of one per-scenario entry inside ``by_scenario``
SLO_SCENARIO_KEYS = ("attainment", "n", "tpot_attainment",
                     "ttft_attainment", "violations")

#: frozen key set of one tier's streaming ledger row (SLOLedger.snapshot)
SLO_LEDGER_KEYS = ("attainment", "error_budget_burn", "ticks",
                   "violations")

# error-budget burn is violations / allowed-violations; cap it so a
# zero-budget objective (objective=1.0) exports a finite, JSON-safe
# number instead of Infinity
_BURN_CAP = 999.0


class SLOSpec:
    """``serving.slo`` block, serving-side parser.

    ``ttft_p95_ms`` / ``tpot_p95_ms`` / ``queue_wait_p95_ms`` are p95
    targets in milliseconds (0 = not targeted).  ``objective`` is the
    attainment goal in (0, 1] — the error budget is ``1 - objective``
    of requests (or sampler ticks).  ``scenario_overrides`` maps a
    scenario-mix name to a partial target override, so e.g.
    ``long_prompt_short_decode`` can carry a looser TTFT target than
    chat traffic without forking the spec.
    """

    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.enabled = bool(d.get("enabled", False))
        self.ttft_p95_ms = float(d.get("ttft_p95_ms", 0.0))
        self.tpot_p95_ms = float(d.get("tpot_p95_ms", 0.0))
        self.queue_wait_p95_ms = float(d.get("queue_wait_p95_ms", 0.0))
        self.objective = float(d.get("objective", 0.99))
        if not (0.0 < self.objective <= 1.0):
            raise ValueError(f"slo.objective={self.objective}: must be "
                             "in (0, 1]")
        for key in SLO_TARGET_KEYS:
            if getattr(self, key) < 0:
                raise ValueError(f"slo.{key}={getattr(self, key)}: "
                                 "must be >= 0 (0 = no target)")
        overrides = d.get("scenario_overrides", {})
        if not isinstance(overrides, Mapping):
            raise ValueError("slo.scenario_overrides must be a mapping "
                             "of scenario name -> partial target dict")
        self.scenario_overrides: Dict[str, Dict[str, float]] = {}
        for scenario, ov in overrides.items():
            bad = set(ov) - set(SLO_TARGET_KEYS)
            if bad:
                raise ValueError(
                    f"slo.scenario_overrides[{scenario!r}] has unknown "
                    f"keys {sorted(bad)} (targets: {SLO_TARGET_KEYS})")
            self.scenario_overrides[str(scenario)] = {
                k: float(v) for k, v in ov.items()}

    def targets_for(self, scenario: Optional[str] = None
                    ) -> Dict[str, float]:
        """Effective targets for one scenario (base + override)."""
        t = {k: getattr(self, k) for k in SLO_TARGET_KEYS}
        if scenario is not None:
            t.update(self.scenario_overrides.get(scenario, {}))
        return t

    def _violates(self, targets: Dict[str, float], metric: str,
                  value: Optional[float]) -> bool:
        target = targets[metric]
        return bool(target > 0 and value is not None and value > target)

    def evaluate(self, requests: Sequence[Mapping]) -> Dict[str, object]:
        """Per-request measurements → the frozen-key ``slo`` block.

        Each request is ``{"scenario", "ttft_ms", "tpot_ms"}`` (missing
        / None measurements count as attained — a one-token request has
        no TPOT).  A request violates when ANY targeted metric exceeds
        its (scenario-effective) target; attainment is the fraction that
        do not, and error-budget burn is violations over the budget the
        objective allows (1.0 = budget exactly spent, >1 = SLO missed).
        """
        n = len(requests)
        by_scenario: Dict[str, Dict[str, float]] = {}
        violations = 0
        for scenario in sorted({str(r.get("scenario", "")) for r in requests}):
            reqs = [r for r in requests
                    if str(r.get("scenario", "")) == scenario]
            targets = self.targets_for(scenario or None)
            ttft_bad = sum(1 for r in reqs if self._violates(
                targets, "ttft_p95_ms", r.get("ttft_ms")))
            tpot_bad = sum(1 for r in reqs if self._violates(
                targets, "tpot_p95_ms", r.get("tpot_ms")))
            bad = sum(1 for r in reqs
                      if self._violates(targets, "ttft_p95_ms",
                                        r.get("ttft_ms"))
                      or self._violates(targets, "tpot_p95_ms",
                                        r.get("tpot_ms")))
            m = len(reqs)
            violations += bad
            by_scenario[scenario] = {
                "n": m,
                "violations": bad,
                "attainment": round(1.0 - bad / max(1, m), 3),
                "ttft_attainment": round(1.0 - ttft_bad / max(1, m), 3),
                "tpot_attainment": round(1.0 - tpot_bad / max(1, m), 3),
            }
        return {
            "targets": self.targets_for(),
            "objective": self.objective,
            "violations": violations,
            "attainment": round(1.0 - violations / max(1, n), 3),
            "error_budget_burn": _burn(violations, n, self.objective),
            "by_scenario": by_scenario,
        }


class SLOLedger:
    """Streaming per-tier attainment ledger (fleet-sampler cadence).

    One :meth:`observe` call per tier per sampler tick, carrying the
    tier's TIME-WINDOWED percentiles (registry Histogram ``max_age_s``
    windows — a stale burst must not burn budget forever).  A tick
    violates when any targeted percentile exceeds its target; the
    ledger keeps lifetime tick/violation counts per tier and reports
    attainment + error-budget burn over ticks.
    """

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self._tiers: Dict[str, List[int]] = {}   # tier -> [ticks, bad]

    def observe(self, tier: str, ttft_p95_ms: float, tpot_p95_ms: float,
                queue_wait_p95_ms: float) -> bool:
        """Record one tier tick; returns True when it violated."""
        targets = self.spec.targets_for()
        bad = (self.spec._violates(targets, "ttft_p95_ms", ttft_p95_ms)
               or self.spec._violates(targets, "tpot_p95_ms", tpot_p95_ms)
               or self.spec._violates(targets, "queue_wait_p95_ms",
                                      queue_wait_p95_ms))
        row = self._tiers.setdefault(tier, [0, 0])
        row[0] += 1
        row[1] += int(bad)
        return bad

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{tier: {ticks, violations, attainment, error_budget_burn}}."""
        out: Dict[str, Dict[str, float]] = {}
        for tier in sorted(self._tiers):
            ticks, bad = self._tiers[tier]
            out[tier] = {
                "ticks": ticks,
                "violations": bad,
                "attainment": round(1.0 - bad / max(1, ticks), 3),
                "error_budget_burn": _burn(bad, ticks,
                                           self.spec.objective),
            }
        return out


def _burn(violations: int, n: int, objective: float) -> float:
    """Violations over the budget the objective allows, capped finite."""
    if n <= 0:
        return 0.0
    allowed = (1.0 - objective) * n
    if allowed <= 0:
        return 0.0 if violations == 0 else _BURN_CAP
    return round(min(violations / allowed, _BURN_CAP), 3)
