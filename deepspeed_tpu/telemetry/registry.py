"""Shared metric primitives: Counter / Gauge / Histogram + a registry.

One implementation for every subsystem that keeps numbers —
``serving/metrics.py`` (TTFT/TPOT windows), the engine's per-step
telemetry, and anything else that wants a percentile — so there is
exactly one definition of "p95" in the codebase.  Prometheus-compatible
naming and a text-exposition renderer live in ``telemetry/export.py``.

Histograms keep a bounded sliding window of the most recent samples
(long-lived servers must not grow without bound) for the percentile
snapshot, while ``count``/``sum`` track every observation ever made
(the Prometheus counter semantics).  The window can additionally be
TIME-bounded (``max_age_s``): samples older than the horizon fall out
of the percentile view, so an idle serving tier's p95 decays to empty
instead of reporting its last burst forever — the property the fleet
sampler (serving/fleet.py) needs for cadence-tick SLO ledgers.  The
default (``max_age_s=0``) keeps the original count-bounded behavior
exactly.

Every primitive is individually thread-safe; the registry is safe for
concurrent get-or-create.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

DEFAULT_WINDOW = 2048  # per-histogram sample cap


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) must be >= 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set-to-current-value metric (Prometheus ``gauge``)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


def _percentile(sorted_xs: List[float], q: float) -> float:
    """Linear-interpolation percentile over a sorted list (numpy
    ``percentile`` semantics, without paying an array round-trip per
    snapshot)."""
    n = len(sorted_xs)
    if n == 0:
        return 0.0
    if n == 1:
        return sorted_xs[0]
    pos = (q / 100.0) * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_xs[lo] * (1.0 - frac) + sorted_xs[hi] * frac


class Histogram:
    """Sliding-window distribution with p50/p95/p99 snapshots.

    ``count``/``sum`` are lifetime totals; percentiles are computed over
    the most recent ``window`` samples — further restricted to the last
    ``max_age_s`` seconds when a time bound is set (0 = count-bounded
    only, the original behavior).  Expired samples are pruned lazily on
    every observe/read, so an idle time-bounded window drains to empty
    (all-zero percentiles) instead of pinning at its last burst.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 window: int = DEFAULT_WINDOW, max_age_s: float = 0.0):
        if window < 1:
            raise ValueError(f"histogram {name}: window must be >= 1")
        if max_age_s < 0:
            raise ValueError(f"histogram {name}: max_age_s must be >= 0")
        self.name = name
        self.help = help
        self.window = window
        self.max_age_s = float(max_age_s)
        self._lock = threading.Lock()
        # (monotonic timestamp, value) — the timestamp is dead weight
        # for pure count-bounded histograms but keeps one code path
        self._samples: Deque[Tuple[float, float]] = deque(maxlen=window)
        self._count = 0
        self._sum = 0.0

    def _window_values(self) -> List[float]:
        """Current-window values; caller holds the lock.  Prunes expired
        samples in place when a time bound is set."""
        if self.max_age_s > 0:
            cutoff = time.monotonic() - self.max_age_s
            while self._samples and self._samples[0][0] < cutoff:
                self._samples.popleft()
        return [v for _, v in self._samples]

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._samples.append((time.monotonic(), v))
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def values(self) -> List[float]:
        """Raw current-window samples (oldest first).  The fleet sampler
        pools these across replicas — a tier p95 must be a percentile of
        the POOLED samples, not an average of per-replica p95s."""
        with self._lock:
            return self._window_values()

    def snapshot(self) -> Dict[str, float]:
        """{"p50", "p95", "p99", "mean", "count"} over the window (count
        is lifetime).  An empty histogram snapshots to all-zeros."""
        with self._lock:
            xs = sorted(self._window_values())
            count = self._count
        if not xs:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0,
                    "mean": 0.0, "count": count}
        return {"p50": _percentile(xs, 50.0),
                "p95": _percentile(xs, 95.0),
                "p99": _percentile(xs, 99.0),
                "mean": sum(xs) / len(xs),
                "count": count}

    def quantile(self, q: float) -> float:
        with self._lock:
            xs = sorted(self._window_values())
        return _percentile(xs, q)

    def lifetime(self) -> Tuple[int, float]:
        """(count, sum) over every observation ever made."""
        with self._lock:
            return self._count, self._sum


class MetricsRegistry:
    """Get-or-create registry of named metrics.

    Re-requesting a name returns the SAME object (so two subsystems can
    share one histogram); re-requesting it as a different kind raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r} "
                             "(want [a-zA-Z_:][a-zA-Z0-9_:]*)")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, requested "
                                f"{cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  window: int = DEFAULT_WINDOW,
                  max_age_s: float = 0.0) -> Histogram:
        """``max_age_s > 0`` time-bounds the percentile window (see
        :class:`Histogram`); like ``window``, it only applies when this
        call CREATES the histogram — re-requests return the original."""
        return self._get_or_create(Histogram, name, help, window=window,
                                   max_age_s=max_age_s)

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[object]:
        """Stable-ordered list of every registered metric."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]
