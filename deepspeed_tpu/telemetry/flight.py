"""Flight recorder: bounded span-event ring + hang watchdog + crash
forensics bundle.

"Heavy traffic from millions of users" (ROADMAP) means a wedged serve
loop or a hung train step must leave evidence behind, not a silent
join-timeout.  Three pieces, all stdlib-only:

* :class:`FlightRecorder` — a fixed-size ring of the most recent
  span/instant events.  The tracer feeds it on every emit; its snapshot
  is the "last N things the process did" record in every dump.
* :func:`dump_bundle` — writes a diagnostic bundle directory:
  ``manifest.json`` (reason/error/thread census), ``stacks.txt``
  (all-thread Python stacks via ``sys._current_frames`` plus a
  ``faulthandler`` dump), ``ring.json`` (the event ring), and
  ``telemetry.json`` (last StepRecord + registry values) when a
  telemetry hub is attached.
* :class:`Watchdog` — a daemon thread armed by ``beat()`` calls from a
  hot loop.  No beat for ``deadline_s`` ⇒ one bundle per stall (it
  re-arms on the next beat), plus a ``watchdog.fire`` instant into the
  trace so the stall is visible in Perfetto too.

The same ``dump_bundle`` is called by the serve loop's crash handler
(reason ``serve_crash``) and by ``engine.destroy()`` when invoked while
an exception is propagating (reason ``engine_crash``) — see
docs/OBSERVABILITY.md for the bundle layout.
"""

from __future__ import annotations

import faulthandler
import itertools
import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

# Frozen bundle-reason vocabulary (linted against the docs table by
# tools/telemetry_check.py, like span names).
FLIGHT_REASONS = ("watchdog", "serve_crash", "engine_crash", "manual",
                  "recovery", "fleet")

DEFAULT_RING_SIZE = 2048

_bundle_seq = itertools.count(1)


class FlightRecorder:
    """Fixed-size ring of recent trace events (newest wins)."""

    def __init__(self, capacity: int = DEFAULT_RING_SIZE):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)

    def record(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(event)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def make_span_recorder(tracing_enabled: bool, flight_enabled: bool,
                       max_events: int = 0, ring_size: int = 0):
    """The ONE place the tracer/ring bootstrap rule lives (``Telemetry``
    hub and hub-less ``InferenceServer`` both call it): ``flight.enabled``
    alone also turns on span *recording* — the ring's "last N things the
    process did" must be populated for bundles to be useful — while the
    trace *file* is still gated on the tracing block's own settings.
    Zero/absent ``max_events``/``ring_size`` fall back to the module
    defaults.  Returns ``(tracer, flight_ring)`` — the ring is ``None``
    when flight is off: nothing ever reads it (dump paths are gated on
    ``flight.enabled``), so tracing-only configs skip the per-emit
    lock + append and the 2048-event retention."""
    from deepspeed_tpu.telemetry.tracing import (DEFAULT_MAX_EVENTS,
                                                 Tracer)

    ring = (FlightRecorder(int(ring_size) or DEFAULT_RING_SIZE)
            if flight_enabled else None)
    tracer = Tracer(enabled=bool(tracing_enabled or flight_enabled),
                    max_events=int(max_events) or DEFAULT_MAX_EVENTS,
                    ring=ring)
    return tracer, ring


def make_watchdog(name: str, flight_cfg: Any, ring: Any = None,
                  telemetry: Any = None, tracer: Any = None):
    """Build the hang :class:`Watchdog` for one hot loop from a
    ``flight`` config block (dict or ``FlightConfig``); ``None`` unless
    the block is enabled.  Companion to :func:`make_span_recorder` — the
    hub and the hub-less server must wire watchdogs (and their
    deadline/output_dir/poll defaults) identically."""
    if flight_cfg is None:
        return None
    get = (flight_cfg.get if isinstance(flight_cfg, dict)
           else lambda k, d=None: getattr(flight_cfg, k, d))
    if not get("enabled", False):
        return None
    return Watchdog(name,
                    deadline_s=float(get("deadline_s", 60.0) or 60.0),
                    output_dir=str(get("output_dir", "")
                                   or "./dstpu_flight"),
                    ring=ring, telemetry=telemetry, tracer=tracer,
                    poll_s=float(get("poll_s", 0.0) or 0.0))


def _format_all_stacks() -> str:
    """Every thread's Python stack, annotated with thread names — the
    first thing to read in a hang bundle."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines: List[str] = []
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        lines.extend(x.rstrip("\n") for x in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


def _telemetry_snapshot(telemetry: Any) -> Dict[str, Any]:
    """Duck-typed snapshot of a telemetry.Telemetry hub: last record +
    every registry metric's current value."""
    out: Dict[str, Any] = {}
    rec = getattr(telemetry, "last_record", None)
    if rec is not None:
        try:
            out["last_record"] = json.loads(rec.to_json())
        except Exception:
            out["last_record"] = repr(rec)
    registry = getattr(telemetry, "registry", None)
    if registry is not None:
        metrics: Dict[str, Any] = {}
        for m in registry.collect():
            if hasattr(m, "snapshot"):       # Histogram
                metrics[m.name] = m.snapshot()
            elif hasattr(m, "value"):        # Counter / Gauge
                metrics[m.name] = m.value
        out["metrics"] = metrics
    return out


def dump_bundle(output_dir: str, reason: str, ring: Any = None,
                telemetry: Any = None, error: Optional[BaseException] = None,
                extra: Optional[Dict[str, Any]] = None) -> str:
    """Write one diagnostic bundle; returns its directory.  Never raises
    — forensics must not mask the failure being recorded."""
    bundle = os.path.join(
        output_dir, f"flight_{reason}_{os.getpid()}_{next(_bundle_seq)}")
    try:
        os.makedirs(bundle, exist_ok=True)
        threads = [{"name": t.name, "ident": t.ident, "daemon": t.daemon,
                    "alive": t.is_alive()} for t in threading.enumerate()]
        with open(os.path.join(bundle, "stacks.txt"), "w",
                  encoding="utf-8") as f:
            f.write(_format_all_stacks())
            f.write("\n=== faulthandler ===\n")
            f.flush()
            try:
                faulthandler.dump_traceback(file=f, all_threads=True)
            except Exception:
                pass
        ring_events = ring.snapshot() if ring is not None else []
        # default=repr everywhere: one exotic span arg must not abort
        # the bundle (the outer except would otherwise swallow the whole
        # write after stacks.txt, losing manifest.json)
        with open(os.path.join(bundle, "ring.json"), "w",
                  encoding="utf-8") as f:
            json.dump({"events": ring_events}, f, default=repr)
        if telemetry is not None:
            with open(os.path.join(bundle, "telemetry.json"), "w",
                      encoding="utf-8") as f:
                json.dump(_telemetry_snapshot(telemetry), f, default=repr)
        manifest = {
            "reason": reason,
            "time_unix": time.time(),
            "pid": os.getpid(),
            "error": repr(error) if error is not None else None,
            "threads": threads,
            "ring_events": len(ring_events),
            "files": sorted(os.listdir(bundle)) + ["manifest.json"],
            **(extra or {}),
        }
        with open(os.path.join(bundle, "manifest.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, indent=2, sort_keys=True, default=repr)
        logger.error(f"flight recorder: {reason} bundle written to {bundle}")
    except Exception as e:  # pragma: no cover - depends on fs failures
        logger.warning(f"flight recorder: bundle write failed: {e}")
    return bundle


class Watchdog:
    """Deadline watchdog over a heartbeat.

    The monitored loop calls ``beat()`` once per iteration (a single
    attribute store — safe and cheap from any thread).  The watchdog
    thread fires when ``time.monotonic() - last_beat > deadline_s``,
    dumps one bundle per stall, and re-arms on the next beat, so a
    recovered loop can be caught stalling again later.
    """

    def __init__(self, name: str, deadline_s: float, output_dir: str,
                 ring: Any = None, telemetry: Any = None, tracer: Any = None,
                 poll_s: float = 0.0,
                 on_fire: Optional[Callable[[str], None]] = None):
        self.name = name
        self.deadline_s = float(deadline_s)
        self.output_dir = output_dir
        self.poll_s = float(poll_s) if poll_s else max(
            0.01, min(1.0, self.deadline_s / 4.0))
        self._ring = ring
        self._telemetry = telemetry
        self._tracer = tracer
        self.on_fire = on_fire
        self._last = time.monotonic()
        self._fired_at = -1.0           # beat timestamp the last fire saw
        self._paused = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fire_count = 0
        self.bundles: List[str] = []

    def beat(self) -> None:
        self._last = time.monotonic()

    def pause(self) -> None:
        """Suspend stall detection (the monitored loop is intentionally
        idle — between train steps, inside an eval/checkpoint gap)."""
        self._paused = True

    def resume(self) -> None:
        """Re-arm after :meth:`pause`; resets the deadline clock and
        starts the thread on first use."""
        self._last = time.monotonic()
        self._paused = False
        self.start()

    def start(self) -> "Watchdog":
        if self._thread is None:
            # a stop()ed watchdog can be re-armed: without the clear()
            # the fresh thread would exit on its first _stop.wait() and
            # monitoring would die silently while beat()/resume() still
            # appear to succeed
            self._stop.clear()
            self._last = time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name=f"ds-watchdog-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def fired(self) -> bool:
        return self.fire_count > 0

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._paused:
                continue
            last = self._last
            stalled = time.monotonic() - last
            if stalled <= self.deadline_s or self._fired_at == last:
                continue  # healthy, or already dumped for this stall
            self._fired_at = last
            try:
                bundle = dump_bundle(
                    self.output_dir, "watchdog", ring=self._ring,
                    telemetry=self._telemetry,
                    extra={"watchdog": self.name,
                           "stalled_s": round(stalled, 3),
                           "deadline_s": self.deadline_s})
                self.bundles.append(bundle)
                if self._tracer is not None:
                    self._tracer.instant("watchdog.fire",
                                         watchdog=self.name,
                                         stalled_s=round(stalled, 3),
                                         bundle=bundle)
                if self.on_fire is not None:
                    self.on_fire(bundle)
            except Exception as e:  # pragma: no cover
                logger.warning(f"watchdog {self.name}: fire failed: {e}")
            finally:
                # incremented LAST: fire_count is the "bundle complete"
                # signal pollers wait on (the bundle list is already
                # populated when it ticks)
                self.fire_count += 1
