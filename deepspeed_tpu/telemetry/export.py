"""Telemetry fan-out: JSONL step log, Prometheus text exposition, and
the MonitorMaster bridge.

One ``Telemetry`` hub owns the shared :class:`MetricsRegistry`, the
append-only JSONL writer, the optional Prometheus textfile, the
MonitorMaster bridge (so TensorBoard/CSV/WandB see the same tags), and
the budgeted auto-capture manager.  The engine and the serving loop
each push :class:`StepRecord` objects; everything downstream is a pure
function of those records.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.flight import (dump_bundle,
                                            make_span_recorder,
                                            make_watchdog)
from deepspeed_tpu.telemetry.record import (StepRecord, collect_hbm_stats,
                                            detect_peak_flops_per_sec)
from deepspeed_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                              MetricsRegistry)
from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]

# Every MonitorMaster tag the train-side bridge can emit.  The docs
# table in docs/OBSERVABILITY.md must list each of these —
# tools/telemetry_check.py enforces it.
EXPORT_TAGS = (
    "telemetry/step_time_ms",
    "telemetry/tokens_per_sec",
    "telemetry/mfu",
    "telemetry/goodput",
    "telemetry/achieved_tflops",
    "telemetry/hbm_bytes_in_use",
    "telemetry/hbm_peak_bytes_in_use",
    "telemetry/comm_bytes_total",
    "telemetry/loss",
    "telemetry/grad_norm",
    "telemetry/lr",
    "telemetry/loss_scale",
)


class JsonlExporter:
    """Append-only JSONL writer (one StepRecord per line, keys sorted)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def write(self, record: StepRecord) -> None:
        line = record.to_json()
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def render_prometheus(registry: MetricsRegistry) -> str:
    """Prometheus text exposition (v0.0.4).  Histograms render as
    summaries (pre-computed quantiles over the sliding window)."""
    lines: List[str] = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        if isinstance(m, Counter):
            lines.append(f"# TYPE {m.name} counter")
            lines.append(f"{m.name} {m.value:g}")
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {m.name} gauge")
            lines.append(f"{m.name} {m.value:g}")
        elif isinstance(m, Histogram):
            snap = m.snapshot()
            count, total = m.lifetime()
            lines.append(f"# TYPE {m.name} summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                lines.append(
                    f'{m.name}{{quantile="{q}"}} {snap[key]:g}')
            lines.append(f"{m.name}_sum {total:g}")
            lines.append(f"{m.name}_count {count}")
    return "\n".join(lines) + "\n"


def write_prometheus_textfile(registry: MetricsRegistry, path: str) -> None:
    """Atomic write for node-exporter textfile collectors (a scraper must
    never see a half-written file)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=parent, suffix=".prom.tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(render_prometheus(registry))
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def events_from_record(rec: StepRecord,
                       tags: Tuple[str, ...] = EXPORT_TAGS) -> List[Event]:
    """Flatten a StepRecord into MonitorMaster ``(tag, value, step)``
    events — the bridge that makes TensorBoard/CSV/WandB see the same
    numbers the JSONL carries."""
    hbm0 = next(iter(rec.hbm.values()), {})
    comm_bytes = sum(int(v.get("bytes", 0)) for v in rec.comm.values())
    values: Dict[str, Optional[float]] = {
        "telemetry/step_time_ms": rec.wall_time_s * 1e3,
        "telemetry/tokens_per_sec": rec.tokens_per_sec,
        "telemetry/mfu": rec.mfu,
        "telemetry/goodput": rec.goodput,
        "telemetry/achieved_tflops": rec.achieved_flops_per_sec / 1e12,
        "telemetry/hbm_bytes_in_use": hbm0.get("bytes_in_use"),
        "telemetry/hbm_peak_bytes_in_use": hbm0.get("peak_bytes_in_use"),
        "telemetry/comm_bytes_total": comm_bytes,
        "telemetry/loss": rec.loss,
        "telemetry/grad_norm": rec.grad_norm,
        "telemetry/lr": rec.lr,
        "telemetry/loss_scale": rec.loss_scale,
    }
    return [(tag, float(values[tag]), rec.step) for tag in tags
            if values.get(tag) is not None]


class Telemetry:
    """The per-process telemetry hub (config: the ``telemetry`` block).

    Thread contract: ``record_train_step`` is called by the training
    thread, ``record_serving_step`` by the serve loop; the registry and
    exporters are individually locked, so the two may coexist.
    """

    def __init__(self, cfg, monitor: Any = None,
                 registry: Optional[MetricsRegistry] = None):
        self.cfg = cfg
        self.monitor = monitor
        self.registry = registry or MetricsRegistry()
        # one run_id per bench row / training run, stamped into every
        # StepRecord, the Tracer's trace metadata, and (via FleetSampler)
        # every TierSnapshot row — the manifest stitching key
        self.run_id = str(getattr(cfg, "run_id", "") or "")
        self.peak_flops_per_sec = (
            float(cfg.peak_flops_per_sec) if cfg.peak_flops_per_sec
            else detect_peak_flops_per_sec())
        self.interval_steps = max(1, int(getattr(cfg, "interval_steps", 1)))
        self.last_record: Optional[StepRecord] = None
        # flops for one whole train batch, set once by the engine
        # (profile_compiled or the analytic model profile)
        self._flops_per_step: Optional[float] = None
        self._flops_source = "none"
        # static per-device memory plan for the compiled step, set by the
        # engine's flops handshake ({"backend", "peak_bytes", ...}) —
        # capture reports diff runtime HBM watermarks against it
        self.static_memory: Optional[Dict] = None
        self._steps = 0
        self._skipped = 0
        self._tokens = 0

        self.jsonl = (JsonlExporter(cfg.jsonl_path)
                      if getattr(cfg, "jsonl_path", "") else None)
        self.prometheus_path = getattr(cfg, "prometheus_path", "") or None

        w = int(getattr(cfg, "window", 0)) or None
        reg = self.registry
        hist_kw = {"window": w} if w else {}
        self.step_time = reg.histogram(
            "telemetry_step_time_seconds",
            "train_batch wall time per optimizer step", **hist_kw)
        self.g_mfu = reg.gauge("telemetry_mfu",
                               "model flops utilization, last step")
        self.g_tps = reg.gauge("telemetry_tokens_per_sec",
                               "tokens/s, last step")
        self.g_goodput = reg.gauge(
            "telemetry_goodput",
            "fraction of optimizer steps that applied (not skipped)")
        self.g_hbm = reg.gauge("telemetry_hbm_bytes_in_use",
                               "device 0 HBM bytes in use")
        self.g_hbm_peak = reg.gauge("telemetry_hbm_peak_bytes_in_use",
                                    "device 0 HBM peak bytes in use")
        self.c_steps = reg.counter("telemetry_steps_total",
                                   "optimizer steps recorded")
        self.c_tokens = reg.counter("telemetry_tokens_total",
                                    "tokens processed")
        self.c_skipped = reg.counter("telemetry_skipped_steps_total",
                                     "overflow-skipped optimizer steps")

        cap_cfg = getattr(cfg, "capture", None)
        self.capture = None
        if cap_cfg is not None and getattr(cap_cfg, "enabled", False):
            from deepspeed_tpu.telemetry.capture import AutoCapture

            self.capture = AutoCapture(cap_cfg, telemetry=self)

        # -- software spans + flight recorder (tracing.py / flight.py) --
        tr_cfg = getattr(cfg, "tracing", None)
        self._flight_cfg = fl_cfg = getattr(cfg, "flight", None)
        self.tracer, self.flight_ring = make_span_recorder(
            tracing_enabled=getattr(tr_cfg, "enabled", False),
            flight_enabled=getattr(fl_cfg, "enabled", False),
            max_events=getattr(tr_cfg, "max_events", 0) or 0,
            ring_size=getattr(fl_cfg, "ring_size", 0) or 0)
        # the trace *file* is gated on the tracing block itself: a
        # flight-only config records spans (for the ring) but a user who
        # disabled tracing must not get a trace written at shutdown
        self.trace_path = (getattr(tr_cfg, "trace_path", "") or ""
                           if getattr(tr_cfg, "enabled", False) else "")
        if self.run_id:
            self.tracer.run_id = self.run_id

    # -- tracing / flight recorder ---------------------------------------
    def make_watchdog(self, name: str):
        """A hang :class:`Watchdog` for one hot loop (``None`` unless the
        ``telemetry.flight`` block is enabled).  The caller owns
        start()/beat()/stop()."""
        return make_watchdog(name, self._flight_cfg,
                             ring=self.flight_ring, telemetry=self,
                             tracer=self.tracer)

    def dump_flight(self, reason: str,
                    error: Optional[BaseException] = None) -> Optional[str]:
        """Crash-forensics bundle on demand (serve-loop crash handler,
        ``engine.destroy()`` during exception unwind).  No flight config
        ⇒ no bundle."""
        fl = self._flight_cfg
        if fl is None or not getattr(fl, "enabled", False):
            return None
        return dump_bundle(fl.output_dir, reason, ring=self.flight_ring,
                           telemetry=self, error=error)

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace-event JSON (Perfetto-viewable); returns
        the path, or ``None`` when tracing never recorded anything."""
        path = path or self.trace_path
        if not path or not self.tracer.enabled:
            return None
        return self.tracer.export_chrome_trace(path)

    # -- flops handshake (engine) ---------------------------------------
    def _capture_wants_times(self) -> bool:
        return (self.capture is not None
                and self.capture.regression_factor > 0
                and self.capture.budget_left > 0)

    def should_record(self, step: int) -> bool:
        """The engine thins record assembly on this gate: off-interval
        steps skip the hard host sync entirely, not just the export.
        While a regression-triggered capture still has budget it needs
        every step's wall time (else the trigger distribution goes
        blind) — those steps return True but the engine only feeds
        ``observe_step_time`` unless the interval also matches."""
        if self._capture_wants_times():
            return True
        return step % self.interval_steps == 0

    def is_full_record_step(self, step: int) -> bool:
        """True when ``step`` gets the full record+export; a
        should_record step that isn't is trigger-bookkeeping only."""
        return step % self.interval_steps == 0

    def observe_step_time(self, wall_time_s: float) -> None:
        """Trigger-only feed for off-interval steps: no record, no
        export — just the capture's trailing step-time window."""
        if self.capture is not None:
            self.capture.observe_step_time(wall_time_s)

    def needs_flops(self) -> bool:
        return self._flops_per_step is None

    def set_flops(self, flops_per_step: float, source: str) -> None:
        self._flops_per_step = float(flops_per_step)
        self._flops_source = source

    def set_static_memory(self, totals: Optional[Dict]) -> None:
        """Record the compiled step's static memory plan (engine flops
        handshake) for the capture report's ``hbm`` cross-check."""
        self.static_memory = dict(totals) if totals else None

    # -- record paths ----------------------------------------------------
    def record_train_step(self, step: int, wall_time_s: float, tokens: int,
                          loss: Optional[float] = None,
                          grad_norm: Optional[float] = None,
                          lr: Optional[float] = None,
                          loss_scale: Optional[float] = None,
                          skipped: bool = False,
                          comm: Optional[Dict] = None,
                          offload_overlap_fraction: Optional[float] = None
                          ) -> StepRecord:
        self._steps += 1
        self._skipped += int(bool(skipped))
        self._tokens += int(tokens)
        goodput = 1.0 - self._skipped / max(1, self._steps)
        rec = StepRecord(
            step=step, kind="train", run_id=self.run_id,
            wall_time_s=float(wall_time_s),
            tokens=int(tokens),
            flops_per_step=float(self._flops_per_step or 0.0),
            peak_flops_per_sec=self.peak_flops_per_sec,
            flops_source=self._flops_source,
            goodput=goodput, skipped=bool(skipped),
            loss=loss, grad_norm=grad_norm, lr=lr, loss_scale=loss_scale,
            offload_overlap_fraction=offload_overlap_fraction,
            hbm=collect_hbm_stats(),
            comm=comm if comm is not None else self._comm_totals())
        self._update_registry(rec)
        if self.capture is not None:
            # the single feed point for the regression trigger's trailing
            # step-time window (AutoCapture keeps no second clock)
            self.capture.observe_step_time(rec.wall_time_s)
        self.last_record = rec
        self._export(rec)
        return rec

    def record_recovery(self, step: int, outage_s: float) -> StepRecord:
        """Goodput-gap record: one recovery outage counts as a SKIPPED
        step whose wall time is the whole detection→resumed gap, so the
        cumulative ``goodput`` curve (1 − skipped/total) prices outages
        next to overflow-skipped steps and the JSONL shows the gap as a
        first-class row (``kind: "recovery"``) rather than a hole in the
        step sequence.  Emitted by the recovery supervisor
        (resilience/supervisor.py) when post-restart progress resumes."""
        self._steps += 1
        self._skipped += 1
        goodput = 1.0 - self._skipped / max(1, self._steps)
        rec = StepRecord(
            step=step, kind="recovery", run_id=self.run_id,
            wall_time_s=float(outage_s),
            peak_flops_per_sec=self.peak_flops_per_sec,
            goodput=goodput, skipped=True, comm={})
        self.g_goodput.set(goodput)
        # both counters, like _update_registry: anyone deriving goodput
        # from the exported steps/skipped totals must agree with the gauge
        self.c_steps.inc()
        self.c_skipped.inc()
        self.last_record = rec
        self._export(rec)
        return rec

    def record_serving_step(self, step: int,
                            snapshot: Dict[str, Any]) -> StepRecord:
        """Serving-side record: queue/preemption/KV stats ride the
        ``serving`` field; throughput comes from the snapshot."""
        flat: Dict[str, float] = {}
        for k, v in snapshot.items():
            if isinstance(v, dict):
                for sub, x in v.items():
                    flat[f"{k}_{sub}"] = float(x)
            else:
                flat[k] = float(v)
        rec = StepRecord(
            step=step, kind="serving", run_id=self.run_id,
            tokens=int(snapshot.get("tokens_out", 0)),
            tokens_per_sec=float(snapshot.get("tokens_per_sec", 0.0)),
            peak_flops_per_sec=self.peak_flops_per_sec,
            hbm=collect_hbm_stats(), comm=self._comm_totals(),
            serving=flat)
        self.last_record = rec
        self._export(rec)
        return rec

    # -- internals -------------------------------------------------------
    @staticmethod
    def _comm_totals() -> Dict[str, Dict[str, int]]:
        from deepspeed_tpu.utils.comms_logging import get_comms_logger

        return get_comms_logger().totals()

    def _update_registry(self, rec: StepRecord) -> None:
        self.step_time.observe(rec.wall_time_s)
        self.g_mfu.set(rec.mfu)
        self.g_tps.set(rec.tokens_per_sec)
        self.g_goodput.set(rec.goodput)
        hbm0 = next(iter(rec.hbm.values()), {})
        if "bytes_in_use" in hbm0:
            self.g_hbm.set(hbm0["bytes_in_use"])
        if "peak_bytes_in_use" in hbm0:
            self.g_hbm_peak.set(hbm0["peak_bytes_in_use"])
        self.c_steps.inc()
        self.c_tokens.inc(rec.tokens)
        if rec.skipped:
            self.c_skipped.inc()

    def _export(self, rec: StepRecord) -> None:
        if self.jsonl is not None:
            try:
                self.jsonl.write(rec)
            except OSError as e:
                logger.warning(f"telemetry: jsonl write failed: {e}")
        if self.prometheus_path:
            try:
                write_prometheus_textfile(self.registry,
                                          self.prometheus_path)
            except OSError as e:
                logger.warning(f"telemetry: prometheus write failed: {e}")
        if self.monitor is not None and getattr(self.monitor, "enabled",
                                                True):
            try:
                self.monitor.write_events(events_from_record(rec))
            except Exception as e:
                logger.warning(f"telemetry: monitor export failed: {e}")

    def close(self) -> None:
        if self.capture is not None:
            self.capture.close()
        if self.jsonl is not None:
            self.jsonl.close()
        try:
            self.export_trace()
        except OSError as e:
            logger.warning(f"telemetry: trace export failed: {e}")


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Load a telemetry JSONL step log (helper for tools/tests)."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
