"""Run ledger: the READ side of the telemetry layer.

Eighteen rounds of instrumentation write StepRecord JSONL, Chrome
traces, TierSnapshot fleet logs, SLO blocks, flight bundles, and
``BENCH_*.json`` row tables — and until this module nothing ingested
them across runs.  The ledger turns that artifact pile into an
auditable trajectory:

* :func:`new_run_id` / :func:`write_manifest` — every ``bench.py`` row
  stamps ONE ``run_id`` through Telemetry / Tracer / FleetSampler and
  writes a ``manifest.json`` next to its artifacts, so stitching a run
  back together never relies on directory-listing guesses.
* :func:`rollup_from_manifest` / :func:`rollup_from_bench_row` /
  :func:`load_bench_history` — parse any manifest (or the committed
  ``BENCH_r*`` / ``BENCH_MEASURED_r*`` history) into a typed,
  frozen-key per-run **Rollup** (:data:`ROLLUP_KEYS` and the per-domain
  ``train`` / ``serve`` / ``recovery`` sub-keys), computed through
  ``telemetry.derive`` — the SAME module bench.py's row math uses, so
  row math and ledger math cannot drift.
* :func:`diff_rollups` / :func:`gate_findings` — the regression
  sentinel: per-metric direction + noise-tolerance bands
  (:data:`METRIC_POLICY`), the frozen verdict vocabulary
  (:data:`VERDICTS`), and graft_lint-style fingerprint suppression via
  ``tools/obs_baseline.json``.
* :func:`scan_run` — the in-run anomaly scan (:data:`ANOMALY_KINDS`):
  step-time spikes vs trailing median (the capture-trigger heuristic,
  via ``derive``), MFU cliffs, goodput gaps, SLO-burn acceleration —
  each cross-linked to the covering trace span and any flight bundle.
* :func:`plan_drift` — joins planner evidence with a measured rollup
  into per-metric drift ratios, the calibration input ROADMAP item 3
  asks to feed back into the analytic cost model.

All key sets and vocabularies here are FROZEN and linted by
``tools/telemetry_check.py`` (``check_obs_ledger``) against
docs/OBSERVABILITY.md — the StepRecord contract, applied to the reader.
Pure stdlib, no jax: the ledger must run on the machine where the TPU
tunnel is down, because that is exactly when you audit history.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.telemetry import derive

# ---------------------------------------------------------------------------
# Frozen vocabularies (docs/OBSERVABILITY.md "Run ledger & regression
# sentinel"; linted by tools/telemetry_check.py check_obs_ledger)
# ---------------------------------------------------------------------------

LEDGER_SCHEMA = 1

#: file name a bench row writes next to its artifacts
MANIFEST_NAME = "manifest.json"

#: top-level key set of one manifest.json
MANIFEST_KEYS = ("artifacts", "created_utc", "ledger_schema", "row",
                 "run_id", "schema_versions", "smoke")

#: the artifact slots a manifest links (absent artifact -> null)
MANIFEST_ARTIFACT_KEYS = ("fleet_jsonl", "flight_dir", "resolved_config",
                          "slo", "telemetry_jsonl", "trace_json")

#: top-level key set of one per-run Rollup
ROLLUP_KEYS = ("error", "metric", "recovery", "round", "row", "run_id",
               "serve", "smoke", "source", "stale", "train", "unit",
               "value", "vs_baseline")

#: train-domain rollup keys (``rollup["train"]``)
ROLLUP_TRAIN_KEYS = ("comm_bytes_by_collective", "goodput",
                     "hbm_peak_bytes", "mfu", "offload_overlap_fraction",
                     "step_time_p50_ms", "step_time_p95_ms",
                     "tokens_per_sec")

#: serve-domain rollup keys (``rollup["serve"]``)
ROLLUP_SERVE_KEYS = ("error_budget_burn", "handoff_bytes_per_req",
                     "prefix_hit_rate", "queue_wait_p95_ms",
                     "slo_attainment", "spec_accept_rate",
                     "tokens_per_sec", "tpot_p50_ms", "tpot_p95_ms",
                     "ttft_p50_ms", "ttft_p95_ms")

#: recovery-domain rollup keys (``rollup["recovery"]``)
ROLLUP_RECOVERY_KEYS = ("goodput_after", "loss_gap", "outage_s")

#: frozen sentinel verdicts (one per compared metric)
VERDICTS = ("flat", "improved", "missing", "new", "regressed", "stale")

#: frozen anomaly kinds the in-run scan can emit
ANOMALY_KINDS = ("goodput_gap", "heal_latency", "mfu_cliff",
                 "slo_burn_spike", "step_time_spike")

#: key set of one anomaly record
ANOMALY_KEYS = ("flight_bundle", "kind", "run_id", "step", "threshold",
                "tier", "trace_span", "value")

#: key set of one plan-vs-actual drift entry (ratio = actual/predicted)
DRIFT_KEYS = ("actual", "metric", "predicted", "ratio", "row")

#: key set of one sentinel finding
FINDING_KEYS = ("baseline", "current", "delta", "fingerprint", "metric",
                "requeue_cmd", "row", "verdict")

# per-metric-path comparison policy: direction ("higher" / "lower" is
# better) + relative noise-tolerance band.  Paths not listed fall back
# to _policy_for's name/unit heuristic.
METRIC_POLICY: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.10),
    "vs_baseline": ("higher", 0.10),
    "train.tokens_per_sec": ("higher", 0.10),
    "train.mfu": ("higher", 0.10),
    "train.step_time_p50_ms": ("lower", 0.15),
    "train.step_time_p95_ms": ("lower", 0.25),
    "train.goodput": ("higher", 0.02),
    "train.hbm_peak_bytes": ("lower", 0.10),
    "train.offload_overlap_fraction": ("higher", 0.15),
    "serve.tokens_per_sec": ("higher", 0.10),
    "serve.ttft_p50_ms": ("lower", 0.25),
    "serve.ttft_p95_ms": ("lower", 0.25),
    "serve.tpot_p50_ms": ("lower", 0.25),
    "serve.tpot_p95_ms": ("lower", 0.25),
    "serve.queue_wait_p95_ms": ("lower", 0.25),
    "serve.slo_attainment": ("higher", 0.02),
    "serve.error_budget_burn": ("lower", 0.50),
    "serve.handoff_bytes_per_req": ("lower", 0.20),
    "serve.spec_accept_rate": ("higher", 0.10),
    "serve.prefix_hit_rate": ("higher", 0.10),
    "recovery.outage_s": ("lower", 0.30),
    "recovery.loss_gap": ("lower", 0.50),
    "recovery.goodput_after": ("higher", 0.05),
}

# the last round with real on-chip measurements; chip rows carried
# forward past it are `stale` (satellite: tools/bench_backlog.py flags
# the same boundary)
LAST_MEASURED_ROUND = 4


# ---------------------------------------------------------------------------
# run_id + manifest (the write side bench.py calls)
# ---------------------------------------------------------------------------

def new_run_id(name: str) -> str:
    """One process-unique, sortable run id: ``<row>-<utc>-<pid>``."""
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    return f"{name}-{stamp}-{os.getpid():x}"


def _schema_versions() -> Dict[str, Optional[int]]:
    from deepspeed_tpu.telemetry.record import SCHEMA_VERSION
    try:
        from deepspeed_tpu.serving.fleet import TIER_SNAPSHOT_SCHEMA
    except Exception:       # serving layer absent/broken: still stitchable
        TIER_SNAPSHOT_SCHEMA = None
    return {"ledger": LEDGER_SCHEMA, "step_record": SCHEMA_VERSION,
            "tier_snapshot": TIER_SNAPSHOT_SCHEMA}


def write_manifest(path: str, row_name: str, run_id: str,
                   artifacts: Dict[str, Any], smoke: bool = False,
                   row: Optional[dict] = None) -> str:
    """Write one RunManifest (frozen :data:`MANIFEST_KEYS`) to ``path``.

    ``artifacts`` values outside :data:`MANIFEST_ARTIFACT_KEYS` are
    rejected — the slot list is part of the frozen contract.  ``row``
    optionally embeds the full bench row dict so a manifest is
    self-contained even if the one-line-per-row stdout log is lost.
    """
    bad = set(artifacts) - set(MANIFEST_ARTIFACT_KEYS)
    if bad:
        raise ValueError(f"unknown manifest artifact keys {sorted(bad)} "
                         f"(allowed: {MANIFEST_ARTIFACT_KEYS})")
    if row_name:
        row = dict(row) if row else {"metric": row_name}
        row.setdefault("_row_name", row_name)
    manifest = {
        "artifacts": {k: artifacts.get(k) for k in MANIFEST_ARTIFACT_KEYS},
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "ledger_schema": LEDGER_SCHEMA,
        "row": row,
        "run_id": str(run_id),
        "schema_versions": _schema_versions(),
        "smoke": bool(smoke),
    }
    assert tuple(sorted(manifest)) == MANIFEST_KEYS
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True, default=float)
    os.replace(tmp, path)
    return path


def _read_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# Rollups
# ---------------------------------------------------------------------------

def _empty_rollup(row: str, source: str) -> Dict[str, Any]:
    return {
        "error": None, "metric": None,
        "recovery": {k: None for k in ROLLUP_RECOVERY_KEYS},
        "round": None, "row": row, "run_id": "",
        "serve": {k: None for k in ROLLUP_SERVE_KEYS},
        "smoke": False, "source": source, "stale": False,
        "train": {k: None for k in ROLLUP_TRAIN_KEYS},
        "unit": None, "value": None, "vs_baseline": None,
    }


def _row_name_from_cmd(cmd: str) -> Optional[str]:
    m = re.search(r"--row\s+([A-Za-z0-9_]+)", cmd or "")
    if m:
        return m.group(1)
    if "--peak-entry" in (cmd or ""):
        return "peak_params"
    return None


def _row_name_from_metric(metric: str) -> str:
    """Best-effort metric -> bench row name for history rows without a
    ``cmd`` field (early BENCH_r0* primaries)."""
    known = ("gpt2_350m_commquant", "gpt2_350m_autosched", "gpt2_350m",
             "llama8b_class_zero3", "longseq_flash", "longseq_ring",
             "peak_params", "v2_decode", "serve_load_multi",
             "serve_load", "serve_disagg", "chaos", "plan_validate")
    for name in known:
        if metric.startswith(name):
            return name
    aliases = {"llama3_8b_class": "llama8b_class_zero3",
               "longseq_32768_flash": "longseq_flash"}
    for prefix, name in aliases.items():
        if metric.startswith(prefix):
            return name
    return metric


def rollup_from_bench_row(row: dict, round_no: Optional[int] = None,
                          source: str = "chip") -> Dict[str, Any]:
    """One committed bench-row dict -> one frozen-key Rollup.

    Handles every historical shape: the r01 primary (metric/value/unit
    only), error rows (tunnel down: ``error`` key, value 0), the r04
    measured rows (cmd + mfu + note), and current rows with slo blocks
    and disagg suffixes.
    """
    metric = str(row.get("metric", ""))
    name = (_row_name_from_cmd(str(row.get("cmd", "")))
            or row.get("_row_name") or _row_name_from_metric(metric))
    r = _empty_rollup(name, source)
    r["metric"] = metric or None
    r["round"] = round_no
    r["run_id"] = str(row.get("run_id", "") or "")
    r["error"] = row.get("error")
    r["unit"] = row.get("unit")
    if isinstance(row.get("value"), (int, float)):
        r["value"] = float(row["value"])
    if isinstance(row.get("vs_baseline"), (int, float)):
        r["vs_baseline"] = float(row["vs_baseline"])

    def num(*keys):
        for k in keys:
            v = row.get(k)
            if isinstance(v, (int, float)):
                return float(v)
        return None

    train, serve, rec = r["train"], r["serve"], r["recovery"]
    serving_row = ("serve" in name or "decode" in name
                   or "prefill" in metric)
    if serving_row:
        serve["tokens_per_sec"] = (r["value"] if r["unit"] == "tokens/s"
                                   else None)
        serve["ttft_p50_ms"] = num("ttft_p50_ms", "ttft_p50_ms_disagg")
        serve["ttft_p95_ms"] = num("ttft_p95_ms", "ttft_p95_ms_disagg",
                                   "ttft_p95_ms_cache")
        serve["tpot_p50_ms"] = num("tpot_p50_ms", "tpot_p50_ms_disagg")
        serve["tpot_p95_ms"] = num("tpot_p95_ms", "tpot_p95_ms_disagg")
        serve["queue_wait_p95_ms"] = num("queue_wait_p95_ms")
        serve["handoff_bytes_per_req"] = num("handoff_bytes_per_req")
        serve["spec_accept_rate"] = num("spec_accept_rate")
        serve["prefix_hit_rate"] = num("prefix_hit_rate")
        slo = row.get("slo")
        if isinstance(slo, dict):
            serve["slo_attainment"] = num_of(slo.get("attainment"))
            serve["error_budget_burn"] = num_of(
                slo.get("error_budget_burn"))
    elif name == "chaos":
        rec["outage_s"] = num("recovery_s", "outage_s")
        rec["loss_gap"] = num("loss_gap")
        rec["goodput_after"] = num("goodput_after", "goodput")
    else:
        train["tokens_per_sec"] = (r["value"] if r["unit"] == "tokens/s"
                                   else num("tokens_per_sec"))
        train["mfu"] = num("mfu", "mfu_tuned")
        train["goodput"] = num("goodput")
        train["offload_overlap_fraction"] = num("offload_overlap_fraction",
                                                "overlap_fraction")
    return r


def num_of(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


def rollup_from_manifest(path: str) -> Dict[str, Any]:
    """One manifest.json -> one Rollup, recomputing the deep stats from
    the linked StepRecord / TierSnapshot JSONL through ``derive`` (the
    same math bench.py's rows use)."""
    with open(path, "r", encoding="utf-8") as f:
        manifest = json.load(f)
    row = manifest.get("row") or {}
    r = rollup_from_bench_row(row, round_no=None, source="manifest")
    r["run_id"] = str(manifest.get("run_id", "") or r["run_id"])
    r["smoke"] = bool(manifest.get("smoke", False))
    arts = manifest.get("artifacts") or {}
    train, serve, rec = r["train"], r["serve"], r["recovery"]

    tel_path = arts.get("telemetry_jsonl")
    if tel_path and os.path.exists(tel_path):
        records = _read_jsonl(tel_path)
        steps = [x for x in records if x.get("kind") == "train"]
        recov = [x for x in records if x.get("kind") == "recovery"]
        if steps:
            times_ms = [1e3 * float(x.get("wall_time_s", 0.0))
                        for x in steps]
            train["step_time_p50_ms"] = round(derive.p50(times_ms), 3)
            train["step_time_p95_ms"] = round(derive.p95(times_ms), 3)
            tps = [float(x["tokens_per_sec"]) for x in steps
                   if x.get("tokens_per_sec")]
            if tps and train["tokens_per_sec"] is None:
                train["tokens_per_sec"] = round(derive.p50(tps), 1)
            mfus = [float(x["mfu"]) for x in steps if x.get("mfu")]
            if mfus and train["mfu"] is None:
                train["mfu"] = round(derive.p50(mfus), 4)
            train["goodput"] = num_of(steps[-1].get("goodput"))
            comm = steps[-1].get("comm") or {}
            train["comm_bytes_by_collective"] = {
                op: int(st.get("bytes", 0)) for op, st in comm.items()
            } or None
            peaks = [int(d.get("peak_bytes_in_use",
                               d.get("bytes_in_use", 0)))
                     for x in steps for d in (x.get("hbm") or {}).values()]
            train["hbm_peak_bytes"] = max(peaks) if peaks else None
            overlaps = [float(x["offload_overlap_fraction"]) for x in steps
                        if x.get("offload_overlap_fraction") is not None]
            if overlaps:
                train["offload_overlap_fraction"] = round(
                    derive.p50(overlaps), 4)
        if recov and rec["outage_s"] is None:
            rec["outage_s"] = round(sum(
                float(x.get("wall_time_s", 0.0)) for x in recov), 3)

    fleet_path = arts.get("fleet_jsonl")
    if fleet_path and os.path.exists(fleet_path):
        rows = _read_jsonl(fleet_path)
        # prefer the decode tier (the latency-bearing one), else unified
        by_tier: Dict[str, List[dict]] = {}
        for t in rows:
            by_tier.setdefault(str(t.get("tier", "")), []).append(t)
        tier = ("decode" if "decode" in by_tier
                else "unified" if "unified" in by_tier
                else (sorted(by_tier)[0] if by_tier else None))
        if tier:
            last = by_tier[tier][-1]
            for src, dst in (("ttft_p50_ms", "ttft_p50_ms"),
                             ("ttft_p95_ms", "ttft_p95_ms"),
                             ("tpot_p50_ms", "tpot_p50_ms"),
                             ("tpot_p95_ms", "tpot_p95_ms"),
                             ("queue_wait_p95_ms", "queue_wait_p95_ms")):
                if serve[dst] is None:
                    serve[dst] = num_of(last.get(src))
    slo = arts.get("slo") or row.get("slo")
    if isinstance(slo, dict):
        if serve["slo_attainment"] is None:
            serve["slo_attainment"] = num_of(slo.get("attainment"))
        if serve["error_budget_burn"] is None:
            serve["error_budget_burn"] = num_of(
                slo.get("error_budget_burn"))
    return r


# ---------------------------------------------------------------------------
# History backfill (the committed BENCH_r* / BENCH_MEASURED_r* files)
# ---------------------------------------------------------------------------

def load_bench_history(repo: str) -> List[Dict[str, Any]]:
    """Parse every committed ``BENCH_rNN.json`` and
    ``BENCH_MEASURED_rNN.json`` into rollups (source ``"chip"``).

    * ``BENCH_rNN`` carries a ``parsed`` primary row (r03-r05 are
      tunnel-down error rows with empty ``rows`` lists — kept, with
      ``error`` set, so the trajectory shows the outage).
    * ``BENCH_MEASURED_r04`` has the last real ``rows``;
      r05+ carry ``rows_last_measured_r04`` forward — those rollups are
      marked ``stale`` with the latest queued re-measurement command
      attached by :func:`attach_requeue_cmds`.
    """
    rollups: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rnd = int(m.group(1))
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            sub_rows = parsed.get("rows") or []
            primary = {k: v for k, v in parsed.items() if k != "rows"}
            rollups.append(rollup_from_bench_row(primary, rnd))
            for row in sub_rows:
                if isinstance(row, dict):
                    rollups.append(rollup_from_bench_row(row, rnd))
    for path in sorted(glob.glob(
            os.path.join(repo, "BENCH_MEASURED_r*.json"))):
        m = re.search(r"BENCH_MEASURED_r(\d+)\.json$", path)
        if not m:
            continue
        rnd = int(m.group(1))
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        for row in doc.get("rows") or []:
            if isinstance(row, dict):
                rollups.append(rollup_from_bench_row(row, rnd))
        for row in _carried_rows(doc, repo):
            r = rollup_from_bench_row(row, rnd)
            r["stale"] = rnd > LAST_MEASURED_ROUND
            rollups.append(r)
    return rollups


def _carried_rows(doc: dict, repo: str, depth: int = 0) -> List[dict]:
    """Resolve ``rows_last_measured_r04``: a literal row list (r05-r07)
    or a "see BENCH_MEASURED_rNN.json (carried forward unchanged)"
    string reference (r08+) chased to the referenced file's rows."""
    carried = doc.get("rows_last_measured_r04")
    if isinstance(carried, list):
        return [row for row in carried if isinstance(row, dict)]
    if isinstance(carried, str) and depth < 4:
        m = re.search(r"(BENCH_MEASURED_r\d+\.json)", carried)
        if m:
            ref = os.path.join(repo, m.group(1))
            if os.path.exists(ref):
                with open(ref, "r", encoding="utf-8") as f:
                    ref_doc = json.load(f)
                rows = [row for row in (ref_doc.get("rows") or [])
                        if isinstance(row, dict)]
                return rows or _carried_rows(ref_doc, repo, depth + 1)
    return []


def collect_queued_cmds(repo: str) -> Dict[str, str]:
    """{row_name: latest queued re-measurement command} from every
    ``queued_measurements_rNN`` list in the measured files."""
    out: Dict[str, str] = {}
    for path in sorted(glob.glob(
            os.path.join(repo, "BENCH_MEASURED_r*.json"))):
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        for key in sorted(doc):
            if not key.startswith("queued_measurements"):
                continue
            for entry in doc[key] or []:
                cmd = str(entry.get("cmd", ""))
                name = _row_name_from_cmd(cmd)
                if name:
                    out[name] = cmd       # later rounds overwrite: latest wins
    return out


def attach_requeue_cmds(rollups: Sequence[Dict[str, Any]],
                        queued: Dict[str, str]) -> Dict[str, str]:
    """{stale row_name: requeue cmd} for the stale rollups present.
    Rows with no queued entry fall back to their own historic cmd shape
    (``python bench.py --row <name>``)."""
    out: Dict[str, str] = {}
    for r in rollups:
        if r.get("stale"):
            out[r["row"]] = queued.get(
                r["row"], f"python bench.py --row {r['row']}")
    return out


def latest_rollups(rollups: Sequence[Dict[str, Any]]
                   ) -> Dict[str, Dict[str, Any]]:
    """{row_name: most-recent non-error rollup} (highest round wins;
    error rollups only win when a row never measured cleanly)."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in sorted(rollups, key=lambda x: (x["round"] is not None,
                                            x["round"] or 0)):
        cur = out.get(r["row"])
        if r.get("error") and cur is not None and not cur.get("error"):
            continue
        out[r["row"]] = r
    return out


# ---------------------------------------------------------------------------
# Regression sentinel
# ---------------------------------------------------------------------------

def flatten_metrics(rollup: Dict[str, Any]) -> Dict[str, float]:
    """Rollup -> flat {metric_path: number} for diffing; dict-valued
    train.comm_bytes_by_collective fans out per collective."""
    out: Dict[str, float] = {}
    for key in ("value", "vs_baseline"):
        if isinstance(rollup.get(key), (int, float)):
            out[key] = float(rollup[key])
    for domain in ("train", "serve", "recovery"):
        for k, v in (rollup.get(domain) or {}).items():
            path = f"{domain}.{k}"
            if isinstance(v, dict):
                for sub, sv in v.items():
                    if isinstance(sv, (int, float)):
                        out[f"{path}.{sub}"] = float(sv)
            elif isinstance(v, (int, float)):
                out[path] = float(v)
    return out


_LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_bytes", "bytes_per_req",
                          "error_budget_burn", "loss_gap", "outage_s")


def _policy_for(path: str, unit: Optional[str] = None
                ) -> Tuple[str, float]:
    """(direction, rel_tolerance) for one metric path; exact
    :data:`METRIC_POLICY` entry, else prefix match (per-collective comm
    bytes), else a name/unit heuristic."""
    if path in METRIC_POLICY:
        return METRIC_POLICY[path]
    for known, pol in METRIC_POLICY.items():
        if path.startswith(known + "."):
            return pol
    if path == "value" and unit in ("s", "ms"):
        return ("lower", 0.25)
    if any(path.endswith(sfx) or sfx.strip("_") in path
           for sfx in _LOWER_BETTER_SUFFIXES):
        return ("lower", 0.20)
    return ("higher", 0.10)


def fingerprint(row: str, metric: str, verdict: str) -> str:
    """Stable id for one finding — the suppression key in
    tools/obs_baseline.json (graft_lint's model)."""
    h = hashlib.sha256(f"obs|{row}|{metric}|{verdict}".encode()).hexdigest()
    return h[:12]


def _verdict(base: Optional[float], cur: Optional[float],
             direction: str, tol: float, stale: bool) -> Optional[str]:
    if base is None and cur is None:
        return None
    if base is None:
        return "new"
    if cur is None:
        return "missing"
    if base == 0:
        delta = 0.0 if cur == 0 else (1.0 if cur > 0 else -1.0)
    else:
        delta = (cur - base) / abs(base)
    gain = delta if direction == "higher" else -delta
    if gain > tol:
        verdict = "improved"
    elif gain < -tol:
        verdict = "regressed"
    else:
        verdict = "flat"
    if verdict == "flat" and stale:
        return "stale"
    return verdict


def diff_rollups(rollups: Sequence[Dict[str, Any]], baseline: dict,
                 requeue: Optional[Dict[str, str]] = None
                 ) -> List[Dict[str, Any]]:
    """Sentinel core: compare each rollup against the committed baseline
    (``rows`` for chip/history rollups, ``smoke_rows`` for smoke runs)
    and emit one finding (:data:`FINDING_KEYS`) per compared metric.

    A smoke rollup's metrics missing from ``smoke_rows`` are verdict
    ``new`` — smoke numbers are plumbing checks, not perf claims, so an
    unbaselined smoke metric never gates.
    """
    requeue = requeue or {}
    findings: List[Dict[str, Any]] = []
    # smoke and chip rollups of the SAME row diff against different
    # baseline sections — partition before taking latest, or a chip
    # history row would shadow the fresh smoke run of the same name
    latest: Dict[Tuple[bool, str], Dict[str, Any]] = {}
    for smoke_flag in (False, True):
        subset = [r for r in rollups
                  if bool(r.get("smoke")) == smoke_flag]
        for row_name, r in latest_rollups(subset).items():
            latest[(smoke_flag, row_name)] = r
    for smoke_flag, row_name in sorted(latest):
        r = latest[(smoke_flag, row_name)]
        section = "smoke_rows" if smoke_flag else "rows"
        base_row = (baseline.get(section) or {}).get(row_name, {})
        cur = flatten_metrics(r)
        for path in sorted(set(cur) | set(base_row)):
            direction, tol = _policy_for(path, r.get("unit"))
            v = _verdict(num_of(base_row.get(path)), cur.get(path),
                         direction, tol, bool(r.get("stale")))
            if v is None:
                continue
            findings.append({
                "baseline": num_of(base_row.get(path)),
                "current": cur.get(path),
                "delta": (None if base_row.get(path) in (None, 0)
                          or path not in cur else round(
                              (cur[path] - base_row[path])
                              / abs(base_row[path]), 4)),
                "fingerprint": fingerprint(row_name, path, v),
                "metric": path,
                "requeue_cmd": (requeue.get(row_name)
                                if r.get("stale") else None),
                "row": row_name,
                "verdict": v,
            })
    return findings


def load_baseline(path: Optional[str]) -> dict:
    if not path or not os.path.exists(path):
        return {"rows": {}, "smoke_rows": {}, "suppress": []}
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    doc.setdefault("rows", {})
    doc.setdefault("smoke_rows", {})
    doc.setdefault("suppress", [])
    return doc


def gate_findings(findings: Sequence[Dict[str, Any]],
                  suppress: Sequence[str] = ()
                  ) -> List[Dict[str, Any]]:
    """The findings that fail the gate: ``regressed`` and not
    fingerprint-suppressed.  ``stale`` / ``new`` / ``missing`` report
    but never gate — a carried-forward history must pass."""
    sup = set(suppress)
    return [f for f in findings
            if f["verdict"] == "regressed"
            and f["fingerprint"] not in sup]


# ---------------------------------------------------------------------------
# In-run anomaly scan
# ---------------------------------------------------------------------------

def _covering_span(trace_events: Sequence[dict], step: Optional[int]
                   ) -> Optional[Dict[str, Any]]:
    """The trace span whose args.step matches (train.step spans stamp
    it), else None — the cross-link from an anomaly to its window."""
    if step is None:
        return None
    for ev in trace_events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if args.get("step") == step:
            return {"name": ev.get("name"), "ts": ev.get("ts"),
                    "dur": ev.get("dur"),
                    "trace_id": args.get("trace_id")}
    return None


def _latest_flight_bundle(flight_dir: Optional[str]) -> Optional[str]:
    if not flight_dir or not os.path.isdir(flight_dir):
        return None
    bundles = sorted(
        d for d in glob.glob(os.path.join(flight_dir, "*"))
        if os.path.isdir(d))
    return bundles[-1] if bundles else None


def scan_run(records: Sequence[dict], fleet_rows: Sequence[dict] = (),
             *, factor: float = 2.0, window: int = 32,
             min_samples: int = 8, mfu_cliff_ratio: float = 0.6,
             objective: float = 0.99, burn_window: int = 20,
             trace_events: Sequence[dict] = (),
             flight_dir: Optional[str] = None,
             run_id: str = "") -> List[Dict[str, Any]]:
    """Scan one run's StepRecords + fleet rows for anomalies
    (:data:`ANOMALY_KINDS`), each cross-linked to the covering trace
    span and the latest flight bundle (if any).

    * ``step_time_spike`` — wall time > ``factor`` × trailing median
      (the capture-trigger heuristic, shared via ``derive``).
    * ``mfu_cliff`` — MFU < ``mfu_cliff_ratio`` × trailing median.
    * ``goodput_gap`` — cumulative goodput dropped (a skipped step) or a
      recovery record interrupted progress.
    * ``slo_burn_spike`` — a tier's windowed error-budget burn crossed
      1.0 (budget for the window exhausted).
    * ``heal_latency`` — a ``fleet.heal`` respawn instant reported
      ``heal_s`` over the supervisor's ``deadline_s`` (the replica
      healed, but too slowly to count as self-healing); the anomaly's
      ``tier`` field carries the replica name.
    """
    bundle = _latest_flight_bundle(flight_dir)

    def anomaly(kind: str, step: Optional[int], value: float,
                threshold: float, tier: Optional[str] = None) -> dict:
        a = {"flight_bundle": bundle, "kind": kind, "run_id": run_id,
             "step": step, "threshold": round(threshold, 6),
             "tier": tier, "trace_span": _covering_span(trace_events, step),
             "value": round(value, 6)}
        assert tuple(sorted(a)) == ANOMALY_KEYS
        return a

    out: List[Dict[str, Any]] = []
    steps = [x for x in records if x.get("kind") == "train"]
    times = [float(x.get("wall_time_s", 0.0)) for x in steps]
    for i, value, threshold in derive.step_time_spikes(
            times, factor, window=window, min_samples=min_samples):
        out.append(anomaly("step_time_spike", int(steps[i]["step"]),
                           value, threshold))
    mfus = [float(x["mfu"]) if x.get("mfu") else None for x in steps]
    for i, value, threshold in derive.value_cliffs(
            mfus, mfu_cliff_ratio, window=window,
            min_samples=min_samples):
        out.append(anomaly("mfu_cliff", int(steps[i]["step"]),
                           value, threshold))
    prev_goodput: Optional[float] = None
    for x in records:
        g = num_of(x.get("goodput"))
        if x.get("kind") == "recovery":
            out.append(anomaly("goodput_gap", int(x.get("step", 0)),
                               g if g is not None else 0.0,
                               prev_goodput or 1.0))
        elif g is not None:
            if prev_goodput is not None and g < prev_goodput:
                out.append(anomaly("goodput_gap", int(x.get("step", 0)),
                                   g, prev_goodput))
            prev_goodput = g

    by_tier: Dict[str, List[dict]] = {}
    for t in fleet_rows:
        by_tier.setdefault(str(t.get("tier", "")), []).append(t)
    allowed_per_tick = 1.0 - objective
    for tier in sorted(by_tier):
        rows = by_tier[tier]
        flags = [int(bool(t.get("slo_violation", 0))) for t in rows]
        prev_burn = 0.0
        for i in range(len(flags)):
            lo = max(0, i + 1 - burn_window)
            n = i + 1 - lo
            viol = sum(flags[lo:i + 1])
            allowed = allowed_per_tick * n
            burn = (0.0 if viol == 0 else
                    (999.0 if allowed <= 0 else viol / allowed))
            if burn >= 1.0 and prev_burn < 1.0:
                out.append(anomaly("slo_burn_spike", i, burn, 1.0,
                                   tier=tier))
            prev_burn = burn

    for ev in trace_events:
        if ev.get("ph") != "i" or ev.get("name") != "fleet.heal":
            continue
        args = ev.get("args") or {}
        if args.get("state") != "respawned":
            continue
        heal_s = num_of(args.get("heal_s"))
        deadline = num_of(args.get("deadline_s"))
        if heal_s is not None and deadline and heal_s > deadline:
            out.append(anomaly("heal_latency", None, heal_s, deadline,
                               tier=str(args.get("replica", ""))))
    return out


def scan_manifest(path: str, **kw) -> List[Dict[str, Any]]:
    """Anomaly-scan the artifacts a manifest links."""
    with open(path, "r", encoding="utf-8") as f:
        manifest = json.load(f)
    arts = manifest.get("artifacts") or {}
    records: List[dict] = []
    tel = arts.get("telemetry_jsonl")
    if tel and os.path.exists(tel):
        records = _read_jsonl(tel)
    fleet_rows: List[dict] = []
    fl = arts.get("fleet_jsonl")
    if fl and os.path.exists(fl):
        fleet_rows = _read_jsonl(fl)
    trace_events: List[dict] = []
    tr = arts.get("trace_json")
    if tr and os.path.exists(tr):
        with open(tr, "r", encoding="utf-8") as f:
            trace_events = (json.load(f) or {}).get("traceEvents", [])
    slo = arts.get("slo") or {}
    objective = (float(slo.get("objective", 0.99))
                 if isinstance(slo, dict) else 0.99)
    kw.setdefault("objective", objective)
    kw.setdefault("flight_dir", arts.get("flight_dir"))
    kw.setdefault("run_id", str(manifest.get("run_id", "")))
    return scan_run(records, fleet_rows, trace_events=trace_events, **kw)


# ---------------------------------------------------------------------------
# Plan-vs-actual drift (ROADMAP item 3's calibration input)
# ---------------------------------------------------------------------------

def plan_drift(rollup: Dict[str, Any], evidence: Dict[str, Any]
               ) -> List[Dict[str, Any]]:
    """Join one planner evidence block (``PLAN_EVIDENCE_KEYS``) with one
    measured rollup into per-metric drift entries (:data:`DRIFT_KEYS`);
    ``ratio = actual / predicted`` (1.0 = the cost model was right).
    Pairs with a missing side are skipped — drift is only meaningful
    where both exist."""
    train = rollup.get("train") or {}
    comm = train.get("comm_bytes_by_collective") or {}
    actual_wire = (float(sum(comm.values())) if comm else None)
    pairs = (
        ("step_ms", num_of(evidence.get("predicted_step_ms")),
         train.get("step_time_p50_ms")),
        ("peak_bytes", num_of(evidence.get("predicted_peak_bytes")),
         train.get("hbm_peak_bytes")),
        ("overlap_fraction", num_of(evidence.get("overlap_fraction")),
         train.get("offload_overlap_fraction")),
        ("wire_bytes_total", num_of(evidence.get("wire_bytes_total")),
         actual_wire),
    )
    out: List[Dict[str, Any]] = []
    for metric, predicted, actual in pairs:
        if predicted in (None, 0) or actual is None:
            continue
        entry = {"actual": float(actual), "metric": metric,
                 "predicted": float(predicted),
                 "ratio": round(float(actual) / float(predicted), 4),
                 "row": rollup.get("row")}
        assert tuple(sorted(entry)) == DRIFT_KEYS
        out.append(entry)
    return out
