"""Software spans: request/step tracing with Perfetto-viewable export.

The host-side complement of the XPlane capture path (utils/trace.py):
XPlane shows *device* timelines inside a budgeted window, but the phases
that make a request slow — queue wait, admission, prefill-vs-decode,
stream fan-out — happen on the host, outside any capture window.  A
:class:`Tracer` records named monotonic-clock spans with a
``trace_id``/``span_id``/``parent_id`` chain, thread-safely, from every
hot loop (serve loop, train loop, submit path), and exports them as
Chrome trace-event JSON (``chrome://tracing`` / Perfetto ``ui``).

Design constraints (docs/OBSERVABILITY.md "Tracing & flight recorder"):

* **Zero dependencies** — stdlib only; serving/ stays jax-free.
* **Bounded** — finished events land in a ``deque(maxlen=max_events)``
  (oldest dropped, ``dropped_events`` counts them) and, when attached,
  in the flight recorder's ring (flight.py).
* **Free when disabled** — ``tracer.span(...)`` returns the shared
  :data:`NULL_SPAN` singleton without touching its arguments, so a
  disabled tracer adds one attribute check + one method call per span
  and allocates nothing.  Hot call sites pass positional args only and
  attach kwargs via ``Span.set`` behind an ``enabled`` guard.

Span names are a frozen vocabulary (:data:`SPAN_NAMES` /
:data:`EVENT_NAMES`), linted against the docs table by
``tools/telemetry_check.py`` — the same frozen-schema contract as the
StepRecord key set.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Frozen name tables (docs/OBSERVABILITY.md span table; telemetry_check lint)
# ---------------------------------------------------------------------------

# Duration spans (Chrome "X" complete events).
SPAN_NAMES = (
    "fleet.sample",            # one fleet-sampler cadence tick (all tiers)
    "offload.d2h",             # chunked offload: grad chunk device->host
    "offload.h2d",             # chunked offload: updated leaf host->device
    "offload.host_step",       # chunked offload: host Adam on one chunk
    "recovery.outage",         # detection -> resumed progress (supervisor)
    "router.leg",              # one replica attempt of a routed request
    "router.request",          # whole routed-request lifetime (root span)
    "serve.admission_block",   # submit blocked on a full queue ('block' policy)
    "serve.decode",            # first token -> terminal (per request)
    "serve.handoff",           # KV-chain export/import (disagg tiers)
    "serve.prefill",           # admission -> first token (per request)
    "serve.queue_wait",        # enqueue -> admission (per request)
    "serve.request",           # whole request lifetime (root span)
    "serve.step",              # one serve-loop engine step (whole batch)
    "spec.draft",              # draft-model proposal loop (one round)
    "spec.verify",             # target verify-k ragged step (one round)
    "train.data_ingest",       # micro-batch stack + host->device put
    "train.dispatch",          # compiled train step dispatch
    "train.step",              # one whole train_batch (root span)
    "train.sync",              # hard host sync (loss value fetch)
    "train.telemetry",         # StepRecord assembly + export
    "v2.ragged_step",          # InferenceEngineV2.step ragged dispatch
)

# Instant events (Chrome "i" events).
EVENT_NAMES = (
    "chaos.inject",            # a scheduled fault fired (resilience/chaos.py)
    "fleet.brownout",          # degradation ladder changed level
    "fleet.heal",              # fleet supervisor state transition / action
    "recovery.detected",       # worker crash / hang noticed by supervisor
    "recovery.replan",         # surviving hosts -> new mesh plan
    "recovery.restart",        # group relaunched (possibly resized)
    "recovery.resumed",        # first post-restart training progress
    "router.dispatch",         # routed request bound to a replica
    "router.failover",         # replica died; request re-dispatched
    "serve.emit",              # one token handed to a response stream
    "serve.enqueue",           # request entered the admission queue
    "serve.finish",            # request reached a terminal state
    "serve.first_token",       # request's first decoded token
    "serve.preempt",           # request evicted for KV pressure
    "serve.prefix_hit",        # admission adopted cached prefix pages
    "slo.violation",           # a tier tick breached an SLO target
    "spec.accept",             # verify round outcome (proposed/accepted)
    "watchdog.fire",           # hang watchdog dumped a flight bundle
)

DEFAULT_MAX_EVENTS = 100_000


def _now_us() -> float:
    return time.monotonic() * 1e6


class _NullSpan:
    """Shared do-nothing span — the disabled-tracer fast path.  One
    process-wide instance; every method is a constant-time no-op."""

    __slots__ = ()

    name = ""
    trace_id = ""
    span_id = 0

    def set(self, **args) -> "_NullSpan":
        return self

    def end(self, **args) -> None:
        return None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def __bool__(self) -> bool:
        # `if req.span:` reads as "is tracing recording this request?"
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One open duration span; ``end()`` (or context-manager exit) stamps
    the duration and emits the event.  Produced only by an *enabled*
    tracer — call sites never construct one directly."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "_t0_us", "_tid", "_tname", "_args", "_done")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: int):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = tracer._next_id()
        self.parent_id = parent_id
        cur = threading.current_thread()
        self._tid = cur.ident
        # captured at creation: a span may be *ended* by a different
        # thread (submit() opens request spans the serve loop closes),
        # and the track must carry the creating thread's name
        self._tname = cur.name
        self._args: Optional[Dict[str, Any]] = None
        self._done = False
        self._t0_us = _now_us()

    def set(self, **args) -> "Span":
        """Attach key/value args (shows under the span in Perfetto)."""
        if self._args is None:
            self._args = args
        else:
            self._args.update(args)
        return self

    def end(self, **args) -> None:
        if self._done:          # idempotent: crash paths may double-end
            return
        self._done = True
        if args:
            self.set(**args)
        t1 = _now_us()
        a: Dict[str, Any] = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            a["parent_id"] = self.parent_id
        if self._args:
            a.update(self._args)
        self._tracer._emit({
            "name": self.name, "cat": self.name.split(".", 1)[0], "ph": "X",
            "ts": self._t0_us, "dur": t1 - self._t0_us,
            "pid": self._tracer._pid, "tid": self._tid, "args": a,
        }, tname=self._tname)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()

    def __bool__(self) -> bool:
        return True


class Tracer:
    """Thread-safe span recorder with bounded memory.

    ``span(name, trace_id, parent)`` takes positional args only so the
    disabled path (`enabled=False`) returns :data:`NULL_SPAN` without
    materializing a kwargs dict; attach args to live spans with
    ``Span.set(...)`` behind an ``if tracer.enabled`` guard when the
    call site is hot.
    """

    def __init__(self, enabled: bool = True,
                 max_events: int = DEFAULT_MAX_EVENTS, ring: Any = None):
        self.enabled = bool(enabled)
        # the owning run's id (Telemetry sets it); exported as a trace
        # metadata event so a trace file names the run it belongs to
        self.run_id = ""
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(max_events)))
        self._ring = ring          # FlightRecorder (flight.py) or None
        self._pid = os.getpid()
        self._ids = itertools.count(1)
        self._thread_names: Dict[int, str] = {}
        self.dropped_events = 0

    # -- recording -------------------------------------------------------
    def _next_id(self) -> int:
        return next(self._ids)

    def new_trace_id(self) -> str:
        """Process-unique id linking every span of one request/run."""
        return f"{self._pid:x}.{self._next_id():x}"

    def span(self, name: str, trace_id: str = "",
             parent: Any = None) -> Any:
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, trace_id or self.new_trace_id(),
                    parent.span_id if parent is not None else 0)

    def instant(self, name: str, trace_id: str = "", **args) -> None:
        """One timestamped marker event (Chrome ``ph: "i"``)."""
        if not self.enabled:
            return
        a = {"trace_id": trace_id, **args}
        self._emit({"name": name, "cat": name.split(".", 1)[0], "ph": "i",
                    "s": "t", "ts": _now_us(), "pid": self._pid,
                    "tid": threading.get_ident(), "args": a})

    def _emit(self, event: Dict[str, Any],
              tname: Optional[str] = None) -> None:
        with self._lock:
            tid = event["tid"]
            # spans pass the name of their *creating* thread; the
            # emitting thread's name is only right for instants.  Always
            # refresh: the OS recycles thread idents, and a stale entry
            # would label a new thread's Perfetto track with a dead
            # thread's name for the rest of the process
            name = (tname if tname is not None
                    else threading.current_thread().name)
            if self._thread_names.get(tid) != name:
                self._thread_names[tid] = name
            if len(self._events) == self._events.maxlen:
                self.dropped_events += 1
            self._events.append(event)
        ring = self._ring
        if ring is not None:
            ring.record(event)

    # -- reading / export ------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name ``{count, total_ms}`` rollup (bench rows report
        the queue/prefill/decode breakdown from this)."""
        out: Dict[str, Dict[str, float]] = {}
        for ev in self.snapshot():
            if ev.get("ph") != "X":
                continue
            row = out.setdefault(ev["name"], {"count": 0, "total_ms": 0.0})
            row["count"] += 1
            row["total_ms"] += ev.get("dur", 0.0) / 1e3
        for row in out.values():
            row["total_ms"] = round(row["total_ms"], 3)
        return out

    def chrome_trace(self) -> Dict[str, Any]:
        """The trace-event JSON object (Chrome/Perfetto ``traceEvents``
        format; ts/dur in microseconds)."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
        meta: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "ts": 0, "args": {"name": "deepspeed_tpu"},
        }]
        if self.run_id:
            meta.append({"name": "run_id", "ph": "M", "pid": self._pid,
                         "tid": 0, "ts": 0,
                         "args": {"run_id": self.run_id}})
        for tid, tname in sorted(names.items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                         "tid": tid, "ts": 0, "args": {"name": tname}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Write the trace JSON (atomically — a half-written trace file
        is worse than none) and return the path."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{self._pid}"
        with open(tmp, "w", encoding="utf-8") as f:
            # default=repr: one exotic span arg (numpy scalar, Path, ...)
            # must not abort the whole export at shutdown — same contract
            # as flight.dump_bundle's ring.json
            json.dump(self.chrome_trace(), f, default=repr)
        os.replace(tmp, path)
        return path


NULL_TRACER = Tracer(enabled=False)
"""Shared disabled tracer — call sites keep one unconditional code path
(`self._tracer = telemetry.tracer if telemetry else NULL_TRACER`)."""
