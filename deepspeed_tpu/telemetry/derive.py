"""Shared derivation math: one home for the numbers everybody re-derives.

``bench.py`` rows, the capture trigger, and the run ledger all compute
the same four things — percentiles, model FLOPs/token, MFU, and the
"p95 vs trailing median" regression heuristic.  Before this module each
had its own copy, which is exactly how row math and ledger math drift
apart.  Now there is ONE implementation:

* :func:`percentile` — the repo-frozen index formula
  ``xs[min(len-1, int(q*(len-1)))]`` on a sorted copy (matches the
  registry Histogram and every inline bench closure, so a ledger p95
  equals the row's p95 bit-for-bit).
* :func:`fwd_flops_per_tok` / :func:`mfu` — GQA-aware analytic model
  FLOPs and the fwd+bwd MFU against a peak (bench.py's row math; the
  ledger re-derives MFU from rollup inputs through the same code).
* :func:`trailing_regressed` — the capture-trigger heuristic
  (``p95 > factor × median`` over a trailing window,
  ``telemetry.capture`` delegates here) and :func:`step_time_spikes`,
  its per-step form used by the ledger's anomaly scan.

Pure stdlib, no jax — telemetry/ stays importable on a machine with the
TPU tunnel down.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

#: bf16 matmul peak of the v5e chip the bench rows quote MFU against
V5E_PEAK_FLOPS_PER_SEC = 197e12

#: fwd+bwd FLOPs multiple of the forward pass (the standard 3x)
FWD_BWD_FACTOR = 3


def percentile(xs: Sequence[float], q: float) -> float:
    """Frozen repo percentile: sorted ``xs[min(len-1, int(q*(len-1)))]``.

    ``q`` is a fraction in [0, 1].  Empty input returns 0.0 — callers
    treat "no samples" as "no signal", not an error.
    """
    if not xs:
        return 0.0
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(q * (len(ys) - 1)))]


def p50(xs: Sequence[float]) -> float:
    return percentile(xs, 0.50)


def p95(xs: Sequence[float]) -> float:
    return percentile(xs, 0.95)


def p99(xs: Sequence[float]) -> float:
    return percentile(xs, 0.99)


def fwd_flops_per_tok(model, seq: int) -> float:
    """Model fwd FLOPs/token: qkvo (GQA-aware) + ffn + lm_head + attn.

    ``model`` is anything with ``hidden_size`` / ``num_layers`` /
    ``vocab_size`` (ModelConfig or a duck-typed stand-in); optional
    ``intermediate_size`` / ``activation`` / ``num_heads`` /
    ``num_kv_heads`` refine the ffn and GQA terms.
    """
    h, L, V = model.hidden_size, model.num_layers, model.vocab_size
    ffn = getattr(model, "intermediate_size", 4 * h)
    act = 3 if getattr(model, "activation", "gelu") == "swiglu" else 2
    heads = getattr(model, "num_heads", 1)
    kv_heads = getattr(model, "num_kv_heads", None) or heads
    qkvo = 2 * h * h + 2 * h * (h * kv_heads // heads)  # q,o + k,v (GQA)
    matmul = L * (qkvo + act * h * ffn)
    return 2 * matmul + 2 * h * V + 2 * seq * h * L


def mfu(tokens_per_sec: float, model, seq: int,
        peak_flops_per_sec: float = V5E_PEAK_FLOPS_PER_SEC) -> float:
    """fwd+bwd model-FLOP utilisation of ``peak_flops_per_sec``."""
    if peak_flops_per_sec <= 0:
        return 0.0
    return (tokens_per_sec * FWD_BWD_FACTOR * fwd_flops_per_tok(model, seq)
            / peak_flops_per_sec)


def trailing_regressed(times: Sequence[float], factor: float,
                       min_samples: int = 8) -> bool:
    """The capture-trigger heuristic: windowed ``p95 > factor × median``.

    ``times`` is the trailing window of step wall-times (the capture
    controller feeds its deque).  Fewer than ``min_samples`` samples or
    a non-positive factor never trigger.
    """
    if factor <= 0 or len(times) < min_samples:
        return False
    xs = sorted(times)
    median = xs[len(xs) // 2]
    p95_ = xs[min(len(xs) - 1, int(0.95 * (len(xs) - 1)))]
    return median > 0 and p95_ > factor * median


def step_time_spikes(times: Sequence[float], factor: float,
                     window: int = 32, min_samples: int = 8
                     ) -> List[Tuple[int, float, float]]:
    """Per-step form of the capture trigger for the ledger anomaly scan.

    Walks the series with a trailing window of up to ``window`` PRIOR
    samples; index ``i`` spikes when ``times[i] > factor × median`` of
    its window (≥ ``min_samples`` priors).  Returns
    ``[(index, value, threshold), ...]``.
    """
    out: List[Tuple[int, float, float]] = []
    if factor <= 0:
        return out
    for i in range(len(times)):
        lo = max(0, i - window)
        prior = sorted(times[lo:i])
        if len(prior) < min_samples:
            continue
        median = prior[len(prior) // 2]
        threshold = factor * median
        if median > 0 and times[i] > threshold:
            out.append((i, times[i], threshold))
    return out


def value_cliffs(values: Sequence[Optional[float]], ratio: float,
                 window: int = 32, min_samples: int = 8
                 ) -> List[Tuple[int, float, float]]:
    """Trailing-median CLIFF detector (the spike dual, for MFU): index
    ``i`` is a cliff when ``values[i] < ratio × median`` of its trailing
    window.  None entries are skipped (rows without the signal)."""
    out: List[Tuple[int, float, float]] = []
    if ratio <= 0:
        return out
    series = [(i, v) for i, v in enumerate(values) if v is not None]
    for j, (i, v) in enumerate(series):
        prior = sorted(x for _, x in series[max(0, j - window):j])
        if len(prior) < min_samples:
            continue
        median = prior[len(prior) // 2]
        threshold = ratio * median
        if median > 0 and v < threshold:
            out.append((i, v, threshold))
    return out
