"""Unified telemetry layer: per-step StepRecords, shared metric
primitives, JSONL/Prometheus/monitor export, and budgeted auto-capture
overlap reports.  See docs/OBSERVABILITY.md.

``capture`` (and only it) pulls ``jax`` via utils/trace — it is loaded
lazily so that jax-free consumers (serving/metrics.py imports the
registry; PR-2's invariant is that serving/ never imports jax) stay
jax-free.
"""

from deepspeed_tpu.telemetry import derive
from deepspeed_tpu.telemetry.export import (EXPORT_TAGS, JsonlExporter,
                                            Telemetry, events_from_record,
                                            read_jsonl, render_prometheus,
                                            write_prometheus_textfile)
from deepspeed_tpu.telemetry.ledger import (ANOMALY_KINDS, ANOMALY_KEYS,
                                            DRIFT_KEYS, LEDGER_SCHEMA,
                                            MANIFEST_ARTIFACT_KEYS,
                                            MANIFEST_KEYS, ROLLUP_KEYS,
                                            ROLLUP_RECOVERY_KEYS,
                                            ROLLUP_SERVE_KEYS,
                                            ROLLUP_TRAIN_KEYS, VERDICTS,
                                            diff_rollups, gate_findings,
                                            load_bench_history, new_run_id,
                                            plan_drift, rollup_from_manifest,
                                            scan_manifest, scan_run,
                                            write_manifest)
from deepspeed_tpu.telemetry.flight import (FLIGHT_REASONS, FlightRecorder,
                                            Watchdog, dump_bundle,
                                            make_span_recorder)
from deepspeed_tpu.telemetry.record import (SCHEMA_VERSION, StepRecord,
                                            collect_hbm_stats,
                                            detect_peak_flops_per_sec,
                                            record_keys)
from deepspeed_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                              MetricsRegistry)
from deepspeed_tpu.telemetry.slo import (SLO_BLOCK_KEYS, SLO_LEDGER_KEYS,
                                         SLO_SCENARIO_KEYS,
                                         SLO_TARGET_KEYS, SLOLedger,
                                         SLOSpec)
from deepspeed_tpu.telemetry.tracing import (EVENT_NAMES, NULL_SPAN,
                                             NULL_TRACER, SPAN_NAMES, Span,
                                             Tracer)

_LAZY = ("AutoCapture", "build_capture_report")


def __getattr__(name):
    if name in _LAZY:
        from deepspeed_tpu.telemetry import capture

        return getattr(capture, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "ANOMALY_KEYS", "ANOMALY_KINDS", "AutoCapture", "Counter",
    "DRIFT_KEYS", "EVENT_NAMES", "EXPORT_TAGS",
    "FLIGHT_REASONS", "FlightRecorder", "Gauge", "Histogram",
    "JsonlExporter", "LEDGER_SCHEMA", "MANIFEST_ARTIFACT_KEYS",
    "MANIFEST_KEYS", "MetricsRegistry", "NULL_SPAN", "NULL_TRACER",
    "ROLLUP_KEYS", "ROLLUP_RECOVERY_KEYS", "ROLLUP_SERVE_KEYS",
    "ROLLUP_TRAIN_KEYS",
    "SCHEMA_VERSION", "SLOLedger", "SLOSpec", "SLO_BLOCK_KEYS",
    "SLO_LEDGER_KEYS", "SLO_SCENARIO_KEYS", "SLO_TARGET_KEYS",
    "SPAN_NAMES", "Span", "StepRecord", "Telemetry",
    "Tracer", "VERDICTS", "Watchdog", "build_capture_report",
    "collect_hbm_stats",
    "derive", "detect_peak_flops_per_sec", "diff_rollups", "dump_bundle",
    "events_from_record", "gate_findings", "load_bench_history",
    "make_span_recorder", "new_run_id", "plan_drift", "read_jsonl",
    "record_keys", "render_prometheus", "rollup_from_manifest",
    "scan_manifest", "scan_run", "write_manifest",
    "write_prometheus_textfile",
]
