"""Unified telemetry layer: per-step StepRecords, shared metric
primitives, JSONL/Prometheus/monitor export, and budgeted auto-capture
overlap reports.  See docs/OBSERVABILITY.md.

``capture`` (and only it) pulls ``jax`` via utils/trace — it is loaded
lazily so that jax-free consumers (serving/metrics.py imports the
registry; PR-2's invariant is that serving/ never imports jax) stay
jax-free.
"""

from deepspeed_tpu.telemetry.export import (EXPORT_TAGS, JsonlExporter,
                                            Telemetry, events_from_record,
                                            read_jsonl, render_prometheus,
                                            write_prometheus_textfile)
from deepspeed_tpu.telemetry.record import (SCHEMA_VERSION, StepRecord,
                                            collect_hbm_stats,
                                            detect_peak_flops_per_sec,
                                            record_keys)
from deepspeed_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                              MetricsRegistry)

_LAZY = ("AutoCapture", "build_capture_report")


def __getattr__(name):
    if name in _LAZY:
        from deepspeed_tpu.telemetry import capture

        return getattr(capture, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AutoCapture", "Counter", "EXPORT_TAGS", "Gauge", "Histogram",
    "JsonlExporter", "MetricsRegistry", "SCHEMA_VERSION", "StepRecord",
    "Telemetry", "build_capture_report", "collect_hbm_stats",
    "detect_peak_flops_per_sec", "events_from_record", "read_jsonl",
    "record_keys", "render_prometheus", "write_prometheus_textfile",
]
