"""Budgeted auto-capture windows + persisted per-capture overlap report.

Closes the loop the ISSUE's motivation describes: instead of
hand-driving XProf, a capture window arms itself — on a configured step,
or when the step-time distribution regresses (p95 > k × trailing
median) — records an XPlane trace via :class:`TraceProfiler`, and
post-processes it with ``utils/xplane`` into a small JSON report:
collective-overlap fraction (the T3/Domino "was the all-reduce hidden?"
number), the top-10 device ops, and an MFU cross-check against the
analytic StepRecord.

Captures are budgeted (``budget`` per process) because a trace is not
free: stop_trace hard-syncs the device and the XPlane file can be
hundreds of MB at scale.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.trace import TraceProfiler


# Engine span names whose per-window totals make up the software-span
# overlap estimate (the CPU-degraded stand-in for the XPlane number).
PHASE_SPANS = ("train.data_ingest", "train.dispatch", "train.sync",
               "train.telemetry")


def spans_overlap_estimate(window_totals: Dict[str, Dict]) -> Dict:
    """Software-span overlap estimate from a capture window's per-span
    ``{name: {count, total_ms}}`` totals (Tracer.summary shape).

    The ``train.sync`` span is the host blocked on the device with
    nothing left to overlap — the software-visible analog of exposed
    communication; the other phase spans are host work the runtime
    pipelines under the device's execution.  ``overlap_estimate`` =
    1 − sync/step is therefore a coarse host-side proxy for "how much of
    the step was the pipeline kept busy" — it lets the overlap
    scheduler's decision logic run where XPlane has no device planes
    (the CPU mesh), and the on-chip XPlane fraction supersedes it
    whenever device planes exist."""
    phase = {name.rsplit(".", 1)[1] + "_ms":
             round(float(window_totals.get(name, {}).get("total_ms", 0.0)),
                   3)
             for name in PHASE_SPANS}
    step_ms = round(sum(phase.values()), 3)
    sync_ms = phase["sync_ms"]
    est = (max(0.0, min(1.0, 1.0 - sync_ms / step_ms))
           if step_ms > 0 else 0.0)
    return {**phase, "step_ms": step_ms, "exposed_ms": sync_ms,
            "overlap_estimate": round(est, 4)}


def hbm_cross_check(static_memory: Optional[Dict],
                    step_record=None) -> Tuple[Optional[Dict], str]:
    """The report's ``hbm`` block: runtime HBM watermarks (the
    StepRecord's per-device ``memory_stats()`` peaks) diffed against the
    compiled step's static memory plan (the engine's flops-handshake
    ``set_static_memory``), so ``model_drift`` has a runtime cross-check.

    Degrades to ``(None, note)`` when no static plan was recorded, when
    the backend is not a TPU (the CPU accelerator's watermarks are host
    RSS — process-wide, not device HBM, so the diff would be
    meaningless), or when the record carries no watermarks."""
    if not static_memory:
        return None, "hbm cross-check omitted (no static memory plan " \
                     "recorded — telemetry.measure_flops off?)"
    if static_memory.get("backend") != "tpu":
        return None, ("hbm cross-check omitted on "
                      f"{static_memory.get('backend', '?')} backend "
                      "(host RSS watermarks are not device HBM)")
    marks = dict(getattr(step_record, "hbm", None) or {})
    peaks = [int(v.get("peak_bytes_in_use", 0)) for v in marks.values()
             if isinstance(v, dict)]
    if not any(peaks):
        return None, "hbm cross-check omitted (no device watermarks in " \
                     "the capture-window StepRecord)"
    predicted = int(static_memory.get("peak_bytes", 0))
    measured = max(peaks)
    return {
        "predicted_peak_bytes": predicted,
        "measured_peak_bytes": measured,
        "drift_ratio": (round(measured / predicted, 4) if predicted
                        else None),
        "per_device": marks,
    }, ""


def build_capture_report(logdir: str, device_substr: str = "TPU",
                         step_record=None, span_totals=None,
                         static_memory: Optional[Dict] = None) -> Dict:
    """Pure post-processing of one capture directory → report dict.

    Degrades explicitly when the capture has no device planes (CPU runs
    carry host events only): overlap_fraction pins to 0.0 with a note,
    the top-ops table falls back to host planes, and — when the caller
    hands per-window span totals — the ``spans`` block carries the
    software overlap estimate so the report still feeds the overlap
    scheduler's decision inputs."""
    from deepspeed_tpu.utils import xplane

    report: Dict = {"logdir": logdir, "device_substr": device_substr,
                    "overlap_fraction": 0.0, "devices": {},
                    "top_ops": [], "dominant_collective": None,
                    "spans": spans_overlap_estimate(span_totals or {}),
                    "note": ""}
    try:
        files = xplane.find_xplane_files(logdir)
        if not files:
            report["note"] = f"no xplane files under {logdir}"
        else:
            res = xplane.analyze_logdir(logdir,
                                        device_substr=device_substr)
            if "error" in res:
                report["note"] = res["error"]
            else:
                report["overlap_fraction"] = res["mean_overlap_fraction"]
                report["devices"] = res["devices"]
            tops: Dict[str, Dict] = {}
            for path in files:
                for op in xplane.top_device_ops(
                        xplane.load_xspace(path),
                        device_substr=device_substr):
                    agg = tops.setdefault(op["name"],
                                          {"name": op["name"],
                                           "total_ms": 0.0, "count": 0})
                    agg["total_ms"] = round(
                        agg["total_ms"] + op["total_ms"], 4)
                    agg["count"] += op["count"]
            report["top_ops"] = sorted(tops.values(),
                                       key=lambda o: -o["total_ms"])[:10]
            report["dominant_collective"] = xplane.dominant_collective(
                report["top_ops"])
    except Exception as e:  # a broken trace must not kill training
        report["note"] = f"capture post-processing failed: {e!r}"
    hbm, hbm_note = hbm_cross_check(static_memory, step_record)
    report["hbm"] = hbm
    if hbm_note:
        report["note"] = (report["note"] + "; " + hbm_note).lstrip("; ")
    if step_record is not None:
        # MFU cross-check: the analytic record's number next to what the
        # capture actually saw, so a disagreement is one diff away
        dev = next(iter(report["devices"].values()), {})
        report["mfu_cross_check"] = {
            "record_step": step_record.step,
            "analytic_mfu": step_record.mfu,
            "analytic_step_time_ms": step_record.wall_time_s * 1e3,
            "flops_source": step_record.flops_source,
            "capture_compute_ms": dev.get("compute_ms", 0.0),
            "capture_collective_ms": dev.get("collective_ms", 0.0),
        }
    return report


class AutoCapture:
    """Arms TraceProfiler windows and persists per-capture reports.

    Engine contract (mirrors the ``profiler`` block's TraceProfiler):

        cap.on_step_start(step)      # before dispatching step `step`
        ... run the step ...
        cap.on_step_end(next_step)   # after; next_step = step + 1

    Triggers: ``capture_step`` forces a window at that step; with
    ``regression_factor`` k > 0, a window also arms when the step-time
    p95 over the trailing window exceeds k × its median (needs at least
    8 samples).  Each finished window writes
    ``<output_dir>/capture_step<N>/report.json``.
    """

    MIN_SAMPLES = 8

    def __init__(self, cfg, telemetry=None):
        self.cfg = cfg
        self.telemetry = telemetry
        self.output_dir = cfg.output_dir
        self.num_steps = max(1, int(cfg.num_steps))
        self.budget_left = max(0, int(cfg.budget))
        self.capture_step = int(cfg.capture_step)
        self.regression_factor = float(cfg.regression_factor)
        self.device_substr = getattr(cfg, "device_substr", "TPU")
        self._times: Deque[float] = deque(maxlen=max(8, int(cfg.window)))
        self._profiler: Optional[TraceProfiler] = None
        self._armed_at = 0
        self._span_base: Optional[Dict] = None  # tracer totals at arming
        self.reports: list = []   # report paths written this process

    # -- trigger logic ---------------------------------------------------
    def _regressed(self) -> bool:
        # shared with the ledger's anomaly scan (telemetry/derive.py):
        # windowed p95 > factor × median over the trailing deque
        from deepspeed_tpu.telemetry.derive import trailing_regressed

        return trailing_regressed(list(self._times),
                                  self.regression_factor,
                                  self.MIN_SAMPLES)

    def observe_step_time(self, wall_time_s: float) -> None:
        self._times.append(float(wall_time_s))

    # -- engine hooks ----------------------------------------------------
    def on_step_start(self, step: int) -> None:
        if self._profiler is not None or self.budget_left <= 0:
            return
        forced = self.capture_step and step == self.capture_step
        if not forced and not self._regressed():
            return
        reason = "forced" if forced else "regression"
        logdir = os.path.join(self.output_dir, f"capture_step{step}")
        prof = TraceProfiler(logdir, start_step=step,
                             num_steps=self.num_steps)
        prof.maybe_start(step)
        if not prof.active:   # another profiler owns the backend
            return
        self._profiler = prof
        self._armed_at = step
        self._span_base = self._span_totals()
        self.budget_left -= 1
        logger.info(f"telemetry capture: armed at step {step} "
                    f"({reason}; {self.budget_left} capture(s) left)")

    def _span_totals(self) -> Optional[Dict]:
        """Per-span totals + drop counter from the hub's tracer
        (``None`` when tracing is off — the spans block then reports
        zeros).  The summary covers the tracer's BOUNDED event ring, so
        a base/now diff is only valid while nothing was evicted."""
        tracer = getattr(self.telemetry, "tracer", None) \
            if self.telemetry is not None else None
        if tracer is None or not getattr(tracer, "enabled", False):
            return None
        return {"summary": tracer.summary(),
                "dropped": tracer.dropped_events}

    def _span_window(self) -> Optional[Dict]:
        """Per-span totals accumulated SINCE the window armed (the
        report must describe only the captured steps)."""
        if self._span_base is None:
            return None
        now = self._span_totals()
        if now is None:
            return None
        if now["dropped"] != self._span_base["dropped"]:
            # the tracer's bounded ring wrapped during the window:
            # events from the base snapshot were evicted, so the diff
            # would under-count (or go negative) — degrade to no spans
            # rather than report a wrong overlap estimate
            logger.warning("telemetry capture: tracer ring wrapped during "
                           "the window; spans estimate omitted")
            return None
        base_sum = self._span_base["summary"]
        out = {}
        for name, row in now["summary"].items():
            base = base_sum.get(name, {"count": 0, "total_ms": 0.0})
            d_count = max(0, row["count"] - base["count"])
            d_ms = max(0.0, round(row["total_ms"] - base["total_ms"], 3))
            if d_count or d_ms:
                out[name] = {"count": d_count, "total_ms": d_ms}
        return out

    def on_step_end(self, next_step: int,
                    wall_time_s: Optional[float] = None) -> None:
        if wall_time_s is not None:
            self.observe_step_time(wall_time_s)
        prof = self._profiler
        if prof is None:
            return
        prof.maybe_stop(next_step)
        if prof.active:
            return          # window spans more steps
        self._profiler = None
        self._write_report(prof.output_dir)

    def _write_report(self, logdir: str) -> Optional[str]:
        rec = self.telemetry.last_record if self.telemetry else None
        if rec is not None and not (self._armed_at <= rec.step
                                    < self._armed_at + self.num_steps):
            # interval-thinned telemetry: the last record describes an
            # OLDER step than the capture window — cross-checking the
            # trace against it would report a phantom MFU disagreement
            rec = None
        report = build_capture_report(
            logdir, device_substr=self.device_substr, step_record=rec,
            span_totals=self._span_window(),
            static_memory=getattr(self.telemetry, "static_memory", None)
            if self.telemetry is not None else None)
        self._span_base = None
        if rec is None and self.telemetry is not None:
            report["note"] = (report["note"] + "; no StepRecord inside "
                              "the capture window (interval-thinned "
                              "telemetry) — mfu_cross_check omitted"
                              ).lstrip("; ")
        report["armed_at_step"] = self._armed_at
        report["step"] = self._armed_at
        report["num_steps"] = self.num_steps
        path = os.path.join(logdir, "report.json")
        try:
            os.makedirs(logdir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1, sort_keys=True)
        except OSError as e:
            logger.warning(f"telemetry capture: report write failed: {e}")
            return None
        logger.info(
            f"telemetry capture: report at {path} "
            f"(overlap_fraction={report['overlap_fraction']})")
        self.reports.append(path)
        return path

    def close(self) -> None:
        """Flush a window cut short by the end of training."""
        prof = self._profiler
        if prof is None:
            return
        self._profiler = None
        prof.close()
        self._write_report(prof.output_dir)
