"""DeepCompile analog — profile-guided graph passes on a jitted step.

Re-design of ``deepspeed/compile/`` (``backend.py`` torch.compile hook,
``profilers/graph_profile.py``, ``list_schedule.py`` + ``passes/`` with the
native runtime ``csrc/compile/*.cpp``).  The reference rewrites the fx
graph to insert prefetching allgathers, selective unsharding and
optimizer-state offload.  Under XLA, collective scheduling and fusion are
the compiler's job — what remains valuable is the *decision layer*: profile
the compiled step's cost/memory, then apply memory passes (remat policy,
host offload of optimizer state) until the step fits the budget.

``deepspeed_compile(make_fn, args, config)`` runs the pass pipeline:
each pass inspects the profile and may re-materialise the step function
with different knobs; the final report records every decision — the analog
of the reference's pass schedule list.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from deepspeed_tpu.profiling.flops_profiler import profile_compiled
from deepspeed_tpu.utils.logging import logger


@dataclass
class CompileReport:
    profile: Dict[str, float] = field(default_factory=dict)
    decisions: List[str] = field(default_factory=list)
    knobs: Dict[str, Any] = field(default_factory=dict)


class CompilePass:
    """One pass: inspect (fn, profile, knobs) → updated knobs or None."""

    name = "base"

    def run(self, report: CompileReport, config: Dict[str, Any]
            ) -> Optional[Dict[str, Any]]:
        raise NotImplementedError


class ProfilePass(CompilePass):
    """Populate report.profile from XLA cost/memory analysis (ref
    profilers/graph_profile.py)."""

    name = "profile"

    def __init__(self, fn_factory: Callable[[Dict[str, Any]], Any], args):
        self.fn_factory = fn_factory
        self.args = args

    def run(self, report, config):
        fn = self.fn_factory(report.knobs)
        report.profile = profile_compiled(jax.jit(fn), *self.args)
        report.decisions.append(
            f"profile: flops={report.profile.get('flops', 0):.3e} "
            f"peak={report.profile.get('peak_memory_bytes', 0):.3e}B")
        return None


class RematPass(CompilePass):
    """Escalate the remat policy while peak memory exceeds the budget
    (ref passes/ selective unsharding ↔ here: selective rematerialisation).
    Escalation: none → dots_saveable → nothing_saveable."""

    name = "remat"
    LADDER = ["none", "dots_saveable", "nothing_saveable"]

    def run(self, report, config):
        budget = config.get("memory_budget_bytes")
        peak = report.profile.get("peak_memory_bytes")
        if not budget or peak is None or peak <= budget:
            return None
        cur = report.knobs.get("remat_policy", "none")
        idx = self.LADDER.index(cur) if cur in self.LADDER else 0
        if idx + 1 >= len(self.LADDER):
            return None
        new = self.LADDER[idx + 1]
        report.decisions.append(
            f"remat: peak {peak:.3e}B > budget {budget:.3e}B → "
            f"policy {cur} → {new}")
        return {"remat_policy": new}


class OffloadOptStatesPass(CompilePass):
    """Offload optimizer state to host when even full remat does not fit
    (ref passes/offload_opt_states + csrc/compile z1/z2/z3 offload)."""

    name = "offload_opt_states"

    def run(self, report, config):
        budget = config.get("memory_budget_bytes")
        peak = report.profile.get("peak_memory_bytes")
        if not budget or peak is None or peak <= budget:
            return None
        if report.knobs.get("remat_policy") != "nothing_saveable":
            return None  # remat ladder not exhausted yet
        if report.knobs.get("offload_optimizer"):
            return None
        report.decisions.append(
            f"offload: peak {peak:.3e}B still > budget → optimizer "
            f"states to host")
        return {"offload_optimizer": True}


class PrefetchPass(CompilePass):
    """Widen the parameter-prefetch window when offload/streaming is
    active and memory headroom allows (ref passes/prefetch.py —
    DeepCompile hoists allgathers ahead of use; under XLA the hoisting is
    the latency-hiding scheduler's job, and the *distance* it can hoist a
    host→device layer fetch across is bounded by the unrolled window of
    the streamed layer scan, cfg.scan_unroll).  Each ladder step doubles
    the window: layer i+1's H2D fetch can overlap layer i's compute
    (runtime/infinity.py streams per dynamic_slice of the host buffer)."""

    name = "prefetch"
    LADDER = [1, 2, 4]
    HEADROOM = 0.7  # prefetch buffers cost HBM; keep a wide margin

    def run(self, report, config):
        streaming = bool(report.knobs.get("offload_optimizer")
                         or report.knobs.get("param_stream")
                         or config.get("param_stream"))
        if not streaming:
            return None
        budget = config.get("memory_budget_bytes")
        peak = report.profile.get("peak_memory_bytes")
        if not budget or peak is None or peak > budget * self.HEADROOM:
            return None
        cur = int(report.knobs.get("scan_unroll", 1))
        idx = self.LADDER.index(cur) if cur in self.LADDER else 0
        if idx + 1 >= len(self.LADDER):
            return None
        new = self.LADDER[idx + 1]
        report.decisions.append(
            f"prefetch: streaming active, peak {peak:.3e}B < "
            f"{self.HEADROOM:.0%} of budget → scan_unroll {cur} → {new}")
        return {"scan_unroll": new}


class SelectiveUnshardPass(CompilePass):
    """With memory headroom under the budget, raise the param-persistence
    threshold so small ZeRO-3 params stay gathered — trading spare HBM for
    fewer per-use all-gathers (ref passes/selective_gather + the
    prefetch/unshard decisions of DeepCompile's list schedule; under XLA
    the *prefetch* half is the latency-hiding scheduler's job, so the
    remaining decision is what to stop sharding at all)."""

    name = "selective_unshard"
    LADDER = [0, 100_000, 1_000_000, 10_000_000]
    HEADROOM = 0.85  # only spend memory while peak < 85% of budget

    def run(self, report, config):
        budget = config.get("memory_budget_bytes")
        peak = report.profile.get("peak_memory_bytes")
        if not budget or peak is None or peak > budget * self.HEADROOM:
            return None
        cur = int(report.knobs.get("persist_threshold", 0))
        idx = self.LADDER.index(cur) if cur in self.LADDER else 0
        if idx + 1 >= len(self.LADDER):
            return None
        new = self.LADDER[idx + 1]
        report.decisions.append(
            f"selective_unshard: peak {peak:.3e}B < {self.HEADROOM:.0%} of "
            f"budget → persist_threshold {cur} → {new}")
        return {"persist_threshold": new}


def deepspeed_compile(fn_factory: Callable[[Dict[str, Any]], Callable],
                      args: Tuple, config: Optional[Dict[str, Any]] = None,
                      max_rounds: int = 4
                      ) -> Tuple[Callable, CompileReport]:
    """Run the pass schedule (ref init_z1/z2/z3 + list_schedule):

    ``fn_factory(knobs) -> step_fn`` rebuilds the step under the given
    knobs ({"remat_policy", "offload_optimizer"}).  Returns the jitted
    final fn and the report.
    """
    config = config or {}
    report = CompileReport(knobs={"remat_policy": config.get(
        "remat_policy", "none")})
    profile = ProfilePass(fn_factory, args)
    passes: List[CompilePass] = [RematPass(), OffloadOptStatesPass(),
                                 PrefetchPass(), SelectiveUnshardPass()]
    for _ in range(max_rounds):
        profile.run(report, config)
        changed = False
        for p in passes:
            upd = p.run(report, config)
            if upd:
                report.knobs.update(upd)
                changed = True
                break  # re-profile after each materialised change
        if not changed:
            break
    final = jax.jit(fn_factory(report.knobs))
    for d in report.decisions:
        logger.info(f"deepspeed_compile: {d}")
    return final, report
