"""Compression manager: config-driven QAT + pruning over a param tree.

Analog of ``deepspeed/compression/compress.py`` (``init_compression``,
``redundancy_clean``) and ``scheduler.py`` (``compression_scheduler``).
The reference swaps nn modules for compress-capable ones and lets a
scheduler flip them on at their ``schedule_offset``.  Here compression is a
*pure function* ``apply(params, step)`` → compressed param view, evaluated
inside the jitted train step: techniques switch on by step comparison
(``jnp.where``-free — the step is a python int at call time, so disabled
techniques compile to nothing).
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.compression import basic_layers as B
from deepspeed_tpu.compression.config import (LayerReductionConfig,
                                              TechniqueConfig,
                                              parse_compression_config)
from deepspeed_tpu.parallel.sharding import path_str
from deepspeed_tpu.utils.logging import logger


def _match(patterns: List[str], path: str) -> bool:
    for p in patterns:
        if p == "*" or fnmatch.fnmatch(path, p) or fnmatch.fnmatch(path, f"*{p}*") \
                or re.search(p, path):
            return True
    return False


class CompressionScheduler:
    """Step-gated technique enablement (ref compression/scheduler.py:12)."""

    def __init__(self, techniques: Dict[str, TechniqueConfig]):
        self.techniques = techniques

    def active(self, tech: str, step: int) -> bool:
        tc = self.techniques.get(tech)
        if tc is None or not tc.enabled:
            return False
        if step < tc.schedule_offset:
            return False
        if tc.schedule_offset_end is not None and step > tc.schedule_offset_end:
            return False
        return True


class CompressionManager:
    """Classifies params against the config's group patterns and applies
    QAT/pruning in the forward path (ref init_compression)."""

    def __init__(self, config: Dict[str, Any]):
        cc = config.get("compression_training", config) or {}
        self.cfg = parse_compression_config(cc)
        self.scheduler = CompressionScheduler(
            {k: v for k, v in self.cfg.items() if isinstance(v, TechniqueConfig)})
        self.layer_reduction: LayerReductionConfig = self.cfg["layer_reduction"]

    # ------------------------------------------------------------------
    def _technique_params(self, tech: str, path: str) -> Optional[Dict[str, Any]]:
        tc: TechniqueConfig = self.cfg[tech]
        if not tc.enabled:
            return None
        for g in tc.groups:
            if _match(g.modules, path):
                return g.params
        return None

    def apply(self, params: Any, step: int, num_heads: int = 0) -> Any:
        """params → compressed view for this step. Pure; call inside the
        jitted loss so masks/quant fuse with the matmuls."""

        def leaf(path, w):
            p = path_str(path)
            if np.ndim(w) < 2:
                return w
            out = w
            gp = self._technique_params("sparse_pruning", p)
            if gp is not None and self.scheduler.active("sparse_pruning", step):
                out = out * B.sparse_pruning_mask(
                    out, float(gp.get("dense_ratio", 0.5)),
                    method=gp.get("method", "topk"))
            gp = self._technique_params("row_pruning", p)
            if gp is not None and self.scheduler.active("row_pruning", step):
                out = out * B.row_pruning_mask(out, float(gp.get("dense_ratio", 0.5)))
            gp = self._technique_params("channel_pruning", p)
            if gp is not None and self.scheduler.active("channel_pruning", step):
                out = out * B.channel_pruning_mask(out, float(gp.get("dense_ratio", 0.5)))
            gp = self._technique_params("head_pruning", p)
            if gp is not None and self.scheduler.active("head_pruning", step) \
                    and num_heads:
                out = out * B.head_pruning_mask(
                    out, float(gp.get("dense_ratio", 0.5)), num_heads)
            gp = self._technique_params("weight_quantization", p)
            if gp is not None and self.scheduler.active("weight_quantization", step):
                out = B.quantize_weight_ste(
                    out, bits=int(gp.get("start_bits", gp.get("target_bits", 8))),
                    symmetric=gp.get("quantization_type", "symmetric") == "symmetric",
                    group_size=int(self.cfg["weight_quantization"].shared.get(
                        "quantize_groups", 0) or 0))
            return out

        return jax.tree_util.tree_map_with_path(leaf, params)

    def quantize_activations(self, x, path: str, step: int):
        gp = self._technique_params("activation_quantization", path)
        if gp is None or not self.scheduler.active("activation_quantization", step):
            return x
        return B.quantize_activation_ste(
            x, bits=int(gp.get("bits", 8)),
            symmetric=gp.get("quantization_type", "asymmetric") == "symmetric")

    def active_signature(self, step: int) -> Tuple[str, ...]:
        """Techniques active at ``step`` — callers re-jit when this tuple
        changes (the step gate is python-static inside apply())."""
        return tuple(sorted(
            t for t in self.scheduler.techniques
            if self.scheduler.active(t, step)))

    def reduce_layers(self, params: Any) -> Any:
        """Teacher params → layer-reduced student params (keeps the
        ``teacher_layer`` rows of each stacked [L, ...] param — ref
        student_initialization, compression/helper.py)."""
        lr = self.layer_reduction
        if not lr.enabled:
            return params
        keep = lr.teacher_layer
        if keep is None and lr.keep_number_layer:
            keep = list(range(lr.keep_number_layer))
        if not keep:
            return params
        keep_idx = np.asarray(keep)

        def cut(path, w):
            p = path_str(path)
            if lr.module_name_prefix and not p.startswith(lr.module_name_prefix):
                return w
            if np.ndim(w) >= 1 and np.shape(w)[0] > keep_idx.max() \
                    and "layers" in p:
                return w[keep_idx]
            return w

        out = jax.tree_util.tree_map_with_path(cut, params)
        logger.info(f"layer_reduction: kept layers {keep}")
        return out

    # ------------------------------------------------------------------
    def redundancy_clean(self, params: Any, num_heads: int = 0) -> Any:
        """Permanently bake all active masks/quant into the weights (ref
        redundancy_clean, compress.py) — run once after training."""
        return self.apply(params, step=1 << 30, num_heads=num_heads)


def init_compression(params: Any, config: Dict[str, Any]
                     ) -> Tuple[Any, CompressionManager]:
    """Ref: ``deepspeed.compression.compress.init_compression``.  Applies
    layer reduction eagerly (student keeps ``teacher_layer`` rows of each
    stacked [L, ...] param) and returns (params, manager)."""
    mgr = CompressionManager(config)
    return mgr.reduce_layers(params), mgr


def student_initialization(student_params: Any, teacher_params: Any,
                           config: Dict[str, Any]) -> Any:
    """Initialise a layer-reduced student from its teacher (ref
    ``deepspeed.compression.helper.student_initialization``): the student
    takes the teacher's ``teacher_layer`` rows of every stacked per-layer
    param and the teacher's non-layer params verbatim."""
    mgr = CompressionManager(config)
    cut = mgr.reduce_layers(teacher_params)
    return jax.tree_util.tree_map(lambda s, t: np.asarray(t).astype(s.dtype)
                                  if np.shape(s) == np.shape(t) else s,
                                  student_params, cut)
