"""Elastic agent: supervise workers, restart on failure, re-shard on resize.

Analog of the reference's ``DSElasticAgent`` (elasticity/elastic_agent.py:32,
built on torch-elastic): monitors the local worker processes
(ref _invoke_run :127), restarts the group up to ``max_restarts`` times, and
on a world-size change relaunches with new DSTPU_NUM_PROCS so workers
re-shard from the universal checkpoint.

TPU differences: there is no rendezvous store to re-join — the launcher
recomputes the world layout and workers rebuild the mesh; parameter state
travels through the atomic universal checkpoint rather than NCCL broadcast.

The group start/stop primitives are module functions so the recovery
supervisor (``resilience/supervisor.py``) drives the SAME process
machinery the agent uses — detection policy differs, lifecycle does not.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

# how long a worker gets to honor SIGTERM before SIGKILL — see stop_group
DEFAULT_STOP_TIMEOUT_S = 30.0


class WorkerSpec:
    def __init__(self, cmd: List[str], env: Optional[Dict[str, str]] = None,
                 local_world_size: int = 1):
        self.cmd = list(cmd)
        self.env = dict(env or {})
        self.local_world_size = int(local_world_size)


def start_group(spec: WorkerSpec, world_size: int,
                extra_env: Optional[Dict[str, str]] = None
                ) -> List[subprocess.Popen]:
    """Launch one worker per rank with the canonical world-layout env."""
    procs = []
    for rank in range(world_size):
        env = {**os.environ, **spec.env, **(extra_env or {}),
               "DSTPU_NUM_PROCS": str(world_size),
               "DSTPU_PROC_ID": str(rank),
               "LOCAL_RANK": str(rank),
               "RANK": str(rank),
               "WORLD_SIZE": str(world_size)}
        procs.append(subprocess.Popen(spec.cmd, env=env))
    return procs


def stop_group(procs: List[subprocess.Popen],
               stop_timeout_s: float = DEFAULT_STOP_TIMEOUT_S) -> None:
    """Stop every worker: SIGTERM to all, ONE shared deadline, then
    SIGKILL the stragglers.

    The deadline is shared (not per-process serial waits) and escalation
    is unconditional: a wedged worker — stuck in a collective, swallowing
    SIGTERM in a signal handler, or blocked in native code — must not be
    able to block the group restart forever; it gets killed when the
    budget runs out, period.  A recovery path that can itself hang is
    not a recovery path."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.terminate()
        except OSError:  # already gone
            pass
    deadline = time.monotonic() + max(0.0, float(stop_timeout_s))
    pending = list(live)
    while pending and time.monotonic() < deadline:
        pending = [p for p in pending if p.poll() is None]
        if pending:
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
    if pending:
        logger.warning(f"stop_group: {len(pending)} worker(s) ignored "
                       f"SIGTERM for {stop_timeout_s}s; escalating to "
                       "SIGKILL")
        for p in pending:
            try:
                p.kill()
            except OSError:
                pass
        for p in pending:
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:  # pragma: no cover - kernel-stuck
                logger.error(f"stop_group: pid {p.pid} survived SIGKILL "
                             "(unkillable D-state); abandoning")


class DSElasticAgent:
    """Run a worker group, restarting on failure (ref elastic_agent.py:32)."""

    def __init__(self, spec: WorkerSpec, max_restarts: int = 3,
                 monitor_interval: float = 1.0,
                 world_size_fn: Optional[Callable[[], int]] = None,
                 stop_timeout_s: float = DEFAULT_STOP_TIMEOUT_S):
        self.spec = spec
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self.stop_timeout_s = float(stop_timeout_s)
        self._world_size_fn = world_size_fn or (lambda: spec.local_world_size)
        self.restarts = 0

    def _start_group(self, world_size: int) -> List[subprocess.Popen]:
        return start_group(self.spec, world_size)

    def _stop_group(self, procs: List[subprocess.Popen]) -> None:
        stop_group(procs, stop_timeout_s=self.stop_timeout_s)

    def run(self) -> int:
        """Monitor loop (ref _invoke_run :127): HEALTHY → poll; a failed
        worker triggers a group restart; world-size change re-launches."""
        world = self._world_size_fn()
        procs = self._start_group(world)
        while True:
            time.sleep(self.monitor_interval)
            codes = [p.poll() for p in procs]
            if all(c == 0 for c in codes):
                return 0
            if any(c not in (None, 0) for c in codes):
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    logger.error("elastic agent: max_restarts exceeded")
                    self._stop_group(procs)
                    return 1
                logger.warning(f"elastic agent: worker failed (codes={codes}); "
                               f"restart {self.restarts}/{self.max_restarts}")
                self._stop_group(procs)
                world = self._world_size_fn()
                procs = self._start_group(world)
                continue
            new_world = self._world_size_fn()
            if new_world != world:
                logger.warning(f"elastic agent: world size {world} → {new_world}; "
                               "restarting group to re-shard")
                self._stop_group(procs)
                world = new_world
                procs = self._start_group(world)
