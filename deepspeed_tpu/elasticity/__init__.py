from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig, ElasticityError, ElasticityConfigError,
    ElasticityIncompatibleWorldSize, compute_elastic_config,
    elasticity_enabled, ensure_immutable_elastic_config,
    get_compatible_gpus_v01, get_valid_gpus)
from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent, WorkerSpec,
                                                    start_group, stop_group)
