"""Analytic step-time model fed by the static collective census.

Pricing protocol (docs/PLANNER.md): each candidate gets an analytic
census — per collective kind, the ring-model wire bytes its mesh/stage
shape implies — priced as bytes/hop × link class.  Where a real lowered
census is available (the audit targets of analysis/targets.py), it
anchors the analytic rows: measured/analytic ratios scale the
extrapolated bytes and the rows flip from ``extrapolated`` to
``anchored``.  Overlap credit from pinned step_schedule fusions is
clamped so it can never exceed the comm it hides; host-pipeline overlap
uses the chunked double-buffer fraction (ZeRO-Offload tier model).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.planner.space import Candidate, FleetSpec, ModelSpec

# link classes the census rows are priced against (FleetSpec carries the
# actual bytes/s; this table is the frozen vocabulary)
LINK_CLASSES = ("ici", "dcn", "pcie", "nvme")

# overlap credit per pinned step_schedule fusion (fraction of the
# overlappable window min(comm, compute) each decision hides), capped at
# MAX_OVERLAP_FRACTION — mirrors overlap_scheduler.SCHEDULE_DECISIONS
OVERLAP_CREDITS = {
    "zero3_prefetch": 0.5,
    "fused_gather_matmul": 0.15,
    "ring_interleave": 0.5,
    "decomposed_update": 0.4,
    "fused_reduce_scatter": 0.15,
}
MAX_OVERLAP_FRACTION = 0.9

# chunked host optimizer: double-buffered chunk pipeline overlaps this
# fraction of the state traffic behind compute (offload_overlap_fraction
# analog from the PR 16 stream rung)
OFFLOAD_OVERLAP_FRACTION = 0.6

# achievable fraction of peak flops the compute term assumes
COMPUTE_EFFICIENCY = 0.4

# bytes/param a grad reduce puts on the wire: the engine reduces fp32
# grads when comm_quantization is off (the audit census shows f32
# all-reduce rows), so the un-quantized default is 4, not 2
WIRE_BYTES_PER_GRAD = {"fp32": 4, "int8": 1, "fp8": 1, None: 4}

# anchor/extrapolate protocol (docs/PLANNER.md): a measured census row's
# wire bytes must agree with the analytic row within this multiplicative
# band on the audit targets, or the analytic formula has drifted from
# what the compiler actually emits (frozen; tests/test_planner.py)
ANCHOR_TOLERANCE = 4.0


def _axis_link(axis: str, fleet: FleetSpec) -> str:
    return "dcn" if axis in fleet.dcn_axes else "ici"


def analytic_census(model: ModelSpec, cand: Candidate,
                    gas: int = 1,
                    fleet: Optional[FleetSpec] = None
                    ) -> Dict[str, Dict[str, Any]]:
    """Per-kind wire bytes (per device, per optimizer step) the shape
    implies — same kind vocabulary as the real census
    (``report.census_summary()``), each row marked ``extrapolated`` until
    :func:`apply_anchors` rescales it against a lowered anchor."""
    fleet = fleet or _DEFAULT_FLEET
    c = model.config
    b, s, h = cand.micro_batch, model.seq_len, c.hidden_size
    d = cand.axis("data")
    tp, pp, sp, ep = (cand.axis("tensor"), cand.axis("pipe"),
                      cand.axis("seq"), cand.axis("expert"))
    f_moe = model.moe_param_fraction
    # param count per model-parallel shard; expert params shard over ep
    p_eff = model.num_params * ((1.0 - f_moe) + f_moe / ep) / (tp * pp)
    grad_bpp = WIRE_BYTES_PER_GRAD[
        (cand.comm_quantization or {}).get("grad_reduce")]
    rows: Dict[str, Dict[str, Any]] = {}

    def add(kind: str, wire: float, count: int, link: str) -> None:
        if wire <= 0 or count <= 0:
            return
        row = rows.setdefault(kind, {"count": 0, "wire_bytes": 0,
                                     "link": link,
                                     "mode": "extrapolated"})
        row["count"] += int(count)
        row["wire_bytes"] += int(wire)

    link_d = _axis_link("data", fleet)
    if d > 1:
        if cand.zero_stage <= 1:
            add("all-reduce", 2.0 * (d - 1) / d * p_eff * grad_bpp, 1,
                link_d)
        else:
            add("reduce-scatter", (d - 1) / d * p_eff * grad_bpp, 1, link_d)
            # post-update param all-gather (ZeRO-2) / fwd+bwd re-gathers
            # (ZeRO-3) move bf16 params back out of the shards
            gathers = 2 if cand.zero_stage >= 3 else 1
            add("all-gather", gathers * (d - 1) / d * p_eff * 2, gathers,
                link_d)
    if tp > 1:
        # Megatron pattern: 2 fwd + 2 bwd activation all-reduces/layer
        wire = gas * c.num_layers * 4 * 2.0 * (tp - 1) / tp * b * s * h * 2
        add("all-reduce", wire, gas * c.num_layers * 4, "ici")
    if sp > 1:
        # ring attention: K/V block rotation, (sp-1) hops fwd + bwd
        kv_frac = c.kv_heads / c.num_heads
        wire = (gas * c.num_layers * 2 * 2 * (sp - 1)
                * b * (s / sp) * h * kv_frac * 2)
        add("collective-permute", wire,
            gas * c.num_layers * 2 * (sp - 1), "ici")
    if ep > 1:
        freq = max(1, getattr(c, "moe_layer_freq", 1) or 1)
        moe_layers = -(-c.num_layers // freq)
        topk = getattr(c, "top_k", 2)
        wire = (gas * moe_layers * 4 * (ep - 1) / ep
                * b * s * topk * h * 2)
        add("all-to-all", wire, gas * moe_layers * 4, "ici")
    if pp > 1:
        wire = gas * 2 * b * s * h * 2
        add("collective-permute", wire, gas * 2,
            _axis_link("pipe", fleet))
    return rows


_DEFAULT_FLEET = FleetSpec()


def offload_traffic(model: ModelSpec, cand: Candidate
                    ) -> Dict[str, Dict[str, Any]]:
    """Host-link traffic per step for the offload tier: param
    round-trips over PCIe/NVMe plus the optimizer-state stream (16
    bytes/shard-param fp32 master + moments), the latter overlappable
    when chunked (double-buffered chunk pipeline)."""
    off = cand.offload or {}
    if not off:
        return {}
    shard = model.num_params / max(1, cand.dp_size
                                   if cand.zero_stage >= 1 else 1)
    rows: Dict[str, Dict[str, Any]] = {}
    if off.get("param"):
        link = "nvme" if off["param"] == "nvme" else "pcie"
        # params stream up for fwd and again for bwd re-gather
        rows["param_stream"] = {
            "wire_bytes": int(2 * model.num_params * 2), "link": link,
            "overlappable": False}
    if off.get("optimizer"):
        link = "nvme" if off["optimizer"] == "nvme" else "pcie"
        # grads down (bf16) + fresh params up (bf16) + state touch (fp32
        # master + two moments read/write ≈ 16 B/param on the slow tier)
        rows["grad_stream"] = {"wire_bytes": int(shard * 4), "link": "pcie",
                               "overlappable": False}
        rows["state_stream"] = {
            "wire_bytes": int(shard * 16), "link": link,
            "overlappable": bool(off.get("chunked"))}
    return rows


def schedule_overlap_fraction(cand: Candidate) -> float:
    """Sum of OVERLAP_CREDITS the candidate's pinned fusions earn,
    capped at MAX_OVERLAP_FRACTION."""
    sched = cand.step_schedule or {}
    credit = 0.0
    if sched.get("gather_prefetch_depth"):
        credit += OVERLAP_CREDITS["zero3_prefetch"]
    if sched.get("fused_gather_matmul"):
        credit += OVERLAP_CREDITS["fused_gather_matmul"]
    if int(sched.get("ring_interleave", 1) or 1) >= 2:
        credit += OVERLAP_CREDITS["ring_interleave"]
    if sched.get("weight_update") == "decomposed":
        credit += OVERLAP_CREDITS["decomposed_update"]
    if sched.get("fused_reduce_scatter"):
        credit += OVERLAP_CREDITS["fused_reduce_scatter"]
    return min(MAX_OVERLAP_FRACTION, credit)


def step_time(model: ModelSpec, cand: Candidate, fleet: FleetSpec, *,
              gas: int = 1,
              census: Optional[Dict[str, Dict[str, Any]]] = None
              ) -> Dict[str, Any]:
    """compute + exposed comm + exposed host stream, in seconds, with
    the dominant term named.  Serving (disagg) candidates are priced as
    a prefill-flops vs decode-bandwidth balance instead."""
    if cand.disagg:
        return _disagg_time(model, cand, fleet)
    from deepspeed_tpu.profiling import get_model_profile

    if census is None:
        census = analytic_census(model, cand, gas=gas)
    prof = get_model_profile(model.config, batch_size=cand.micro_batch,
                             seq_len=model.seq_len)
    mp = cand.axis("tensor") * cand.axis("pipe") * cand.axis("seq")
    compute_s = (prof["total_flops_per_step"] * gas
                 / (fleet.peak_flops * COMPUTE_EFFICIENCY * mp))
    pp = cand.axis("pipe")
    if pp > 1:
        # 1F1B bubble: (pp-1) idle microbatch slots per step — pipeline
        # only pays off once gas amortizes the fill/drain ramp
        compute_s *= (gas + pp - 1) / gas
    comm_s = sum(row["wire_bytes"] / fleet.link_speed(row["link"])
                 for row in census.values())
    overlap = schedule_overlap_fraction(cand)
    credit_s = overlap * min(comm_s, compute_s)
    exposed_comm_s = comm_s - credit_s
    host_s = exposed_host_s = 0.0
    for row in offload_traffic(model, cand).values():
        t = row["wire_bytes"] / fleet.link_speed(row["link"])
        host_s += t
        exposed_host_s += (t * (1.0 - OFFLOAD_OVERLAP_FRACTION)
                           if row["overlappable"] else t)
    total = compute_s + exposed_comm_s + exposed_host_s
    terms = {"compute": compute_s, "comm": exposed_comm_s,
             "host": exposed_host_s}
    # the mp chips of one model replica share the same mb×seq×gas tokens
    tokens = cand.micro_batch * model.seq_len * gas / mp
    return {
        "step_seconds": total,
        "compute_seconds": compute_s,
        "comm_seconds": comm_s,
        "exposed_comm_seconds": exposed_comm_s,
        "overlap_credit_seconds": credit_s,
        "overlap_fraction": overlap,
        "host_seconds": host_s,
        "exposed_host_seconds": exposed_host_s,
        "dominant_cost_term": max(terms, key=terms.get),
        "tokens_per_sec_per_chip": tokens / total if total > 0 else 0.0,
        "wire_bytes_total": int(sum(r["wire_bytes"]
                                    for r in census.values())),
    }


def _disagg_time(model: ModelSpec, cand: Candidate,
                 fleet: FleetSpec) -> Dict[str, Any]:
    """Prefill is compute-bound (prompt flops), decode is
    bandwidth-bound (weights re-read per token); the tier split is good
    when neither side waits on the other (docs/SERVING.md)."""
    from deepspeed_tpu.profiling import get_model_profile

    p = cand.disagg["prefill_replicas"]
    dec = cand.disagg["decode_replicas"]
    prof = get_model_profile(model.config, batch_size=1,
                             seq_len=model.seq_len,
                             include_backward=False)
    prefill_s = prof["fwd_flops"] / (
        fleet.peak_flops * COMPUTE_EFFICIENCY) / p
    # decode: DECODE_TOKENS_PER_PROMPT tokens, each streaming the weights
    decode_tokens = max(1, model.seq_len // 4)
    hbm_stream = 8.19e11  # HBM bytes/s a decode step re-reads weights at
    decode_s = decode_tokens * (model.num_params * 2) / hbm_stream / dec
    total = max(prefill_s, decode_s)
    imbalance = abs(prefill_s - decode_s)
    terms = {"prefill": prefill_s, "decode": decode_s}
    return {
        "step_seconds": total + 0.1 * imbalance,
        "compute_seconds": prefill_s,
        "comm_seconds": 0.0,
        "exposed_comm_seconds": 0.0,
        "overlap_credit_seconds": 0.0,
        "overlap_fraction": 0.0,
        "host_seconds": 0.0,
        "exposed_host_seconds": 0.0,
        "dominant_cost_term": max(terms, key=terms.get),
        "tokens_per_sec_per_chip": ((model.seq_len + decode_tokens)
                                    / (total + 0.1 * imbalance)
                                    / max(1, p + dec)),
        "wire_bytes_total": 0,
    }


def anchor_ratios(measured_census: Dict[str, Dict[str, Any]],
                  model: ModelSpec, cand: Candidate,
                  gas: int = 1) -> Dict[str, float]:
    """measured/analytic wire-byte ratio per collective kind, from a
    REAL lowered census (``census_summary()`` of an audit target) of the
    same shape — the anchor half of the anchor/extrapolate protocol."""
    analytic = analytic_census(model, cand, gas=gas)
    out: Dict[str, float] = {}
    for kind, row in analytic.items():
        meas = measured_census.get(kind)
        if not isinstance(meas, dict) or "wire_bytes" not in meas:
            continue
        if row["wire_bytes"] > 0 and meas["wire_bytes"] > 0:
            out[kind] = meas["wire_bytes"] / row["wire_bytes"]
    return out


def apply_anchors(census: Dict[str, Dict[str, Any]],
                  ratios: Dict[str, float]) -> Dict[str, Dict[str, Any]]:
    """Rescale extrapolated rows by the anchor ratios; anchored rows are
    marked so the emitted evidence records which bytes were measured-
    derived vs purely analytic."""
    out = {}
    for kind, row in census.items():
        row = dict(row)
        if kind in ratios:
            row["wire_bytes"] = int(row["wire_bytes"] * ratios[kind])
            row["mode"] = "anchored"
        out[kind] = row
    return out
