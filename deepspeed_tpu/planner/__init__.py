"""Parallelism plan compiler (docs/PLANNER.md).

Searches the whole (mesh × ZeRO stage × comm_quantization ×
step_schedule fusion × offload tier × disagg split) config space
offline, prunes with the calibrated memory model, prices survivors with
an analytic step-time model fed by the static collective census, and
emits ranked, pinned, load-ready ``DeepSpeedConfig`` fragments with
evidence attached.  CLI: ``tools/plan.py`` (``dstpu-plan``).
"""

from deepspeed_tpu.planner.cost import (ANCHOR_TOLERANCE, LINK_CLASSES,
                                        MAX_OVERLAP_FRACTION,
                                        OFFLOAD_OVERLAP_FRACTION,
                                        OVERLAP_CREDITS, analytic_census,
                                        anchor_ratios, apply_anchors,
                                        offload_traffic,
                                        schedule_overlap_fraction,
                                        step_time)
from deepspeed_tpu.planner.rank import (PLAN_EVIDENCE_KEYS, Plan,
                                        PlannedConfig, compile_plan,
                                        config_fragment, load_plan_file,
                                        plan_rank_of, save_plan,
                                        seed_candidates,
                                        validate_fragment)
from deepspeed_tpu.planner.space import (OFFLOAD_TIERS, Candidate,
                                         FleetSpec, ModelSpec,
                                         enumerate_candidates,
                                         prune_candidates, schedule_for)

__all__ = [
    "ANCHOR_TOLERANCE", "LINK_CLASSES", "MAX_OVERLAP_FRACTION", "OFFLOAD_OVERLAP_FRACTION",
    "OVERLAP_CREDITS", "OFFLOAD_TIERS", "PLAN_EVIDENCE_KEYS",
    "Candidate", "FleetSpec", "ModelSpec", "Plan", "PlannedConfig",
    "analytic_census", "anchor_ratios", "apply_anchors", "compile_plan",
    "config_fragment", "enumerate_candidates", "load_plan_file",
    "offload_traffic", "plan_rank_of", "prune_candidates", "save_plan",
    "schedule_for", "schedule_overlap_fraction", "seed_candidates",
    "step_time", "validate_fragment",
]
