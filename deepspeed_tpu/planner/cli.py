"""``dstpu-plan`` CLI (tools/plan.py): ranked table + JSON plan file.

Examples::

    dstpu-plan --model gpt2-6.7b --chips 1 --hbm 16GiB \\
               --host-ram 64GiB --nvme --seq 512 --json plan.json
    dstpu-plan --model gpt2-350m --chips 8 --top 5
    dstpu-plan --model llama3-8b --chips 8 --serving
"""

from __future__ import annotations

import argparse
import re
import sys
from typing import Optional

_SIZE_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([KMGT]i?B?|B?)\s*$",
                      re.IGNORECASE)
_SIZE_MULT = {"": 1, "B": 1,
              "K": 10 ** 3, "KB": 10 ** 3, "KIB": 1 << 10,
              "M": 10 ** 6, "MB": 10 ** 6, "MIB": 1 << 20,
              "G": 10 ** 9, "GB": 10 ** 9, "GIB": 1 << 30,
              "T": 10 ** 12, "TB": 10 ** 12, "TIB": 1 << 40}


def parse_bytes(text: str) -> int:
    """'16GiB' → 17179869184; bare ints pass through."""
    m = _SIZE_RE.match(str(text))
    if not m:
        raise argparse.ArgumentTypeError(
            f"cannot parse size {text!r} (try e.g. 16GiB, 64GB, 512MiB)")
    num, unit = m.groups()
    unit = unit.upper()
    if unit in ("", "B"):
        return int(float(num))
    if not unit.endswith("B"):
        unit += "B"
    if unit not in _SIZE_MULT:
        raise argparse.ArgumentTypeError(f"unknown size unit {unit!r}")
    return int(float(num) * _SIZE_MULT[unit])


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dstpu-plan", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--model", required=True,
                   help="models registry name (e.g. gpt2-6.7b, "
                        "gpt2-350m, moe-1b-ep8)")
    p.add_argument("--seq", type=int, default=None,
                   help="sequence length (default: model max_seq_len)")
    p.add_argument("--chips", type=int, default=8)
    p.add_argument("--hbm", type=parse_bytes, default=16 << 30,
                   metavar="SIZE", help="HBM per chip (default 16GiB)")
    p.add_argument("--host-ram", type=parse_bytes, default=None,
                   metavar="SIZE",
                   help="host RAM budget for cpu-offload tiers "
                        "(default: unconstrained)")
    p.add_argument("--nvme", action="store_true",
                   help="NVMe available (enables nvme offload tiers)")
    p.add_argument("--gas", type=int, default=1,
                   help="gradient accumulation steps to price")
    p.add_argument("--max-micro-batch", type=int, default=64)
    p.add_argument("--stages", type=int, nargs="*", default=None,
                   metavar="S", help="restrict ZeRO stages (e.g. 2 3)")
    p.add_argument("--no-quant", action="store_true",
                   help="drop comm_quantization candidates")
    p.add_argument("--no-offload", action="store_true",
                   help="drop offload-tier candidates")
    p.add_argument("--no-schedule", action="store_true",
                   help="drop step_schedule fusion candidates")
    p.add_argument("--serving", action="store_true",
                   help="plan disaggregated serving splits instead of "
                        "training configs")
    p.add_argument("--calibration", default="auto",
                   help="memory-model calibration: 'auto' (frozen "
                        "model_drift ratio), 'none', or a float")
    p.add_argument("--top", type=int, default=10,
                   help="ranked entries to keep (default 10)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write the full plan (ranked + pruned + "
                        "evidence) as JSON")
    p.add_argument("--show-pruned", type=int, default=3, metavar="N",
                   help="print the first N pruning reasons (default 3)")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    from deepspeed_tpu.planner.rank import compile_plan, save_plan
    from deepspeed_tpu.planner.space import FleetSpec, ModelSpec

    if args.calibration == "auto":
        from deepspeed_tpu.autotuning import load_memory_calibration
        cal = load_memory_calibration(backend="cpu")
    elif args.calibration in ("none", "1", "1.0"):
        cal = 1.0
    else:
        cal = float(args.calibration)

    model = ModelSpec.from_name(args.model, seq_len=args.seq)
    fleet = FleetSpec(chips=args.chips, hbm_bytes=args.hbm,
                      host_bytes=args.host_ram, nvme=args.nvme)
    plan = compile_plan(
        model, fleet,
        stages=tuple(args.stages) if args.stages else (0, 1, 2, 3),
        gas=args.gas, max_micro_batch=args.max_micro_batch,
        enable_quant=not args.no_quant,
        enable_offload=not args.no_offload,
        enable_schedule=not args.no_schedule,
        serving=args.serving, calibration=cal, top=args.top)
    print(plan.table())
    if plan.pruned and args.show_pruned:
        print(f"pruned ({len(plan.pruned)} total, first "
              f"{min(args.show_pruned, len(plan.pruned))}):")
        for row in plan.pruned[:args.show_pruned]:
            print(f"  {row['candidate']}: {row['reason']}")
    if args.json_path:
        save_plan(plan, args.json_path)
        print(f"plan written to {args.json_path} (top entry is a "
              f"load-ready DeepSpeedConfig fragment)")
    if not plan.ranked:
        print("no candidate fits this fleet — see pruning reasons",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
