"""Static audit of planner output: ``graft_lint --plan``.

A plan entry is only "load-ready" if the config it pins actually lowers
clean — so for each registered bench-row query, compile the plan, take
the TOP-ranKed fragment, scale it onto the tiny-geometry 8-device twin
(same discipline as analysis/targets.py: the audit checks graph
structure, not byte volumes), and run the graph + memory-plan audits
over one shared lowering.  A top-ranked config that fails its own
static audit is a planner bug and must fail the lint, not ship in a
plan file.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.planner.rank import Plan, compile_plan
from deepspeed_tpu.planner.space import FleetSpec, ModelSpec

# bench rows with a pinned known-good config whose planner query is
# re-auditable offline (the regression gate in tests/test_planner.py
# asserts top-3 rank against these same queries)
PLAN_AUDIT_ROWS = ("gpt2_350m", "gpt2_350m_commquant",
                   "gpt2_350m_autosched", "longseq_ring")


def plan_for_row(name: str, chips: int = 8, *,
                 top: Optional[int] = 10) -> Plan:
    """The planner query mirroring a bench row's config space: same
    model class and fleet shape, constrained the way the row's
    experiment is (the autosched row studies stage-3 scheduling, the
    commquant row enables the quantized wire, ...)."""
    fleet = FleetSpec(chips=chips)
    if name == "gpt2_350m":
        model = ModelSpec.from_name("gpt2-350m", seq_len=1024)
        return compile_plan(model, fleet, enable_quant=False,
                            max_micro_batch=16, top=top)
    if name == "gpt2_350m_commquant":
        model = ModelSpec.from_name("gpt2-350m", seq_len=1024)
        return compile_plan(model, fleet, enable_quant=True,
                            max_micro_batch=16, top=top)
    if name == "gpt2_350m_autosched":
        model = ModelSpec.from_name("gpt2-350m", seq_len=1024)
        return compile_plan(model, fleet, stages=(3,),
                            enable_quant=False, max_micro_batch=16,
                            top=top)
    if name == "longseq_ring":
        model = ModelSpec.from_name(
            "llama3-8b", seq_len=32768, hidden_size=2048, num_heads=16,
            num_kv_heads=8, intermediate_size=8192, num_layers=6,
            vocab_size=32256, max_seq_len=32768, seq_impl="ring")
        # the row shards the sequence over EVERY chip (mesh {"seq": n})
        # — the planner ranks stage/schedule within that placement family
        return compile_plan(model, fleet, enable_quant=False,
                            enable_offload=False, max_micro_batch=4,
                            top=top,
                            mesh_filter=lambda m: m.get("seq", 1) == chips)
    raise KeyError(f"unknown plan audit row {name!r} "
                   f"(known: {list(PLAN_AUDIT_ROWS)})")


def _scale_mesh(mesh: Dict[str, int], cfg, n: int) -> Dict[str, int]:
    """Clamp a planned mesh onto the twin model's divisibility (tiny
    head/layer counts) while keeping the device product at ``n``."""
    heads = cfg.num_heads
    kv = cfg.num_kv_heads or heads
    layers = cfg.num_layers
    experts = getattr(cfg, "num_experts", 0) or 0
    tp = int(mesh.get("tensor", 1))
    while tp > 1 and (heads % tp or kv % tp):
        tp //= 2
    sp = int(mesh.get("seq", 1))
    while sp > 1 and heads % sp:
        sp //= 2
    pp = int(mesh.get("pipe", 1))
    while pp > 1 and layers % pp:
        pp //= 2
    ep = int(mesh.get("expert", 1))
    while ep > 1 and (not experts or experts % ep):
        ep //= 2
    mp = tp * sp * pp * ep
    while mp > 1 and n % mp:
        # shave the largest axis until the product divides the mesh
        biggest = max(("tensor", tp), ("seq", sp), ("pipe", pp),
                      ("expert", ep), key=lambda t: t[1])[0]
        if biggest == "tensor":
            tp //= 2
        elif biggest == "seq":
            sp //= 2
        elif biggest == "pipe":
            pp //= 2
        else:
            ep //= 2
        mp = tp * sp * pp * ep
    out = {"data": max(1, n // mp)}
    for k, v in (("tensor", tp), ("pipe", pp), ("seq", sp),
                 ("expert", ep)):
        if v > 1:
            out[k] = v
    return out


def prepared_plan_target(name: str):
    """(PreparedTarget, fragment): the row's top-ranked plan fragment
    applied to the tiny twin geometry, engine built and ready to lower."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.analysis.targets import _prep_engine
    from deepspeed_tpu.models import get_model_config

    plan = plan_for_row(name)
    if not plan.ranked:
        raise RuntimeError(f"plan for {name} ranked no candidates")
    frag = copy.deepcopy(plan.ranked[0].config)
    n = jax.device_count()
    if name == "longseq_ring":
        twin = get_model_config("llama-tiny", max_seq_len=128,
                                seq_impl="ring",
                                ring_placement="striped",
                                attn_impl="xla")
    else:
        twin = get_model_config("gpt2-tiny", max_seq_len=64)
    cfg = dict(frag)
    cfg["train_micro_batch_size_per_gpu"] = 1
    cfg["gradient_accumulation_steps"] = 2
    cfg["mesh"] = _scale_mesh(frag.get("mesh") or {"data": n}, twin, n)
    cfg["gradient_clipping"] = 1.0
    cfg["steps_per_print"] = 10_000
    engine, _, _, _ = ds.initialize(model=twin, config=cfg)
    return _prep_engine(engine, f"plan:{name}"), frag


def audit_planned_config(name: str, budget: Optional[int] = None
                         ) -> Tuple[Dict[str, Any], Any, Any]:
    """Lower the row's top-ranked plan twin once and run both audit
    families → (fragment, GraphAuditReport, MemoryAuditReport)."""
    from deepspeed_tpu.analysis.auditor import audit_artifacts, lower_step
    from deepspeed_tpu.analysis.memory import audit_memory

    prep, frag = prepared_plan_target(name)
    try:
        art = lower_step(prep.fn, *prep.args, label=prep.label)
    finally:
        prep.cleanup()
    graph = audit_artifacts(art, intent=prep.intent)
    mem = audit_memory(art, intent=prep.memory_intent, budget=budget)
    return frag, graph, mem
