"""Plan compilation: prune → price → rank → emit pinned configs.

The output contract: every ranked entry is a *load-ready*
``DeepSpeedConfig`` fragment (it parses round-trip, see
``runtime.config.load_plan``) carrying its evidence under the frozen
``PLAN_EVIDENCE_KEYS`` — the census rollup it was priced with (anchored
vs extrapolated per row), the calibrated peak prediction, the dominant
cost term, and the overlap credit.  Losers keep their pruning reasons so
a plan file explains the whole space, not just the winners.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.planner.cost import (analytic_census, apply_anchors,
                                        step_time)
from deepspeed_tpu.planner.space import (DEFAULT_CHUNK_BYTES,
                                         DEFAULT_WORKING_SET_BYTES,
                                         Candidate, FleetSpec, ModelSpec,
                                         enumerate_candidates,
                                         prune_candidates)

# every ranked plan entry's evidence dict carries exactly these keys
# (frozen in tools/telemetry_check.py + docs/PLANNER.md)
PLAN_EVIDENCE_KEYS = (
    "census",
    "census_mode",
    "dominant_class",
    "dominant_cost_term",
    "overlap_fraction",
    "predicted_peak_bytes",
    "predicted_step_ms",
    "wire_bytes_total",
)

_TIER_ORDER = {"none": 0, "opt_cpu": 1, "cpu": 2, "cpu_chunked": 3,
               "nvme_chunked": 4, "nvme": 5}


@dataclass
class PlannedConfig:
    rank: int
    candidate: str
    tokens_per_sec_per_chip: float
    config: Dict[str, Any]
    evidence: Dict[str, Any]

    def to_dict(self) -> Dict[str, Any]:
        return {"rank": self.rank, "candidate": self.candidate,
                "tokens_per_sec_per_chip": self.tokens_per_sec_per_chip,
                "config": self.config, "evidence": self.evidence}


@dataclass
class Plan:
    model: str
    seq_len: int
    fleet: Dict[str, Any]
    gas: int
    ranked: List[PlannedConfig] = field(default_factory=list)
    pruned: List[Dict[str, Any]] = field(default_factory=list)
    n_candidates: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"model": self.model, "seq_len": self.seq_len,
                "fleet": self.fleet, "gas": self.gas,
                "n_candidates": self.n_candidates,
                "ranked": [r.to_dict() for r in self.ranked],
                "pruned": self.pruned}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Plan":
        plan = cls(model=d["model"], seq_len=d["seq_len"],
                   fleet=dict(d.get("fleet") or {}),
                   gas=int(d.get("gas", 1)),
                   n_candidates=int(d.get("n_candidates", 0)),
                   pruned=list(d.get("pruned") or []))
        for r in d.get("ranked", []):
            plan.ranked.append(PlannedConfig(
                rank=r["rank"], candidate=r["candidate"],
                tokens_per_sec_per_chip=r["tokens_per_sec_per_chip"],
                config=r["config"], evidence=r["evidence"]))
        return plan

    def table(self, top: Optional[int] = None) -> str:
        rows = self.ranked[:top] if top else self.ranked
        lines = [f"plan: {self.model} seq={self.seq_len} "
                 f"chips={self.fleet.get('chips')} "
                 f"({self.n_candidates} candidates, "
                 f"{len(self.pruned)} pruned)",
                 f"{'#':>3} {'tok/s/chip':>12} {'step_ms':>9} "
                 f"{'peak_GiB':>9} {'dominant':>9}  candidate"]
        for r in rows:
            ev = r.evidence
            lines.append(
                f"{r.rank:>3} {r.tokens_per_sec_per_chip:>12.1f} "
                f"{ev['predicted_step_ms']:>9.2f} "
                f"{ev['predicted_peak_bytes'] / (1 << 30):>9.2f} "
                f"{ev['dominant_cost_term']:>9}  {r.candidate}")
        return "\n".join(lines)


def config_fragment(model: ModelSpec, cand: Candidate,
                    gas: int = 1) -> Dict[str, Any]:
    """The pinned, load-ready DeepSpeedConfig fragment for a candidate —
    the same block shapes the bench rows pin (bench.PINNED_ROW_CONFIGS),
    so a plan's top entry drops straight into ``deepspeed.initialize``."""
    if cand.disagg:
        n = (cand.disagg["prefill_replicas"]
             + cand.disagg["decode_replicas"])
        return {
            "train_micro_batch_size_per_gpu": 1,
            "serving": {"n_replicas": n,
                        "disagg": {"enabled": True, **cand.disagg}},
        }
    frag: Dict[str, Any] = {
        "train_micro_batch_size_per_gpu": cand.micro_batch,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "mesh": dict(cand.mesh),
        "zero_optimization": {"stage": cand.zero_stage},
    }
    off = cand.offload or {}
    if off.get("param"):
        frag["zero_optimization"]["offload_param"] = {
            "device": off["param"]}
    if off.get("optimizer"):
        block: Dict[str, Any] = {"device": off["optimizer"]}
        if off.get("chunked"):
            block["chunk_bytes"] = DEFAULT_CHUNK_BYTES
            block["working_set_bytes"] = DEFAULT_WORKING_SET_BYTES
        frag["zero_optimization"]["offload_optimizer"] = block
    if cand.comm_quantization:
        frag["comm_quantization"] = dict(cand.comm_quantization)
    if cand.step_schedule:
        frag["step_schedule"] = {"mode": "static",
                                 **copy.deepcopy(cand.step_schedule)}
    return frag


def validate_fragment(fragment: Dict[str, Any],
                      world_size: int = 1) -> None:
    """Round-trip the fragment through DeepSpeedConfig — a plan whose
    top entry does not parse is a planner bug, caught at emit time.
    Same code path a user's ``runtime.config.load_plan`` takes."""
    from deepspeed_tpu.runtime.config import load_plan

    load_plan(copy.deepcopy(fragment), world_size=world_size)


def compile_plan(model: ModelSpec, fleet: FleetSpec, *,
                 stages: Tuple[int, ...] = (0, 1, 2, 3),
                 gas: int = 1,
                 max_micro_batch: int = 64,
                 enable_quant: bool = True,
                 enable_offload: bool = True,
                 enable_schedule: bool = True,
                 serving: bool = False,
                 calibration: float = 1.0,
                 anchors: Optional[Dict[str, float]] = None,
                 top: Optional[int] = None,
                 validate_top: int = 3,
                 mesh_filter=None) -> Plan:
    """Enumerate → prune (predict_fit) → price (census × link class) →
    dedupe per placement key → rank by modeled throughput."""
    cands = enumerate_candidates(
        model, fleet, stages=stages, max_micro_batch=max_micro_batch,
        enable_quant=enable_quant, enable_offload=enable_offload,
        enable_schedule=enable_schedule, serving=serving,
        mesh_filter=mesh_filter)
    fit, pruned = prune_candidates(model, fleet, cands,
                                   calibration=calibration)
    best: Dict[Tuple, Tuple[Candidate, Dict[str, Any], Dict[str, Any],
                            Dict[str, Any]]] = {}
    for cand, fitres in fit:
        census = analytic_census(model, cand, gas=gas, fleet=fleet)
        if anchors:
            census = apply_anchors(census, anchors)
        timing = step_time(model, cand, fleet, gas=gas, census=census)
        key = cand.key()
        prev = best.get(key)
        if prev is None or (timing["tokens_per_sec_per_chip"]
                            > prev[3]["tokens_per_sec_per_chip"]):
            best[key] = (cand, fitres, census, timing)
    ordered = sorted(
        best.values(),
        key=lambda t: (-t[3]["tokens_per_sec_per_chip"], t[0].zero_stage,
                       _TIER_ORDER.get(t[0].offload_tier, 9),
                       -t[0].micro_batch))
    plan = Plan(model=model.name, seq_len=model.seq_len,
                fleet={"chips": fleet.chips, "hbm_bytes": fleet.hbm_bytes,
                       "host_bytes": fleet.host_bytes,
                       "nvme": fleet.nvme},
                gas=gas, pruned=pruned, n_candidates=len(cands))
    for i, (cand, fitres, census, timing) in enumerate(
            ordered[:top] if top else ordered, start=1):
        modes = {row["mode"] for row in census.values()}
        evidence = {
            "census": {k: {"count": r["count"],
                           "wire_bytes": r["wire_bytes"],
                           "link": r["link"], "mode": r["mode"]}
                       for k, r in sorted(census.items())},
            "census_mode": ("anchored" if modes == {"anchored"} else
                            "extrapolated" if modes in ({"extrapolated"},
                                                        set())
                            else "mixed"),
            "dominant_class": fitres["dominant_class"],
            "dominant_cost_term": timing["dominant_cost_term"],
            "overlap_fraction": round(timing["overlap_fraction"], 4),
            "predicted_peak_bytes": fitres["predicted_peak_bytes"],
            "predicted_step_ms": round(timing["step_seconds"] * 1e3, 3),
            "wire_bytes_total": timing["wire_bytes_total"],
        }
        assert tuple(sorted(evidence)) == tuple(sorted(PLAN_EVIDENCE_KEYS))
        plan.ranked.append(PlannedConfig(
            rank=i, candidate=cand.describe(),
            tokens_per_sec_per_chip=round(
                timing["tokens_per_sec_per_chip"], 3),
            config=config_fragment(model, cand, gas=gas),
            evidence=evidence))
    for entry in plan.ranked[:validate_top]:
        validate_fragment(entry.config, world_size=fleet.chips)
    return plan


# ---------------------------------------------------------------------
# regression-gate helpers: match a pinned bench-row config against a
# plan's ranking (mesh, stage, quant wire, offload tier — the dimensions
# a row pins; micro-batch/gas are workload knobs the gate ignores)
# ---------------------------------------------------------------------

def _frag_key(frag: Dict[str, Any], chips: int) -> Tuple:
    zero = frag.get("zero_optimization") or {}
    mesh = dict(frag.get("mesh") or {"data": chips})
    mesh = {k: int(v) for k, v in mesh.items() if int(v) > 1 or k == "data"}
    mesh.setdefault("data", 1)
    quant = (frag.get("comm_quantization") or {})
    wire = quant.get("grad_reduce") if quant.get("enabled") else None
    op = (zero.get("offload_param") or {}).get("device")
    oo = zero.get("offload_optimizer") or {}
    od = oo.get("device")
    chunked = bool(oo.get("working_set_bytes"))
    if op in (None, "none"):
        op = None
    if od in (None, "none"):
        od = None
    if op == "nvme":
        tier = "nvme"
    elif od == "nvme" and chunked:
        tier = "nvme_chunked"
    elif op == "cpu" and od == "cpu":
        tier = "cpu_chunked" if chunked else "cpu"
    elif od == "cpu":
        tier = "opt_cpu"
    else:
        tier = "none"
    return (tuple(sorted(mesh.items())), int(zero.get("stage", 0)),
            wire, tier)


def plan_rank_of(plan: Plan, known_good: Dict[str, Any],
                 chips: Optional[int] = None) -> Optional[int]:
    """1-based rank of the first planned entry whose placement matches
    the pinned fragment; None if the planner never proposed it."""
    n = chips or plan.fleet.get("chips") or 1
    want = _frag_key(known_good, n)
    for entry in plan.ranked:
        if _frag_key(entry.config, n) == want:
            return entry.rank
    return None


# ---------------------------------------------------------------------
# Autotuner seeding: ranked plan entries as tuning-space candidates
# ---------------------------------------------------------------------

def seed_candidates(model_cfg, *, seq_len: int, chips: int,
                    hbm_bytes: int, calibration: float = 1.0,
                    top: int = 8) -> List[Dict[str, Any]]:
    """The Autotuner's planner-mode space: top-N plan entries mapped to
    trial-candidate dicts ({zero_stage, micro_batch, mesh, overrides}),
    best first — trials then confirm the analytic ordering."""
    from deepspeed_tpu.planner.space import _moe_fraction
    from deepspeed_tpu.profiling import get_model_profile

    prof = get_model_profile(model_cfg, batch_size=1, seq_len=seq_len)
    spec = ModelSpec(name=getattr(model_cfg, "arch", "model"),
                     config=model_cfg, seq_len=seq_len,
                     num_params=int(prof["params"]),
                     moe_param_fraction=_moe_fraction(
                         model_cfg, int(prof["params"])))
    plan = compile_plan(spec, FleetSpec(chips=chips, hbm_bytes=hbm_bytes),
                        calibration=calibration, top=top, validate_top=0)
    out = []
    for entry in plan.ranked:
        frag = entry.config
        cand: Dict[str, Any] = {
            "zero_stage": frag["zero_optimization"]["stage"],
            "micro_batch": frag["train_micro_batch_size_per_gpu"],
            "mesh": dict(frag.get("mesh") or {"data": chips}),
            "est_bytes": entry.evidence["predicted_peak_bytes"],
        }
        overrides = {}
        for k in ("comm_quantization", "step_schedule"):
            if k in frag:
                overrides[k] = copy.deepcopy(frag[k])
        zo = {k: v for k, v in frag["zero_optimization"].items()
              if k != "stage"}
        if zo:
            overrides["zero_optimization"] = {
                "stage": frag["zero_optimization"]["stage"], **zo}
        if overrides:
            cand["overrides"] = overrides
        out.append(cand)
    return out


def save_plan(plan: Plan, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(plan.to_dict(), f, indent=2, sort_keys=True)


def load_plan_file(path: str) -> Plan:
    with open(path, "r", encoding="utf-8") as f:
        return Plan.from_dict(json.load(f))
