"""Plan-compiler search space: fleet/model specs + candidate enumeration.

The offline half of the reference autotuner (PAPER.md layer 8): instead
of *running* candidate configs, enumerate the whole (mesh × ZeRO stage ×
comm_quantization × step_schedule fusion × offload tier × disagg split)
space symbolically (Placement Semantics, arXiv:2601.02311) and let the
calibrated memory model (``predict_fit``) prune what cannot fit before
anything is priced.  Survivors go to :mod:`deepspeed_tpu.planner.cost`.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.autotuning.autotuner import (ModelInfo, enumerate_meshes,
                                                predict_fit)

# offload tiers mirror the peak_params ladder rungs (bench.py
# _PEAK_LADDER): device-resident → host optimizer → full host → chunked
# host pipeline (PR 16) → NVMe chunk files → full NVMe
OFFLOAD_TIERS: Tuple[Tuple[str, Optional[Dict[str, Any]]], ...] = (
    ("none", None),
    ("opt_cpu", {"param": None, "optimizer": "cpu", "chunked": False}),
    ("cpu", {"param": "cpu", "optimizer": "cpu", "chunked": False}),
    ("cpu_chunked", {"param": "cpu", "optimizer": "cpu", "chunked": True}),
    ("nvme_chunked", {"param": "cpu", "optimizer": "nvme", "chunked": True}),
    ("nvme", {"param": "nvme", "optimizer": "nvme", "chunked": False}),
)

DEFAULT_CHUNK_BYTES = 64 << 20
DEFAULT_WORKING_SET_BYTES = 1 << 30


@dataclass(frozen=True)
class FleetSpec:
    """What the hardware offers: chips, per-chip HBM, host RAM behind
    them, NVMe, and the link classes (bytes/s) the cost model prices
    wire traffic against (docs/PLANNER.md "Link classes")."""
    chips: int = 8
    hbm_bytes: int = 16 << 30
    host_bytes: Optional[int] = None
    nvme: bool = False
    ici_bytes_per_s: float = 9.0e10     # intra-slice interconnect
    dcn_bytes_per_s: float = 6.25e9     # inter-slice data-center network
    pcie_bytes_per_s: float = 1.6e10    # host <-> device
    nvme_bytes_per_s: float = 3.0e9     # NVMe streaming
    peak_flops: float = 1.97e14         # bf16 per chip
    dcn_axes: Tuple[str, ...] = ()      # mesh axes that cross DCN

    def link_speed(self, link: str) -> float:
        return {"ici": self.ici_bytes_per_s, "dcn": self.dcn_bytes_per_s,
                "pcie": self.pcie_bytes_per_s,
                "nvme": self.nvme_bytes_per_s}[link]


@dataclass
class ModelSpec:
    """What is being trained/served: a registry TransformerConfig plus
    the workload sequence length, with the analytic param count (and the
    expert-parallel-shardable fraction of it) precomputed."""
    name: str
    config: Any
    seq_len: int
    num_params: int = 0
    moe_param_fraction: float = 0.0

    @classmethod
    def from_name(cls, name: str, seq_len: Optional[int] = None,
                  **overrides) -> "ModelSpec":
        from deepspeed_tpu.models.registry import get_model_config
        from deepspeed_tpu.profiling import get_model_profile

        cfg = get_model_config(name, **overrides)
        s = int(seq_len or cfg.max_seq_len)
        prof = get_model_profile(cfg, batch_size=1, seq_len=s)
        return cls(name=name, config=cfg, seq_len=s,
                   num_params=int(prof["params"]),
                   moe_param_fraction=_moe_fraction(cfg, prof["params"]))

    def model_info(self) -> ModelInfo:
        return ModelInfo(num_params=self.num_params,
                         hidden_size=self.config.hidden_size,
                         num_layers=self.config.num_layers,
                         vocab_size=self.config.vocab_size)


def _moe_fraction(cfg, total_params: int) -> float:
    if not getattr(cfg, "num_experts", 0):
        return 0.0
    n_mats = 3 if getattr(cfg, "activation", "") == "swiglu" else 2
    ffn = getattr(cfg, "moe_intermediate_size", None) or cfg.intermediate_size
    freq = max(1, getattr(cfg, "moe_layer_freq", 1) or 1)
    moe_layers = -(-cfg.num_layers // freq)
    expert_p = moe_layers * cfg.num_experts * n_mats * cfg.hidden_size * ffn
    return min(1.0, expert_p / max(1, total_params))


@dataclass
class Candidate:
    """One point of the config space.  ``key()`` collapses the
    micro-batch and schedule sweep: ranking keeps the best variant per
    (mesh, stage, quant wire, offload tier) so the top-N list shows
    *distinct* placements, not one placement's batch ladder."""
    mesh: Dict[str, int]
    zero_stage: int
    micro_batch: int
    comm_quantization: Optional[Dict[str, Any]] = None
    step_schedule: Optional[Dict[str, Any]] = None
    offload: Optional[Dict[str, Any]] = None
    offload_tier: str = "none"
    disagg: Optional[Dict[str, int]] = None

    def axis(self, name: str) -> int:
        return int(self.mesh.get(name, 1) or 1)

    @property
    def dp_size(self) -> int:
        return self.axis("data") * self.axis("expert")

    def key(self) -> Tuple:
        return (tuple(sorted(self.mesh.items())), self.zero_stage,
                (self.comm_quantization or {}).get("grad_reduce"),
                self.offload_tier,
                tuple(sorted((self.disagg or {}).items())))

    def describe(self) -> str:
        bits = ["x".join(f"{k}{v}" for k, v in sorted(self.mesh.items())),
                f"zero{self.zero_stage}", f"mb{self.micro_batch}"]
        if self.comm_quantization:
            bits.append(f"q:{self.comm_quantization.get('grad_reduce')}")
        if self.offload_tier != "none":
            bits.append(f"off:{self.offload_tier}")
        if self.step_schedule:
            bits.append("sched")
        if self.disagg:
            bits.append(f"disagg:{self.disagg['prefill_replicas']}p"
                        f"{self.disagg['decode_replicas']}d")
        return " ".join(bits)


def schedule_for(mesh: Dict[str, int], zero_stage: int) -> Optional[Dict[str, Any]]:
    """The deterministic pinned-fusion block the overlap scheduler's
    decide() table would land on for this shape (overlap_scheduler.py):
    ZeRO-3 → prefetch + fused gather; ring sequence → interleave 2;
    replicated-grad DP → decomposed update + fused reduce-scatter."""
    d = mesh.get("data", 1) * mesh.get("expert", 1)
    if zero_stage >= 3 and d > 1:
        return {"gather_prefetch_depth": 2,
                "param_persistence_threshold": 100_000,
                "prefetch_bucket_size": 50_000_000,
                "fused_gather_matmul": True}
    if mesh.get("seq", 1) > 1:
        return {"ring_interleave": 2}
    if zero_stage <= 1 and d > 1:
        return {"weight_update": "decomposed", "fused_reduce_scatter": True}
    return None


def _quant_eligible(mesh: Dict[str, int], zero_stage: int) -> bool:
    # mirrors the engine's quantized-DP gate: dp > 1, pure data mesh,
    # stage <= 2 (engine.py warn-fallback conditions)
    return (zero_stage <= 2 and mesh.get("data", 1) > 1
            and set(mesh) <= {"data"})


def enumerate_candidates(model: ModelSpec, fleet: FleetSpec, *,
                         stages: Tuple[int, ...] = (0, 1, 2, 3),
                         max_micro_batch: int = 64,
                         enable_quant: bool = True,
                         enable_offload: bool = True,
                         enable_schedule: bool = True,
                         serving: bool = False,
                         mesh_filter=None) -> List[Candidate]:
    """The full candidate lattice BEFORE memory pruning.
    ``mesh_filter(mesh) -> bool`` restricts the mesh sweep — how a
    row-mirroring query pins its experiment's placement family (e.g.
    the longseq_ring row shards the sequence over EVERY chip)."""
    if serving:
        return _serving_candidates(model, fleet)
    out: List[Candidate] = []
    ring = getattr(model.config, "seq_impl", "") == "ring"
    for mesh in enumerate_meshes(fleet.chips, model.config):
        if mesh_filter is not None and not mesh_filter(mesh):
            continue
        sp = mesh.get("seq", 1)
        if sp > 1 and model.seq_len % sp:
            continue
        if ring and sp <= 1 and fleet.chips > 1:
            continue  # ring attention demands a sequence axis
        pure_data = set(mesh) <= {"data"}
        for stage in stages:
            if mesh.get("pipe", 1) > 1 and stage >= 2:
                continue  # pipeline composes with ZeRO-0/1 only
            quants: List[Optional[Dict[str, Any]]] = [None]
            if enable_quant and _quant_eligible(mesh, stage):
                quants.append({"enabled": True, "grad_reduce": "int8"})
            tiers = [OFFLOAD_TIERS[0]]
            if enable_offload and pure_data:
                for name, tier in OFFLOAD_TIERS[1:]:
                    if tier["param"] and stage != 3:
                        continue  # param offload is a ZeRO-3 feature
                    if tier["optimizer"] and stage < 1:
                        continue  # offloaded masters need sharded masters
                    if ("nvme" in (tier["param"], tier["optimizer"])
                            and not fleet.nvme):
                        continue
                    tiers.append((name, tier))
            mb = 1
            while mb <= max_micro_batch:
                for quant, (tier_name, tier) in itertools.product(
                        quants, tiers):
                    if quant and tier:
                        continue  # engine gate: quantized DP is
                        # incompatible with the offloaded optimizer store
                    scheds: List[Optional[Dict[str, Any]]] = [None]
                    if enable_schedule:
                        s = schedule_for(mesh, stage)
                        if s:
                            scheds.append(s)
                    for sched in scheds:
                        out.append(Candidate(
                            mesh=dict(mesh), zero_stage=stage,
                            micro_batch=mb,
                            comm_quantization=dict(quant) if quant else None,
                            step_schedule=dict(sched) if sched else None,
                            offload=dict(tier) if tier else None,
                            offload_tier=tier_name))
                mb *= 2
    return out


def _serving_candidates(model: ModelSpec, fleet: FleetSpec) -> List[Candidate]:
    """Disaggregated serving splits: partition the fleet's replicas into
    prefill/decode tiers (serving/disagg.py semantics; one chip per
    replica here — the per-replica mesh sweep stays a training concern)."""
    out = []
    n = fleet.chips
    for p in range(1, n):
        out.append(Candidate(
            mesh={"data": 1}, zero_stage=0, micro_batch=1,
            disagg={"prefill_replicas": p, "decode_replicas": n - p}))
    return out


def prune_candidates(model: ModelSpec, fleet: FleetSpec,
                     candidates: List[Candidate], *,
                     calibration: float = 1.0
                     ) -> Tuple[List[Tuple[Candidate, Dict[str, Any]]],
                                List[Dict[str, Any]]]:
    """predict_fit gate over the lattice → (survivors with their fit
    record, pruned losers with machine-readable reasons).  Host-RAM and
    O(chunk) working-set pricing ride along via predict_fit's offload
    re-homing (ZeRO-Offload, arXiv:2101.06840)."""
    mi = model.model_info()
    fit: List[Tuple[Candidate, Dict[str, Any]]] = []
    pruned: List[Dict[str, Any]] = []
    for cand in candidates:
        if cand.disagg:
            # serving: weights + one sequence of KV per replica chip —
            # no grads/optimizer classes exist at inference time
            c = model.config
            kv = (c.num_layers * 2 * model.seq_len
                  * c.kv_heads * c.dim_per_head * 2)
            need = int((model.num_params * 2 + kv) * calibration)
            if need <= fleet.hbm_bytes:
                fit.append((cand, {"predicted_peak_bytes": need,
                                   "predicted_fit": True,
                                   "dominant_class": "params",
                                   "breakdown": {"params": model.num_params * 2,
                                                 "kv_cache": kv},
                                   "shortfall_bytes": 0}))
            else:
                pruned.append({"candidate": cand.describe(),
                               "reason": (f"device_oom: params class, "
                                          f"{need - fleet.hbm_bytes} bytes "
                                          f"over {fleet.hbm_bytes} budget"),
                               "dominant_class": "params",
                               "shortfall_bytes": need - fleet.hbm_bytes,
                               "predicted_peak_bytes": need})
            continue
        off = cand.offload or {}
        res = predict_fit(
            mi, cand.zero_stage, max(1, cand.dp_size), cand.micro_batch,
            model.seq_len, hbm_bytes=fleet.hbm_bytes,
            calibration=calibration,
            tp_size=cand.axis("tensor"), pp_size=cand.axis("pipe"),
            sp_size=cand.axis("seq"),
            offload_param=off.get("param"),
            offload_optimizer=off.get("optimizer"),
            host_bytes=fleet.host_bytes,
            chunk_bytes=DEFAULT_CHUNK_BYTES if off.get("chunked") else None,
            comm_quant=bool(cand.comm_quantization))
        if res["predicted_fit"]:
            fit.append((cand, res))
        else:
            budget = (fleet.hbm_bytes
                      if res["predicted_peak_bytes"] > fleet.hbm_bytes
                      else fleet.host_bytes)
            where = ("device" if res["predicted_peak_bytes"]
                     > fleet.hbm_bytes else "host")
            pruned.append({
                "candidate": cand.describe(),
                "reason": (f"{where}_oom: {res['dominant_class']} class, "
                           f"{res['shortfall_bytes']} bytes over "
                           f"{budget} budget"),
                "dominant_class": res["dominant_class"],
                "shortfall_bytes": res["shortfall_bytes"],
                "predicted_peak_bytes": res["predicted_peak_bytes"],
            })
    return fit, pruned
