"""Domino — tensor-parallel communication hiding via batch splitting.

TPU-native analog of ``runtime/domino/transformer.py``
(``DominoTransformerLayer``) and ``domino/async_linear.py``.  The reference
splits each batch in two and hand-schedules async NCCL allreduces of chunk
i's TP output against chunk i+1's compute.  On TPU the same overlap comes
from giving XLA *independent* per-chunk computation chains: the chunks'
row-parallel psums and the other chunk's matmuls have no data dependence,
so XLA's latency-hiding scheduler interleaves them on ICI — the compiled
equivalent of Domino's hand-rolled double-buffering.

``domino_transformer_layer`` is numerically identical to the plain layer
(same params, same math, batch-chunked) — verified by test, and the
compile-level independence that overlap requires is pinned by
``test_domino_chunk_collectives_stay_independent``: the per-chunk psums
survive compilation as separate chunk-shaped all-reduce ops on distinct
channels (XLA's combiner does not merge them into one serializing
collective).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as tf
from deepspeed_tpu.models.transformer import TransformerConfig


def split_batch(x, n_chunks: int):
    """Split on the batch dim (ref DominoTransformerLayer input split)."""
    b = x.shape[0]
    if b % n_chunks != 0:
        raise ValueError(f"batch {b} not divisible into {n_chunks} domino chunks")
    return jnp.split(x, n_chunks, axis=0)


def domino_transformer_layer(x, layer_params, positions, cfg: TransformerConfig,
                             n_chunks: int = 2):
    """One transformer block computed in ``n_chunks`` independent batch
    chunks (ref DominoTransformerLayer forward: intra-layer μbatch overlap).

    Returns the same (x, aux) as ``transformer_layer``.
    """
    xs = split_batch(x, n_chunks)
    ps = split_batch(positions, n_chunks)
    outs, auxes = [], []
    for xc, pc in zip(xs, ps):
        # Each chunk is an independent chain; XLA overlaps chunk i's TP
        # collectives with chunk j's matmuls (i≠j).
        yc, aux = tf.transformer_layer(xc, layer_params, pc, cfg)
        outs.append(yc)
        auxes.append(aux)
    # Per-chunk aux losses are batch means — average, don't sum, so the
    # MoE auxiliary objective matches the unchunked layer.
    return jnp.concatenate(outs, axis=0), sum(auxes) / len(auxes)


def domino_forward(params, input_ids, cfg: TransformerConfig, n_chunks: int = 2):
    """Full-model forward with domino batch splitting at every layer.

    The chunks run the whole layer stack independently and join at the
    logits — the generalisation of Domino's per-layer split that gives the
    scheduler the longest independent chains (TP-only; the engine selects
    this path when ``mesh.tensor > 1`` and domino is enabled).
    """
    chunks = split_batch(input_ids, n_chunks)
    outs = [tf.forward(params, c, cfg) for c in chunks]
    if isinstance(outs[0], tuple):
        return (jnp.concatenate([o[0] for o in outs], axis=0),
                sum(o[1] for o in outs) / n_chunks)
    return jnp.concatenate(outs, axis=0)
