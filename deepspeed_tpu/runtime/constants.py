"""Config key names and defaults.

Mirrors the reference ``deepspeed/runtime/constants.py`` key surface so that
DeepSpeed JSON configs can be consumed unchanged by the TPU build.
"""

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
SCHEDULER = "scheduler"
SCHEDULER_TYPE = "type"
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
LAMB_OPTIMIZER = "lamb"
LION_OPTIMIZER = "lion"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
MUON_OPTIMIZER = "muon"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, LAMB_OPTIMIZER, LION_OPTIMIZER,
    ADAGRAD_OPTIMIZER, SGD_OPTIMIZER, MUON_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"
BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Misc engine knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
MEMORY_BREAKDOWN = "memory_breakdown"
DUMP_STATE = "dump_state"
ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
DISABLE_ALLGATHER = "disable_allgather"
COMMUNICATION_DATA_TYPE = "communication_data_type"
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE = "seq_parallel_communication_data_type"
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_ATTENTION = "sparse_attention"

#############################################
# Activation checkpointing (→ remat on TPU)
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"

#############################################
# Monitoring
#############################################
TENSORBOARD = "tensorboard"
WANDB = "wandb"
CSV_MONITOR = "csv_monitor"
COMET = "comet"
FLOPS_PROFILER = "flops_profiler"
PROFILER = "profiler"
COMMS_LOGGER = "comms_logger"
TELEMETRY = "telemetry"  # unified telemetry layer (telemetry/)
# sub-blocks of the telemetry config (runtime/config.py TelemetryConfig)
TELEMETRY_TRACING = "tracing"  # software spans -> Chrome trace JSON
TELEMETRY_FLIGHT = "flight"    # span ring + hang watchdog + crash bundles

#############################################
# Parallel topology (TPU mesh extension + reference keys)
#############################################
MESH = "mesh"  # TPU extension: explicit axis sizes
TENSOR_PARALLEL = "tensor_parallel"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
PIPELINE = "pipeline"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
COMPRESSION_TRAINING = "compression_training"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal_checkpoint"
USE_DATA_BEFORE_EXPERT_PARALLEL = "use_data_before_expert_parallel_"
DATALOADER_DROP_LAST = "dataloader_drop_last"

#############################################
# Defaults
#############################################
STEPS_PER_PRINT_DEFAULT = 10
GRADIENT_ACCUMULATION_STEPS_DEFAULT = 1
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
GRADIENT_CLIPPING_DEFAULT = 0.0
