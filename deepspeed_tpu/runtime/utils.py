"""Runtime utility surface (ref ``deepspeed/runtime/utils.py``).

The reference module is 1,471 lines because eager torch needs hand-rolled
bucketing/overflow/clip machinery; under XLA those live inside the
compiled step (engine.py `_global_norm`/`_all_finite`/clip).  What remains
user-facing — and what reference scripts import — is kept here with the
same names:

* :func:`see_memory_usage` (ref :815) — device HBM + host RSS snapshot.
* :func:`get_global_norm_of_tensors` / :func:`get_global_norm`
  (ref :878) — eager global L2 norm over a pytree/list.
* :func:`clip_grad_norm_` (ref :359) — eager clip-by-global-norm
  (returns the pre-clip norm like torch's).
"""

from __future__ import annotations

from typing import Any, Iterable

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def see_memory_usage(message: str, force: bool = False) -> dict:
    """Log device + host memory (ref see_memory_usage, runtime/utils.py:815:
    MA/Max_MA/CA cuda stats + virtual-memory percent).  Returns the stats
    dict so tests/tools can consume it without parsing logs."""
    if not force and not logger.isEnabledFor(20):  # INFO
        return {}
    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    try:
        stats = acc.memory_stats() or {}
    except Exception:
        stats = {}
    used = stats.get("bytes_in_use", 0)
    peak = stats.get("peak_bytes_in_use", stats.get("largest_alloc_size", 0))
    limit = stats.get("bytes_limit", 0)
    try:
        import resource

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # non-POSIX
        rss = 0
    ga = 1 << 30
    logger.info(
        f"{message} | device MA {used / ga:.2f} GB "
        f"Max_MA {peak / ga:.2f} GB "
        f"limit {limit / ga:.2f} GB | host peak RSS {rss / ga:.2f} GB")
    return {"bytes_in_use": used, "peak_bytes_in_use": peak,
            "bytes_limit": limit, "host_peak_rss": rss}


def _leaves(tensors: Any) -> Iterable[jnp.ndarray]:
    return jax.tree_util.tree_leaves(tensors)


def get_global_norm_of_tensors(tensors: Any, norm_type: float = 2.0):
    """Global norm over a pytree/list (ref get_global_norm_of_tensors,
    runtime/utils.py:878).  Jit-safe."""
    leaves = _leaves(tensors)
    if not leaves:
        return jnp.float32(0.0)
    if norm_type == float("inf"):
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(x.astype(jnp.float32))) for x in leaves]))
    acc = sum(jnp.sum(jnp.abs(x.astype(jnp.float32)) ** norm_type)
              for x in leaves)
    return acc ** (1.0 / norm_type)


def get_global_norm(norm_list: Iterable[float]) -> float:
    """sqrt of sum of squares of per-group norms (ref get_global_norm)."""
    import math

    return math.sqrt(sum(float(n) ** 2 for n in norm_list))


def clip_grad_norm_(parameters: Any, max_norm: float,
                    norm_type: float = 2.0):
    """Clip a gradient pytree by global norm (ref clip_grad_norm_,
    runtime/utils.py:359).  Returns ``(clipped_tree, pre_clip_norm)`` —
    functional arrays cannot be mutated in place, so unlike torch the
    clipped tree is returned rather than written through."""
    norm = get_global_norm_of_tensors(parameters, norm_type)
    coef = jnp.minimum(max_norm / (norm + 1e-6), 1.0)
    clipped = jax.tree.map(lambda x: (x * coef).astype(x.dtype), parameters)
    return clipped, norm
