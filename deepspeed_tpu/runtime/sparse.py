"""Sparse gradients: COO tensors + the sparse embedding-gradient path.

TPU-native re-design of the reference's sparse gradient support
(``deepspeed/runtime/sparse_tensor.py`` ``SparseTensor`` and the sparse
bucket of ``runtime/engine.py:145 split_half_float_double_sparse`` /
``sparse_allreduce_bucket``): torch produces sparse embedding grads that
DeepSpeed must allreduce as (indices, values) pairs to avoid moving the
dense [vocab, hidden] gradient over the wire.

On TPU the same capability is expressed at the AD boundary: the token
embedding lookup is hoisted OUT of the differentiated function, so the
cotangent arrives as d(embeddings) [B, S, H] — naturally batch-sharded —
and the data-parallel reduction becomes an ``all_gather`` of
(token_ids, d_embeddings) over the dp axes (O(tokens·H) bytes) followed by
a local scatter-add, instead of XLA's dense scatter + psum of the whole
[V, H] table (O(V·H) bytes).  For B·S ≪ V this is the same bandwidth win
the reference's sparse allreduce buys.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import BATCH_AXES
from deepspeed_tpu.utils.jax_compat import shard_map


@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """COO sparse tensor over the leading dim of a dense [D0, ...] array
    (API parity with ref sparse_tensor.py: to_dense / sparse_size / add).
    ``indices`` [N] int32 rows, ``values`` [N, ...] rows; duplicates are
    legal and mean "sum" (scatter-add semantics)."""

    def __init__(self, indices, values, dense_shape: Tuple[int, ...]):
        self.indices = indices
        self.values = values
        self.dense_shape = tuple(dense_shape)

    @staticmethod
    def from_dense_rows(dense, indices):
        """Rows ``indices`` of ``dense`` as a SparseTensor."""
        return SparseTensor(indices, jnp.take(dense, indices, axis=0),
                            dense.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def add_into(self, dense):
        """Scatter-add into an existing dense buffer (grad accumulation)."""
        return dense.at[self.indices].add(self.values.astype(dense.dtype))

    def sparse_size(self) -> int:
        return int(self.indices.shape[0]) * int(
            jnp.prod(jnp.asarray(self.values.shape[1:]))) \
            + int(self.indices.shape[0])

    def dense_size(self) -> int:
        n = 1
        for d in self.dense_shape:
            n *= d
        return n

    def add(self, other: "SparseTensor") -> "SparseTensor":
        assert self.dense_shape == other.dense_shape
        return SparseTensor(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]), self.dense_shape)

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.indices, self.values), self.dense_shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    def __repr__(self):
        return (f"SparseTensor(nnz_rows={self.indices.shape[0]}, "
                f"dense_shape={self.dense_shape})")


def dp_allgather_sparse(st: SparseTensor, topo) -> SparseTensor:
    """Gather a batch-sharded SparseTensor across the dp axes so every
    shard holds all (index, value) rows — the sparse analog of the dense
    grad psum (ref sparse_allreduce_bucket).  Call INSIDE the jitted step;
    a one-shot shard_map scopes the collective to the dp axes."""
    dp = 1
    for ax in BATCH_AXES:
        dp *= topo.axis_size(ax)
    if dp == 1:
        return st

    axes = tuple(ax for ax in BATCH_AXES if topo.axis_size(ax) > 1)

    def gather(idx, vals):
        for ax in axes:
            idx = lax.all_gather(idx, ax, tiled=True)
            vals = lax.all_gather(vals, ax, tiled=True)
        return idx, vals

    idx, vals = shard_map(
        gather, mesh=topo.mesh,
        in_specs=(P(BATCH_AXES), P(BATCH_AXES)),
        out_specs=(P(), P()),
        check_vma=False)(st.indices, st.values)
    return SparseTensor(idx, vals, st.dense_shape)


def sparse_embedding_grad(d_embeds, input_ids, dense_shape, topo=None):
    """(d_embeddings [B,S,H], token ids [B,S]) → SparseTensor gradient for
    the [V,H] table, gathered across dp when a topology is given."""
    n = input_ids.size
    st = SparseTensor(input_ids.reshape(n).astype(jnp.int32),
                      d_embeds.reshape(n, d_embeds.shape[-1]), dense_shape)
    if topo is not None:
        st = dp_allgather_sparse(st, topo)
    return st
