"""SuperOffload — pipelined host optimizer with rollback.

Analog of ``deepspeed/runtime/superoffload/superoffload_stage3.py`` (646
LoC): on superchip-class hosts (fast host↔device links; on TPU VMs the
PCIe/DMA path plays this role), the full fp32 optimizer state lives on the
host and the Adam step runs there, *bucketed and pipelined* so host compute
for bucket i overlaps the device→host transfer of bucket i+1.  A one-step
rollback window supports overflow recovery: if the engine detects a
non-finite global grad norm after the fact, ``rollback()`` restores the
previous master params and moments (the reference's rollback optimizer).

The device keeps only the working-precision params; ``step`` returns the
refreshed device tree (the host→device push of updated masters).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class SuperOffloadOptimizer:
    """Host-resident bucketed Adam with one-step rollback.

    ``bucket_size``: leaves are grouped into roughly equal-byte buckets;
    each bucket's (transfer → host adam) runs on a thread pool so transfers
    and host math overlap (ref CPUAdam batching in superoffload_stage3).
    """

    def __init__(self, params: Any, lr: float = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 bucket_bytes: int = 64 << 20, max_workers: int = 4,
                 rollback_window: int = 1, adamw: bool = False):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw = adamw  # decoupled (AdamW) vs coupled (Adam) decay
        self.step_count = 0
        self.rollback_window = rollback_window
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._dtypes = [l.dtype for l in leaves]
        # np.array (copy) — device_get may return read-only buffers
        self._master = [np.array(jax.device_get(l), np.float32) for l in leaves]
        self._m = [np.zeros_like(x) for x in self._master]
        self._v = [np.zeros_like(x) for x in self._master]
        self._prev: Optional[Dict[str, Any]] = None
        # bucket planning by bytes
        self._buckets: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        for i, x in enumerate(self._master):
            cur.append(i)
            cur_bytes += x.nbytes
            if cur_bytes >= bucket_bytes:
                self._buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            self._buckets.append(cur)
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=max_workers)

    # ------------------------------------------------------------------
    def _snapshot(self) -> None:
        if self.rollback_window > 0:
            self._prev = {"master": [x.copy() for x in self._master],
                          "m": [x.copy() for x in self._m],
                          "v": [x.copy() for x in self._v],
                          "step": self.step_count}

    def rollback(self) -> None:
        """Restore the pre-step state (ref rollback optimizer for overflow
        recovery)."""
        if self._prev is None:
            raise RuntimeError("no snapshot available to roll back to")
        self._master = self._prev["master"]
        self._m = self._prev["m"]
        self._v = self._prev["v"]
        self.step_count = self._prev["step"]
        self._prev = None

    def _bucket_step(self, bucket: List[int], grads: List[np.ndarray],
                     step: int, grad_scale: float = 1.0) -> None:
        from deepspeed_tpu.ops.cpu_optimizer import _lib, _ptr, adam_step_numpy

        lib = _lib()
        b1, b2 = self.beta1, self.beta2
        for j, i in enumerate(bucket):
            g = np.ascontiguousarray(grads[j], np.float32)
            if grad_scale != 1.0:
                g = g * grad_scale  # loss-scale/gas normalisation + clip coef
            p, m, v = self._master[i], self._m[i], self._v[i]
            if lib is not None:
                # vectorized fused step (csrc/cpu_optimizer); the last arg
                # selects decoupled (AdamW) vs coupled (Adam) weight decay
                lib.ds_adam_step(_ptr(p), _ptr(g), _ptr(m), _ptr(v), p.size,
                                 self.lr, b1, b2, self.eps,
                                 self.weight_decay, step,
                                 1 if self.adamw else 0)
            else:
                adam_step_numpy(p, g, m, v, self.lr, b1, b2, self.eps,
                                self.weight_decay, step, adamw=self.adamw)

    def step(self, params: Any, grads: Any, grad_scale: float = 1.0) -> Any:
        """grads (device tree) → updated device params.  Transfers and host
        Adam are pipelined per bucket.  ``grad_scale`` multiplies gradients
        on the host (loss-scale/grad-accum normalisation + clip coef,
        computed on device by the engine)."""
        self._snapshot()
        self.step_count += 1
        step = self.step_count
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        futures = []
        for bucket in self._buckets:
            # device→host fetch for this bucket (async under the hood), then
            # hand host math to the pool while the next bucket transfers
            host_g = [np.asarray(jax.device_get(flat_g[i]), np.float32)
                      for i in bucket]
            futures.append(self._pool.submit(self._bucket_step, bucket,
                                             host_g, step, grad_scale))
        for f in futures:
            f.result()
        return self.push_params(params)

    def push_params(self, params_like: Any) -> Any:
        """Host masters → device tree matching ``params_like``'s dtypes and
        shardings (used by step() and by engine rollback)."""
        flat_p = jax.tree_util.tree_flatten(params_like)[0]
        new_leaves = [jnp.asarray(x, dt) for x, dt in
                      zip(self._master, self._dtypes)]
        new_leaves = [jax.device_put(x, l.sharding) if hasattr(l, "sharding")
                      else x for x, l in zip(new_leaves, flat_p)]
        return jax.tree_util.tree_unflatten(self._treedef, new_leaves)

    def reset_masters(self, params: Any,
                      reset_moments: bool = True) -> None:
        """Re-seed the host fp32 masters from a (freshly loaded) device
        param tree.  A weights-only checkpoint resume must call this —
        otherwise the next step's ``push_params`` silently reverts the
        load to the stale masters."""
        leaves = jax.tree_util.tree_flatten(params)[0]
        self._master = [np.array(jax.device_get(l), np.float32)
                        for l in leaves]
        if reset_moments:
            self._m = [np.zeros_like(x) for x in self._master]
            self._v = [np.zeros_like(x) for x in self._master]
            self.step_count = 0
        self._prev = None

    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {"step": self.step_count,
                "master": self._master, "m": self._m, "v": self._v}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.step_count = int(state["step"])
        self._master = [np.array(x, np.float32) for x in state["master"]]
        self._m = [np.array(x, np.float32) for x in state["m"]]
        self._v = [np.array(x, np.float32) for x in state["v"]]
