"""TiledLinear — split big linears into tiles (ref runtime/zero/tiling.py).

The reference's ``TiledLinear`` decomposes one huge ``nn.Linear`` into an
``in_splits × out_splits`` grid of small Linears so ZeRO-3 can
gather/release one tile's weights at a time instead of the whole matrix.
The TPU realisation keeps the same capability with compiled control flow:
the weight lives as a stacked ``[in_splits * out_splits, in_tile,
out_tile]`` array scanned tile-by-tile under ``jax.checkpoint``, so at most
one tile's activation product is live during the backward — the
sequence-tiled analog in ``sequence/alst.py:tiled_mlp`` tiles the TOKEN
dim; this module tiles the FEATURE dims.

Functional API (no module system): ``init`` → params, ``apply`` → output.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


class TiledLinear:
    """``y = x @ W + b`` computed as an in_splits×out_splits tile grid.

    ``in_features`` must divide by ``in_splits`` and ``out_features`` by
    ``out_splits``.  ``remat`` wraps each tile's product in
    ``jax.checkpoint`` so the backward recomputes per-tile (O(tile)
    activation residency, the point of the reference module).
    """

    def __init__(self, in_features: int, out_features: int,
                 in_splits: int = 1, out_splits: int = 1, bias: bool = True,
                 remat: bool = True, dtype=jnp.float32):
        if in_features % in_splits or out_features % out_splits:
            raise ValueError(
                f"splits must divide features: {in_features}/{in_splits}, "
                f"{out_features}/{out_splits}")
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.in_tile = in_features // in_splits
        self.out_tile = out_features // out_splits
        self.use_bias = bias
        self.remat = remat
        self.dtype = dtype

    def init(self, key, scale: Optional[float] = None):
        """Stacked tile weights [in_splits*out_splits, in_tile, out_tile]
        (+ bias [out_features]); tile (i, o) is row ``i * out_splits + o``.
        """
        scale = scale if scale is not None else self.in_features ** -0.5
        wkey, _ = jax.random.split(key)
        w = jax.random.normal(
            wkey, (self.in_splits * self.out_splits, self.in_tile,
                   self.out_tile), self.dtype) * scale
        params = {"w_tiles": w}
        if self.use_bias:
            params["b"] = jnp.zeros((self.out_features,), self.dtype)
        return params

    def from_dense(self, w, b=None):
        """Pack a dense [in, out] weight into the tiled layout."""
        w = jnp.asarray(w, self.dtype)
        if w.shape != (self.in_features, self.out_features):
            raise ValueError(f"weight shape {w.shape} != "
                             f"({self.in_features}, {self.out_features})")
        tiles = w.reshape(self.in_splits, self.in_tile,
                          self.out_splits, self.out_tile)
        tiles = tiles.transpose(0, 2, 1, 3).reshape(
            self.in_splits * self.out_splits, self.in_tile, self.out_tile)
        params = {"w_tiles": tiles}
        if self.use_bias:
            params["b"] = (jnp.zeros((self.out_features,), self.dtype)
                           if b is None else jnp.asarray(b, self.dtype))
        return params

    def to_dense(self, params):
        """Tiled layout → dense [in, out] weight (checkpoint export)."""
        t = params["w_tiles"].reshape(self.in_splits, self.out_splits,
                                      self.in_tile, self.out_tile)
        return t.transpose(0, 2, 1, 3).reshape(self.in_features,
                                               self.out_features)

    def apply(self, params, x):
        """x [..., in_features] → [..., out_features], scanning the tile
        grid; each (in, out) product is rematerialized in the backward."""
        lead = x.shape[:-1]
        xs = x.reshape(-1, self.in_splits, self.in_tile)  # [N, IS, it]

        def tile_product(w_row, x_in):
            return x_in @ w_row

        if self.remat:
            tile_product = jax.checkpoint(tile_product)

        def out_block(o):
            def body(acc, i):
                w_row = params["w_tiles"][i * self.out_splits + o]
                return acc + tile_product(w_row, xs[:, i, :]), None

            acc0 = jnp.zeros((xs.shape[0], self.out_tile), x.dtype)
            acc, _ = lax.scan(body, acc0, jnp.arange(self.in_splits))
            return acc

        # out blocks are independent → vmap'd scan over the grid
        blocks = [out_block(o) for o in range(self.out_splits)]
        y = jnp.concatenate(blocks, axis=-1)
        if self.use_bias:
            y = y + params["b"].astype(x.dtype)
        return y.reshape(*lead, self.out_features)

    def __call__(self, params, x):
        return self.apply(params, x)
