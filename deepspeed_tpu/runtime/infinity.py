"""ZeRO-Infinity parameter streaming: train models whose parameters exceed
HBM by keeping the stacked layer weights in host memory (optionally backed
by NVMe via the AIO engine) and streaming one layer at a time through the
compiled step.

TPU-native re-design of the reference's ``AsyncPartitionedParameterSwapper``
(``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:37``) and the
ZeRO-3 gather/release hooks (``runtime/zero/parameter_offload.py:246``): the
reference swaps each parameter in around its module's forward with explicit
CUDA streams; here the swap schedule is *compiled* — every fetch is a
``dynamic_slice`` of a ``pinned_host`` buffer followed by an H2D copy that
XLA's latency-hiding scheduler overlaps with the previous layer's compute
(raise ``scan_unroll`` to widen the overlap window).

The hard part is the backward: naive AD would accumulate the parameter
cotangent as a full-size device buffer, defeating the offload (measured:
full param bytes reappear as XLA temp).  :func:`streamed_scan` therefore
carries a custom VJP whose backward walks the layers in reverse,
re-linearizing one layer at a time (``jax.vjp``) from an activation stash
and writing each layer's gradient straight back into a host-resident
accumulator — device residency stays O(one layer) in both directions.

The same slice-wise pattern covers the other full-size trees:
:func:`streamed_tree_add` (gradient accumulation across micro-batches) and
:func:`streamed_update` (the optimizer step, ref
``partitioned_optimizer_swapper.py:27``) loop over the layer axis with
host-resident operands.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.jax_compat import memory_spaces

HOST, DEVICE = memory_spaces()

_MEMORY_KINDS: dict = {}


def memory_kinds_supported() -> bool:
    """Whether this backend executes host-space placement. Real TPUs: yes.
    The CPU test mesh: no — it *compiles* small probe programs (XLA folds
    the placement annotations away) but aborts at runtime when an
    `annotate_device_placement` custom call survives into a real program,
    so behavioral probing is unreliable and the decision is by platform.
    When False every placement below is an identity and the streaming code
    paths run against unified memory (numerics still fully testable)."""
    plat = jax.devices()[0].platform
    if plat not in _MEMORY_KINDS:
        if plat not in ("tpu", "axon"):
            _MEMORY_KINDS[plat] = False
        else:
            try:
                # Probe the exact patterns streaming uses: a host-space
                # DUS accumulation in a scan carry (park_slice) and a
                # host-arg slice read (fetch_slice).  BOTH DUS operands
                # must be host-placed — libtpu's host offloader rejects a
                # device-resident update operand, and a probe written that
                # way reads as "unsupported" on runtimes where the real
                # pattern is fine (r04: that false negative silently
                # degraded every Infinity placement to device and OOM'd
                # the 6.7B streaming ladder entry).
                def probe(w):
                    z = jax.device_put(jnp.zeros(w.shape, w.dtype), HOST)

                    def body(c, i):
                        u = jax.device_put(
                            lax.dynamic_index_in_dim(w, i, keepdims=False)
                            * 2.0, HOST)
                        return lax.dynamic_update_index_in_dim(
                            c, u, i, axis=0), None

                    out, _ = lax.scan(body, z, jnp.arange(w.shape[0]))
                    return out

                jax.jit(probe)(jnp.ones((2, 256)))[0].block_until_ready()
                _MEMORY_KINDS[plat] = True
            except Exception:
                _MEMORY_KINDS[plat] = False
    return _MEMORY_KINDS[plat]


def _put(x, space):
    return jax.device_put(x, space) if memory_kinds_supported() else x


def split_layers(tree):
    """Split an engine param-style dict into (layers, resident) partitions."""
    return tree["layers"], {k: v for k, v in tree.items() if k != "layers"}


def to_host(tree):
    """Place a pytree in host memory (inside or outside jit)."""
    return jax.tree.map(lambda x: _put(x, HOST), tree)


def to_device(tree):
    return jax.tree.map(lambda x: _put(x, DEVICE), tree)


def fetch_slice(stacked_host, i):
    """Layer ``i`` of a host-resident stacked tree → device."""
    return jax.tree.map(
        lambda p: _put(lax.dynamic_index_in_dim(p, i, keepdims=False),
                       DEVICE),
        stacked_host)


def park_slice(acc_host, sl, i):
    """Write a device slice into row ``i`` of a host-resident stacked tree
    (dynamic-update-slice on the host buffer — the D2H path).  Both DUS
    operands are normalised to host space (no-ops when already there)."""
    return jax.tree.map(
        lambda a, s: lax.dynamic_update_index_in_dim(
            _put(a, HOST), _put(s.astype(a.dtype), HOST), i, axis=0),
        acc_host, sl)


def streamed_scan(step_fn: Callable, stacked_host, h0, extras=()):
    """``h, aux = step_fn(layer_params, h, i)`` scanned over the leading
    layer axis of ``stacked_host`` (host-resident), with O(1-layer) device
    parameter residency in forward AND backward.

    Returns ``(h_final, aux_sum, grad_fn_residual-free loss path)`` —
    concretely ``(h, aux)`` with a custom VJP: the backward re-fetches each
    layer, re-linearizes it from the stashed layer *inputs* (activation
    checkpointing at layer granularity), and parks each ``d(layer_params)``
    into a host accumulator slice, so the full parameter gradient never
    exists in device memory.
    """
    steps = jax.tree.leaves(stacked_host)[0].shape[0]

    @jax.custom_vjp
    def run(stacked_host, h0, extras):
        def body(carry, i):
            h, aux = carry
            lp = fetch_slice(stacked_host, i)
            h, a = step_fn(lp, h, extras, i)
            return (h, aux + a.astype(jnp.float32)), None

        (h, aux), _ = lax.scan(body, (h0, jnp.zeros((), jnp.float32)),
                               jnp.arange(steps))
        return h, aux

    def run_fwd(stacked_host, h0, extras):
        def body(carry, i):
            h, aux = carry
            lp = fetch_slice(stacked_host, i)
            h2, a = step_fn(lp, h, extras, i)
            return (h2, aux + a.astype(jnp.float32)), h

        (h, aux), h_stash = lax.scan(
            body, (h0, jnp.zeros((), jnp.float32)), jnp.arange(steps))
        return (h, aux), (stacked_host, h_stash, extras)

    def run_bwd(res, cts):
        stacked_host, h_stash, extras = res
        dh_out, daux = cts
        gacc = jax.tree.map(
            lambda p: _put(jnp.zeros(p.shape, jnp.float32), HOST),
            stacked_host)

        def body(carry, i):
            dh, gacc = carry
            lp = fetch_slice(stacked_host, i)
            h_in = jax.tree.map(lambda s: s[i], h_stash)

            def apply(lp_, h_):
                return step_fn(lp_, h_, extras, i)

            _, pull = jax.vjp(apply, lp, h_in)
            dlp, dh_in = pull((dh, daux.astype(jnp.float32)))
            gacc = park_slice(gacc, dlp, i)
            return (dh_in, gacc), None

        (dh0, gacc), _ = lax.scan(body, (dh_out, gacc),
                                  jnp.arange(steps - 1, -1, -1))
        # accumulation runs in fp32; the cotangent handed back to JAX must
        # match the primal dtype (custom_vjp checks avals), so cast at the
        # boundary for non-fp32 parameter trees
        gacc = jax.tree.map(
            lambda g, p: g if g.dtype == p.dtype else _put(
                g.astype(p.dtype), HOST),
            gacc, stacked_host)
        return gacc, dh0, None

    run.defvjp(run_fwd, run_bwd)
    h, aux = run(stacked_host, h0, extras)
    return h, aux


def streamed_tree_add(a_host, b_host):
    """``a + b`` over stacked host trees, one layer slice at a time."""
    steps = jax.tree.leaves(a_host)[0].shape[0]

    def body(acc, i):
        s = jax.tree.map(jnp.add, fetch_slice(a_host, i),
                         fetch_slice(b_host, i))
        return park_slice(acc, s, i), None

    zero = jax.tree.map(
        lambda p: _put(jnp.zeros(p.shape, p.dtype), HOST), a_host)
    acc, _ = lax.scan(body, zero, jnp.arange(steps))
    return acc


def streamed_sq_norm(tree_host):
    """Global squared L2 norm of a stacked host tree, slice-wise."""
    steps = jax.tree.leaves(tree_host)[0].shape[0]

    def body(acc, i):
        sl = fetch_slice(tree_host, i)
        s = sum(jnp.sum(x.astype(jnp.float32) ** 2)
                for x in jax.tree.leaves(sl))
        return acc + s, None

    acc, _ = lax.scan(body, jnp.zeros((), jnp.float32), jnp.arange(steps))
    return acc


def streamed_update(update_fn: Callable, grads_host, state_host, params_host,
                    lr, scale=None, gate=None):
    """Optimizer step over host-resident stacked trees, one layer at a time
    (ref PartitionedOptimizerSwapper, swap_tensor/partitioned_optimizer_
    swapper.py:27 — swap in a partition, step it, swap out).

    ``update_fn(grads, state, params, lr) -> (params, state)`` is applied
    to per-layer slices.  State leaves whose leading dim matches the layer
    count are sliced; scalars (e.g. adam's ``count``) pass through and are
    taken from the **last** slice call so they advance exactly once.
    ``scale`` optionally multiplies gradients slice-wise (loss-scale /
    grad-accum normalization + clipping coefficient, fused into the same
    pass so no full-size intermediate ever materialises).
    """
    steps = jax.tree.leaves(params_host)[0].shape[0]

    def is_stacked(x):
        return hasattr(x, "shape") and x.ndim >= 1 and x.shape[0] == steps

    def state_slice(state, i):
        return jax.tree.map(
            lambda x: _put(lax.dynamic_index_in_dim(x, i, keepdims=False),
                           DEVICE)
            if is_stacked(x) else x, state)

    def body(carry, i):
        p_acc, s_acc = carry
        g = fetch_slice(grads_host, i)
        if scale is not None:
            g = jax.tree.map(lambda x: x * scale, g)
        p = fetch_slice(params_host, i)
        s = state_slice(state_host, i)
        new_p, new_s = update_fn(g, s, p, lr)
        if gate is not None:
            # loss-scale overflow skip: keep the old slice, branch-free
            new_p = jax.tree.map(lambda n, o: jnp.where(gate, n, o), new_p, p)
            new_s = jax.tree.map(lambda n, o: jnp.where(gate, n, o.astype(n.dtype)),
                                 new_s, s)
        p_acc = park_slice(p_acc, new_p, i)
        s_acc = jax.tree.map(
            lambda a, n: lax.dynamic_update_index_in_dim(
                _put(a, HOST), _put(n.astype(a.dtype), HOST), i, axis=0)
            if is_stacked(a) else n,
            s_acc, new_s)
        return (p_acc, s_acc), None

    p0 = jax.tree.map(
        lambda p: _put(jnp.zeros(p.shape, p.dtype), HOST), params_host)
    # carry types must be stable: stacked state leaves live in host space
    # throughout the scan, and non-stacked ones (adam's scalar count) on
    # device — update_fn returns device scalars, so a host-typed input
    # would flip memory space across the carry
    state_host = jax.tree.map(
        lambda x: _put(x, HOST) if is_stacked(x) else _put(x, DEVICE),
        state_host)
    (new_params, new_state), _ = lax.scan(
        body, (p0, state_host), jnp.arange(steps))
    return new_params, new_state
