"""DeepSpeedEngine — the training engine.

TPU-native re-design of ``runtime/engine.py`` (DeepSpeedEngine :206).  The
reference wraps an eager nn.Module and orchestrates hooks, buckets and NCCL
ops per micro-batch; here the entire train batch — gradient-accumulation
scan over micro-batches, gradient reduction, clipping, loss-scale logic and
the (ZeRO-sharded) optimizer update — is ONE jitted XLA program:

    train_batch → jit[ scan(micro: value_and_grad) → clip → opt.update ]

ZeRO stages are realised purely as shardings (see parallel/sharding.py):
XLA inserts reduce-scatter for sharded grad accumulators (stage 2), per-layer
all-gathers for sharded params (stage 3), and its latency-hiding scheduler
overlaps them with compute — replacing the reference's IPG buckets
(stage_1_and_2.py:1028), prefetch coordinator and overlap_comm machinery.

API parity: ``forward``/``backward``/``step`` trio, ``train_batch``,
``eval_batch``, ``save_checkpoint``/``load_checkpoint``, ``global_steps``,
``get_global_grad_norm``, gradient-accumulation boundary semantics.
"""

from __future__ import annotations

import os
import pickle
import sys
import time
from functools import partial
from typing import Any, Callable, Dict, Iterator, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.models import transformer as tf_model
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.resilience.oracle import (PartitionOracle,
                                             secondary_mode_from_config)
from deepspeed_tpu.parallel.topology import (BATCH_AXES, SEQ_AXIS, MeshTopology, get_topology,
                                             set_topology)
from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.lr_schedules import LRSchedule, build_lr_schedule, constant_lr
from deepspeed_tpu.runtime.optimizers import Optimizer, build_optimizer
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                                       STEP_GLOBAL_TIMER, TRAIN_BATCH_TIMER,
                                       SynchronizedWallClockTimer, ThroughputTimer)

Batch = Dict[str, Any]

# once-per-process throttle for the discarded-prefetch warning (same
# pattern as the accelerator's unbalanced range_pop throttle): every
# checkpoint load cancels prefetches, and a store whose reads reliably
# fail would otherwise warn once per load for the rest of the run
_DISCARDED_PREFETCH_WARNED = False


def _tree_zeros_like(tree, dtype=None):
    return jax.tree.map(lambda x: jnp.zeros(x.shape, dtype or x.dtype), tree)


def _advance_loss_scale(scale, good, skipped, finite, dynamic: bool,
                        window: int, ls_min: float, xp):
    """Dynamic-loss-scale policy (grow after `window` good steps, halve on
    overflow, floor at `ls_min`).  One implementation for both dialects:
    ``xp=jnp`` inside the jitted step, ``xp=np`` on host step paths
    (SuperOffload) — so the two can never drift."""
    skipped = skipped + xp.where(finite, 0, 1)
    if not dynamic:
        return scale, good, skipped
    good = xp.where(finite, good + 1, 0)
    grow = good >= window
    scale = xp.where(finite,
                     xp.where(grow, scale * 2.0, scale),
                     xp.maximum(scale * 0.5, ls_min))
    good = xp.where(grow, 0, good)
    return scale, good, skipped


def _stacked_batch_specs(batch_stack, axes):
    """Per-leaf PartitionSpecs of a stacked micro-batch ``[gas, rows,
    ...]`` for a manual (shard_map) region: row dims shard over the DP
    ``axes``; PRNG keys and sub-2D leaves replicate.  Shared by every
    explicit-collective path (comm-quant reduce, fused reduce-scatter,
    1-bit build) so a new batch leaf's layout is decided once."""
    return {k: (P() if k == "dropout_key" or np.ndim(v) < 2
                else P(*([None, axes] + [None] * (np.ndim(v) - 2))))
            for k, v in batch_stack.items()}


def _global_norm(tree) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def _all_finite(tree) -> jnp.ndarray:
    leaves = [jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)]
    return jnp.all(jnp.stack(leaves))


def _translate_safe_modules(entries):
    """Map torch_autocast ``lower_precision_safe_modules`` entries (torch
    class names like "torch.nn.Linear" in reference configs) onto this
    model's module classes ("attn"/"mlp"/"embed"/"lm_head").  Unknown
    names are warned about and dropped; if nothing survives, return None
    (= every module low-precision, the pre-policy behavior) rather than
    silently promoting the whole model to fp32."""
    if entries is None:
        return None
    table = {"linear": ("attn", "mlp", "embed", "lm_head"),
             "attention": ("attn",), "attn": ("attn",),
             "mlp": ("mlp",), "ffn": ("mlp",),
             "embedding": ("embed",), "embed": ("embed",),
             "lm_head": ("lm_head",), "conv": ()}
    out = []
    for e in entries:
        key = str(e).rsplit(".", 1)[-1].lower()
        if key in table:
            out.extend(table[key])
        else:
            logger.warning(
                f"torch_autocast.lower_precision_safe_modules: unknown "
                f"module class '{e}' ignored (known: {sorted(table)})")
    if not out:
        logger.warning(
            "torch_autocast.lower_precision_safe_modules matched no model "
            "module classes; keeping every module in the low dtype")
        return None
    return tuple(dict.fromkeys(out))


def _match_state_shardings(state_shape_tree, params_treedef, param_shardings, replicated):
    """Map optimizer-state pytrees to shardings: any subtree whose structure
    equals the params tree reuses the param sharding tree; other leaves are
    replicated (step counts etc.)."""

    def walk(subtree):
        try:
            if jax.tree_util.tree_structure(subtree) == params_treedef:
                return param_shardings
        except Exception:
            pass
        if isinstance(subtree, (list, tuple)):
            rebuilt = [walk(x) for x in subtree]
            if hasattr(subtree, "_fields"):  # namedtuple
                return type(subtree)(*rebuilt)
            return type(subtree)(rebuilt)
        if isinstance(subtree, dict):
            return {k: walk(v) for k, v in subtree.items()}
        if jax.tree_util.treedef_is_leaf(jax.tree_util.tree_structure(subtree)):
            return replicated
        return jax.tree.map(lambda _: replicated, subtree)

    return walk(state_shape_tree)


class DeepSpeedEngine:
    """Training engine over a functional model.

    ``model`` is either a :class:`TransformerConfig` (built-in model zoo) or
    any object exposing ``init(rng) -> params`` and
    ``loss(params, batch) -> scalar`` (duck-typed trainable).
    """

    def __init__(self,
                 model: Union[TransformerConfig, Any],
                 config: Union[DeepSpeedConfig, Dict[str, Any], str, None] = None,
                 topology: Optional[MeshTopology] = None,
                 model_params: Optional[Any] = None,
                 optimizer: Optional[Optimizer] = None,
                 lr_scheduler: Optional[LRSchedule] = None,
                 seed: Optional[int] = None):
        # -- config (batch resolution deferred until topology is known) --
        if isinstance(config, DeepSpeedConfig):
            self.config = config
        else:
            self.config = DeepSpeedConfig(config or {}, world_size=None)

        # -- topology: mesh block merged with tensor_parallel/pipeline/etc.
        zc = self.config.zero_config
        self._secondary_mode = secondary_mode_from_config(zc)
        if topology is None:
            mesh_sizes = self.config.mesh.resolved(len(jax.devices()))
            if self._secondary_mode != "none":
                from deepspeed_tpu.parallel.topology import factor_data_axis

                shard = (zc.zero_hpz_partition_size
                         if self._secondary_mode == "hpz" else zc.mics_shard_size)
                mesh_sizes = factor_data_axis(mesh_sizes, shard)
                log_dist(f"ZeRO++ {self._secondary_mode}: DP world factored "
                         f"into outer={mesh_sizes['data']} × "
                         f"inner={mesh_sizes['subdata']}")
            topology = MeshTopology(mesh_sizes)
        self.topology = topology
        set_topology(topology)

        if not isinstance(config, DeepSpeedConfig):
            self.config.resolve_world(topology.dp_size)
        cfg = self.config
        self.zero_stage = cfg.zero_config.stage
        self.micro_batch_size = cfg.train_micro_batch_size_per_gpu
        self.gradient_accumulation_steps_value = cfg.gradient_accumulation_steps
        self.train_batch_size_value = cfg.train_batch_size
        self.seed = seed if seed is not None else cfg.seed

        # -- ZeRO-Infinity param streaming (decided before the model config
        # freezes: the loss fn must compile the streamed layer scan) -------
        off_param = cfg.zero_config.offload_param
        self._param_stream = bool(
            off_param and off_param.device in ("cpu", "nvme")
            and isinstance(model, TransformerConfig))
        if off_param and off_param.device in ("cpu", "nvme") \
                and not isinstance(model, TransformerConfig):
            logger.warning(
                "layer-streamed offload_param requires the built-in "
                "transformer model; falling back to whole-tree host "
                "placement where supported (no NVMe store%s)"
                % (" — device='nvme' degrades to host RAM"
                   if off_param.device == "nvme" else ""))

        # -- compression (ref deepspeed/compression/compress.py) --------
        # init_compression semantics built into the engine: layer
        # reduction shrinks the model BEFORE params exist; the per-step
        # technique masks are applied inside the jitted loss (see
        # _compile_steps) and re-jit when the active set changes.
        self._compression = None
        cc = cfg.to_dict().get("compression_training")
        if cc:
            from deepspeed_tpu.compression.compress import CompressionManager

            self._compression = CompressionManager(
                {"compression_training": cc})
            self._compression_sig = None
            lr_cfg = self._compression.layer_reduction
            if lr_cfg.enabled and isinstance(model, TransformerConfig):
                keep = lr_cfg.teacher_layer or list(
                    range(lr_cfg.keep_number_layer or model.num_layers))
                model = model.replace(num_layers=len(keep))
                log_dist(f"layer_reduction: student has {len(keep)} layers")

        # -- model ------------------------------------------------------
        self.model_config: Optional[TransformerConfig] = None
        if isinstance(model, TransformerConfig):
            mc = model
            if cfg.bf16.enabled:
                mc = mc.replace(dtype=jnp.bfloat16)
            elif cfg.fp16.enabled:
                mc = mc.replace(dtype=jnp.float16)
            else:
                mc = mc.replace(dtype=jnp.float32)
            if cfg.torch_autocast.enabled:
                ac = cfg.torch_autocast
                if ac.fp32_ops is not None:
                    mc = mc.replace(fp32_ops=tuple(ac.fp32_ops))
                safe = _translate_safe_modules(
                    ac.lower_precision_safe_modules)
                if safe is not None:
                    mc = mc.replace(autocast_safe_modules=safe)
            mc = mc.replace(remat_policy=cfg.activation_checkpointing.remat_policy
                            if cfg.activation_checkpointing.partition_activations
                            or cfg.activation_checkpointing.remat_policy != "nothing_saveable"
                            else mc.remat_policy)
            if (mc.seq_impl == "ring" and topology.sp_size > 1
                    and mc.remat_policy == "nothing_saveable"):
                # Ring attention's forward is a ring of ppermute hops; under
                # nothing_saveable the backward would re-run that whole
                # collective chain per layer just to rebuild (o, lse).  The
                # ring tags exactly those residuals "flash_out"/"flash_lse"
                # (sequence/ring.py), so saving them — and only them — keeps
                # the backward collective-free on the forward side at
                # O(B·S_l·H) extra HBM per layer.
                mc = mc.replace(remat_policy="flash_saveable")
                log_dist("ring sequence parallelism: remat policy upgraded "
                         "nothing_saveable -> flash_saveable (saves the "
                         "ring's (o, lse) so the backward never re-runs "
                         "the forward ppermute chain)", level="info")
            ss = cfg.step_schedule
            if ss.gather_prefetch_depth > 1:
                # gather-prefetch depth (step_schedule): unrolling the
                # layer scan widens the window XLA's latency-hiding
                # scheduler can hoist a ZeRO-3 param all-gather (or a
                # streamed-layer H2D fetch) across — layer i+1's gather
                # overlaps layer i's compute.  The scan only honors a
                # divisor of its length (transformer falls back to 1
                # otherwise), so clamp to the largest divisor <= the
                # pinned depth rather than record a silently-no-op knob.
                depth = ss.gather_prefetch_depth
                while mc.num_layers % depth:
                    depth -= 1
                if depth != ss.gather_prefetch_depth:
                    logger.warning(
                        f"step_schedule.gather_prefetch_depth="
                        f"{ss.gather_prefetch_depth} does not divide "
                        f"num_layers={mc.num_layers}; clamped to {depth}")
                if depth > 1:
                    mc = mc.replace(scan_unroll=max(mc.scan_unroll, depth))
            if ss.ring_interleave > 1 and mc.seq_impl == "ring":
                # ring hop schedule (step_schedule): issue the next hop's
                # ppermute before the current hop's attend
                mc = mc.replace(ring_interleave=ss.ring_interleave)
            cq_ring = cfg.comm_quantization
            if cq_ring.enabled and cq_ring.ring_rotation != "fp32":
                if mc.seq_impl == "ring" and topology.sp_size > 1:
                    # quantized ring wire (comm_quantization.ring_rotation;
                    # sequence/ring.py): the K/V rotation and the traveling
                    # dk/dv move int8/fp8 payloads + fp32 per-row scales
                    # per hop, dequantized in the flash kernel epilogue
                    mc = mc.replace(ring_wire_dtype=cq_ring.ring_rotation)
                    log_dist("comm_quantization: ring rotation wire = "
                             f"{cq_ring.ring_rotation} over "
                             f"sp={topology.sp_size}")
                else:
                    logger.warning(
                        "comm_quantization.ring_rotation: no >1 'seq' "
                        "mesh axis (or seq_impl != 'ring') — nothing "
                        "travels a ring; keeping the fp32 wire")
            if cfg.pipeline.num_microbatches:
                mc = mc.replace(pipeline_microbatches=cfg.pipeline.num_microbatches)
            if self._param_stream:
                mc = mc.replace(param_stream=True)
            self.model_config = mc
            self._init_fn = partial(tf_model.init_params, mc)
            self._loss_fn = partial(tf_model.loss_fn, cfg=mc)
        else:
            self._init_fn = model.init
            self._loss_fn = model.loss

        # -- sharding oracle -------------------------------------------
        # THE partition-spec source for this engine: init, checkpoint
        # save/load (universal resharding included) and any serving
        # engine sharing these weights all read specs from here — the
        # construction recipe (zero stage, hpZ/MiCS mode, persistence
        # threshold incl. the pinned step_schedule override) lives in
        # PartitionOracle.from_config, not at this call site.
        self.oracle = PartitionOracle.from_config(topology, cfg)
        self.rules = self.oracle
        rng = jax.random.PRNGKey(self.seed)

        params_shape = jax.eval_shape(self._init_fn, rng)
        self.param_shardings = self.rules.tree_shardings(
            jax.tree.map(lambda x: x, params_shape), param_style=True)
        self._replicated = NamedSharding(topology.mesh, P())

        offenders = self.rules.audit_replicated(params_shape)
        if offenders:
            desc = ", ".join(f"{p} {s} ({b / 1e6:.1f}MB)"
                             for p, s, b in offenders[:8])
            msg = (f"{len(offenders)} large param(s) could not be sharded "
                   f"(no dim divisible by the shard world) and will be "
                   f"REPLICATED on every device: {desc}")
            if self.config.zero_config.strict_sharding:
                from deepspeed_tpu.runtime.config import DeepSpeedConfigError

                raise DeepSpeedConfigError(
                    msg + " — zero_optimization.strict_sharding is set")
            log_dist(msg, level="warning")

        # -- ZeRO-3 fused gather-matmul (step_schedule.fused_gather_matmul;
        # ops/pallas/gather_matmul.py) ----------------------------------
        # The layer MLP runs as an explicit shard_map over the fsdp axes
        # whose matmul region issues the FOLLOWING matmul's param
        # all-gather ahead of the current one (T3, arXiv:2401.16677) —
        # decided here, after the sharding rules exist, because the path
        # is only correct when the MLP weights actually carry the
        # expected fsdp pattern (wi/wg sharded on the embed dim 0, wo on
        # the embed dim 1, same axes).
        if cfg.step_schedule.fused_gather_matmul:
            mc2 = self.model_config
            cqg = cfg.comm_quantization
            qwz_on = ((cqg.enabled and cqg.zero3_gather != "fp32")
                      or cfg.zero_config.zero_quantized_weights)
            blocked = (
                "requires the built-in transformer model" if mc2 is None
                else "requires ZeRO stage 3" if self.zero_stage < 3 else
                "TP/PP/SP/EP mesh axes unsupported" if (
                    topology.tp_size > 1 or topology.pp_size > 1
                    or topology.sp_size > 1 or topology.ep_size > 1) else
                "hierarchical (hpz/mics) partitioning unsupported"
                if self._secondary_mode != "none" else
                "param streaming unsupported" if self._param_stream else
                "quantized zero3_gather (qwZ) already owns the gather"
                if qwz_on else
                "compression masking unsupported"
                if self._compression is not None else
                "MoE layers unsupported" if mc2.is_moe else "")
            axes = None
            if not blocked:
                def _axes_of(entry):
                    if entry is None:
                        return ()
                    return tuple(entry) if isinstance(entry, (tuple, list)) \
                        else (entry,)

                try:
                    mlp_sh = self.param_shardings["layers"]["mlp"]
                    wi_s = tuple(mlp_sh["wi"].spec)
                    wo_s = tuple(mlp_sh["wo"].spec)
                except (KeyError, TypeError):
                    wi_s = wo_s = ()
                ok = (len(wi_s) == 3 and len(wo_s) == 3
                      and wi_s[0] is None and wi_s[2] is None
                      and wo_s[0] is None and wo_s[1] is None
                      and _axes_of(wi_s[1])
                      and _axes_of(wi_s[1]) == _axes_of(wo_s[2]))
                if ok and mc2.activation == "swiglu":
                    wg_s = tuple(mlp_sh["wg"].spec)
                    ok = wg_s == wi_s
                elif ok and "bi" in mlp_sh:
                    # the pre-activation bias rides the fused region with
                    # an in_spec over the same axes — an indivisible bias
                    # dim (replicated spec) must fall back, not crash at
                    # trace time
                    bi_s = tuple(mlp_sh["bi"].spec)
                    ok = (len(bi_s) == 2 and bi_s[0] is None
                          and _axes_of(bi_s[1]) == _axes_of(wi_s[1]))
                if ok:
                    axes = _axes_of(wi_s[1])
                else:
                    blocked = ("MLP weights do not carry the expected "
                               "fsdp sharding pattern (persistence "
                               "threshold or indivisible dims)")
            if axes:
                mc2 = mc2.replace(fused_gather_matmul=True,
                                  fused_gather_axes=axes)
                self.model_config = mc2
                self._init_fn = partial(tf_model.init_params, mc2)
                self._loss_fn = partial(tf_model.loss_fn, cfg=mc2)
                log_dist("step_schedule: fused gather-matmul — MLP "
                         f"all-gathers issued in-region over {axes}")
            else:
                logger.warning(
                    "step_schedule.fused_gather_matmul: unsupported with "
                    f"this configuration ({blocked}) — keeping the "
                    "scheduled (GSPMD) gather path")

        def _init_sharding_unsafe() -> bool:
            """True when jitting rng init straight into the param
            shardings is known-miscompiled on jax 0.4.37: some leaf is
            sharded over a proper subset of the >1-sized mesh axes
            (fully-replicated leaves and leaves covering every big axis
            are observed-correct — see the init branch below)."""
            big = {ax for ax, sz in self.topology.sizes.items() if sz > 1}
            if not big:
                return False
            for shd in jax.tree.leaves(self.param_shardings):
                used = set()
                for part in getattr(shd, "spec", ()) or ():
                    if part is None:
                        continue
                    if isinstance(part, (tuple, list)):
                        used.update(part)
                    else:
                        used.add(part)
                if used and (big - used):
                    return True
            return False

        self._init_sharding_unsafe = _init_sharding_unsafe

        if model_params is not None:
            if self._compression is not None:
                # teacher checkpoint → layer-reduced student rows
                model_params = self._compression.reduce_layers(model_params)
            self.params = jax.device_put(model_params, self.param_shardings)
        elif self._init_sharding_unsafe():
            # jax 0.4.37 / XLA SPMD miscompiles rng-based init when jitted
            # straight into out_shardings where some leaf is sharded over
            # a PROPER SUBSET of the >1-sized mesh axes: P(pipe) stacked
            # layers on a pipe×data mesh come back scaled by the data-axis
            # size (exactly 4x at data=4 — summed over the replica group
            # instead of selected from it), and P(tensor) leaves on a
            # data×tensor×seq mesh come back as different draws entirely.
            # A hot/wrong init trains visibly slower while every
            # grad-parity test still passes (the schedules are correct;
            # the weights aren't).  Materialize unsharded, then place —
            # device_put is pure data movement and cannot rescale.  The
            # fast sharded-init path is kept when every sharded leaf
            # covers all big axes (pure-data ZeRO-3: the peak-params
            # ladder must not materialize its models replicated).
            # Known tradeoff: this branch peaks at full-model size on ONE
            # device — a pipe/TP model sharded precisely because it
            # exceeds one chip should load params from a checkpoint
            # (model_params path above) rather than rng-init here; wrong
            # silent init was strictly worse than a loud OOM.
            self.params = jax.device_put(jax.jit(self._init_fn)(rng),
                                         self.param_shardings)
        else:
            init_jit = jax.jit(self._init_fn, out_shardings=self.param_shardings)
            self.params = init_jit(rng)
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(self.params))
        log_dist(f"engine: {n_params/1e6:.1f}M params | zero_stage={self.zero_stage} "
                 f"| mesh={topology.sizes} | micro_bs={self.micro_batch_size} "
                 f"| gas={self.gradient_accumulation_steps_value}")

        # -- decomposed weight-update schedule (step_schedule block;
        # autotuning/overlap_scheduler.py; arXiv:2004.13336) ------------
        # "decomposed" shards the optimizer state AND the gradient
        # accumulator over the ZeRO axes even at stage 0/1: XLA then
        # compiles the DP gradient reduction as reduce-scatter, each
        # replica steps its 1/world shard of the optimizer, and the
        # updated params are re-gathered — the all-gathers of early
        # tensors overlap the update compute of later ones under the
        # latency-hiding scheduler.  Stage ≥ 2 already has this layout
        # (the knob is a no-op there); stage 3 additionally defers the
        # re-gather to the next step's per-layer forward gathers.
        self._decomposed_update = False
        if cfg.step_schedule.weight_update == "decomposed":
            off_opt_pre = cfg.zero_config.offload_optimizer
            onebit_opt = (cfg.optimizer is not None and cfg.optimizer.type
                          in ("onebitadam", "onebitlamb", "zerooneadam",
                              "0/1adam"))
            blocked = ("no >1 ZeRO axis" if topology.zero_size <= 1 else
                       "offload_param streaming" if self._param_stream else
                       "SuperOffload" if (off_opt_pre is not None
                                          and off_opt_pre.super_offload)
                       else
                       "chunked host optimizer"
                       if (off_opt_pre is not None
                           and off_opt_pre.device in ("cpu", "nvme")
                           and off_opt_pre.working_set_bytes > 0) else
                       "NVMe optimizer store" if (off_opt_pre is not None
                                                  and off_opt_pre.device
                                                  == "nvme") else
                       "1-bit optimizer" if onebit_opt else
                       "qgZ compressed gradients"
                       if zc.zero_quantized_gradients
                       and self.zero_stage <= 1 else "")
            if blocked:
                logger.warning(
                    "step_schedule.weight_update='decomposed': unsupported "
                    f"with this configuration ({blocked}) — keeping the "
                    "stage's native update layout")
            else:
                self._decomposed_update = True
                log_dist("step_schedule: decomposed weight update — "
                         "optimizer state + grad accumulator sharded over "
                         f"the ZeRO axes (world={topology.zero_size}, "
                         f"stage={self.zero_stage})")

        # -- optimizer --------------------------------------------------
        if optimizer is not None:
            self.optimizer = optimizer
        else:
            # sharded when ZeRO partitions opt state (stage≥1), any param
            # sharding is non-replicated (tensor parallel), or the update
            # runs host-streamed — in all of these the pallas_fused kernel
            # path must be downgraded (see build_optimizer).
            any_sharded = any(
                any(ax is not None for ax in getattr(sh, "spec", P()))
                for sh in jax.tree.leaves(self.param_shardings))
            sharded = (self.zero_stage >= 1 or any_sharded
                       or bool(self._param_stream)
                       or self._decomposed_update)
            if cfg.optimizer is not None:
                self.optimizer = build_optimizer(cfg.optimizer.type, cfg.optimizer.params,
                                                 sharded_params=sharded)
            else:
                self.optimizer = build_optimizer("adamw", {}, sharded_params=sharded)
        self.base_lr = (cfg.optimizer.lr if cfg.optimizer else 1e-3)

        params_treedef = jax.tree_util.tree_structure(params_shape)
        if self._decomposed_update:
            # always-fsdp specs (what stage >= 1 / >= 2 would use)
            opt_param_shardings = self.rules.tree_shardings(
                params_shape, param_style=False)
        else:
            opt_param_shardings = self.rules.optimizer_shardings(params_shape)
        if self._param_stream:
            # split the optimizer: the streamed layer partition's state
            # lives host-resident and is stepped one layer-slice at a time
            # (runtime/infinity.streamed_update); the small resident part
            # (embed/norm/head) keeps the normal device update.  On
            # backends without memory kinds (the CPU test mesh) the
            # streaming code path still runs; placement is a no-op.
            from deepspeed_tpu.runtime.offload import (host_offload_supported,
                                                       with_memory_kind)

            self._host_kinds = host_offload_supported(topology)

            def hostify(sh):
                return with_memory_kind(sh, "pinned_host") \
                    if self._host_kinds else sh

            res_shape = {k: v for k, v in params_shape.items()
                         if k != "layers"}
            res_treedef = jax.tree_util.tree_structure(res_shape)
            res_param_sh = {k: v for k, v in opt_param_shardings.items()
                            if k != "layers"}
            res_state_shape = jax.eval_shape(self.optimizer.init, res_shape)
            layers_treedef = jax.tree_util.tree_structure(
                params_shape["layers"])
            layers_state_shape = jax.eval_shape(self.optimizer.init,
                                                params_shape["layers"])
            self.opt_shardings = {
                "resident": _match_state_shardings(
                    res_state_shape, res_treedef, res_param_sh,
                    self._replicated),
                "stream": hostify(_match_state_shardings(
                    layers_state_shape, layers_treedef,
                    opt_param_shardings["layers"], self._replicated)),
            }
            opt_state_shape = {"resident": res_state_shape,
                               "stream": layers_state_shape}
        else:
            opt_state_shape = jax.eval_shape(self.optimizer.init, params_shape)
            self.opt_shardings = _match_state_shardings(
                opt_state_shape, params_treedef, opt_param_shardings,
                self._replicated)

        # -- ZeRO-Offload / -Infinity tiering --------------------------
        # Two realisations (runtime/offload.py): streaming mode keeps opt
        # state in host memory via XLA memory kinds with device↔host
        # transfers compiled into the step (TPU); store mode keeps numpy
        # arrays on the host / NVMe and swaps around each step.
        self._opt_store = None
        self._opt_stream_offload = False
        self._opt_device_shardings = self.opt_shardings
        self._super_opt = None
        off_opt = cfg.zero_config.offload_optimizer
        if off_opt and getattr(off_opt, "super_offload", False) \
                and self._param_stream:
            raise DeepSpeedConfigError(
                "offload_optimizer.super_offload cannot combine with "
                "offload_param streaming (ZeRO-Infinity already steps the "
                "streamed partition host-side); drop one of the two")
        # Chunked host optimizer pipeline (runtime/offload.
        # ChunkedHostOptimizer): opted in via working_set_bytes > 0, taken
        # only when the fp32 state (12 B/param) actually exceeds the
        # budget — smaller models keep the legacy streaming/store paths.
        self._chunked_opt = bool(
            off_opt and off_opt.device in ("cpu", "nvme")
            and not off_opt.super_offload
            and off_opt.working_set_bytes > 0
            and 12 * n_params > off_opt.working_set_bytes)
        if self._chunked_opt:
            log_dist(f"ZeRO-Offload chunked: host Adam over "
                     f"{off_opt.chunk_bytes >> 20}MB chunks "
                     f"(tier={off_opt.device}, state="
                     f"{12 * n_params >> 20}MB > working set="
                     f"{off_opt.working_set_bytes >> 20}MB)")
        elif off_opt and off_opt.device == "cpu" and off_opt.super_offload \
                and not self._param_stream:
            # SuperOffload (ref engine.py:935 + superoffload_stage3.py):
            # the full fp32 master + moments live on the host; the step is
            # a pipelined bucketed host Adam (device keeps working params
            # only). Created after params exist, below.
            log_dist("SuperOffload: host-resident pipelined Adam with "
                     "rollback")
        elif off_opt and off_opt.device == "cpu" and self._param_stream:
            # the streamed layer partition's opt state is already
            # host-resident and slice-stepped; nothing extra to offload
            log_dist("ZeRO-Offload: opt state host placement subsumed by "
                     "param streaming")
        elif off_opt and off_opt.device == "cpu":
            from deepspeed_tpu.runtime.offload import (HostOptimizerStore,
                                                       host_offload_supported,
                                                       partial_offload_shardings)

            if host_offload_supported(topology):
                self.opt_shardings = partial_offload_shardings(
                    opt_state_shape, self.opt_shardings, off_opt.ratio)
                self._opt_stream_offload = True
                log_dist(f"ZeRO-Offload: opt state → host RAM via memory kinds "
                         f"(ratio={off_opt.ratio})")
            else:
                self._opt_store = HostOptimizerStore()
                log_dist("ZeRO-Offload: opt state → host-store (numpy) mode")
        self._param_store = None
        if off_param and off_param.device in ("cpu", "nvme") \
                and not self._param_stream:
            # custom (non-TransformerConfig) models can't stream the layer
            # scan; keep the coarse whole-tree host placement (XLA bulk-
            # transfers params into the step)
            from deepspeed_tpu.runtime.offload import (host_offload_supported,
                                                       with_memory_kind)

            if host_offload_supported(topology):
                self.param_shardings = with_memory_kind(self.param_shardings,
                                                        "pinned_host")
                self.params = jax.device_put(self.params, self.param_shardings)
                log_dist("ZeRO-Infinity: params → host RAM (whole-tree)")
        if self._param_stream:
            # ZeRO-Infinity: the stacked layer weights live in pinned host
            # memory and are streamed one layer at a time through the
            # compiled step (models/transformer.py streamed scan_segment +
            # runtime/infinity.py; ref partitioned_param_swapper.py:37)
            layer_sh = hostify(self.param_shardings["layers"])
            self.param_shardings = {**self.param_shardings,
                                    "layers": layer_sh}
            self.params = {**self.params,
                           "layers": jax.device_put(self.params["layers"],
                                                    layer_sh)}
            log_dist("ZeRO-Infinity: layer params → host RAM, streamed "
                     "layer-by-layer through the step")
            if off_param.device == "nvme":
                from deepspeed_tpu.runtime.offload import NVMeOptimizerSwapper

                swap_dir = off_param.nvme_path or os.path.join(
                    os.environ.get("TMPDIR", "/tmp"), "dstpu_param_swap")
                # the swapper is a generic AIO-backed tree store; between
                # steps the layer weights live on NVMe, around each step
                # they are staged through host RAM only
                self._param_store = NVMeOptimizerSwapper(swap_dir,
                                                         cfg.aio_config,
                                                         prefix="param")
                log_dist(f"ZeRO-Infinity: layer params → NVMe at {swap_dir}")

        if self._chunked_opt:
            from deepspeed_tpu.runtime.offload import ChunkedHostOptimizer

            opt_type = (cfg.optimizer.type if cfg.optimizer else "adamw").lower()
            if opt_type not in ("adam", "adamw", "fusedadam"):
                raise DeepSpeedConfigError(
                    f"offload_optimizer.working_set_bytes (chunked host "
                    f"step) supports Adam/AdamW only, got "
                    f"optimizer.type={opt_type!r}")
            op = (cfg.optimizer.params if cfg.optimizer else {})
            store = None
            if off_opt.device == "nvme":
                from deepspeed_tpu.nvme.chunk_store import NVMeChunkStore

                swap_dir = off_opt.nvme_path or os.path.join(
                    os.environ.get("TMPDIR", "/tmp"), "dstpu_nvme_swap")
                store = NVMeChunkStore(swap_dir, cfg.aio_config,
                                       buffer_count=off_opt.buffer_count)
                log_dist(f"ZeRO-Infinity: optimizer chunks → NVMe at "
                         f"{swap_dir}")
            # rides the _super_opt slot: the grads-only device program,
            # the host-stepped train_batch, and the superoffload
            # checkpoint format are all shared with SuperOffload.
            # adamw/wd defaults MIRROR build_optimizer's fused chain
            # (adam_w_mode defaults True, AdamW wd defaults 0.01) — the
            # chunked host step must be numerically the same update the
            # fused path would have applied
            adamw = (opt_type == "adamw"
                     or bool(op.get("adam_w_mode", True)))
            self._super_opt = ChunkedHostOptimizer(
                self.params, lr=self.base_lr,
                betas=tuple(op.get("betas", (0.9, 0.999))),
                eps=float(op.get("eps", 1e-8)),
                weight_decay=float(op.get("weight_decay",
                                          0.01 if adamw else 0.0)),
                chunk_bytes=off_opt.chunk_bytes,
                adamw=adamw,
                store=store)
            self.opt_state = None  # host/NVMe chunks are authoritative
        elif off_opt and off_opt.device == "cpu" and off_opt.super_offload \
                and not self._param_stream:
            from deepspeed_tpu.runtime.superoffload import SuperOffloadOptimizer

            opt_type = (cfg.optimizer.type if cfg.optimizer else "adamw").lower()
            if opt_type not in ("adam", "adamw", "fusedadam"):
                raise DeepSpeedConfigError(
                    f"super_offload supports Adam/AdamW only, got "
                    f"optimizer.type={opt_type!r}")
            op = (cfg.optimizer.params if cfg.optimizer else {})
            workers = max(1, int((os.cpu_count() or 4)
                                 * off_opt.cpuadam_cores_perc))
            self._super_opt = SuperOffloadOptimizer(
                self.params, lr=self.base_lr,
                betas=tuple(op.get("betas", (0.9, 0.999))),
                eps=float(op.get("eps", 1e-8)),
                weight_decay=float(op.get("weight_decay", 0.0)),
                max_workers=workers,
                adamw=opt_type in ("adamw", "fusedadam"))
            self.opt_state = None  # host masters/moments are authoritative
        elif self._param_stream:
            res_params = {k: v for k, v in self.params.items()
                          if k != "layers"}
            opt_init_jit = jax.jit(
                lambda lp, rp: {"stream": self.optimizer.init(lp),
                                "resident": self.optimizer.init(rp)},
                out_shardings={"stream": self.opt_shardings["stream"],
                               "resident": self.opt_shardings["resident"]})
            self.opt_state = opt_init_jit(self.params["layers"], res_params)
        else:
            opt_init_jit = jax.jit(self.optimizer.init,
                                   out_shardings=self.opt_shardings)
            self.opt_state = opt_init_jit(self.params)

        if off_opt and off_opt.device == "nvme" and not self._chunked_opt:
            from deepspeed_tpu.runtime.offload import NVMeOptimizerSwapper

            swap_dir = off_opt.nvme_path or os.path.join(
                os.environ.get("TMPDIR", "/tmp"), "dstpu_nvme_swap")
            self._opt_store = NVMeOptimizerSwapper(swap_dir, cfg.aio_config)
            log_dist(f"ZeRO-Infinity: optimizer state → NVMe at {swap_dir}")
        if self._opt_store is not None:
            self._opt_store.swap_out(self.opt_state)
            self.opt_state = None  # store is authoritative between steps
        if self._param_store is not None:
            self._param_store.swap_out(self.params["layers"])
            self.params = {**self.params, "layers": None}
        # Pipelined (overlapped) store swapping, ref
        # swap_tensor/pipelined_optimizer_swapper.py:26: with
        # offload_optimizer.pipeline_read set, the next step's store reads
        # drain on a worker thread behind the writes while the host
        # dispatches this step's compute.  (pipeline_write is accepted for
        # config parity but controls nothing extra: store writes are
        # always issued async via the AIO handle.)
        self._opt_fut = None
        self._param_fut = None
        self._swap_pool = None
        if (off_opt is not None and off_opt.pipeline_read
                and (self._opt_store is not None
                     or self._param_store is not None)):
            import concurrent.futures

            self._swap_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="dstpu-swap")

        if self._decomposed_update:
            self.grad_shardings = self.rules.tree_shardings(
                params_shape, param_style=False)
        else:
            self.grad_shardings = self.rules.grad_accum_shardings(params_shape)
        if self._param_stream:
            self.grad_shardings = {
                **self.grad_shardings,
                "layers": hostify(self.grad_shardings["layers"])}

        # -- precision / loss scaling ----------------------------------
        self.fp16_enabled = cfg.fp16.enabled
        self.bfloat16_enabled = cfg.bf16.enabled
        if self.fp16_enabled and cfg.fp16.dynamic:
            init_scale = 2.0 ** cfg.fp16.initial_scale_power
        elif self.fp16_enabled:
            init_scale = float(cfg.fp16.loss_scale)
        else:
            init_scale = 1.0
        self.loss_scale_state = jax.device_put(
            {"scale": jnp.float32(init_scale), "good_steps": jnp.int32(0),
             "skipped": jnp.int32(0)},
            self._replicated)
        self._ls_window = cfg.fp16.loss_scale_window
        self._ls_min = cfg.fp16.min_loss_scale
        self._ls_dynamic = self.fp16_enabled and cfg.fp16.dynamic

        # -- lr schedule ------------------------------------------------
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
        elif cfg.scheduler is not None:
            self.lr_scheduler = build_lr_schedule(cfg.scheduler.type, cfg.scheduler.params,
                                                  base_lr=self.base_lr)
        else:
            self.lr_scheduler = constant_lr(self.base_lr)

        # -- bookkeeping ------------------------------------------------
        self.global_steps = 0
        self.micro_steps = 0
        self._last_metrics: Dict[str, float] = {}
        self.timers = SynchronizedWallClockTimer(synchronize=cfg.wall_clock_breakdown)
        self.tput_timer = ThroughputTimer(batch_size=cfg.train_batch_size,
                                          steps_per_output=cfg.steps_per_print)
        self.monitor = self._build_monitor(cfg)

        # -- unified telemetry (telemetry/; docs/OBSERVABILITY.md) -------
        self.telemetry = None
        self._last_batch_tokens = 0
        if cfg.telemetry.enabled:
            from deepspeed_tpu.telemetry import Telemetry
            from deepspeed_tpu.utils.comms_logging import get_comms_logger

            self.telemetry = Telemetry(cfg.telemetry, monitor=self.monitor)
            # the comm-volume field of every StepRecord reads the global
            # CommsLogger; telemetry implies recording even when the
            # verbose comms_logger block is off.  The logger is process-
            # global, so records carry the DELTA vs this baseline (a
            # second engine in the same process must not inherit the
            # first one's traffic) and destroy() restores the flag.
            cl = get_comms_logger()
            self._comms_prev_enabled = cl.enabled
            cl.enabled = True
            self._comms_baseline = cl.totals()
        # -- software spans + hang watchdog (telemetry/tracing, flight) --
        # one unconditional code path: without telemetry the NULL tracer
        # answers every span call with the shared no-op singleton
        from deepspeed_tpu.telemetry.tracing import NULL_TRACER

        self._tracer = (self.telemetry.tracer if self.telemetry is not None
                        else NULL_TRACER)
        self._train_trace_id = (self._tracer.new_trace_id()
                                if self._tracer.enabled else "")
        if self._super_opt is not None and hasattr(self._super_opt,
                                                   "_tracer"):
            # chunked host optimizer (built before telemetry exists): its
            # pipeline stages emit the offload.* spans through this tracer
            self._super_opt._tracer = self._tracer
            self._super_opt._trace_id = self._train_trace_id
        self._step_span = None
        # created here, armed per-step from train_batch: monitoring only
        # covers time spent *inside* a step (eval/checkpoint gaps are
        # legitimate silence), and this process's first train_batch is
        # skipped so a >60s XLA compile doesn't write a spurious hang
        # bundle — per-process, not global_steps, because a checkpoint
        # resume restores global_steps yet still pays the full compile
        self._compiled_step_done = False
        self._watchdog = (self.telemetry.make_watchdog("train")
                          if self.telemetry is not None else None)

        # -- data efficiency: curriculum learning (seqlen truncation) ----
        # Ref: engine curriculum integration — batches are truncated to the
        # schedule's current difficulty; difficulty_step rounding bounds the
        # number of distinct shapes (= XLA recompiles).
        self.curriculum_scheduler = None
        cl_cfg = cfg.data_efficiency.curriculum_config \
            if cfg.data_efficiency.enabled else None
        if cl_cfg:
            from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(cl_cfg)
            self._curriculum_type = cl_cfg.get("curriculum_type", "seqlen")

        # -- random-LTD: kept-seqlen schedule → model re-jit per value ----
        self.random_ltd_scheduler = None
        rl_cfg = cfg.data_efficiency.random_ltd_config \
            if cfg.data_efficiency.enabled else None
        if rl_cfg and self.model_config is not None:
            from deepspeed_tpu.runtime.data_pipeline import RandomLTDScheduler

            sched = rl_cfg.get("random_ltd_schedule", rl_cfg)
            sc = sched.get("schedule_config", {})
            self.random_ltd_scheduler = RandomLTDScheduler(
                min_value=int(sched.get("min_value", 128)),
                max_value=int(sched.get("max_value",
                                        self.model_config.max_seq_len)),
                total_steps=int(sc.get("require_steps",
                                       sched.get("total_steps", 1000))),
                step_size=int(sc.get("seq_per_step",
                                     sched.get("step_size", 16))))
            self._ltd_band = (int(rl_cfg.get("ltd_start", 1)),
                              rl_cfg.get("ltd_end"))

        # -- progressive layer drop (theta rides the batch; no recompile) --
        self.progressive_layer_drop = None
        pld_dict = (cfg.to_dict().get("progressive_layer_drop", {})
                    if hasattr(cfg, "to_dict") else {})
        if pld_dict.get("enabled"):
            from deepspeed_tpu.runtime.model_features import ProgressiveLayerDrop

            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=float(pld_dict.get("theta", 0.5)),
                gamma=float(pld_dict.get("gamma", 0.001)))

        # -- flops profiler (XLA cost analysis at profile_step) ----------
        self._flops_profiler = None
        self._last_flops_profile = None
        if cfg.flops_profiler.enabled:
            from deepspeed_tpu.profiling import FlopsProfiler

            self._flops_profiler = FlopsProfiler(cfg.flops_profiler)

        # -- XPlane trace capture (ref pytorch-profiler integration) -----
        self._trace_profiler = None
        if cfg.profiler.enabled:
            from deepspeed_tpu.utils.trace import TraceProfiler

            self._trace_profiler = TraceProfiler(
                cfg.profiler.output_dir, cfg.profiler.start_step,
                cfg.profiler.num_steps)

        # grad accumulation buffer for the forward/backward/step trio
        self._grad_buffer = None
        self._micro_in_step = 0
        self._checkpoint_engine = None

        # -- 1-bit compressed-DP mode (OnebitAdam/OnebitLamb/ZeroOneAdam) --
        self._onebit = None
        self._onebit_state = None
        _dp_only = (self.topology.dp_size > 1 and self.topology.tp_size == 1
                    and self.topology.pp_size == 1 and self.topology.sp_size == 1
                    and not self._param_stream)
        if (cfg.optimizer is not None and _dp_only
                and cfg.optimizer.type in ("onebitadam", "onebitlamb",
                                           "zerooneadam", "0/1adam")):
            from deepspeed_tpu.runtime.onebit import OnebitConfig, OnebitTrainStep

            variant = ("zerooneadam" if cfg.optimizer.type in ("zerooneadam",
                                                               "0/1adam")
                       else cfg.optimizer.type)
            ob_cfg = OnebitConfig(cfg.optimizer.params, variant)
            self._onebit = OnebitTrainStep(self.topology, self._loss_fn,
                                           self.params, ob_cfg,
                                           gas=self.gradient_accumulation_steps_value,
                                           grad_clip=cfg.gradient_clipping)
            self._onebit_state = self._onebit.init_state(self.params)
        elif (zc.zero_quantized_gradients and _dp_only and self.zero_stage <= 1
              and cfg.optimizer is not None
              and cfg.optimizer.type in ("adam", "adamw", "fusedadam")):
            # qgZ without ZeRO-3: int8-compressed DP gradient reduction
            from deepspeed_tpu.runtime.onebit import OnebitConfig, OnebitTrainStep

            ob_cfg = OnebitConfig(cfg.optimizer.params, "qgz")
            self._onebit = OnebitTrainStep(self.topology, self._loss_fn,
                                           self.params, ob_cfg,
                                           gas=self.gradient_accumulation_steps_value,
                                           grad_clip=cfg.gradient_clipping)
            self._onebit_state = self._onebit.init_state(self.params)

        if self._onebit is not None and self._compression is not None:
            raise DeepSpeedConfigError(
                "compression_training is not supported with 1-bit/qgZ "
                "compressed-DP optimizers (their step wraps the raw loss, "
                "so compression masks would silently not apply)")

        # -- quantized ZeRO collectives (comm_quantization block;
        # comm/quantized.py, docs/QUANTIZED_COMM.md) -------------------
        # grad_reduce: the engine grows an EXPLICIT reduce path — the DP
        # gradient reduction leaves GSPMD's implicit insertion and runs
        # as a shard_map quantized all-reduce whose wire volume (int8/
        # fp8/fp32 payload + scales) is recorded per-collective in
        # telemetry.  zero3_gather is wired in _compile_steps (the qwZ
        # straight-through gather with a selectable wire dtype).
        self._comm_quant = None         # active grad-reduce config
        self._comm_quant_state = None   # error-feedback residual state
        cqc = cfg.comm_quantization
        if cqc.enabled:
            from deepspeed_tpu.comm.quantized import fp8_supported

            for coll in cqc.COLLECTIVES:
                if getattr(cqc, coll) == "fp8" and not fp8_supported():
                    raise DeepSpeedConfigError(
                        f"comm_quantization.{coll}='fp8' requires "
                        "jnp.float8_e4m3fn, which this jax build lacks — "
                        "use 'int8'")
            _quant_dp = (_dp_only and self.zero_stage <= 2
                         and self._onebit is None
                         and self._super_opt is None
                         and self._opt_store is None)
            if _quant_dp:
                self._comm_quant = cqc
                n_total = sum(int(np.prod(x.shape))
                              for x in jax.tree.leaves(self.params))
                world = self.topology.dp_size
                base = world * cqc.group_size
                self._comm_quant_padded = -(-n_total // base) * base
                from deepspeed_tpu.parallel.topology import BATCH_AXES as _BA

                self._comm_quant_res_sharding = NamedSharding(
                    self.topology.mesh, P(_BA))
                if cqc.error_feedback and cqc.grad_reduce != "fp32":
                    # per-rank first-send quantization residual, carried
                    # step to step (LoCo-style).  Stored [world, padded]
                    # with the leading axis sharded over the DP axes —
                    # the same layout as the onebit error state.  Not
                    # checkpointed: a resume re-accumulates it within a
                    # step at no quality cost.
                    self._comm_quant_state = {
                        "residual": jax.device_put(
                            jnp.zeros((world, self._comm_quant_padded),
                                      jnp.float32),
                            self._comm_quant_res_sharding)}
                log_dist(
                    f"comm_quantization: explicit grad reduce over "
                    f"dp={world} wire={cqc.grad_reduce} "
                    f"group_size={cqc.group_size} "
                    f"error_feedback={self._comm_quant_state is not None}")
            elif cqc.grad_reduce != "fp32":
                logger.warning(
                    "comm_quantization.grad_reduce: unsupported with this "
                    "configuration (needs a >1 data-parallel mesh without "
                    "TP/PP/SP, ZeRO stage <= 2, no param streaming / "
                    "SuperOffload / optimizer store / 1-bit optimizer) — "
                    "falling back to the implicit fp32 reduction")

        # -- fused reduce-scatter epilogue (step_schedule block) --------
        # With the decomposed update, GSPMD compiles the DP grad reduce
        # as reduce-scatter wherever its layout pass places it; the
        # fused variant instead accumulates gradients LOCALLY inside a
        # shard_map over the DP axes and issues an explicit per-leaf
        # psum_scatter in the accumulation epilogue — the scatter
        # consumes the just-written accumulator in place (the last
        # micro-batch's adds and the wire movement are one fused region)
        # and early leaves' scatters overlap later leaves' update math.
        self._fused_rs = False
        if cfg.step_schedule.fused_reduce_scatter:
            blocked = (
                "requires weight_update='decomposed'"
                if not self._decomposed_update else
                "requires ZeRO stage <= 1 (stage >= 2 grads are already "
                "scatter-laid-out by GSPMD)" if self.zero_stage > 1 else
                "needs a >1 data-parallel mesh without TP/PP/SP"
                if not _dp_only else
                # the full-manual region over BATCH_AXES cannot host the
                # MoE expert-parallel nested shard_map, and expert-
                # sharded grad leaves would scatter over the wrong axes
                "MoE / expert-parallel unsupported"
                if (self.topology.ep_size > 1
                    or (self.model_config is not None
                        and self.model_config.is_moe)) else
                "hierarchical (hpz/mics) partitioning unsupported"
                if self._secondary_mode != "none" else
                "comm_quantization grad reduce already owns the wire"
                if self._comm_quant is not None else
                "1-bit/qgZ optimizer owns the reduction"
                if self._onebit is not None else
                "sparse gradients unsupported"
                if cfg.sparse_gradients_enabled else "")
            if blocked:
                logger.warning(
                    "step_schedule.fused_reduce_scatter: unsupported with "
                    f"this configuration ({blocked}) — keeping the GSPMD "
                    "scatter placement")
            else:
                self._fused_rs = True
                log_dist("step_schedule: fused reduce-scatter — explicit "
                         "per-leaf psum_scatter in the grad-accumulator "
                         f"epilogue over dp={self.topology.dp_size}")

        self._compile_steps()

    # ------------------------------------------------------------------
    def _build_monitor(self, cfg):
        try:
            from deepspeed_tpu.monitor.monitor import MonitorMaster

            return MonitorMaster(cfg)
        except Exception:
            return None

    # ------------------------------------------------------------------
    # Compiled step functions
    # ------------------------------------------------------------------
    def _watchdog_expect_compile(self) -> None:
        """Disarm the hang watchdog for the remainder of the current step:
        the caller just changed the compiled functions or traced shapes,
        so this step legitimately pays a fresh XLA compile that can
        exceed any sane stall deadline (same reasoning as the per-process
        first-step skip in train_batch).  Re-armed at the next step."""
        wd = getattr(self, "_watchdog", None)
        if wd is not None:
            wd.pause()

    def _compile_steps(self) -> None:
        self._watchdog_expect_compile()
        cfg = self.config
        clip = cfg.gradient_clipping
        gas = self.gradient_accumulation_steps_value
        opt = self.optimizer
        loss_fn = self._loss_fn
        if self._compression is not None:
            # per-step compression view of the params inside the jitted
            # loss (masks fuse with the matmuls); the step gate is python-
            # static — train_batch re-compiles when the active set changes
            mgr = self._compression
            comp_step = self.global_steps
            nh = self.model_config.num_heads if self.model_config else 0
            inner_loss = loss_fn

            def loss_fn(params, batch, **kw):  # noqa: F811
                return inner_loss(mgr.apply(params, comp_step,
                                            num_heads=nh), batch, **kw)

            self._compression_sig = mgr.active_signature(comp_step)
        grad_shardings = self.grad_shardings
        ls_dynamic = self._ls_dynamic
        ls_window, ls_min = self._ls_window, self._ls_min
        fp16 = self.fp16_enabled

        # stage-3 gather quantization: the comm_quantization block's
        # zero3_gather selects the wire dtype; the legacy ZeRO++
        # zero_quantized_weights flag keeps meaning int8
        cqc = cfg.comm_quantization
        qwz_dtype = None
        if self.zero_stage >= 3:
            if cqc.enabled and cqc.zero3_gather != "fp32":
                qwz_dtype = cqc.zero3_gather
            elif cfg.zero_config.zero_quantized_weights:
                qwz_dtype = "int8"
        qwz = qwz_dtype is not None
        qwz_group = cqc.group_size if cqc.enabled else 256
        rules = self.rules

        # -- sparse gradients (ref runtime/sparse_tensor.py + the sparse
        # allreduce bucket of engine.py:145): hoist the token-embedding
        # lookup out of AD so the table cotangent is (ids, values)-COO and
        # the dp reduction is an all_gather of O(tokens·H) bytes, not a
        # dense [V,H] scatter+psum. See runtime/sparse.py.
        mc = self.model_config
        # compression masks the embed table inside loss_fn, which the
        # sparse path's hoisted lookup would bypass — keep dense grads
        sparse_grads = (cfg.sparse_gradients_enabled and mc is not None
                        and not mc.tie_embeddings
                        and self.topology.pp_size == 1
                        and not self._param_stream and not qwz
                        and self._compression is None
                        and self._comm_quant is None)
        if cfg.sparse_gradients_enabled and not sparse_grads:
            logger.warning(
                "sparse_gradients: unsupported with this configuration "
                "(tied embeddings, pipeline, param streaming, qwZ, or "
                "comm_quantization) — falling back to dense gradients")
        topo = self.topology

        def micro_grads_dense(params, batch, scale):
            def scaled_loss(p):
                if qwz:
                    from deepspeed_tpu.parallel.zeropp import qwz_weight_gather

                    p = qwz_weight_gather(p, rules, group_size=qwz_group,
                                          wire_dtype=qwz_dtype)
                loss = loss_fn(p, batch)
                return loss * scale.astype(loss.dtype)

            sloss, grads = jax.value_and_grad(scaled_loss)(params)
            return sloss / scale, grads

        def micro_grads_sparse(params, batch, scale):
            from deepspeed_tpu.runtime.sparse import sparse_embedding_grad

            ids = batch["input_ids"]
            table = params["embed"]["tokens"]
            emb = jnp.take(table, ids, axis=0)

            def scaled_loss(p, emb_):
                loss = loss_fn(p, batch, token_embeds=emb_)
                return loss * scale.astype(loss.dtype)

            sloss, (g_params, g_emb) = jax.value_and_grad(
                scaled_loss, argnums=(0, 1))(params, emb)
            st = sparse_embedding_grad(g_emb, ids, table.shape, topo)
            g_table = st.add_into(g_params["embed"]["tokens"])
            g_params = {**g_params,
                        "embed": {**g_params["embed"], "tokens": g_table}}
            return sloss / scale, g_params

        micro_grads = micro_grads_sparse if sparse_grads else micro_grads_dense

        stream_offload = self._opt_stream_offload
        opt_device_shardings = self._opt_device_shardings

        def ls_advance(finite, ls_state):
            scale, good, skipped = _advance_loss_scale(
                ls_state["scale"], ls_state["good_steps"],
                ls_state["skipped"], finite, ls_dynamic, ls_window, ls_min,
                jnp)
            return {"scale": scale, "good_steps": good.astype(jnp.int32),
                    "skipped": skipped.astype(jnp.int32)}

        def apply_update(params, opt_state, grads, lr, ls_state):
            if stream_offload:
                # ZeRO-Offload streaming: state arrives in host memory; move
                # to device for the update (XLA schedules the transfers).
                opt_state = jax.device_put(opt_state, opt_device_shardings)
            scale = ls_state["scale"]
            inv = 1.0 / (scale * gas)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
            grad_norm = _global_norm(grads)
            if clip and clip > 0:
                coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * coef, grads)

            if fp16:
                finite = _all_finite(grads) & jnp.isfinite(grad_norm)
            else:
                finite = jnp.bool_(True)

            new_params, new_opt = opt.update(grads, opt_state, params, lr)
            # overflow → keep old state (select, branch-free)
            new_params = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_params, params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(finite, n.astype(o.dtype), o), new_opt, opt_state)

            return new_params, new_opt, ls_advance(finite, ls_state), grad_norm, finite

        from deepspeed_tpu.runtime.infinity import split_layers

        def stream_apply_update(params, opt_state, g_layers, g_res, lr,
                                ls_state):
            """ZeRO-Infinity update: layer partition stepped slice-wise
            against host-resident grads/params/opt-state; the small
            resident partition (embed/norms/head) updated normally."""
            from deepspeed_tpu.runtime.infinity import (streamed_sq_norm,
                                                        streamed_update)

            p_layers, p_res = split_layers(params)
            scale = ls_state["scale"]
            inv = 1.0 / (scale * gas)
            g_res = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, g_res)
            sq = streamed_sq_norm(g_layers) * inv * inv
            sq = sq + sum(jnp.sum(g ** 2) for g in jax.tree.leaves(g_res))
            grad_norm = jnp.sqrt(sq)
            coef = jnp.float32(1.0)
            if clip and clip > 0:
                coef = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                g_res = jax.tree.map(lambda g: g * coef, g_res)
            finite = jnp.isfinite(grad_norm) if fp16 else jnp.bool_(True)

            new_res, new_opt_res = opt.update(g_res, opt_state["resident"],
                                              p_res, lr)
            new_res = jax.tree.map(lambda n, o: jnp.where(finite, n, o),
                                   new_res, p_res)
            new_opt_res = jax.tree.map(
                lambda n, o: jnp.where(finite, n.astype(o.dtype), o),
                new_opt_res, opt_state["resident"])

            new_layers, new_opt_stream = streamed_update(
                opt.update, g_layers, opt_state["stream"], p_layers, lr,
                scale=inv * coef, gate=finite)

            new_params = {**new_res, "layers": new_layers}
            new_opt = {"resident": new_opt_res, "stream": new_opt_stream}
            return (new_params, new_opt, ls_advance(finite, ls_state),
                    grad_norm, finite)

        def accum_grads(params, batch_stack, scale):
            """Scan gas micro-batches, accumulating fp32 grads under the
            grad shardings (shared by train_step and the SuperOffload
            grads_batch so the accumulation semantics cannot drift)."""
            zeros = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
            zeros = lax.with_sharding_constraint(zeros, grad_shardings)

            def body(carry, mb):
                grad_acc, loss_acc = carry
                loss, grads = micro_grads(params, mb, scale)
                grad_acc = jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                                        grad_acc, grads)
                grad_acc = lax.with_sharding_constraint(grad_acc, grad_shardings)
                return (grad_acc, loss_acc + loss), None

            (grads, loss_sum), _ = lax.scan(
                body, (zeros, jnp.float32(0.0)), batch_stack)
            return grads, loss_sum

        # -- explicit quantized DP gradient reduction (comm_quantization;
        # comm/quantized.py) -------------------------------------------
        cq = self._comm_quant
        cq_ef = self._comm_quant_state is not None
        if cq is not None:
            from deepspeed_tpu.comm.quantized import quantized_all_reduce
            from deepspeed_tpu.parallel.topology import BATCH_AXES as _Q_AXES
            from deepspeed_tpu.utils.jax_compat import shard_map as _shard_map

            q_world = topo.dp_size
            q_pad = self._comm_quant_padded
            q_wire, q_gs = cq.grad_reduce, cq.group_size
            q_param_specs = jax.tree.map(lambda s: s.spec,
                                         self.param_shardings)
            q_grad_out_specs = jax.tree.map(lambda _: P(), q_param_specs,
                                            is_leaf=lambda x: isinstance(x, P))

            def accum_grads_quant(params, batch_stack, scale, residual):
                """Explicit-reduce variant of accum_grads: gradients
                accumulate LOCALLY inside a shard_map over the DP axes (no
                implicit GSPMD reduction), then ONE quantized all-reduce
                moves the flat buffer — int8/fp8 payload + fp32 block
                scales on the wire, fp32 accumulation, optional LoCo-style
                error-feedback residual carried across steps."""
                batch_specs = _stacked_batch_specs(batch_stack, _Q_AXES)
                err_spec = P(_Q_AXES) if cq_ef else P()

                def local(params, batch_stack, scale, res):
                    def body(carry, mb):
                        grad_acc, loss_acc = carry
                        loss, grads = micro_grads(params, mb, scale)
                        grad_acc = jax.tree.map(
                            lambda a, g: a + g.astype(jnp.float32),
                            grad_acc, grads)
                        return (grad_acc, loss_acc + loss), None

                    zeros = jax.tree.map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), params)
                    (grads, loss_sum), _ = lax.scan(
                        body, (zeros, jnp.float32(0.0)), batch_stack)
                    # local loss is a mean over this shard's rows; the
                    # pmean restores the global-batch mean
                    loss_sum = lax.pmean(loss_sum, _Q_AXES)
                    leaves, treedef = jax.tree.flatten(grads)
                    shapes = [x.shape for x in leaves]
                    sizes = [int(np.prod(s)) for s in shapes]
                    flat = jnp.concatenate([jnp.ravel(x) for x in leaves])
                    flat = jnp.pad(flat, (0, q_pad - flat.size))
                    # the residual is stored in UNSCALED grad units — the
                    # flat buffer carries the fp16 loss-scale factor, and
                    # a dynamic-scale change between steps would otherwise
                    # mis-weight the carried compensation by old/new
                    avg, new_r = quantized_all_reduce(
                        flat, _Q_AXES, q_world, wire_dtype=q_wire,
                        group_size=q_gs,
                        residual=res[0] * scale if cq_ef else None)
                    out, off = [], 0
                    for shape, size in zip(shapes, sizes):
                        out.append(avg[off:off + size].reshape(shape))
                        off += size
                    new_res = (new_r / scale)[None] if cq_ef else res
                    return jax.tree.unflatten(treedef, out), loss_sum, new_res

                res_in = residual if cq_ef else jnp.zeros((1, 1), jnp.float32)
                mapped = _shard_map(
                    local, mesh=topo.mesh,
                    in_specs=(q_param_specs, batch_specs, P(), err_spec),
                    out_specs=(q_grad_out_specs, P(), err_spec),
                    check_vma=False)
                grads, loss_sum, new_res = mapped(params, batch_stack, scale,
                                                  res_in)
                # stage-2 configs keep their sharded grad layout downstream
                # (slicing a replicated mean is local — no extra comm)
                grads = lax.with_sharding_constraint(grads, grad_shardings)
                return grads, loss_sum, new_res

        def train_step(params, opt_state, ls_state, batch_stack, lr):
            """One full train batch: scan over gas micro-batches + update.
            micro_grads returns grads of scale·loss; apply_update divides the
            accumulated sum by scale·gas."""
            grads, loss_sum = accum_grads(params, batch_stack, ls_state["scale"])
            new_params, new_opt, new_ls, grad_norm, finite = apply_update(
                params, opt_state, grads, lr, ls_state)
            metrics = {"loss": loss_sum / gas, "grad_norm": grad_norm,
                       "loss_scale": ls_state["scale"],
                       "skipped": jnp.logical_not(finite)}
            return new_params, new_opt, new_ls, metrics

        def stream_train_step(params, opt_state, ls_state, batch_stack, lr):
            """ZeRO-Infinity train batch: layer gradients accumulate
            host-resident via slice-wise adds — no full-size device
            gradient buffer ever exists.  The gas loop is a lax.scan so the
            compiled program stays O(1) in gradient_accumulation_steps."""
            from deepspeed_tpu.runtime.infinity import streamed_tree_add, to_host

            p_layers, p_res = split_layers(params)
            zeros_l = to_host(jax.tree.map(
                lambda x: jnp.zeros(x.shape, jnp.float32), p_layers))
            zeros_r = jax.tree.map(
                lambda x: jnp.zeros(x.shape, jnp.float32), p_res)

            def body(carry, mb):
                g_layers, g_res, loss_acc = carry
                loss, grads = micro_grads(params, mb, ls_state["scale"])
                gl, gr = split_layers(grads)
                g_res = jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                                     g_res, gr)
                g_layers = streamed_tree_add(g_layers, gl)
                return (g_layers, g_res, loss_acc + loss), None

            (g_layers, g_res, loss_sum), _ = lax.scan(
                body, (zeros_l, zeros_r, jnp.float32(0.0)), batch_stack)
            new_params, new_opt, new_ls, grad_norm, finite = \
                stream_apply_update(params, opt_state, g_layers, g_res, lr,
                                    ls_state)
            metrics = {"loss": loss_sum / gas, "grad_norm": grad_norm,
                       "loss_scale": ls_state["scale"],
                       "skipped": jnp.logical_not(finite)}
            return new_params, new_opt, new_ls, metrics

        if self._param_stream:
            train_step = stream_train_step

        if cq is not None:
            def _quant_step_core(params, opt_state, ls_state, batch_stack,
                                 lr, cq_res):
                """One comm-quant train batch: grads → explicit quantized
                reduce → the shared update; the residual rides the step
                signature so one jitted program owns the whole thing."""
                grads, loss_sum, new_res = accum_grads_quant(
                    params, batch_stack, ls_state["scale"], cq_res)
                new_params, new_opt, new_ls, grad_norm, finite = \
                    apply_update(params, opt_state, grads, lr, ls_state)
                metrics = {"loss": loss_sum / gas, "grad_norm": grad_norm,
                           "loss_scale": ls_state["scale"],
                           "skipped": jnp.logical_not(finite)}
                return new_params, new_opt, new_ls, new_res, metrics, finite

            if cq_ef:
                def train_step(params, opt_state, ls_state, cq_res,  # noqa: F811
                               batch_stack, lr):
                    new_params, new_opt, new_ls, new_res, metrics, finite = \
                        _quant_step_core(params, opt_state, ls_state,
                                         batch_stack, lr, cq_res)
                    # an overflow-skipped step must not poison the carried
                    # residual (its compensation buffer contains the very
                    # inf/NaN grads that made the step skip) — keep the
                    # previous residual, matching the params/opt rollback
                    new_res = jnp.where(finite, new_res, cq_res)
                    return new_params, new_opt, new_ls, new_res, metrics
            else:
                def train_step(params, opt_state, ls_state,  # noqa: F811
                               batch_stack, lr):
                    new_params, new_opt, new_ls, _, metrics, _ = \
                        _quant_step_core(params, opt_state, ls_state,
                                         batch_stack, lr, None)
                    return new_params, new_opt, new_ls, metrics

        if self._fused_rs:
            # -- fused reduce-scatter epilogue (step_schedule block;
            # eligibility decided in __init__) ------------------------
            from deepspeed_tpu.parallel.topology import BATCH_AXES as _RS_AXES
            from deepspeed_tpu.utils.jax_compat import \
                shard_map as _rs_shard_map

            rs_world = topo.dp_size
            rs_param_specs = jax.tree.map(lambda s: s.spec,
                                          self.param_shardings)
            rs_grad_specs = jax.tree.map(lambda s: s.spec,
                                         self.grad_shardings)

            def accum_grads_fused_rs(params, batch_stack, scale):
                """Decomposed-update variant of accum_grads: gradients
                accumulate LOCALLY inside a shard_map over the DP axes
                (no implicit GSPMD reduction), and the accumulation
                epilogue issues ONE explicit psum_scatter per leaf into
                the always-fsdp grad layout — the scatter consumes the
                local accumulator in place and the 1/world update
                (apply_update) runs on the shard it returns."""
                batch_specs = _stacked_batch_specs(batch_stack, _RS_AXES)

                def local(params, batch_stack, scale):
                    def body(carry, mb):
                        grad_acc, loss_acc = carry
                        loss, grads = micro_grads(params, mb, scale)
                        grad_acc = jax.tree.map(
                            lambda a, g: a + g.astype(jnp.float32),
                            grad_acc, grads)
                        return (grad_acc, loss_acc + loss), None

                    zeros = jax.tree.map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), params)
                    (grads, loss_sum), _ = lax.scan(
                        body, (zeros, jnp.float32(0.0)), batch_stack)
                    # local loss is a mean over this shard's rows; the
                    # pmean restores the global-batch mean
                    loss_sum = lax.pmean(loss_sum, _RS_AXES)

                    def scatter(g, spec):
                        dims = [i for i, s in enumerate(spec)
                                if s is not None]
                        if not dims:
                            # indivisible leaf: the fsdp layout kept it
                            # replicated, so the reduce stays a mean
                            return lax.pmean(g, _RS_AXES)
                        return lax.psum_scatter(
                            g, _RS_AXES, scatter_dimension=dims[0],
                            tiled=True) / rs_world

                    grads = jax.tree.map(scatter, grads, rs_grad_specs)
                    return grads, loss_sum

                mapped = _rs_shard_map(
                    local, mesh=topo.mesh,
                    in_specs=(rs_param_specs, batch_specs, P()),
                    out_specs=(rs_grad_specs, P()),
                    check_vma=False)
                return mapped(params, batch_stack, scale)

            def train_step(params, opt_state, ls_state,  # noqa: F811
                           batch_stack, lr):
                grads, loss_sum = accum_grads_fused_rs(
                    params, batch_stack, ls_state["scale"])
                new_params, new_opt, new_ls, grad_norm, finite = \
                    apply_update(params, opt_state, grads, lr, ls_state)
                metrics = {"loss": loss_sum / gas, "grad_norm": grad_norm,
                           "loss_scale": ls_state["scale"],
                           "skipped": jnp.logical_not(finite)}
                return new_params, new_opt, new_ls, metrics

        if self._super_opt is not None:
            # SuperOffload path: device computes grads + norm + finite in
            # one jit; the optimizer step runs on the host (pipelined
            # bucketed Adam), so no fused device update is compiled.
            def grads_batch(params, batch_stack, scale):
                grads, loss_sum = accum_grads(params, batch_stack, scale)
                gn = _global_norm(grads)
                # match apply_update's semantics: only fp16 runs skip on
                # overflow — fp32/bf16 NaNs must land in params and be
                # visible, not silently stall training by skipping forever
                finite = (_all_finite(grads) & jnp.isfinite(gn)) if fp16 \
                    else jnp.bool_(True)
                return loss_sum / gas, grads, gn, finite

            self._grads_batch_jit = jax.jit(
                grads_batch,
                out_shardings=(self._replicated, self.grad_shardings,
                               self._replicated, self._replicated))

        if self._opt_store is not None and not self._param_stream:
            # Pipelined-swap split: grads need no optimizer state, so the
            # store read can drain while this compiles/runs; apply_step
            # then consumes the prefetched state (train_batch split path).
            def grads_batch_store(params, batch_stack, scale):
                grads, loss_sum = accum_grads(params, batch_stack, scale)
                return loss_sum / gas, grads

            self._grads_batch_store_jit = jax.jit(
                grads_batch_store,
                out_shardings=(self._replicated, self.grad_shardings))

        metrics_sh = jax.tree.map(
            lambda _: self._replicated,
            {"loss": 0, "grad_norm": 0, "loss_scale": 0, "skipped": 0})
        if cq is not None and cq_ef:
            state_out = (self.param_shardings, self.opt_shardings,
                         self._replicated, self._comm_quant_res_sharding,
                         metrics_sh)
            donate = (0, 1, 2, 3)
        else:
            state_out = (self.param_shardings, self.opt_shardings,
                         self._replicated, metrics_sh)
            donate = (0, 1, 2)
        self._train_step_jit = jax.jit(
            train_step,
            donate_argnums=donate,
            out_shardings=state_out)

        def micro_step(params, grad_acc, batch, scale):
            loss, grads = micro_grads(params, batch, scale)
            if self._param_stream:
                from deepspeed_tpu.runtime.infinity import streamed_tree_add

                gl, gr = split_layers(grads)
                al, ar = split_layers(grad_acc)
                ar = jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                                  ar, gr)
                return loss, {**ar, "layers": streamed_tree_add(al, gl)}
            grad_acc = jax.tree.map(lambda a, g: a + g.astype(jnp.float32), grad_acc, grads)
            grad_acc = lax.with_sharding_constraint(grad_acc, grad_shardings)
            return loss, grad_acc

        self._micro_step_jit = jax.jit(
            micro_step, donate_argnums=(1,),
            out_shardings=(self._replicated, self.grad_shardings))

        def apply_step(params, opt_state, ls_state, grads, lr):
            if self._param_stream:
                gl, gr = split_layers(grads)
                new_params, new_opt, new_ls, grad_norm, finite = \
                    stream_apply_update(params, opt_state, gl, gr, lr,
                                        ls_state)
            else:
                new_params, new_opt, new_ls, grad_norm, finite = apply_update(
                    params, opt_state, grads, lr, ls_state)
            metrics = {"grad_norm": grad_norm, "loss_scale": ls_state["scale"],
                       "skipped": jnp.logical_not(finite)}
            if self._param_stream:
                return new_params, new_opt, new_ls, metrics
            # Return the DONATED grad buffer zeroed in place: without a
            # same-shaped output the donation could never be honored
            # (params/opt/ls already claim the other aliases — graph
            # auditor finding `donation_miss`), so the full fp32 gradient
            # tree stayed live across the update AND the next
            # accumulation round re-materialized a fresh zeros tree,
            # unsharded on one device, before resharding it.  Now the
            # alias is real (a memset, no allocation) and step()/forward()
            # recycle the buffer instead.
            zero_grads = jax.tree.map(jnp.zeros_like, grads)
            return new_params, new_opt, new_ls, zero_grads, metrics

        metrics3_sh = jax.tree.map(
            lambda _: self._replicated,
            {"grad_norm": 0, "loss_scale": 0, "skipped": 0})
        if self._param_stream:
            apply_out = (self.param_shardings, self.opt_shardings,
                         self._replicated, metrics3_sh)
            # no grad-shaped output exists to alias (the streamed grads
            # are consumed layer-wise), so donating grads could never be
            # honored — same pigeonhole as apply_step_store
            apply_donate = (0, 1, 2)
            self._zero_grads_jit = None
        else:
            apply_out = (self.param_shardings, self.opt_shardings,
                         self._replicated, self.grad_shardings, metrics3_sh)
            apply_donate = (0, 1, 2, 3)
            gshapes = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, jnp.float32),
                self.params)
            # cold-start grad buffer born IN the accumulator sharding —
            # the eager zeros + device_put it replaces held the whole
            # unsharded fp32 tree on one device first
            self._zero_grads_jit = jax.jit(
                lambda: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), gshapes),
                out_shardings=self.grad_shardings)
        self._apply_step_jit = jax.jit(
            apply_step, donate_argnums=apply_donate,
            out_shardings=apply_out)

        def apply_step_store(params, opt_state, ls_state, grads, lr):
            """Overlapped opt-store variant: grads arrive fresh from
            `_grads_batch_store_jit` each step and are never recycled,
            so skip apply_step's zero-grads output (a full-tree memset)
            — and don't donate a buffer no output can alias (4 input
            trees, 3 outputs: the pigeonhole leaves grads over)."""
            new_params, new_opt, new_ls, grad_norm, finite = apply_update(
                params, opt_state, grads, lr, ls_state)
            metrics = {"grad_norm": grad_norm,
                       "loss_scale": ls_state["scale"],
                       "skipped": jnp.logical_not(finite)}
            return new_params, new_opt, new_ls, metrics

        self._apply_step_store_jit = jax.jit(
            apply_step_store, donate_argnums=(0, 1, 2),
            out_shardings=(self.param_shardings, self.opt_shardings,
                           self._replicated, metrics3_sh))

        def eval_step(params, batch):
            return loss_fn(params, batch)

        self._eval_step_jit = jax.jit(eval_step, out_shardings=self._replicated)

    # ------------------------------------------------------------------
    # NVMe optimizer-state swapping (ZeRO-Infinity)
    # ------------------------------------------------------------------
    def _opt_store_read(self):
        """All opt-store reads funnel here: join an in-flight prefetch if
        one exists (the AIO handle is single-owner; concurrent use from
        two threads is not allowed), else read synchronously."""
        fut, self._opt_fut = self._opt_fut, None
        return fut.result() if fut is not None else self._opt_store.swap_in()

    def _param_store_read(self):
        fut, self._param_fut = self._param_fut, None
        return fut.result() if fut is not None \
            else self._param_store.swap_in()

    def _prefetch_stores(self) -> None:
        """Queue the next step's store reads behind the writes just issued
        (ref pipelined_optimizer_swapper.py:26 + async_swapper.py:19): the
        swapper's swap_in drains pending writes then reads, all on a worker
        thread, overlapping the host's dispatch of the next step."""
        if self._swap_pool is None:
            return
        if self._opt_store is not None and self._opt_fut is None:
            self._opt_fut = self._swap_pool.submit(self._opt_store.swap_in)
        if self._param_store is not None and self._param_fut is None:
            self._param_fut = self._swap_pool.submit(
                self._param_store.swap_in)

    def _cancel_prefetch(self) -> None:
        """Join and discard in-flight prefetches — required before any
        out-of-band store write (checkpoint load) so the stale read result
        is never consumed.  Errors are swallowed: the result is discarded
        by construction, and the caller is usually about to overwrite the
        very state the failed read targeted."""
        global _DISCARDED_PREFETCH_WARNED
        for name in ("_opt_fut", "_param_fut"):
            fut = getattr(self, name, None)
            if fut is not None:
                try:
                    fut.result()
                except Exception as e:
                    if not _DISCARDED_PREFETCH_WARNED:
                        _DISCARDED_PREFETCH_WARNED = True
                        logger.warning(
                            f"discarded prefetch failed: {e} (further "
                            "discarded-prefetch failures are not logged)")
                setattr(self, name, None)

    def destroy(self) -> None:
        """Release background resources (swap worker pool, in-flight
        prefetches).  Call when done training — the last step always
        leaves one speculative store read in flight (whose NVMe buffer
        stays pinned until consumed).  Ref DeepSpeedEngine.destroy."""
        # the recycled (trio-path) grad accumulator persists between
        # steps by design — that is what lets apply_step alias it in
        # place — but must not outlive training
        self._grad_buffer = None
        self._cancel_prefetch()
        ce = self._checkpoint_engine
        if ce is not None and hasattr(ce, "wait"):
            # an async writer (orbax/decoupled) publishes meta.json + the
            # `latest` pointer only at wait() — without this, the run's
            # FINAL save would stream all its shards and still be
            # unloadable because its commit point never ran
            try:
                ce.wait()
            except Exception as e:
                logger.warning(f"checkpoint writer wait() failed during "
                               f"destroy: {e}")
        if self._watchdog is not None:
            self._watchdog.stop()
        if self.telemetry is not None and sys.exc_info()[0] is not None:
            # destroy() running while an exception propagates (the usual
            # `finally: engine.destroy()` after a crashed step): leave
            # forensics behind — same bundle the hang watchdog writes.
            # Deliberately conservative: exc_info is also set inside an
            # `except:` handler that already recovered, so a handled-
            # error teardown writes a (harmless) bundle too — a spare
            # bundle is noise, a missing one on a real crash is not.
            self.telemetry.dump_flight("engine_crash",
                                       error=sys.exc_info()[1])
        if self._trace_profiler is not None:
            self._trace_profiler.close()  # flush a capture cut short
        if self.telemetry is not None:
            self.telemetry.close()  # flush jsonl + trace + capture
            from deepspeed_tpu.utils.comms_logging import get_comms_logger

            get_comms_logger().enabled = self._comms_prev_enabled
        if self._swap_pool is not None:
            self._swap_pool.shutdown(wait=True)
            self._swap_pool = None
        so = self._super_opt
        if so is not None and hasattr(so, "close"):
            so.close()  # chunked pipeline: drain d2h/h2d pools + NVMe IO

    def __del__(self):  # best-effort: destroy() is the real API
        try:
            if getattr(self, "_swap_pool", None) is not None:
                self._swap_pool.shutdown(wait=False)
        except Exception:
            pass

    def _swap_in_opt_state(self):
        if self._opt_store is None:
            return self.opt_state
        return jax.device_put(self._opt_store_read(),
                              self._opt_device_shardings)

    def _swap_out_opt_state(self, opt_state) -> None:
        if self._opt_store is None:
            self.opt_state = opt_state
            return
        self._opt_store.swap_out(opt_state)
        self.opt_state = None

    def _swap_in_params(self) -> None:
        """NVMe param tier (ZeRO-Infinity): stage the layer weights
        NVMe → host pinned RAM for this step (ref
        partitioned_param_swapper.py:37)."""
        if self._param_store is None or self.params.get("layers") is not None:
            return
        layers = jax.device_put(self._param_store_read(),
                                self.param_shardings["layers"])
        self.params = {**self.params, "layers": layers}

    def _swap_out_params(self) -> None:
        if self._param_store is None:
            return
        self._param_store.swap_out(self.params["layers"])
        self.params = {**self.params, "layers": None}

    def offload_states(self, include=None) -> None:
        """Move params/optimizer state to host RAM (ref offload_states.py:90)."""
        from deepspeed_tpu.runtime.offload import offload_states as _off

        _off(self, include)

    def reload_states(self, include=None) -> None:
        from deepspeed_tpu.runtime.offload import reload_states as _rl

        _rl(self, include)

    # ------------------------------------------------------------------
    # Batch handling
    # ------------------------------------------------------------------
    def _batch_sharding_for(self, arr, stacked: bool) -> NamedSharding:
        ndim = np.ndim(arr)
        spec: list = [None] * ndim
        batch_dim = 1 if stacked else 0
        seq_dim = batch_dim + 1
        if ndim > batch_dim:
            spec[batch_dim] = BATCH_AXES
        if ndim > seq_dim and self.topology.sp_size > 1:
            spec[seq_dim] = SEQ_AXIS
        return NamedSharding(self.topology.mesh, P(*spec))

    def _put_batch(self, batch: Batch, stacked: bool) -> Batch:
        if stacked and "input_ids" in batch:
            # token count for this train batch (telemetry tokens/s) —
            # shape-only, so curriculum truncation is accounted exactly
            self._last_batch_tokens = int(
                np.prod(np.shape(batch["input_ids"])))
        out = {}
        for k, v in batch.items():
            if k == "dropout_key":
                # [gas, 2] PRNG keys: replicated (the [gas] axis is the
                # accumulation scan, dim 1 is key data — not batch rows)
                sh = NamedSharding(self.topology.mesh, P())
            else:
                sh = self._batch_sharding_for(v, stacked)
            out[k] = jax.device_put(np.asarray(v), sh)
        return out

    def _stack_micro_batches(self, data) -> Batch:
        """Accept a stacked batch dict [gas*dp*micro, ...], a dict already
        shaped [gas, dp*micro, ...], or an iterator of micro-batches."""
        gas = self.gradient_accumulation_steps_value
        if isinstance(data, dict):
            first = next(iter(data.values()))
            n = np.shape(first)[0]
            per_step = self.micro_batch_size * self.topology.dp_size
            if n == gas and np.ndim(first) >= 2 and np.shape(first)[1] == per_step:
                return self._maybe_stripe_ring(data, seq_axis=2)
            if n != gas * per_step:
                raise ValueError(
                    f"batch dim {n} != gas({gas}) * micro*dp({per_step})")
            return self._maybe_stripe_ring(
                {k: np.asarray(v).reshape((gas, per_step) + np.shape(v)[1:])
                 for k, v in data.items()}, seq_axis=2)
        # iterator of micro-batches
        micros = [next(data) for _ in range(gas)]
        return self._maybe_stripe_ring(
            {k: np.stack([np.asarray(m[k]) for m in micros], axis=0)
             for k in micros[0]}, seq_axis=2)

    def _maybe_stripe_ring(self, batch, seq_axis: int):
        """Striped ring placement (model cfg ring_placement="striped"):
        permute sequence-axis batch arrays into the stripe order the
        model's positions assume — shard r of the seq mesh then owns
        tokens r, r+sp, … and every causal ring hop is load-balanced
        (sequence/ring.py).  Host-side numpy: the permutation costs no
        device collectives, and labels ride the same order so the loss
        pairing is untouched."""
        mc = self.model_config
        if (mc is None or getattr(mc, "seq_impl", None) != "ring"
                or getattr(mc, "ring_placement", None) != "striped"
                or self.topology.sp_size <= 1):
            return batch
        from deepspeed_tpu.sequence.ring import stripe_sequence

        sp = self.topology.sp_size
        out = dict(batch)
        for k in ("input_ids", "labels", "attention_mask",
                  "token_type_ids", "position_ids"):
            v = out.get(k)
            if v is not None and np.ndim(v) > seq_axis \
                    and np.shape(v)[seq_axis] % sp == 0:
                out[k] = stripe_sequence(np.asarray(v), sp, axis=seq_axis)
        return out

    def _apply_curriculum(self, data):
        """Truncate seq-dim batch keys to the curriculum's current
        difficulty (seqlen curricula only)."""
        if self.curriculum_scheduler is None or self._curriculum_type != "seqlen":
            return data
        seqlen = self.curriculum_scheduler.update_difficulty(self.global_steps)
        # a difficulty change means new traced shapes → an implicit XLA
        # recompile at dispatch; don't let the watchdog count it as a stall
        if getattr(self, "_last_curriculum_seqlen", None) not in (None, seqlen):
            self._watchdog_expect_compile()
        self._last_curriculum_seqlen = seqlen

        def trunc(batch):
            out = {}
            for k, v in batch.items():
                if k in ("input_ids", "labels", "attention_mask",
                         "position_ids") and np.ndim(v) >= 2 \
                        and np.shape(v)[1] > seqlen:
                    out[k] = v[:, :seqlen]
                else:
                    out[k] = v
            return out

        if isinstance(data, dict):
            return trunc(data)
        if isinstance(data, (list, tuple)):
            return type(data)(trunc(b) if isinstance(b, dict) else b for b in data)
        return data

    def _maybe_recompile_compression(self) -> None:
        """Re-jit when the compression schedule flips a technique on/off
        (the step gate inside apply() is python-static; ref
        compression/scheduler.py schedule_offset)."""
        if self._compression is None:
            return
        if self._compression.active_signature(self.global_steps) \
                != self._compression_sig:
            self._compile_steps()

    def _maybe_update_random_ltd(self) -> None:
        """Raise the model's kept-token count per the LTD schedule; a value
        change swaps the model config and re-jits the step (the bounded
        recompile the reference pays as a reshape)."""
        if self.random_ltd_scheduler is None:
            return
        kept = self.random_ltd_scheduler.update(self.global_steps)
        # reaching the schedule's max means full-sequence training resumes
        effective = 0 if kept >= self.random_ltd_scheduler.max_value else kept
        if effective == self.model_config.ltd_kept:
            return
        from functools import partial as _partial

        from deepspeed_tpu.models import transformer as tf_model

        start, end = self._ltd_band
        self.model_config = self.model_config.replace(
            ltd_kept=effective, ltd_start=start, ltd_end=end)
        self._loss_fn = _partial(tf_model.loss_fn, cfg=self.model_config)
        self._compile_steps()
        log_dist(f"random-ltd: kept seqlen → "
                 f"{effective if effective else 'full'}")

    def _maybe_add_pld(self, batch_stack):
        """Attach the PLD keep-prob to the stacked batch (traced scalar —
        the theta schedule never forces a recompile)."""
        if self.progressive_layer_drop is None:
            return batch_stack
        theta = self.progressive_layer_drop.update_state(self.global_steps)
        gas = next(iter(batch_stack.values())).shape[0]
        # copy: _stack_micro_batches can return the caller's own dict
        return {**batch_stack,
                "pld_theta": np.full((gas,), theta, np.float32)}

    def _maybe_add_dropout_key(self, batch_stack):
        """Attach per-micro-batch PRNG keys when the model needs training
        randomness (cfg.dropout > 0 or a noisy MoE gate policy).  Keys
        are data, not trace constants —
        every step reuses the one compiled program.  Inference/eval paths
        never thread a key, so dropout is identically off there.
        Returns a COPY: _stack_micro_batches can hand back the caller's
        own dict, which must not grow a dropout_key entry."""
        mc = self.model_config
        needs_key = mc is not None and (
            getattr(mc, "dropout", 0.0) > 0.0
            or getattr(mc, "moe_noisy_gate_policy", None))
        if not needs_key:
            return batch_stack
        if not hasattr(self, "_dropout_base_key"):
            self._dropout_base_key = jax.random.PRNGKey(self.seed + 7919)
        step_key = jax.random.fold_in(self._dropout_base_key,
                                      self.global_steps)
        gas = next(iter(batch_stack.values())).shape[0]
        keys = np.asarray(jax.vmap(jax.random.fold_in, (None, 0))(
            step_key, np.arange(gas)))  # one dispatch, one fetch
        return {**batch_stack, "dropout_key": keys}

    # ------------------------------------------------------------------
    # Public API (DeepSpeed parity)
    # ------------------------------------------------------------------
    def train_batch(self, data) -> jnp.ndarray:
        """Run one full train batch (gas micro-batches + optimizer step).
        Ref: PipelineEngine.train_batch / engine forward+backward+step."""
        tel = self.telemetry
        cap = tel.capture if tel is not None else None
        if cap is not None:
            cap.on_step_start(self.global_steps + 1)
        tr = self._tracer
        self._step_span = sp = tr.span("train.step", self._train_trace_id)
        if tr.enabled:
            sp.set(step=self.global_steps + 1)
        wd = self._watchdog
        if wd is not None and self._compiled_step_done:
            wd.resume()     # arm for this step (no-op deadline otherwise)
        t0 = time.perf_counter()
        try:
            if self._trace_profiler is not None:
                step = self.global_steps + 1
                self._trace_profiler.maybe_start(step)
                with self._trace_profiler.step(step):
                    loss = self._train_batch_traced_body(data)
                self._trace_profiler.maybe_stop(self.global_steps + 1)
            else:
                loss = self._train_batch_traced_body(data)
            if tel is not None:
                self._emit_telemetry(tel, t0)
                if cap is not None:
                    # next_step: global_steps already advanced in the body
                    cap.on_step_end(self.global_steps + 1)
        finally:
            sp.end()
            self._step_span = None
            if wd is not None:
                wd.beat()
                wd.pause()  # inter-step time is not a stall
        self._compiled_step_done = True
        return loss

    # ------------------------------------------------------------------
    # Telemetry (unified per-step StepRecord; telemetry/)
    # ------------------------------------------------------------------
    def _step_flops(self, step_args=None):
        """FLOPs for one whole train batch on this device: XLA cost
        analysis of the compiled step when args are at hand (exact for
        the fused program), analytic model profile fallback.

        profile_compiled pays one extra AOT compile (lower().compile()
        does not share the jit dispatch cache) — once per process, at
        the first recorded step; a flops_profiler run that already
        measured is reused instead."""
        prof = self._last_flops_profile
        if prof and prof.get("flops"):
            return float(prof["flops"]), "measured"
        if step_args is not None and self.config.telemetry.measure_flops:
            try:
                from deepspeed_tpu.profiling.flops_profiler import \
                    profile_compiled

                prof = profile_compiled(self._train_step_jit, *step_args)
                if self.telemetry is not None and prof.get("memory"):
                    # static-memory handshake: the same one-time AOT
                    # compile that prices flops also reads XLA's memory
                    # plan — capture reports diff the runtime HBM
                    # watermarks against it (report.json `hbm` block)
                    self.telemetry.set_static_memory(
                        {"backend": jax.default_backend(),
                         **prof["memory"]})
                if prof.get("flops"):
                    return float(prof["flops"]), "measured"
            except Exception as e:
                logger.warning(f"telemetry: profile_compiled failed "
                               f"({e}); using the analytic profile")
        if self.model_config is not None:
            from deepspeed_tpu.profiling.flops_profiler import \
                get_model_profile

            prof = get_model_profile(
                self.model_config, self.micro_batch_size,
                getattr(self.model_config, "max_seq_len", 0),
                recompute_fwd_factor=self.config.flops_profiler
                .recompute_fwd_factor)
            return (prof["total_flops_per_step"]
                    * self.gradient_accumulation_steps_value, "analytic")
        return 0.0, "none"

    def _emit_telemetry(self, tel, t0: float) -> None:
        """Assemble this step's StepRecord.  Fetching the loss value is a
        hard host sync — the price of a record; off-interval steps skip
        the whole assembly (sync included), except when a regression-
        triggered capture needs every step time (tel.should_record)."""
        if not tel.should_record(self.global_steps):
            return
        metrics = self._last_metrics
        if not tel.is_full_record_step(self.global_steps):
            # regression-trigger bookkeeping only (capture still has
            # budget): sync so the wall time is real, feed the trailing
            # window, skip record assembly and export
            with self._tracer.span("train.sync", self._train_trace_id,
                                   self._step_span):
                np.asarray(metrics["loss"])
            tel.observe_step_time(time.perf_counter() - t0)
            return
        if tel.needs_flops():     # paths without step args: analytic
            tel.set_flops(*self._step_flops(None))

        def _f(key):
            v = metrics.get(key)
            return None if v is None else float(np.asarray(v))

        with self._tracer.span("train.sync", self._train_trace_id,
                               self._step_span):
            # fetching the loss VALUE is the hard host sync — its span is
            # the "how much overlap did the record cost" number
            loss = _f("loss")
        wall = time.perf_counter() - t0
        skipped = metrics.get("skipped")
        with self._tracer.span("train.telemetry", self._train_trace_id,
                               self._step_span):
            tel.record_train_step(
                step=self.global_steps, wall_time_s=wall,
                tokens=self._last_batch_tokens, loss=loss,
                grad_norm=_f("grad_norm"),
                lr=float(self.lr_scheduler(self.global_steps - 1)),
                loss_scale=_f("loss_scale"),
                skipped=bool(np.asarray(skipped)) if skipped is not None
                else False,
                comm=self._comm_delta(),
                offload_overlap_fraction=getattr(
                    self, "_last_offload_overlap", None))

    def _comm_delta(self):
        """Comm volume since THIS engine's construction (the CommsLogger
        is process-global; the raw cumulative totals would include a
        previous engine's traffic)."""
        from deepspeed_tpu.utils.comms_logging import get_comms_logger

        out = {}
        for op, cur in get_comms_logger().totals().items():
            base = self._comms_baseline.get(op, {"count": 0, "bytes": 0})
            count = cur["count"] - base["count"]
            nbytes = cur["bytes"] - base["bytes"]
            if count or nbytes:
                out[op] = {"count": count, "bytes": nbytes}
        return out

    def _train_step_args(self, opt_state, batch_stack, lr):
        """Argument tuple matching the active ``_train_step_jit``
        signature (the comm-quant error-feedback path threads its
        residual state between loss-scale state and the batch)."""
        if self._comm_quant_state is not None:
            return (self.params, opt_state, self.loss_scale_state,
                    self._comm_quant_state["residual"], batch_stack, lr)
        return (self.params, opt_state, self.loss_scale_state, batch_stack,
                lr)

    def audit_step_args(self, data=None):
        """``(jitted step, example args)`` for the static graph auditor
        (``analysis/auditor.py``) — everything needed to lower and
        compile the train step WITHOUT running it.  ``data`` defaults to
        a zero-filled batch of the configured geometry (the auditor only
        reads shapes).  Donated example buffers are never consumed: AOT
        ``lower()``/``compile()`` does not execute.

        Host-stepped paths are auditable too: with a SuperOffload/chunked
        optimizer mounted the device-side program IS the grads batch
        (params, batch stack, loss-scale scalar) — the Adam update runs
        on the host and owns no HBM; with an offload store the fused step
        is lowered against the store's state staged at the device
        shardings, exactly what the non-pipelined step path executes."""
        if data is None:
            mc = self.model_config
            if mc is None:
                raise ValueError("audit_step_args: no model_config to "
                                 "synthesize a batch from — pass data")
            rows = (self.micro_batch_size
                    * self.gradient_accumulation_steps_value
                    * self.topology.dp_size)
            seq = int(getattr(mc, "max_seq_len", 128)) or 128
            ids = np.zeros((rows, seq), np.int32)
            data = {"input_ids": ids, "labels": ids}
        batch_stack = self._stack_micro_batches(data)
        batch_stack = self._maybe_add_pld(batch_stack)
        batch_stack = self._maybe_add_dropout_key(batch_stack)
        batch_stack = self._put_batch(batch_stack, stacked=True)
        lr = jnp.float32(self.lr_scheduler(self.global_steps))
        if self._super_opt is not None:
            return (self._grads_batch_jit,
                    (self.params, batch_stack,
                     self.loss_scale_state["scale"]))
        opt_state = self.opt_state
        if self._opt_store is not None:
            opt_state = self._swap_in_opt_state()
        return (self._train_step_jit,
                self._train_step_args(opt_state, batch_stack, lr))

    def audit_arg_categories(self):
        """Memory-class manifest for the ``audit_step_args`` tuple — one
        ``analysis.MEMORY_CLASSES`` entry per top-level argument, in the
        exact ``_train_step_args`` order (the comm-quant error-feedback
        residual rides between loss-scale state and the batch), so the
        memory auditor can classify every flat parameter buffer by its
        tree-path subtree (the same name manifests the PartitionOracle
        exposes)."""
        if self._super_opt is not None:
            # grads-program signature: params, batch stack, scale scalar
            return ("params", "activations", "other")
        cats = ["params", "opt_state", "opt_state"]
        if self._comm_quant_state is not None:
            cats.append("grads")    # error-feedback residual, grad units
        cats += ["activations", "other"]   # batch stack, lr scalar
        return tuple(cats)

    def _train_batch_traced_body(self, data) -> jnp.ndarray:
        if self._onebit is not None:
            return self._train_batch_onebit(data)
        if self._super_opt is not None:
            return self._train_batch_super(data)
        data = self._apply_curriculum(data)
        self._maybe_update_random_ltd()
        self._maybe_recompile_compression()
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        with self._tracer.span("train.data_ingest", self._train_trace_id,
                               self._step_span):
            batch_stack = self._stack_micro_batches(data)
            batch_stack = self._maybe_add_pld(batch_stack)
            batch_stack = self._maybe_add_dropout_key(batch_stack)
            batch_stack = self._put_batch(batch_stack, stacked=True)
        lr = jnp.float32(self.lr_scheduler(self.global_steps))
        profiling = (self._flops_profiler is not None
                     and not self._flops_profiler.profile_done
                     and self.global_steps + 1
                     >= self.config.flops_profiler.profile_step)
        if (self._swap_pool is not None and self._opt_store is not None
                and not self._param_stream and not profiling):
            # Overlapped store path: dispatch the grads compute (needs no
            # optimizer state), then join the prefetched store read — the
            # NVMe/host transfer drains while the device computes, so step
            # time approaches max(compute, transfer) instead of the sum.
            self._swap_in_params()
            with self._tracer.span("train.dispatch", self._train_trace_id,
                                   self._step_span):
                loss, grads = self._grads_batch_store_jit(
                    self.params, batch_stack, self.loss_scale_state["scale"])
                opt_state = self._swap_in_opt_state()
                (self.params, opt_state, self.loss_scale_state,
                 metrics) = self._apply_step_store_jit(
                    self.params, opt_state, self.loss_scale_state, grads,
                    lr)
            metrics = {**metrics, "loss": loss}
        else:
            opt_state = self._swap_in_opt_state()
            self._swap_in_params()
            step_args = self._train_step_args(opt_state, batch_stack, lr)
            if self.telemetry is not None and self.telemetry.needs_flops():
                # before the step runs, while donated buffers are still
                # live (lowering reads their shapes); the compile() behind
                # profile_compiled is a one-time AOT cost — see _step_flops
                self.telemetry.set_flops(*self._step_flops(step_args))
            if profiling:
                self._last_flops_profile = \
                    self._flops_profiler.profile_engine_step(
                        self, *step_args)
                self._flops_profiler.print_profile(self._last_flops_profile)
            with self._tracer.span("train.dispatch", self._train_trace_id,
                                   self._step_span):
                if self._comm_quant_state is not None:
                    (self.params, opt_state, self.loss_scale_state,
                     self._comm_quant_state["residual"], metrics) = \
                        self._train_step_jit(*step_args)
                else:
                    self.params, opt_state, self.loss_scale_state, metrics = \
                        self._train_step_jit(*step_args)
        self._swap_out_opt_state(opt_state)
        self._swap_out_params()
        self._prefetch_stores()
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps_value
        self.lr_scheduler.step()
        self._after_step(metrics)
        self.timers(TRAIN_BATCH_TIMER).stop(ready=metrics["loss"])
        self.tput_timer.stop()
        return metrics["loss"]

    def _train_batch_super(self, data) -> jnp.ndarray:
        """SuperOffload train batch (ref superoffload_stage3.py): grads are
        computed in one compiled step; the optimizer runs on the host as a
        pipelined bucketed Adam (overflow skips the step; the rollback
        window additionally allows post-hoc recovery via engine.rollback)."""
        data = self._apply_curriculum(data)
        self._maybe_update_random_ltd()
        self._maybe_recompile_compression()
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        batch_stack = self._stack_micro_batches(data)
        batch_stack = self._maybe_add_pld(batch_stack)
        batch_stack = self._maybe_add_dropout_key(batch_stack)
        batch_stack = self._put_batch(batch_stack, stacked=True)
        self._swap_in_params()  # chunked mode can ride the NVMe param tier
        lr = float(self.lr_scheduler(self.global_steps))
        gas = self.gradient_accumulation_steps_value
        scale = self.loss_scale_state["scale"]
        # bookkeeping snapshot so rollback() can restore EVERYTHING the
        # step mutates (scheduler counter, loss scale, step counts), not
        # just the optimizer masters
        self._super_prev_bookkeeping = {
            "sched": self.lr_scheduler.state_dict(),
            "ls": self.loss_scale_state,
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
        }
        loss, grads, gn, finite = self._grads_batch_jit(
            self.params, batch_stack, scale)
        scale_v = float(np.asarray(scale))
        finite_v = bool(np.asarray(finite))
        inv = 1.0 / (scale_v * gas)
        gnorm = float(np.asarray(gn)) * inv
        clip = self.config.gradient_clipping
        coef = inv * (min(1.0, clip / (gnorm + 1e-6))
                      if clip and clip > 0 else 1.0)
        if finite_v:
            self._super_opt.lr = lr
            self.params = self._super_opt.step(self.params, grads,
                                               grad_scale=coef)
        self._super_last_skipped = not finite_v
        # chunked pipeline: how much of the d2h/h2d transfer time the host
        # Adam hid this step (None on plain SuperOffload → field omitted)
        self._last_offload_overlap = getattr(
            self._super_opt, "last_overlap_fraction", None)
        self._swap_out_params()
        self._prefetch_stores()
        self._advance_loss_scale_host(finite_v)
        self.global_steps += 1
        self.micro_steps += gas
        self.lr_scheduler.step()
        metrics = {"loss": loss, "grad_norm": gnorm, "loss_scale": scale_v,
                   "skipped": not finite_v}
        self._after_step(metrics)
        self.timers(TRAIN_BATCH_TIMER).stop(ready=loss)
        self.tput_timer.stop()
        return loss

    def rollback(self) -> None:
        """Undo the last SuperOffload optimizer step (host masters, moments,
        step counter) and restore the device params from the rolled-back
        masters — post-hoc overflow/divergence recovery (ref
        superoffload_stage3 rollback optimizer)."""
        if self._super_opt is None:
            raise RuntimeError("rollback requires SuperOffload mode "
                               "(offload_optimizer.super_offload)")
        if getattr(self, "_super_last_skipped", False):
            raise RuntimeError(
                "last train_batch was overflow-skipped (no optimizer step "
                "ran); the rollback snapshot belongs to an earlier step")
        bk = getattr(self, "_super_prev_bookkeeping", None)
        if bk is None:
            # No snapshot means there is no consistent state to revert the
            # scheduler/loss-scale/counters to; a partial revert (params
            # rolled back, bookkeeping not) would silently diverge.
            raise RuntimeError(
                "rollback requires a bookkeeping snapshot from a completed "
                "train_batch; none exists (no step has run since the last "
                "rollback or load)")
        self._super_opt.rollback()
        self.params = self._super_opt.push_params(self.params)
        self.lr_scheduler.load_state_dict(bk["sched"])
        self.loss_scale_state = bk["ls"]
        self.global_steps = bk["global_steps"]
        self.micro_steps = bk["micro_steps"]
        self._super_prev_bookkeeping = None

    def _advance_loss_scale_host(self, finite: bool) -> None:
        """Host-side entry to the SAME loss-scale policy the jitted step
        uses (_advance_loss_scale with xp=np) for step paths that decide on
        the host (SuperOffload)."""
        ls = {k: np.asarray(v) for k, v in self.loss_scale_state.items()}
        scale, good, skipped = _advance_loss_scale(
            ls["scale"], ls["good_steps"], ls["skipped"], np.bool_(finite),
            self._ls_dynamic, self._ls_window, self._ls_min, np)
        self.loss_scale_state = jax.device_put(
            {"scale": jnp.float32(float(scale)),
             "good_steps": jnp.int32(int(good)),
             "skipped": jnp.int32(int(skipped))},
            self._replicated)

    def _train_batch_onebit(self, data) -> jnp.ndarray:
        """Compressed-DP train batch: explicit shard_map step with 1-bit
        error-feedback momentum allreduce (ref onebit/adam.py step)."""
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.parallel.topology import BATCH_AXES

        data = self._apply_curriculum(data)
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        batch_stack = self._stack_micro_batches(data)
        batch_stack = self._maybe_add_dropout_key(batch_stack)
        batch_stack = self._put_batch(batch_stack, stacked=True)
        if not self._onebit._built:
            self._onebit.build(self.param_shardings,
                               _stacked_batch_specs(batch_stack,
                                                    BATCH_AXES))
        lr = jnp.float32(self.lr_scheduler(self.global_steps))
        self.params, self._onebit_state, loss = self._onebit(
            self.params, self._onebit_state, batch_stack, lr)
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps_value
        self.lr_scheduler.step()
        metrics = {"loss": loss}
        self._after_step(metrics)
        self.timers(TRAIN_BATCH_TIMER).stop(ready=loss)
        self.tput_timer.stop()
        return loss

    def forward(self, batch: Batch) -> jnp.ndarray:
        """Compute loss AND gradients for one micro-batch (accumulated).
        With XLA there is no separate autograd tape, so forward+backward fuse;
        ``backward`` is then bookkeeping only — same user-visible contract."""
        self.timers(FORWARD_GLOBAL_TIMER).start()
        self._swap_in_params()
        if self._grad_buffer is None:
            if self._zero_grads_jit is not None:
                # sharded from birth; also aliased-recycled from the
                # previous step() so this only runs on the cold start
                self._grad_buffer = self._zero_grads_jit()
            else:
                zeros = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), self.params)
                self._grad_buffer = jax.device_put(zeros, self.grad_shardings)
        mc = self.model_config
        if mc is not None and (getattr(mc, "dropout", 0.0) > 0.0
                               or getattr(mc, "moe_noisy_gate_policy", None)):
            # trio path gets its own per-micro key (train_batch's stacked
            # path attaches [gas, 2] keys via _maybe_add_dropout_key)
            if not hasattr(self, "_dropout_base_key"):
                self._dropout_base_key = jax.random.PRNGKey(self.seed + 7919)
            k = jax.random.fold_in(
                jax.random.fold_in(self._dropout_base_key, self.global_steps),
                100_000 + self._micro_in_step)
            batch = {**batch, "dropout_key": np.asarray(k)}
        batch = self._put_batch(batch, stacked=False)
        loss, self._grad_buffer = self._micro_step_jit(
            self.params, self._grad_buffer, batch, self.loss_scale_state["scale"])
        self._last_loss = loss
        self.timers(FORWARD_GLOBAL_TIMER).stop(ready=loss)
        return loss

    def backward(self, loss=None) -> None:
        """Gradients were produced in ``forward`` (fused). Advances the
        micro-step counter that defines the accumulation boundary."""
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        self._micro_in_step += 1
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._micro_in_step >= self.gradient_accumulation_steps_value

    def step(self) -> None:
        """Apply the optimizer step at the accumulation boundary."""
        self.timers(STEP_GLOBAL_TIMER).start()
        if not self.is_gradient_accumulation_boundary():
            self.timers(STEP_GLOBAL_TIMER).stop()
            return
        lr = jnp.float32(self.lr_scheduler(self.global_steps))
        opt_state = self._swap_in_opt_state()
        self._swap_in_params()
        if self._param_stream:
            (self.params, opt_state, self.loss_scale_state,
             metrics) = self._apply_step_jit(
                self.params, opt_state, self.loss_scale_state,
                self._grad_buffer, lr)
            self._grad_buffer = None
        else:
            # the donated grad buffer comes back zeroed (aliased in
            # place) and seeds the next accumulation round
            (self.params, opt_state, self.loss_scale_state,
             self._grad_buffer, metrics) = self._apply_step_jit(
                self.params, opt_state, self.loss_scale_state,
                self._grad_buffer, lr)
        self._swap_out_opt_state(opt_state)
        self._swap_out_params()
        self._prefetch_stores()
        self._micro_in_step = 0
        self.global_steps += 1
        self.lr_scheduler.step()
        self._after_step(metrics)
        self.timers(STEP_GLOBAL_TIMER).stop()

    def eval_batch(self, batch: Batch) -> jnp.ndarray:
        self._swap_in_params()
        batch = self._maybe_stripe_ring(batch, seq_axis=1)
        batch = self._put_batch(batch, stacked=False)
        return self._eval_step_jit(self.params, batch)

    # ------------------------------------------------------------------
    def _after_step(self, metrics) -> None:
        self._last_metrics = metrics
        if self.global_steps % self.config.steps_per_print == 0:
            m = {k: float(np.asarray(v)) for k, v in metrics.items()}
            log_dist(f"step={self.global_steps} "
                     + " ".join(f"{k}={v:.6g}" for k, v in m.items())
                     + f" lr={self.lr_scheduler(self.global_steps - 1):.3e}")
            if self.monitor:
                self.monitor.write_events([
                    ("Train/Samples/train_loss", m.get("loss", 0.0), self.global_steps),
                    ("Train/Samples/lr", self.lr_scheduler(self.global_steps - 1), self.global_steps),
                ])
        if self.config.memory_breakdown:
            # independent of steps_per_print (ref memory_breakdown logs
            # around every step); deferred import so tests can patch it
            from deepspeed_tpu.runtime import utils as _rt_utils

            _rt_utils.see_memory_usage(f"after step {self.global_steps}",
                                       force=True)

    def get_global_grad_norm(self) -> float:
        gn = self._last_metrics.get("grad_norm")
        return float(np.asarray(gn)) if gn is not None else 0.0

    @property
    def loss_scale(self) -> float:
        return float(np.asarray(self.loss_scale_state["scale"]))

    @property
    def skipped_steps(self) -> int:
        """Total optimizer steps skipped on fp16 overflow. Counted on device
        (no per-step host sync); reading this syncs."""
        return int(np.asarray(self.loss_scale_state["skipped"]))

    def get_lr(self):
        return self.lr_scheduler.get_last_lr()

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return self.micro_batch_size

    def train_batch_size(self) -> int:
        return self.train_batch_size_value

    def gradient_accumulation_steps(self) -> int:
        return self.gradient_accumulation_steps_value

    # ------------------------------------------------------------------
    # Checkpointing (basic pickle-of-host-arrays; checkpoint/ has the full
    # sharded + universal formats)
    # ------------------------------------------------------------------
    @property
    def checkpoint_engine(self):
        """Pluggable writer (ref runtime/checkpoint_engine/): 'orbax' (sharded
        tensorstore, optional async) or the default pickle engine."""
        if self._checkpoint_engine is None:
            cc = self.config.checkpoint_config
            writer_type = (cc.writer or {}).get("type", "")
            if writer_type == "fast":
                from deepspeed_tpu.checkpoint.fast_engine import FastCheckpointEngine

                self._checkpoint_engine = FastCheckpointEngine()
            elif writer_type == "decoupled":
                from deepspeed_tpu.checkpoint.fast_engine import DecoupledCheckpointEngine

                self._checkpoint_engine = DecoupledCheckpointEngine()
            elif writer_type == "orbax" or cc.async_save:
                from deepspeed_tpu.checkpoint.orbax_engine import OrbaxCheckpointEngine

                self._checkpoint_engine = OrbaxCheckpointEngine(async_save=cc.async_save)
            else:
                self._checkpoint_engine = "pickle"
        return self._checkpoint_engine

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None) -> None:
        self._swap_in_params()  # NVMe param tier: stage layers for the save
        ce = self.checkpoint_engine
        if ce != "pickle":
            ce.save(self, save_dir, tag or f"global_step{self.global_steps}",
                    client_state=client_state or {})
            return
        from deepspeed_tpu.checkpoint.engine import save_checkpoint as _save

        _save(self, save_dir, tag=tag, client_state=client_state or {})

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        if self.config.load_universal_checkpoint:
            from deepspeed_tpu.checkpoint.universal import (load_universal,
                                                            resolve_universal_dir)

            load_universal(self, resolve_universal_dir(load_dir, tag))
            self._sync_store_after_load()
            return load_dir, {}
        ce = self.checkpoint_engine
        if ce != "pickle":
            result = ce.load(self, load_dir, tag=tag,
                             load_optimizer_states=load_optimizer_states,
                             load_lr_scheduler_states=load_lr_scheduler_states)
        else:
            from deepspeed_tpu.checkpoint.engine import load_checkpoint as _load

            result = _load(self, load_dir, tag=tag,
                           load_optimizer_states=load_optimizer_states,
                           load_lr_scheduler_states=load_lr_scheduler_states)
        self._sync_store_after_load()
        return result

    def _opt_state_template(self):
        """Optimizer-state pytree usable as a structure/shape template even
        when an offload store (host/NVMe) is authoritative."""
        if self.opt_state is not None:
            return self.opt_state
        if self._opt_store is not None:
            return self._opt_store_read()
        return None

    def _sync_store_after_load(self) -> None:
        """After any checkpoint load: if an offload store is authoritative,
        push the freshly-loaded optimizer state into it."""
        self._cancel_prefetch()  # a pre-load prefetch would be stale
        if self._opt_store is not None and self.opt_state is not None:
            self._opt_store.swap_out(self.opt_state)
            self.opt_state = None
