"""ZeRO-Offload / ZeRO-Infinity: host-memory and NVMe tiering.

TPU-native re-design of the reference offload stack:

* **CPU offload** (ref ZeRO-Offload, ``offload_optimizer.device == "cpu"``):
  optimizer state lives in TPU-VM host RAM via XLA memory kinds
  (``pinned_host``); the compiled step streams state device↔host around the
  update, replacing the reference's CPU-Adam + grad copy machinery
  (csrc/adam/cpu_adam_impl.cpp) — the update itself still runs on the TPU,
  which is faster than host SIMD and keeps one compiled program.
* **Partial offload ratio** (ref ZeRO-Offload++ TwinFlow ``ratio``):
  the largest leaves are offloaded until the requested fraction of bytes is
  host-resident; the rest stays in HBM.
* **NVMe offload** (ref ZeRO-Infinity, partitioned_optimizer_swapper.py):
  optimizer state is staged on NVMe via the native AIO engine
  (csrc/aio/ds_aio.cpp) and swapped in/out around each optimizer step with
  double-buffered async writes.
* **offload_states API** (ref runtime/zero/offload_states.py:90): move
  engine state device↔host at runtime.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def with_memory_kind(shardings, kind: str):
    def _wk(s):
        try:
            return s.with_memory_kind(kind)
        except ValueError:
            # backend has no such memory space (CPU mesh: only
            # unpinned_host) — placement degrades to a no-op, matching
            # memory_kinds_supported()'s platform gate
            return s

    return jax.tree.map(_wk, shardings)


_HOST_OFFLOAD_PROBE: Dict[str, bool] = {}


def host_offload_supported(topo) -> bool:
    """Compile-probe whether this backend supports pinned_host placement of
    sharded arrays under SPMD (real TPUs: yes; the CPU test backend: no —
    and behavioral probes are unreliable there, small programs fold the
    placement annotations away while large ones abort at runtime, so the
    platform gate in runtime/infinity.memory_kinds_supported decides
    first). Cached per mesh shape."""
    from deepspeed_tpu.runtime.infinity import memory_kinds_supported

    if not memory_kinds_supported():
        return False
    key = str(sorted(topo.sizes.items())) + str(jax.devices()[0].platform)
    if key in _HOST_OFFLOAD_PROBE:
        return _HOST_OFFLOAD_PROBE[key]
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        host = NamedSharding(topo.mesh, P()).with_memory_kind("pinned_host")
        dev = NamedSharding(topo.mesh, P())
        x = jax.device_put(jnp.ones((8,)), host)

        def f(a):
            return jax.device_put(a, dev) * 2.0

        jax.jit(f, out_shardings=host)(x).block_until_ready()
        ok = True
    except Exception as e:  # UNIMPLEMENTED / RET_CHECK on unsupported backends
        logger.warning(f"host-offload via memory kinds unavailable ({type(e).__name__}); "
                       "falling back to host-store offload")
        ok = False
    _HOST_OFFLOAD_PROBE[key] = ok
    return ok


class HostOptimizerStore:
    """RAM-resident optimizer state (ZeRO-Offload fallback): state lives as
    host numpy arrays between steps; each step streams it device↔host.
    Same interface as NVMeOptimizerSwapper."""

    def __init__(self):
        self._tree = None

    def swap_out(self, opt_state) -> None:
        self._tree = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), opt_state)

    def swap_in(self):
        assert self._tree is not None, "swap_in before any swap_out"
        return self._tree

    def wait(self) -> None:
        pass


def partial_offload_shardings(param_shape_tree, device_shardings, ratio: float):
    """Offload the largest leaves first until ``ratio`` of total bytes are
    host-resident (TwinFlow, ref engine.py:932 zero_partial_offload).
    Scalar leaves (step counts) always stay on device — XLA rejects host
    placement annotations on side-effect scalars."""
    if ratio <= 0.0:
        return device_shardings
    leaves, treedef = jax.tree_util.tree_flatten(param_shape_tree)
    shard_leaves = jax.tree_util.tree_flatten(device_shardings)[0]
    sizes = [int(np.prod(l.shape)) * getattr(l.dtype, "itemsize", 4) for l in leaves]
    total = sum(sizes)
    order = sorted(range(len(leaves)), key=lambda i: -sizes[i])
    host_bytes = 0
    host_set = set()
    for i in order:
        if len(leaves[i].shape) == 0:
            continue
        if ratio < 1.0 and host_bytes >= ratio * total:
            break
        host_set.add(i)
        host_bytes += sizes[i]
    out = [with_memory_kind(s, "pinned_host") if i in host_set else s
           for i, s in enumerate(shard_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


class NVMeOptimizerSwapper:
    """Swap optimizer state to NVMe between steps via native async IO.

    Ref: PartitionedOptimizerSwapper (swap_tensor/partitioned_optimizer_
    swapper.py:27) + AsyncTensorSwapper (:19).  State layout: one file per
    optimizer-state leaf under ``swap_dir``; reads are issued for the next
    step while the write-back of the previous step drains (double buffer).
    """

    def __init__(self, swap_dir: str, aio_config=None, prefix: str = "opt"):
        from deepspeed_tpu.ops.aio import AsyncIOHandle

        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        # distinct prefixes let the param tier and the optimizer tier share
        # one NVMe mount (the canonical setup) without clobbering files
        self.prefix = prefix
        cfg = aio_config
        self.handle = AsyncIOHandle(
            block_size=getattr(cfg, "block_size", 1 << 20),
            queue_depth=getattr(cfg, "queue_depth", 8),
            thread_count=getattr(cfg, "thread_count", 4),
            use_direct=getattr(cfg, "use_direct", False))
        self._templates = None  # list of (path, shape, dtype)
        self._treedef = None

    def _leaf_path(self, idx: int) -> str:
        return os.path.join(self.swap_dir, f"{self.prefix}_leaf_{idx}.bin")

    def swap_out(self, opt_state) -> None:
        """Write opt state to NVMe (async) and record templates."""
        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        self._treedef = treedef
        self._templates = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(jax.device_get(leaf))
            self._templates.append((arr.shape, arr.dtype))
            self.handle.async_pwrite(arr, self._leaf_path(i))

    def swap_in(self):
        """Read opt state back from NVMe → host numpy pytree."""
        assert self._templates is not None, "swap_in before any swap_out"
        self.handle.wait()  # ensure prior writes committed
        bufs = []
        for i, (shape, dtype) in enumerate(self._templates):
            buf = np.empty(shape, dtype)
            self.handle.async_pread(buf, self._leaf_path(i))
            bufs.append(buf)
        errs = self.handle.wait()
        if errs:
            raise IOError(f"NVMe swap_in: {errs} failed chunks")
        return jax.tree_util.tree_unflatten(self._treedef, bufs)

    def wait(self) -> None:
        self.handle.wait()


def offload_states(engine, include: Optional[list] = None) -> None:
    """Move engine states to host memory (ref offload_states.py:90)."""
    include = list(include or ["optimizer", "params"])
    if "optimizer" in include:
        if engine.opt_state is None:
            # offload-store mode: state is already host/NVMe-resident
            include.remove("optimizer")
        else:
            host_shardings = partial_offload_shardings(engine.opt_state,
                                                       engine.opt_shardings, 1.0)
            engine.opt_state = jax.device_put(engine.opt_state, host_shardings)
    if "params" in include:
        if getattr(engine, "_param_store", None) is not None \
                and engine.params.get("layers") is None:
            # NVMe param tier between steps: layers already off-device, but
            # the resident partition (embed/norms/head) still needs the move
            from deepspeed_tpu.runtime.infinity import split_layers

            _, res = split_layers(engine.params)
            _, res_sh = split_layers(engine.param_shardings)
            res = jax.device_put(res, with_memory_kind(res_sh, "pinned_host"))
            engine.params = {**res, "layers": None}
        else:
            engine.params = jax.device_put(
                engine.params, with_memory_kind(engine.param_shardings, "pinned_host"))
            if getattr(engine, "_param_store", None) is not None:
                # restore the between-steps invariant: NVMe is authoritative
                engine._swap_out_params()
    log_dist(f"offloaded states to host: {include}")


def reload_states(engine, include: Optional[list] = None) -> None:
    include = list(include or ["optimizer", "params"])
    if "optimizer" in include:
        if engine.opt_state is None:  # store mode: swapped in per-step anyway
            include.remove("optimizer")
        else:
            engine.opt_state = jax.device_put(engine.opt_state, engine.opt_shardings)
    if "params" in include:
        if getattr(engine, "_param_store", None) is not None \
                and engine.params.get("layers") is None:
            engine._swap_in_params()  # NVMe → host staging at param_shardings
        engine.params = jax.device_put(engine.params, engine.param_shardings)
    log_dist(f"reloaded states to device: {include}")
