"""ZeRO-Offload / ZeRO-Infinity: host-memory and NVMe tiering.

TPU-native re-design of the reference offload stack:

* **CPU offload** (ref ZeRO-Offload, ``offload_optimizer.device == "cpu"``):
  optimizer state lives in TPU-VM host RAM via XLA memory kinds
  (``pinned_host``); the compiled step streams state device↔host around the
  update, replacing the reference's CPU-Adam + grad copy machinery
  (csrc/adam/cpu_adam_impl.cpp) — the update itself still runs on the TPU,
  which is faster than host SIMD and keeps one compiled program.
* **Partial offload ratio** (ref ZeRO-Offload++ TwinFlow ``ratio``):
  the largest leaves are offloaded until the requested fraction of bytes is
  host-resident; the rest stays in HBM.
* **NVMe offload** (ref ZeRO-Infinity, partitioned_optimizer_swapper.py):
  optimizer state is staged on NVMe via the native AIO engine
  (csrc/aio/ds_aio.cpp) and swapped in/out around each optimizer step with
  double-buffered async writes.
* **offload_states API** (ref runtime/zero/offload_states.py:90): move
  engine state device↔host at runtime.
"""

from __future__ import annotations

import concurrent.futures
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

_MEMORY_KIND_DEGRADE_WARNED = False


def with_memory_kind(shardings, kind: str):
    def _wk(s):
        global _MEMORY_KIND_DEGRADE_WARNED
        try:
            return s.with_memory_kind(kind)
        except ValueError:
            # backend has no such memory space (CPU mesh: only
            # unpinned_host) — placement degrades to a no-op, matching
            # memory_kinds_supported()'s platform gate.  Warn once per
            # process (the range_pop/_cancel_prefetch throttle pattern):
            # a TPU run that unexpectedly loses pinned_host placement
            # would otherwise silently keep everything device-resident.
            if not _MEMORY_KIND_DEGRADE_WARNED:
                _MEMORY_KIND_DEGRADE_WARNED = True
                logger.warning(
                    f"memory kind {kind!r} unavailable on this backend — "
                    "placement degrades to the default memory space "
                    "(warned once per process)")
            return s

    return jax.tree.map(_wk, shardings)


_HOST_OFFLOAD_PROBE: Dict[str, bool] = {}


def host_offload_supported(topo) -> bool:
    """Compile-probe whether this backend supports pinned_host placement of
    sharded arrays under SPMD (real TPUs: yes; the CPU test backend: no —
    and behavioral probes are unreliable there, small programs fold the
    placement annotations away while large ones abort at runtime, so the
    platform gate in runtime/infinity.memory_kinds_supported decides
    first). Cached per mesh shape."""
    from deepspeed_tpu.runtime.infinity import memory_kinds_supported

    if not memory_kinds_supported():
        return False
    key = str(sorted(topo.sizes.items())) + str(jax.devices()[0].platform)
    if key in _HOST_OFFLOAD_PROBE:
        return _HOST_OFFLOAD_PROBE[key]
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        host = NamedSharding(topo.mesh, P()).with_memory_kind("pinned_host")
        dev = NamedSharding(topo.mesh, P())
        x = jax.device_put(jnp.ones((8,)), host)

        def f(a):
            return jax.device_put(a, dev) * 2.0

        jax.jit(f, out_shardings=host)(x).block_until_ready()
        ok = True
    except Exception as e:  # UNIMPLEMENTED / RET_CHECK on unsupported backends
        logger.warning(f"host-offload via memory kinds unavailable ({type(e).__name__}); "
                       "falling back to host-store offload")
        ok = False
    _HOST_OFFLOAD_PROBE[key] = ok
    return ok


class HostOptimizerStore:
    """RAM-resident optimizer state (ZeRO-Offload fallback): state lives as
    host numpy arrays between steps; each step streams it device↔host.
    Same interface as NVMeOptimizerSwapper."""

    def __init__(self):
        self._tree = None

    def swap_out(self, opt_state) -> None:
        self._tree = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), opt_state)

    def swap_in(self):
        assert self._tree is not None, "swap_in before any swap_out"
        return self._tree

    def wait(self) -> None:
        pass


def partial_offload_shardings(param_shape_tree, device_shardings, ratio: float):
    """Offload the largest leaves first until ``ratio`` of total bytes are
    host-resident (TwinFlow, ref engine.py:932 zero_partial_offload).
    Scalar leaves (step counts) always stay on device — XLA rejects host
    placement annotations on side-effect scalars."""
    if ratio <= 0.0:
        return device_shardings
    leaves, treedef = jax.tree_util.tree_flatten(param_shape_tree)
    shard_leaves = jax.tree_util.tree_flatten(device_shardings)[0]
    sizes = [int(np.prod(l.shape)) * getattr(l.dtype, "itemsize", 4) for l in leaves]
    total = sum(sizes)
    order = sorted(range(len(leaves)), key=lambda i: -sizes[i])
    host_bytes = 0
    host_set = set()
    for i in order:
        if len(leaves[i].shape) == 0:
            continue
        if ratio < 1.0 and host_bytes >= ratio * total:
            break
        host_set.add(i)
        host_bytes += sizes[i]
    out = [with_memory_kind(s, "pinned_host") if i in host_set else s
           for i, s in enumerate(shard_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


class NVMeOptimizerSwapper:
    """Swap optimizer state to NVMe between steps via native async IO.

    Ref: PartitionedOptimizerSwapper (swap_tensor/partitioned_optimizer_
    swapper.py:27) + AsyncTensorSwapper (:19).  State layout: one file per
    optimizer-state leaf under ``swap_dir``; reads are issued for the next
    step while the write-back of the previous step drains (double buffer).
    """

    def __init__(self, swap_dir: str, aio_config=None, prefix: str = "opt"):
        from deepspeed_tpu.ops.aio import AsyncIOHandle

        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        # distinct prefixes let the param tier and the optimizer tier share
        # one NVMe mount (the canonical setup) without clobbering files
        self.prefix = prefix
        cfg = aio_config
        self.handle = AsyncIOHandle(
            block_size=getattr(cfg, "block_size", 1 << 20),
            queue_depth=getattr(cfg, "queue_depth", 8),
            thread_count=getattr(cfg, "thread_count", 4),
            use_direct=getattr(cfg, "use_direct", False))
        self._templates = None  # list of (path, shape, dtype)
        self._treedef = None

    def _leaf_path(self, idx: int) -> str:
        return os.path.join(self.swap_dir, f"{self.prefix}_leaf_{idx}.bin")

    def swap_out(self, opt_state) -> None:
        """Write opt state to NVMe (async) and record templates."""
        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        self._treedef = treedef
        self._templates = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(jax.device_get(leaf))
            self._templates.append((arr.shape, arr.dtype))
            self.handle.async_pwrite(arr, self._leaf_path(i))

    def swap_in(self):
        """Read opt state back from NVMe → host numpy pytree."""
        assert self._templates is not None, "swap_in before any swap_out"
        self.handle.wait()  # ensure prior writes committed
        bufs = []
        for i, (shape, dtype) in enumerate(self._templates):
            buf = np.empty(shape, dtype)
            self.handle.async_pread(buf, self._leaf_path(i))
            bufs.append(buf)
        errs = self.handle.wait()
        if errs:
            raise IOError(f"NVMe swap_in: {errs} failed chunks")
        return jax.tree_util.tree_unflatten(self._treedef, bufs)

    def wait(self) -> None:
        self.handle.wait()


class ChunkedHostOptimizer:
    """Chunked host Adam with double-buffered device↔host streams
    (ZeRO-Offload chunked CPU step + ZeRO-Infinity NVMe state tier; ref
    cpu_adam_impl.cpp + partitioned_optimizer_swapper.py).

    The whole param tree is viewed as one concatenated fp32 vector cut
    into fixed ``chunk_bytes`` pieces (the tail chunk keeps the
    remainder, so no size has to divide).  Each chunk's optimizer state
    is ONE contiguous ``(3, n)`` fp32 array — rows master | exp_avg |
    exp_avg_sq — owned by a chunk store between steps:
    ``nvme.chunk_store.HostChunkStore`` (host RAM, ``device == "cpu"``)
    or ``nvme.chunk_store.NVMeChunkStore`` (chunk files via the AIO
    engine, ``device == "nvme"``).  Peak host working set is
    O(buffers × chunk), not O(state).

    ``step`` runs a software pipeline: while chunk k's host Adam runs,
    the grad d2h fetch and the store read of chunk k+1 are already in
    flight, and the h2d push of every finished leaf is handed to a
    writer thread.  The stages emit the frozen trace spans
    ``offload.d2h`` / ``offload.host_step`` / ``offload.h2d`` and the
    per-step summary lands in ``last_overlap_fraction``
    (0 = fully serialized, 1 = transfers fully hidden), which the
    engine forwards into the StepRecord.

    Interface-compatible with ``SuperOffloadOptimizer`` (the engine
    mounts either in the same slot; checkpointing shares the
    ``{"step","master","m","v"}`` state_dict layout).  No rollback
    window — keeping one is an O(state) host copy, exactly what this
    tier exists to avoid.  The Adam formula is the same fused
    ``ops/cpu_optimizer`` kernel SuperOffload uses, which is
    algebraically identical to the on-device optax update
    (``sqrt(v)/sqrt(bc2) == sqrt(v/bc2)``) — parity is pinned to 1e-6
    by tests/test_offload.py.
    """

    def __init__(self, params: Any, lr: float = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 chunk_bytes: int = 64 << 20, adamw: bool = False,
                 store=None, tracer=None):
        from deepspeed_tpu.nvme.chunk_store import HostChunkStore
        from deepspeed_tpu.telemetry.tracing import NULL_TRACER

        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw = adamw
        self.step_count = 0
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._dtypes = [l.dtype for l in leaves]
        self._shapes = [tuple(np.shape(l)) for l in leaves]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self.total_numel = sum(self._sizes)
        self.chunk_numel = max(1, int(chunk_bytes) // 4)
        # flat-element chunk plan: per chunk, (leaf, start, stop) segments
        self._chunks: List[List[Tuple[int, int, int]]] = []
        cur: List[Tuple[int, int, int]] = []
        cur_n = 0
        for i, n in enumerate(self._sizes):
            start = 0
            while start < n:
                take = min(n - start, self.chunk_numel - cur_n)
                cur.append((i, start, start + take))
                cur_n += take
                start += take
                if cur_n == self.chunk_numel:
                    self._chunks.append(cur)
                    cur, cur_n = [], 0
        if cur:
            self._chunks.append(cur)
        self.num_chunks = len(self._chunks)
        self._store = store if store is not None else HostChunkStore()
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._trace_id = ""
        self.last_overlap_fraction = 0.0
        self._t_d2h = 0.0
        self._t_h2d = 0.0
        # single-worker pools keep each pipeline stage ordered: one fetch
        # ahead (double buffer), one push behind
        self._io = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dstpu-offload-d2h")
        self._push = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dstpu-offload-h2d")
        self.reset_masters(params, reset_moments=True)

    # ------------------------------------------------------------------
    def _chunk_len(self, k: int) -> int:
        return sum(s2 - s1 for _, s1, s2 in self._chunks[k])

    def _fetch_grads(self, k: int, flat_g, cache) -> np.ndarray:
        """d2h stage: assemble chunk k's flat fp32 grad slice.  Leaves are
        fetched whole and cached until their last segment is consumed, so
        transient host memory is O(chunk + largest leaf)."""
        t0 = time.perf_counter()
        with self._tracer.span("offload.d2h", self._trace_id):
            parts = []
            for i, s1, s2 in self._chunks[k]:
                a = cache.get(i)
                if a is None:
                    a = np.asarray(jax.device_get(flat_g[i]),
                                   np.float32).ravel()
                    cache[i] = a
                parts.append(a[s1:s2])
                if s2 == self._sizes[i]:
                    cache.pop(i, None)
            # always own the memory: the kernel may scale/decay in place
            g = (np.concatenate(parts) if len(parts) > 1
                 else np.array(parts[0], np.float32))
        self._t_d2h += time.perf_counter() - t0
        return g

    def _host_adam(self, st: np.ndarray, g: np.ndarray, step: int,
                   grad_scale: float) -> None:
        from deepspeed_tpu.ops.cpu_optimizer import (_lib, _ptr,
                                                     adam_step_numpy)

        if grad_scale != 1.0:
            g = g * np.float32(grad_scale)
        p, m, v = st[0], st[1], st[2]
        lib = _lib()
        if lib is not None:
            lib.ds_adam_step(_ptr(p), _ptr(g), _ptr(m), _ptr(v), p.size,
                             self.lr, self.beta1, self.beta2, self.eps,
                             self.weight_decay, step,
                             1 if self.adamw else 0)
        else:
            adam_step_numpy(p, g, m, v, self.lr, self.beta1, self.beta2,
                            self.eps, self.weight_decay, step,
                            adamw=self.adamw)

    def _push_leaf(self, i: int, buf: np.ndarray, like):
        """h2d stage: one finished leaf's masters → device working dtype."""
        t0 = time.perf_counter()
        with self._tracer.span("offload.h2d", self._trace_id):
            x = jnp.asarray(buf.reshape(self._shapes[i]), self._dtypes[i])
            if hasattr(like, "sharding"):
                x = jax.device_put(x, like.sharding)
        self._t_h2d += time.perf_counter() - t0
        return i, x

    # ------------------------------------------------------------------
    def step(self, params: Any, grads: Any, grad_scale: float = 1.0) -> Any:
        """grads (device tree) → updated device params, chunk-pipelined.
        ``grad_scale`` folds loss-scale/grad-accum normalisation and the
        clip coefficient (computed on device by the engine)."""
        self.step_count += 1
        step = self.step_count
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        flat_p = jax.tree_util.tree_flatten(params)[0]
        new_flat = list(flat_p)
        self._t_d2h = self._t_h2d = 0.0
        t_comp = 0.0
        t0_wall = time.perf_counter()
        cache: Dict[int, np.ndarray] = {}
        fetch = {0: self._io.submit(self._fetch_grads, 0, flat_g, cache)}
        self._store.prefetch(0)
        push_futs = []
        staging: Dict[int, np.ndarray] = {}
        for k in range(self.num_chunks):
            if k + 1 < self.num_chunks:
                fetch[k + 1] = self._io.submit(self._fetch_grads, k + 1,
                                               flat_g, cache)
            g = fetch.pop(k).result()
            st = self._store.get(k)
            if k + 1 < self.num_chunks:
                self._store.prefetch(k + 1)
            t0 = time.perf_counter()
            with self._tracer.span("offload.host_step", self._trace_id):
                self._host_adam(st, g, step, grad_scale)
            t_comp += time.perf_counter() - t0
            self._store.put(k, st)  # write-behind (async on NVMe)
            # scatter updated masters into per-leaf staging; a leaf whose
            # last segment just landed is pushed while later chunks compute
            off = 0
            for i, s1, s2 in self._chunks[k]:
                buf = staging.get(i)
                if buf is None:
                    buf = staging[i] = np.empty(self._sizes[i], np.float32)
                n = s2 - s1
                buf[s1:s2] = st[0, off:off + n]
                off += n
                if s2 == self._sizes[i]:
                    push_futs.append(self._push.submit(
                        self._push_leaf, i, staging.pop(i), flat_p[i]))
        for f in push_futs:
            i, arr = f.result()
            new_flat[i] = arr
        self._store.flush()
        wall = time.perf_counter() - t0_wall
        xfer = self._t_d2h + self._t_h2d
        # how much of the transfer time the host compute hid: 0 = fully
        # serialized, 1 = transfers entirely behind compute
        self.last_overlap_fraction = (
            max(0.0, min(1.0, (t_comp + xfer - wall) / xfer))
            if xfer > 1e-9 else 0.0)
        return jax.tree_util.tree_unflatten(self._treedef, new_flat)

    def push_params(self, params_like: Any) -> Any:
        """Host masters → device tree matching ``params_like``'s dtypes
        and shardings (checkpoint resume path)."""
        flat_p = jax.tree_util.tree_flatten(params_like)[0]
        new_flat = list(flat_p)
        staging: Dict[int, np.ndarray] = {}
        for k, segs in enumerate(self._chunks):
            st = self._store.get(k)
            off = 0
            for i, s1, s2 in segs:
                buf = staging.get(i)
                if buf is None:
                    buf = staging[i] = np.empty(self._sizes[i], np.float32)
                buf[s1:s2] = st[0, off:off + s2 - s1]
                off += s2 - s1
                if s2 == self._sizes[i]:
                    _, new_flat[i] = self._push_leaf(i, staging.pop(i),
                                                     flat_p[i])
            self._store.release(k, st)
        return jax.tree_util.tree_unflatten(self._treedef, new_flat)

    def reset_masters(self, params: Any, reset_moments: bool = True) -> None:
        """(Re-)seed the fp32 masters from a device param tree, chunk by
        chunk (a weights-only checkpoint resume must call this, same
        contract as SuperOffloadOptimizer.reset_masters)."""
        flat_p = jax.tree_util.tree_flatten(params)[0]
        cache: Dict[int, np.ndarray] = {}
        for k, segs in enumerate(self._chunks):
            if reset_moments:
                st = np.zeros((3, self._chunk_len(k)), np.float32)
            else:
                st = self._store.get(k)
            off = 0
            for i, s1, s2 in segs:
                a = cache.get(i)
                if a is None:
                    a = np.asarray(jax.device_get(flat_p[i]),
                                   np.float32).ravel()
                    cache[i] = a
                st[0, off:off + s2 - s1] = a[s1:s2]
                off += s2 - s1
                if s2 == self._sizes[i]:
                    cache.pop(i, None)
            self._store.put(k, st)
        self._store.flush()
        if reset_moments:
            self.step_count = 0

    def rollback(self) -> None:
        raise RuntimeError(
            "chunked host optimizer keeps O(chunk) state — no rollback "
            "window; use SuperOffload (offload_optimizer.super_offload) "
            "when post-hoc rollback is required")

    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """SuperOffloadOptimizer-compatible layout (checkpoint/engine.py
        stores it under the ``superoffload`` key): per-leaf fp32 arrays."""
        L = len(self._sizes)
        master = [np.empty(self._sizes[i], np.float32) for i in range(L)]
        m = [np.empty(self._sizes[i], np.float32) for i in range(L)]
        v = [np.empty(self._sizes[i], np.float32) for i in range(L)]
        for k, segs in enumerate(self._chunks):
            st = self._store.get(k)
            off = 0
            for i, s1, s2 in segs:
                n = s2 - s1
                master[i][s1:s2] = st[0, off:off + n]
                m[i][s1:s2] = st[1, off:off + n]
                v[i][s1:s2] = st[2, off:off + n]
                off += n
            self._store.release(k, st)
        return {"step": self.step_count,
                "master": [a.reshape(self._shapes[i])
                           for i, a in enumerate(master)],
                "m": [a.reshape(self._shapes[i]) for i, a in enumerate(m)],
                "v": [a.reshape(self._shapes[i]) for i, a in enumerate(v)]}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.step_count = int(state["step"])
        master = [np.asarray(x, np.float32).ravel() for x in state["master"]]
        m = [np.asarray(x, np.float32).ravel() for x in state["m"]]
        v = [np.asarray(x, np.float32).ravel() for x in state["v"]]
        for k, segs in enumerate(self._chunks):
            st = np.empty((3, self._chunk_len(k)), np.float32)
            off = 0
            for i, s1, s2 in segs:
                n = s2 - s1
                st[0, off:off + n] = master[i][s1:s2]
                st[1, off:off + n] = m[i][s1:s2]
                st[2, off:off + n] = v[i][s1:s2]
                off += n
            self._store.put(k, st)
        self._store.flush()

    def close(self) -> None:
        self._io.shutdown(wait=True)
        self._push.shutdown(wait=True)
        self._store.close()


def offload_states(engine, include: Optional[list] = None) -> None:
    """Move engine states to host memory (ref offload_states.py:90)."""
    include = list(include or ["optimizer", "params"])
    if "optimizer" in include:
        if engine.opt_state is None:
            # offload-store mode: state is already host/NVMe-resident
            include.remove("optimizer")
        else:
            host_shardings = partial_offload_shardings(engine.opt_state,
                                                       engine.opt_shardings, 1.0)
            engine.opt_state = jax.device_put(engine.opt_state, host_shardings)
    if "params" in include:
        if getattr(engine, "_param_store", None) is not None \
                and engine.params.get("layers") is None:
            # NVMe param tier between steps: layers already off-device, but
            # the resident partition (embed/norms/head) still needs the move
            from deepspeed_tpu.runtime.infinity import split_layers

            _, res = split_layers(engine.params)
            _, res_sh = split_layers(engine.param_shardings)
            res = jax.device_put(res, with_memory_kind(res_sh, "pinned_host"))
            engine.params = {**res, "layers": None}
        else:
            engine.params = jax.device_put(
                engine.params, with_memory_kind(engine.param_shardings, "pinned_host"))
            if getattr(engine, "_param_store", None) is not None:
                # restore the between-steps invariant: NVMe is authoritative
                engine._swap_out_params()
    log_dist(f"offloaded states to host: {include}")


def reload_states(engine, include: Optional[list] = None) -> None:
    include = list(include or ["optimizer", "params"])
    if "optimizer" in include:
        if engine.opt_state is None:  # store mode: swapped in per-step anyway
            include.remove("optimizer")
        else:
            engine.opt_state = jax.device_put(engine.opt_state, engine.opt_shardings)
    if "params" in include:
        if getattr(engine, "_param_store", None) is not None \
                and engine.params.get("layers") is None:
            engine._swap_in_params()  # NVMe → host staging at param_shardings
        engine.params = jax.device_put(engine.params, engine.param_shardings)
    log_dist(f"reloaded states to device: {include}")
