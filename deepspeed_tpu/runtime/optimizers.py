"""Optimizer factory.

Analog of the reference's optimizer zoo (``_configure_basic_optimizer``,
runtime/engine.py:1536 — FusedAdam/CPUAdam/Lamb/Lion/Adagrad/Muon/1-bit).
On TPU there is no fused-vs-unfused split: every optimizer below is a pure
pytree transform that XLA fuses into the (sharded) update step, which *is*
the fused multi-tensor kernel — applied to ZeRO-partitioned state when the
engine shards opt state (ZeRO-1).

The learning rate is NOT baked into the transform chain: ``update_fn`` takes
``lr`` as a traced scalar so host-side LR schedules never retrigger
compilation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger


@dataclass
class Optimizer:
    """init/update pair over param pytrees."""
    name: str
    init_fn: Callable[[Any], Any]
    update_fn: Callable[..., Tuple[Any, Any]]  # (grads, state, params, lr) -> (params, state)
    defaults: Dict[str, Any]

    def init(self, params):
        return self.init_fn(params)

    def update(self, grads, state, params, lr):
        return self.update_fn(grads, state, params, lr)


def _chain_to_optimizer(name: str, tx: optax.GradientTransformation,
                        defaults: Dict[str, Any]) -> Optimizer:
    def update_fn(grads, state, params, lr):
        updates, new_state = tx.update(grads, state, params)
        updates = jax.tree.map(lambda u: (-lr * u).astype(u.dtype), updates)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_state

    return Optimizer(name=name, init_fn=tx.init, update_fn=update_fn, defaults=defaults)


def _adam(params_cfg: Dict[str, Any], adam_w_mode: bool) -> Optimizer:
    betas = params_cfg.get("betas", (0.9, 0.999))
    eps = float(params_cfg.get("eps", 1e-8))
    wd = float(params_cfg.get("weight_decay", 0.01 if adam_w_mode else 0.0))
    txs = [optax.scale_by_adam(b1=float(betas[0]), b2=float(betas[1]), eps=eps)]
    if wd:
        if adam_w_mode:
            txs.append(optax.add_decayed_weights(wd))
        else:
            # plain Adam + L2: decay folded into grads happens pre-moment in
            # torch Adam; approximate with decoupled decay is NOT identical,
            # so add L2 term up front instead.
            txs.insert(0, optax.add_decayed_weights(wd))
    name = "adamw" if adam_w_mode else "adam"
    return _chain_to_optimizer(name, optax.chain(*txs),
                               dict(betas=betas, eps=eps, weight_decay=wd))


class _FusedResult:
    """Opaque per-leaf result wrapper for the fused update maps: a plain
    tuple would be ambiguous with structural tuple nodes in the params
    pytree (is_leaf by tuple length misfires on e.g. a (w, b, scale)
    triple), while this class is never a pytree node."""

    __slots__ = ("vals",)

    def __init__(self, *vals):
        self.vals = vals


def _fused_leaf_ok(p) -> bool:
    from deepspeed_tpu.ops.pallas import fused_optimizer as fo

    if not fo.supports(p.shape):
        return False
    # fp32 leaves only: the kernels declare fp32 out_shape for m/v and
    # alias them onto the optax-initialized mu/nu (whose dtype follows
    # params) — a non-fp32 leaf would fail the alias at trace time, and
    # letting the jnp fallback silently flip state dtype would break the
    # "checkpoints interchangeable with the optax chain" contract.
    if p.dtype != jnp.float32:
        return False
    if fo.INTERPRET:
        return True
    return jax.default_backend() not in ("cpu",)


def _fused_adam(params_cfg: Dict[str, Any], adam_w_mode: bool) -> Optimizer:
    """AdamW with the Pallas fused-step kernel (ops/pallas/fused_optimizer)
    on servable leaves; jnp math (bit-identical to the optax chain) on the
    rest.  State layout mirrors the optax chain exactly, so checkpoints are
    interchangeable with the default path.  Opt in via optimizer params
    ``{"pallas_fused": true}`` — measured marginally faster than the optax
    chain on v5e (556 vs 541 GB/s effective, both near the HBM bound; see
    ops/pallas/fused_optimizer.py)."""
    from deepspeed_tpu.ops.pallas import fused_optimizer as fo

    betas = params_cfg.get("betas", (0.9, 0.999))
    b1, b2 = float(betas[0]), float(betas[1])
    eps = float(params_cfg.get("eps", 1e-8))
    wd = float(params_cfg.get("weight_decay", 0.01 if adam_w_mode else 0.0))
    # decoupled decay only (AdamW); plain-Adam L2 keeps the optax path.
    # Always chain (even length-1): _adam does, and chain state is a tuple
    # regardless of length — keeps the two layouts interchangeable.
    txs = [optax.scale_by_adam(b1=b1, b2=b2, eps=eps)]
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    tx = optax.chain(*txs)

    def _jnp_leaf(p, g, m, v, lr, t):
        # every intermediate stays in the STATE dtype, exactly as the
        # optax chain computes (scale_by_adam accumulates moments in
        # mu/nu's native dtype; weak-typed python scalars don't promote)
        # — so fp32 leaves are bit-identical to the chain and non-fp32
        # leaves follow the same trajectory with a stable state dtype,
        # keeping checkpoints interchangeable between the two paths.
        md = m.dtype
        g = g.astype(md)
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * g * g
        mh = m / (1.0 - b1 ** t).astype(md)
        vh = v / (1.0 - b2 ** t).astype(md)
        u = mh / (jnp.sqrt(vh) + eps)
        if wd:
            u = u + wd * p.astype(md)
        step = (-lr * u).astype(md)
        return (p + step).astype(p.dtype), m, v

    def update_fn(grads, state, params, lr):
        adam_state = state[0]  # chain state: (ScaleByAdamState, [EmptyState])
        t = (adam_state.count + 1).astype(jnp.float32)

        def leaf(p, g, m, v):
            if _fused_leaf_ok(p):
                return _FusedResult(*fo.fused_adamw_leaf(
                    p, g, m, v, lr, adam_state.count, b1, b2, eps, wd))
            return _FusedResult(*_jnp_leaf(p, g, m, v, lr, t))

        out = jax.tree.map(leaf, params, grads, adam_state.mu, adam_state.nu)
        is_res = lambda x: isinstance(x, _FusedResult)
        new_p = jax.tree.map(lambda o: o.vals[0], out, is_leaf=is_res)
        new_m = jax.tree.map(lambda o: o.vals[1], out, is_leaf=is_res)
        new_v = jax.tree.map(lambda o: o.vals[2], out, is_leaf=is_res)
        new_adam = adam_state._replace(count=adam_state.count + 1,
                                       mu=new_m, nu=new_v)
        return new_p, (new_adam,) + tuple(state[1:])

    name = "fused_adamw" if adam_w_mode else "fused_adam"
    return Optimizer(name=name, init_fn=tx.init, update_fn=update_fn,
                     defaults=dict(betas=betas, eps=eps, weight_decay=wd))


def _fused_lion(params_cfg: Dict[str, Any]) -> Optimizer:
    """Lion with the Pallas fused-step kernel on servable leaves (see
    :func:`_fused_adam` for routing/state-compat notes)."""
    from deepspeed_tpu.ops.pallas import fused_optimizer as fo

    betas = params_cfg.get("betas", (0.9, 0.99))
    b1, b2 = float(betas[0]), float(betas[1])
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_lion(b1=b1, b2=b2)]
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    tx = optax.chain(*txs)

    def _jnp_leaf(p, g, m, lr):
        # state-dtype math mirroring the optax chain (see the AdamW
        # fallback's note) so the two paths stay interchangeable.
        md = m.dtype
        g = g.astype(md)
        u = jnp.sign(b1 * m + (1.0 - b1) * g)
        if wd:
            u = u + wd * p.astype(md)
        step = (-lr * u).astype(md)
        return (p + step).astype(p.dtype), b2 * m + (1.0 - b2) * g

    def update_fn(grads, state, params, lr):
        lion_state = state[0]

        def leaf(p, g, m):
            if _fused_leaf_ok(p):
                return _FusedResult(*fo.fused_lion_leaf(p, g, m, lr, b1,
                                                        b2, wd))
            return _FusedResult(*_jnp_leaf(p, g, m, lr))

        out = jax.tree.map(leaf, params, grads, lion_state.mu)
        is_res = lambda x: isinstance(x, _FusedResult)
        new_p = jax.tree.map(lambda o: o.vals[0], out, is_leaf=is_res)
        new_m = jax.tree.map(lambda o: o.vals[1], out, is_leaf=is_res)
        new_lion = lion_state._replace(count=lion_state.count + 1, mu=new_m)
        return new_p, (new_lion,) + tuple(state[1:])

    return Optimizer(name="fused_lion", init_fn=tx.init, update_fn=update_fn,
                     defaults=dict(betas=betas, weight_decay=wd))


def _lion(params_cfg: Dict[str, Any]) -> Optimizer:
    betas = params_cfg.get("betas", (0.9, 0.99))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_lion(b1=float(betas[0]), b2=float(betas[1]))]
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    return _chain_to_optimizer("lion", optax.chain(*txs), dict(betas=betas, weight_decay=wd))


def _lamb(params_cfg: Dict[str, Any]) -> Optimizer:
    betas = params_cfg.get("betas", (0.9, 0.999))
    eps = float(params_cfg.get("eps", 1e-6))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_adam(b1=float(betas[0]), b2=float(betas[1]), eps=eps)]
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    txs.append(optax.scale_by_trust_ratio())
    return _chain_to_optimizer("lamb", optax.chain(*txs),
                               dict(betas=betas, eps=eps, weight_decay=wd))


def _adagrad(params_cfg: Dict[str, Any]) -> Optimizer:
    eps = float(params_cfg.get("eps", 1e-10))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = [optax.scale_by_rss(initial_accumulator_value=0.0, eps=eps)]
    if wd:
        txs.insert(0, optax.add_decayed_weights(wd))
    return _chain_to_optimizer("adagrad", optax.chain(*txs), dict(eps=eps, weight_decay=wd))


def _sgd(params_cfg: Dict[str, Any]) -> Optimizer:
    momentum = float(params_cfg.get("momentum", 0.0))
    wd = float(params_cfg.get("weight_decay", 0.0))
    txs = []
    if wd:
        txs.append(optax.add_decayed_weights(wd))
    if momentum:
        txs.append(optax.trace(decay=momentum, nesterov=bool(params_cfg.get("nesterov", False))))
    tx = optax.chain(*txs) if txs else optax.identity()
    return _chain_to_optimizer("sgd", tx, dict(momentum=momentum, weight_decay=wd))


def _muon(params_cfg: Dict[str, Any]) -> Optimizer:
    """Muon: momentum + Newton–Schulz orthogonalisation for 2-D params
    (ref runtime/zero/muon/original_muon.py:36); non-2D params fall back to
    Adam, matching the reference's use_muon split."""
    from deepspeed_tpu.ops.muon import build_muon

    return build_muon(params_cfg)


def build_optimizer(opt_type: str, params_cfg: Optional[Dict[str, Any]] = None,
                    *, sharded_params: bool = False) -> Optimizer:
    """``sharded_params=True`` means the caller will run ``update`` on
    GSPMD-partitioned params/state (ZeRO≥1, tensor-parallel, or the
    host-streamed path).  A ``pallas_call`` does not partition under
    GSPMD — XLA would replicate p/g/m/v per leaf (all-gathers inside the
    step), defeating ZeRO — so ``pallas_fused`` is downgraded to the
    optax chain there (same numerics, partitionable)."""
    params_cfg = dict(params_cfg or {})
    params_cfg.pop("lr", None)  # lr flows through update_fn
    t = opt_type.lower()
    pallas_fused = bool(params_cfg.pop("pallas_fused", False))
    if pallas_fused and sharded_params:
        logger.warning(
            "pallas_fused requested with sharded params/optimizer state: "
            "a pallas_call is unpartitionable under GSPMD, so the fused "
            "kernel would force per-leaf replication (all-gathers inside "
            "the step). Downgrading to the optax chain (identical "
            "numerics, GSPMD-partitionable).")
        pallas_fused = False
    if t in (C.ADAM_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER):
        adam_w_mode = bool(params_cfg.pop("adam_w_mode", True))
        if pallas_fused and adam_w_mode:
            return _fused_adam(params_cfg, True)
        return _adam(params_cfg, adam_w_mode)
    if t == C.ADAMW_OPTIMIZER:
        params_cfg.pop("adam_w_mode", None)
        if pallas_fused:
            return _fused_adam(params_cfg, True)
        return _adam(params_cfg, True)
    if t in (C.LION_OPTIMIZER, "fusedlion"):
        if pallas_fused:
            return _fused_lion(params_cfg)
        return _lion(params_cfg)
    if t in (C.LAMB_OPTIMIZER, "fusedlamb"):
        return _lamb(params_cfg)
    if t == C.ADAGRAD_OPTIMIZER:
        return _adagrad(params_cfg)
    if t == C.SGD_OPTIMIZER:
        return _sgd(params_cfg)
    if t == C.MUON_OPTIMIZER:
        return _muon(params_cfg)
    if t in (C.ONEBIT_ADAM_OPTIMIZER, C.ONEBIT_LAMB_OPTIMIZER, C.ZERO_ONE_ADAM_OPTIMIZER):
        # Compressed-communication optimizers: on TPU gradient reduction is
        # compiled; the compression variant lives in ops/compressed_optimizer.
        logger.warning(f"{opt_type}: using uncompressed TPU variant (XLA-reduced grads)")
        return _adam(params_cfg, bool(params_cfg.pop("adam_w_mode", True)))
    raise ValueError(f"unknown optimizer type '{opt_type}'")
