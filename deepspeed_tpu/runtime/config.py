"""DeepSpeed-compatible JSON config → typed config objects.

TPU-native re-design of the reference config system
(``deepspeed/runtime/config.py`` + ``runtime/config_utils.py`` +
``runtime/zero/config.py``).  A single JSON document (path or dict) with the
same key surface as DeepSpeed produces a ``DeepSpeedConfig`` instance; batch
sizes are resolved with the same divisibility rules
(``train_batch_size == micro_batch * gradient_accumulation_steps * dp_world``).

TPU extensions live under the ``"mesh"`` key (explicit axis sizes) but every
reference key keeps its meaning, so an existing ``ds_config.json`` ports
unchanged.
"""

from __future__ import annotations

import copy
import dataclasses
import json
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Tuple, Union

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


def _filter_kwargs(cls, data: Dict[str, Any], context: str) -> Dict[str, Any]:
    """Keep only keys that are fields of ``cls``; warn about the rest."""
    valid = {f.name for f in fields(cls)}
    out = {}
    for k, v in data.items():
        if k in valid:
            out[k] = v
        else:
            logger.warning(f"Config: ignoring unknown key '{k}' in '{context}'")
    return out


def _from_dict(cls, data: Optional[Dict[str, Any]], context: str):
    data = data or {}
    if not isinstance(data, dict):
        raise DeepSpeedConfigError(f"'{context}' must be a dict, got {type(data)}")
    return cls(**_filter_kwargs(cls, data, context))


@dataclass
class OptimizerConfig:
    """``"optimizer": {"type": ..., "params": {...}}``"""
    type: str = C.ADAMW_OPTIMIZER
    params: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.type = self.type.lower()

    @property
    def lr(self) -> float:
        return float(self.params.get("lr", 1e-3))


@dataclass
class SchedulerConfig:
    """``"scheduler": {"type": ..., "params": {...}}``"""
    type: str = "WarmupLR"
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TorchAutocastConfig:
    """Ref: runtime/torch_autocast.py — per-op mixed precision.  Enabling
    selects the compute dtype like bf16/fp16 blocks do, plus two policy
    knobs the model consults per op (models/transformer.py op_fp32):

    * ``fp32_ops``: op classes kept in fp32 (default
      layernorm/softmax/rope/router/loss — the built-in safe set).
      Removing entries is the aggressive full-low-precision mode.
    * ``lower_precision_safe_modules``: module classes ("attn", "mlp")
      allowed in the low dtype; when set, unlisted modules are promoted
      to fp32 (the torch autocast contract)."""
    enabled: bool = False
    dtype: str = "bfloat16"
    fp32_ops: Optional[List[str]] = None
    lower_precision_safe_modules: Optional[List[str]] = None


@dataclass
class FP16Config:
    """Reference: ``runtime/fp16`` config block. ``loss_scale == 0`` means
    dynamic loss scaling (DynamicLossScaler, ref loss_scaler.py:99)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0


@dataclass
class BF16Config:
    enabled: bool = False
    immediate_grad_update: bool = True
    check_grad_overflow: bool = False


@dataclass
class OffloadParamConfig:
    """Ref: runtime/zero/offload_config.py (DeepSpeedZeroOffloadParamConfig)."""
    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


@dataclass
class OffloadOptimizerConfig:
    """Ref: runtime/zero/offload_config.py (DeepSpeedZeroOffloadOptimizerConfig)."""
    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0  # TwinFlow/Offload++ partial offload fraction
    # SuperOffload (ref engine.py:935 super_offload +
    # superoffload_stage3.py): pipelined host Adam with speculative step +
    # rollback-on-overflow
    super_offload: bool = False
    cpuadam_cores_perc: float = 0.8
    # Chunked host optimizer pipeline (ZeRO-Offload chunked CPU Adam +
    # ZeRO-Infinity NVMe chunk tier; runtime/offload.ChunkedHostOptimizer).
    # working_set_bytes > 0 opts in: when the fp32 optimizer state
    # (12 B/param) exceeds this budget, the Adam step runs on the host over
    # fixed chunk_bytes chunks with double-buffered device↔host streams —
    # peak host residency O(chunk), not O(state).  0 keeps the legacy
    # whole-state streaming/store paths.
    chunk_bytes: int = 64 << 20
    working_set_bytes: int = 0


@dataclass
class ZeroConfig:
    """Ref: ``DeepSpeedZeroConfig`` (runtime/zero/config.py).

    On TPU the stages map to sharding specs over the (data×fsdp) mesh axes:
      stage 0 → replicated params/grads/opt-state (pure DP)
      stage 1 → optimizer state sharded
      stage 2 → optimizer state + gradients sharded (reduce-scatter semantics)
      stage 3 → params also sharded; XLA inserts gather/release collectives
    Bucket-size knobs are accepted for compat; XLA's latency-hiding scheduler
    replaces the IPG bucketing machinery.
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    cpu_offload: Optional[bool] = None  # deprecated alias
    cpu_offload_params: Optional[bool] = None  # deprecated alias
    prefetch_bucket_size: int = 50_000_000
    param_persistence_threshold: int = 100_000
    model_persistence_threshold: int = 2 ** 63 - 1
    max_live_parameters: int = 1_000_000_000
    max_reuse_distance: int = 1_000_000_000
    gather_16bit_weights_on_model_save: bool = False
    use_all_reduce_for_fetch_params: bool = False
    stage3_gather_16bit_weights_on_model_save: Optional[bool] = None
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # ZeRO++ knobs (ref runtime/zero/config.py:300-313)
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    # MiCS (ref runtime/zero/mics.py)
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True
    log_trace_cache_warnings: bool = False
    # TPU extension: fail hard when a >1MB param falls through the
    # divisibility fallback and silently replicates under ZeRO-3/TP
    # (ShardingRules.audit_replicated)
    strict_sharding: bool = False

    def __post_init__(self):
        if isinstance(self.offload_param, dict):
            self.offload_param = _from_dict(OffloadParamConfig, self.offload_param,
                                            "zero_optimization.offload_param")
        if isinstance(self.offload_optimizer, dict):
            self.offload_optimizer = _from_dict(OffloadOptimizerConfig, self.offload_optimizer,
                                                "zero_optimization.offload_optimizer")
        # deprecated aliases from older DeepSpeed configs
        if self.cpu_offload and self.offload_optimizer is None:
            self.offload_optimizer = OffloadOptimizerConfig(device="cpu")
        if self.cpu_offload_params and self.offload_param is None:
            self.offload_param = OffloadParamConfig(device="cpu")
        if self.stage3_gather_16bit_weights_on_model_save is not None:
            self.gather_16bit_weights_on_model_save = self.stage3_gather_16bit_weights_on_model_save
        if not 0 <= self.stage <= 3:
            raise DeepSpeedConfigError(f"zero_optimization.stage must be in [0,3], got {self.stage}")

    @property
    def offload_optimizer_device(self) -> str:
        return self.offload_optimizer.device if self.offload_optimizer else "none"

    @property
    def offload_param_device(self) -> str:
        return self.offload_param.device if self.offload_param else "none"


@dataclass
class ActivationCheckpointingConfig:
    """Ref: runtime/activation_checkpointing/config. On TPU this selects the
    ``jax.checkpoint`` (remat) policy applied to each transformer block.

    ``partition_activations`` needs no dedicated machinery here: the
    reference splits each saved activation across TP ranks by hand
    (checkpointing.py partition_activations) because torch saves full
    replicas per rank; under GSPMD the saved residuals inherit the
    sharding of the computation that produced them (batch/seq/tensor
    axes), so checkpointed activations are already partitioned whenever
    the activations themselves are.  ``cpu_checkpointing``'s analog is
    the ``offload_dots`` remat policy (pinned-host saved residuals)."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU extension: jax remat policy name
    # (full | nothing_saveable | dots_saveable | dots_with_no_batch_dims_saveable | offload_dots)
    remat_policy: str = "nothing_saveable"


@dataclass
class DataEfficiencyConfig:
    """Ref: data_efficiency JSON block (runtime/data_pipeline/config.py):
    curriculum learning under data_sampling, random-LTD under data_routing.
    Legacy top-level ``curriculum_learning`` is also accepted."""
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = field(default_factory=dict)
    data_routing: Dict[str, Any] = field(default_factory=dict)

    @property
    def curriculum_config(self) -> Optional[Dict[str, Any]]:
        cl = (self.data_sampling or {}).get("curriculum_learning", {})
        if cl.get("enabled"):
            # single-metric shorthand or per-metric "curriculum_metrics"
            metrics = cl.get("curriculum_metrics")
            if metrics:
                return next(iter(metrics.values()))
            return cl
        return None

    @property
    def random_ltd_config(self) -> Optional[Dict[str, Any]]:
        rl = (self.data_routing or {}).get("random_ltd", {})
        return rl if rl.get("enabled") else None


@dataclass
class MonitorBackendConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"
    # wandb/comet extras
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None
    experiment_name: Optional[str] = None
    api_key: Optional[str] = None
    workspace: Optional[str] = None
    mode: Optional[str] = None
    samples_log_interval: int = 100


@dataclass
class ProfilerConfig:
    """``"profiler"`` block — windowed XPlane trace capture (the TPU
    analog of the reference's pytorch-profiler integration; see
    utils/trace.py).  The capture brackets train steps
    [start_step, start_step + num_steps)."""
    enabled: bool = False
    output_dir: str = "./dstpu_profile"
    start_step: int = 1
    num_steps: int = 3


@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class TelemetryCaptureConfig:
    """``"telemetry": {"capture": {...}}`` — budgeted XPlane auto-capture
    windows post-processed into overlap reports (telemetry/capture.py)."""
    enabled: bool = False
    capture_step: int = 0          # force a window at this step (0 = off)
    num_steps: int = 1             # steps per capture window
    budget: int = 2                # max captures per process
    regression_factor: float = 0.0  # arm when p95 > k × trailing median
    window: int = 32               # trailing step-time samples consulted
    output_dir: str = "./dstpu_telemetry"
    device_substr: str = "TPU"     # plane filter for the overlap report


@dataclass
class TracingConfig:
    """``"telemetry": {"tracing": {...}}`` — software request/step spans
    (telemetry/tracing.py): host-side monotonic-clock spans exported as
    Chrome trace-event JSON (Perfetto-viewable).  Disabled tracing costs
    one attribute check per span site and allocates nothing."""
    enabled: bool = False
    trace_path: str = ""           # Chrome trace JSON, written at close()
    max_events: int = 100_000      # bounded in-memory event buffer


@dataclass
class FlightConfig:
    """``"telemetry": {"flight": {...}}`` — flight recorder + hang
    watchdog (telemetry/flight.py): a ring of recent span events plus a
    deadline watchdog that dumps all-thread stacks / ring / telemetry
    snapshot bundles on stalls and crashes."""
    enabled: bool = False
    deadline_s: float = 60.0       # no heartbeat for this long => dump
    poll_s: float = 0.0   # watchdog poll (0 = deadline/4, capped at 1s)
    ring_size: int = 2048          # span-event ring capacity
    output_dir: str = "./dstpu_flight"


@dataclass
class TelemetryConfig:
    """``"telemetry"`` block — the unified per-step telemetry layer
    (telemetry/: StepRecord JSONL + Prometheus + monitor bridge +
    auto-capture + span tracing + flight recorder; see
    docs/OBSERVABILITY.md).

    Enabling adds one hard host sync per recorded step (the record needs
    the loss value); ``interval_steps`` thins that cost on TPU — an
    off-interval step skips record assembly (sync included) entirely,
    unless a regression-triggered capture needs every step time.
    ``measure_flops`` pays one extra AOT compile of the train step at
    the first recorded step (exact fused-program FLOPs); set False for
    the free analytic estimate."""
    enabled: bool = False
    run_id: str = ""               # run-ledger stitching key ("" = none):
    # stamped into every StepRecord, the Tracer's trace metadata, and
    # (via FleetSampler) every TierSnapshot row — telemetry/ledger.py
    # joins a run's artifacts back together on it
    jsonl_path: str = ""           # append-only StepRecord log ("" = off)
    prometheus_path: str = ""      # textfile-collector exposition ("" = off)
    interval_steps: int = 1        # record every Nth step
    window: int = 2048             # shared-histogram sliding window
    peak_flops_per_sec: float = 0.0  # MFU denominator (0 = auto-detect)
    measure_flops: bool = True     # profile_compiled; analytic fallback
    capture: TelemetryCaptureConfig = field(
        default_factory=TelemetryCaptureConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    flight: FlightConfig = field(default_factory=FlightConfig)

    def __post_init__(self):
        if isinstance(self.capture, dict):
            self.capture = _from_dict(TelemetryCaptureConfig, self.capture,
                                      "telemetry.capture")
        if isinstance(self.tracing, dict):
            self.tracing = _from_dict(TracingConfig, self.tracing,
                                      "telemetry.tracing")
        if isinstance(self.flight, dict):
            self.flight = _from_dict(FlightConfig, self.flight,
                                     "telemetry.flight")


@dataclass
class RouterServingConfig:
    """``"serving": {"router": {...}}`` — the replica-set front door
    (serving/router.py; docs/SERVING.md "Router & prefix cache"):
    KV-headroom-aware least-loaded dispatch, sticky sessions, fail-over
    with bit-identical greedy continuation."""
    queue_weight: float = 0.05     # score penalty per outstanding request
    max_failovers: int = 2         # re-dispatches before the error sticks
    sticky_sessions: bool = True   # session key -> replica affinity
    max_sessions: int = 4096       # affinity-map bound (oldest evicted)

    def __post_init__(self):
        if self.queue_weight < 0:
            raise DeepSpeedConfigError(
                f"serving.router.queue_weight={self.queue_weight}: "
                "must be >= 0")
        if self.max_failovers < 0:
            raise DeepSpeedConfigError(
                f"serving.router.max_failovers={self.max_failovers}: "
                "must be >= 0")
        if self.max_sessions < 1:
            raise DeepSpeedConfigError(
                f"serving.router.max_sessions={self.max_sessions}: "
                "must be >= 1")


@dataclass
class PrefixCacheServingConfig:
    """``"serving": {"prefix_cache": {...}}`` — paged prefix cache
    (serving/prefix_cache.py): token-block-aligned prompt prefixes map
    to refcounted KV pages, so shared-system-prompt requests adopt
    already-written KV instead of re-prefilling; eviction is LRU over
    cache-only pages under the admission watermarks."""
    enabled: bool = False
    max_blocks: int = 0            # page cap (0 = watermark-bounded only)
    min_prefix_blocks: int = 1     # don't cache prefixes shorter than this

    def __post_init__(self):
        if self.max_blocks < 0:
            raise DeepSpeedConfigError(
                f"serving.prefix_cache.max_blocks={self.max_blocks}: "
                "must be >= 0 (0 = unbounded)")
        if self.min_prefix_blocks < 1:
            raise DeepSpeedConfigError(
                "serving.prefix_cache.min_prefix_blocks="
                f"{self.min_prefix_blocks}: must be >= 1")


@dataclass
class SpeculativeServingConfig:
    """``"serving": {"disagg": {"speculative": {...}}}`` — speculative
    decoding on the decode tier (serving/disagg.py SpeculativeDecoder):
    a draft model in the same serve loop proposes ``spec_k`` tokens per
    sequence, the target verifies them in one ragged step, and greedy
    acceptance is bit-identical to decoding without a draft."""
    enabled: bool = False
    draft_model: str = ""          # models.get_model_config name
    spec_k: int = 4                # proposals per sequence per round

    def __post_init__(self):
        if self.spec_k < 1:
            raise DeepSpeedConfigError(
                f"serving.disagg.speculative.spec_k={self.spec_k}: "
                "must be >= 1")
        if self.enabled and not self.draft_model:
            raise DeepSpeedConfigError(
                "serving.disagg.speculative.enabled requires a "
                "draft_model (a models registry name sharing the "
                "target's vocabulary)")


@dataclass
class DisaggServingConfig:
    """``"serving": {"disagg": {...}}`` — disaggregated prefill/decode
    tiers (serving/disagg.py): the first ``prefill_replicas`` device
    slices serve compute-bound prompt legs and hand finished KV chains
    to the ``decode_replicas`` bandwidth-bound slices through the
    refcounted allocator (docs/SERVING.md "Disaggregated tiers")."""
    enabled: bool = False
    prefill_replicas: int = 1
    decode_replicas: int = 1
    speculative: SpeculativeServingConfig = field(
        default_factory=SpeculativeServingConfig)

    def __post_init__(self):
        if isinstance(self.speculative, dict):
            self.speculative = _from_dict(SpeculativeServingConfig,
                                          self.speculative,
                                          "serving.disagg.speculative")
        if self.enabled and (self.prefill_replicas < 1
                             or self.decode_replicas < 1):
            raise DeepSpeedConfigError(
                "serving.disagg needs >= 1 replica per tier, got "
                f"prefill_replicas={self.prefill_replicas} "
                f"decode_replicas={self.decode_replicas}")


@dataclass
class SLOServingConfig:
    """``"serving": {"slo": {...}}`` — latency objectives feeding the
    fleet SLO ledger (telemetry/slo.py; docs/OBSERVABILITY.md "Fleet
    snapshots & SLO ledger"): p95 targets in ms (0 = not targeted), an
    attainment ``objective`` in (0, 1], and per-scenario target
    overrides keyed by bench scenario-mix name.  Consumed by the
    ``serve_disagg``/``serve_load_multi`` bench rows (frozen-key ``slo``
    block) and by ``FleetSampler`` ticks — the PR-19 autoscaler's
    scale-up evidence."""
    enabled: bool = False
    ttft_p95_ms: float = 0.0
    tpot_p95_ms: float = 0.0
    queue_wait_p95_ms: float = 0.0
    objective: float = 0.99
    scenario_overrides: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        # parse through the telemetry-side SLOSpec: ITS validation is
        # the contract (bad objective, unknown override keys), and the
        # round-trip doubles as the drift tripwire for this block
        from deepspeed_tpu.telemetry.slo import SLOSpec
        try:
            parsed = SLOSpec(dict(vars(self)))
        except ValueError as e:
            raise DeepSpeedConfigError(f"serving.slo: {e}") from e
        missing = set(vars(self)) - set(vars(parsed))
        if missing:
            raise DeepSpeedConfigError(
                f"serving.slo keys {sorted(missing)} are not understood "
                "by telemetry.slo.SLOSpec — add them to the telemetry-"
                "side parser in the same commit")


@dataclass
class ServingTierConfig:
    """``"serving"`` block — the multi-replica serving tier: N
    data-parallel replicas on disjoint mesh slices behind one router
    (serving/replica.py + router.py), each with an optional paged
    prefix cache.  ``server_config()``/``router.__dict__`` feed the
    serving classes directly, so the block round-trips into
    ``ReplicaSet.build`` + ``Router`` with no translation layer."""
    n_replicas: int = 1
    metrics_window_s: float = 0.0
    router: RouterServingConfig = field(
        default_factory=RouterServingConfig)
    prefix_cache: PrefixCacheServingConfig = field(
        default_factory=PrefixCacheServingConfig)
    disagg: DisaggServingConfig = field(
        default_factory=DisaggServingConfig)
    slo: SLOServingConfig = field(default_factory=SLOServingConfig)

    def __post_init__(self):
        if isinstance(self.router, dict):
            self.router = _from_dict(RouterServingConfig, self.router,
                                     "serving.router")
        if isinstance(self.prefix_cache, dict):
            self.prefix_cache = _from_dict(PrefixCacheServingConfig,
                                           self.prefix_cache,
                                           "serving.prefix_cache")
        if isinstance(self.disagg, dict):
            self.disagg = _from_dict(DisaggServingConfig, self.disagg,
                                     "serving.disagg")
        if isinstance(self.slo, dict):
            self.slo = _from_dict(SLOServingConfig, self.slo,
                                  "serving.slo")
        if self.n_replicas < 1:
            raise DeepSpeedConfigError(
                f"serving.n_replicas={self.n_replicas}: must be >= 1")
        if self.metrics_window_s < 0:
            raise DeepSpeedConfigError(
                f"serving.metrics_window_s={self.metrics_window_s}: "
                "must be >= 0 (0 = lifetime window)")
        if self.disagg.enabled:
            want = (self.disagg.prefill_replicas
                    + self.disagg.decode_replicas)
            if want != self.n_replicas:
                raise DeepSpeedConfigError(
                    f"serving.disagg tiers ({self.disagg.prefill_replicas}"
                    f" prefill + {self.disagg.decode_replicas} decode = "
                    f"{want}) must sum to serving.n_replicas="
                    f"{self.n_replicas}")
        # drift tripwire: the serving-side parsers (serving/router.py
        # RouterConfig, serving/prefix_cache.py PrefixCacheConfig,
        # serving/disagg.py DisaggConfig) accept these dicts and silently
        # IGNORE unknown keys — a field added here but not there would
        # validate at config load and then be dropped at runtime.
        # Round-trip through them and require every block key to come
        # back as an attribute.
        from deepspeed_tpu.serving.disagg import DisaggConfig
        from deepspeed_tpu.serving.prefix_cache import PrefixCacheConfig
        from deepspeed_tpu.serving.router import RouterConfig
        for block, cls in ((self.router_config(), RouterConfig),
                           (self.prefix_cache_config(), PrefixCacheConfig),
                           (self.disagg_config(), DisaggConfig)):
            parsed = cls(block)
            missing = set(block) - set(vars(parsed))
            if missing:
                raise DeepSpeedConfigError(
                    f"serving config keys {sorted(missing)} are not "
                    f"understood by {cls.__name__} — add them to the "
                    "serving-side parser in the same commit")
        # ...and one level deeper for the nested speculative block
        from deepspeed_tpu.serving.disagg import SpeculativeConfig
        spec_block = dict(vars(self.disagg.speculative))
        spec_missing = set(spec_block) - set(vars(
            SpeculativeConfig(spec_block)))
        if spec_missing:
            raise DeepSpeedConfigError(
                f"serving.disagg.speculative keys {sorted(spec_missing)} "
                "are not understood by SpeculativeConfig — add them to "
                "the serving-side parser in the same commit")

    def prefix_cache_config(self) -> Dict[str, Any]:
        """Per-replica prefix-cache config dict."""
        return dict(vars(self.prefix_cache))

    def server_config(self) -> Dict[str, Any]:
        """Per-replica ``InferenceServer`` config dict."""
        return {"prefix_cache": self.prefix_cache_config(),
                "metrics_window_s": self.metrics_window_s}

    def router_config(self) -> Dict[str, Any]:
        """``Router`` config dict."""
        return dict(vars(self.router))

    def disagg_config(self) -> Dict[str, Any]:
        """``serving.disagg`` dict for ``ReplicaSet.build(disagg=...)``
        (the nested speculative block flattens to a plain dict so the
        serving-side ``DisaggConfig`` can re-parse it)."""
        d = dict(vars(self.disagg))
        d["speculative"] = dict(vars(self.disagg.speculative))
        return d

    def slo_config(self) -> Dict[str, Any]:
        """``serving.slo`` dict for ``telemetry.slo.SLOSpec``."""
        return dict(vars(self.slo))


@dataclass
class StepScheduleConfig:
    """``"step_schedule"`` block — the overlap-driven step schedule
    (autotuning/overlap_scheduler.py; docs/AUTOTUNING.md).

    ``mode``:

    * ``"static"`` — the defaults below (or explicit values) apply as-is;
      no probing.
    * ``"probe"`` — a launch path that honors the block (bench rows,
      ``ensure_schedule``) runs ``probe_steps`` compiled steps under a
      forced telemetry capture, reads the overlap report, and rewrites
      the block to ``"pinned"`` with the chosen knobs.
    * ``"pinned"`` — a tuned schedule frozen by a previous probe; never
      re-probes, so a tuned run is reproducible.  ``decisions`` carries
      the :class:`ScheduleDecision` records (evidence included) that
      justified the pinned values.

    Knob families (each actuated by ``runtime/engine.py``):

    * ``gather_prefetch_depth`` — ZeRO-3 gather prefetch window: the
      layer-scan unroll factor, which bounds how far XLA's
      latency-hiding scheduler can hoist a parameter all-gather ahead of
      its use (models/transformer.py ``scan_unroll``).
    * ``param_persistence_threshold`` / ``prefetch_bucket_size`` —
      overrides for the static ``zero_optimization`` values (``None`` =
      keep the zero block's setting).  The persistence threshold feeds
      the sharding rules directly (small ZeRO-3 params stay gathered).
    * ``ring_interleave`` — ring-attention hop schedule: 1 = attend then
      rotate (serial), 2 = issue the next hop's ppermute before the
      attend so transfer and compute are dataflow-independent
      (sequence/ring.py).
    * ``weight_update`` — ``"fused"`` (the stage's native layout) or
      ``"decomposed"`` (shard optimizer state + grad accumulator over
      the ZeRO axes even at stage 0/1: reduce-scatter + 1/world update +
      params all-gather, arXiv:2004.13336).
    * ``fused_gather_matmul`` — ZeRO-3 fused gather-matmul
      (ops/pallas/gather_matmul.py): the layer MLP runs as an explicit
      shard_map whose matmul region issues the following matmul's param
      all-gather ahead of the current one, instead of leaving the
      gathers to GSPMD scheduling (the T3 fusion, arXiv:2401.16677);
      composes with ``gather_prefetch_depth``'s unroll window.
      Warn-fallback to the scheduled path when the config is ineligible.
    * ``fused_reduce_scatter`` — with ``weight_update="decomposed"``,
      the train step accumulates gradients LOCALLY inside a shard_map
      over the DP axes and issues an explicit per-leaf reduce-scatter in
      the accumulation epilogue, consuming the accumulator in place,
      instead of relying on GSPMD to insert the scatter at the layout
      constraint.  Warn-fallback when ineligible.
    """
    mode: str = "static"            # static | probe | pinned
    probe_steps: int = 3            # compiled steps per probe (+1 warmup)
    overlap_threshold: float = 0.5  # overlap below this ⇒ act
    gather_prefetch_depth: int = 1
    param_persistence_threshold: Optional[int] = None
    prefetch_bucket_size: Optional[int] = None
    ring_interleave: int = 1
    weight_update: str = "fused"    # fused | decomposed
    fused_gather_matmul: bool = False
    fused_reduce_scatter: bool = False
    decisions: Optional[List[Dict[str, Any]]] = None

    MODES = ("static", "probe", "pinned")
    WEIGHT_UPDATES = ("fused", "decomposed")
    RING_INTERLEAVES = (1, 2)

    def __post_init__(self):
        if self.mode not in self.MODES:
            raise DeepSpeedConfigError(
                f"step_schedule.mode must be one of {list(self.MODES)}, "
                f"got {self.mode!r}")
        if self.weight_update not in self.WEIGHT_UPDATES:
            raise DeepSpeedConfigError(
                f"step_schedule.weight_update must be one of "
                f"{list(self.WEIGHT_UPDATES)}, got {self.weight_update!r}")
        if int(self.ring_interleave) not in self.RING_INTERLEAVES:
            raise DeepSpeedConfigError(
                f"step_schedule.ring_interleave must be one of "
                f"{list(self.RING_INTERLEAVES)}, got {self.ring_interleave}")
        self.ring_interleave = int(self.ring_interleave)
        self.fused_gather_matmul = bool(self.fused_gather_matmul)
        self.fused_reduce_scatter = bool(self.fused_reduce_scatter)
        if int(self.probe_steps) < 1:
            raise DeepSpeedConfigError(
                f"step_schedule.probe_steps must be >= 1, got "
                f"{self.probe_steps}")
        self.probe_steps = int(self.probe_steps)
        if int(self.gather_prefetch_depth) < 1:
            raise DeepSpeedConfigError(
                "step_schedule.gather_prefetch_depth must be >= 1, got "
                f"{self.gather_prefetch_depth}")
        self.gather_prefetch_depth = int(self.gather_prefetch_depth)
        if not 0.0 <= float(self.overlap_threshold) <= 1.0:
            raise DeepSpeedConfigError(
                "step_schedule.overlap_threshold must be in [0, 1], got "
                f"{self.overlap_threshold}")
        if self.param_persistence_threshold is not None:
            if int(self.param_persistence_threshold) < 0:
                raise DeepSpeedConfigError(
                    "step_schedule.param_persistence_threshold must be >= 0")
            self.param_persistence_threshold = \
                int(self.param_persistence_threshold)
        if self.prefetch_bucket_size is not None:
            if int(self.prefetch_bucket_size) <= 0:
                raise DeepSpeedConfigError(
                    "step_schedule.prefetch_bucket_size must be positive")
            self.prefetch_bucket_size = int(self.prefetch_bucket_size)
        if self.decisions is not None:
            # decision records round-trip through the frozen vocabulary —
            # a hand-edited pinned block with a bogus decision fails at
            # config load, not at some later analysis step
            from deepspeed_tpu.autotuning.overlap_scheduler import \
                ScheduleDecision

            try:
                for d in self.decisions:
                    ScheduleDecision.from_dict(d)
            except (KeyError, TypeError, ValueError) as e:
                raise DeepSpeedConfigError(
                    f"step_schedule.decisions: invalid record ({e})") from e


@dataclass
class CommQuantizationConfig:
    """``"comm_quantization"`` block — quantized ZeRO collectives
    (comm/quantized.py; docs/QUANTIZED_COMM.md).

    Selects a wire dtype per collective:

    * ``grad_reduce`` — the data-parallel gradient reduction of the
      train step.  Any non-default setting (including explicit
      ``"fp32"``) routes the reduction through the engine's explicit
      shard_map collective path, whose wire volume is recorded
      per-collective in telemetry; ``int8``/``fp8`` quantize the
      payload (EQuARX-style block scaling, fp32 accumulation).
    * ``zero3_gather`` — the stage-3 parameter all-gather (the qwZ
      straight-through gather, parallel/zeropp.py); ``int8``/``fp8``
      move quantized payloads on the wire.
    * ``ring_rotation`` — the ring-attention K/V (and traveling-grad)
      rotation over the "seq" mesh ring (sequence/ring.py):
      ``int8``/``fp8`` move block-quantized payloads + per-row fp32
      scales on every ``ppermute`` hop; dequant runs inside the
      consuming flash kernel's epilogue on the fused path (int8) or
      through the shared XLA codec otherwise.  Blocks are the head dim
      (``group_size`` does not apply to this collective).

    ``error_feedback`` carries the grad-reduce quantization residual
    into the next step (LoCo-style; ignored for fp32 wire).  The
    ``collectives`` dict is an equivalent per-collective spelling
    (``{"grad_reduce": "int8"}``); unknown collective names are
    rejected."""
    enabled: bool = False
    grad_reduce: str = "fp32"      # fp32 | int8 | fp8
    zero3_gather: str = "fp32"     # fp32 | int8 | fp8
    ring_rotation: str = "fp32"    # fp32 | int8 | fp8
    group_size: int = 256          # block size per fp32 scale
    error_feedback: bool = True
    collectives: Optional[Dict[str, str]] = None

    COLLECTIVES = ("grad_reduce", "zero3_gather", "ring_rotation")
    WIRE_DTYPES = ("fp32", "int8", "fp8")

    def __post_init__(self):
        if self.collectives is not None:
            if not isinstance(self.collectives, dict):
                raise DeepSpeedConfigError(
                    "comm_quantization.collectives must be a dict of "
                    "{collective: wire_dtype}")
            for name, dtype in self.collectives.items():
                if name not in self.COLLECTIVES:
                    raise DeepSpeedConfigError(
                        f"comm_quantization.collectives: unknown collective "
                        f"{name!r} (known: {list(self.COLLECTIVES)})")
                setattr(self, name, dtype)
        for name in self.COLLECTIVES:
            val = str(getattr(self, name)).lower()
            if val not in self.WIRE_DTYPES:
                raise DeepSpeedConfigError(
                    f"comm_quantization.{name} must be one of "
                    f"{list(self.WIRE_DTYPES)}, got {val!r}")
            setattr(self, name, val)
        if int(self.group_size) <= 0:
            raise DeepSpeedConfigError(
                f"comm_quantization.group_size must be positive, got "
                f"{self.group_size}")
        self.group_size = int(self.group_size)


@dataclass
class CommsLoggerConfig:
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@dataclass
class TensorParallelConfig:
    """Ref: runtime/tensor_parallel config + AutoTP. ``autotp_size`` sets the
    mesh "tensor" axis; sharding rules come from the model's param-path
    patterns (AutoTP-equivalent, module_inject/auto_tp.py:193)."""
    enabled: bool = True
    autotp_size: int = 1
    tp_size: Optional[int] = None
    tp_grain_size: int = 64

    @property
    def size(self) -> int:
        return int(self.tp_size or self.autotp_size or 1)


@dataclass
class PipelineConfig:
    """Ref: runtime/pipe. ``stages`` sets the mesh "pipe" axis."""
    stages: int = 1
    partition_method: str = "parameters"
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    num_microbatches: Optional[int] = None


@dataclass
class MeshConfig:
    """TPU extension: explicit logical mesh axis sizes.

    Any axis set to -1 is inferred so the product equals the device count.
    Axis semantics (outer→inner, DCN-friendly axes first):
      pipe   — pipeline stages            (ref: runtime/pipe/topology.py)
      data   — pure data parallel / ZeRO  (ref: DP groups, groups.py)
      expert — expert parallel subdivision of data (ref: groups.py:240)
      seq    — Ulysses sequence parallel  (ref: sequence/layer.py)
      tensor — tensor/model parallel      (ref: AutoTP)
    """
    pipe: int = 1
    data: int = -1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def sizes(self) -> Dict[str, int]:
        return {"pipe": self.pipe, "data": self.data, "expert": self.expert,
                "seq": self.seq, "tensor": self.tensor}

    def resolved(self, n_devices: int) -> Dict[str, int]:
        """Delegates to the topology resolver so config and MeshTopology can
        never disagree on mesh semantics."""
        from deepspeed_tpu.parallel.topology import resolve_mesh_sizes

        try:
            return resolve_mesh_sizes(self.sizes(), n_devices)
        except ValueError as e:
            raise DeepSpeedConfigError(str(e)) from e


@dataclass
class CheckpointConfig:
    """Ref: runtime/config checkpoint block + checkpoint_engine selection."""
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False
    writer: Optional[Dict[str, Any]] = None


@dataclass
class AIOConfig:
    """Ref: op_builder/async_io.py + deepspeed/runtime/swap_tensor/constants.py."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False
    use_direct: bool = False  # O_DIRECT data path (bypass the page cache)


class DeepSpeedConfig:
    """Parsed + validated config. Accepts a JSON path or a dict.

    Ref: ``DeepSpeedConfig`` (runtime/config.py). ``world_size`` here is the
    *data-parallel* world (dp×expert axes), used for batch resolution exactly
    like the reference's ``dp_world_size``.
    """

    def __init__(self, config: Union[str, Dict[str, Any], None],
                 world_size: Optional[int] = 1,
                 n_devices: Optional[int] = None):
        if config is None:
            config = {}
        if isinstance(config, str):
            with open(config, "r") as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise DeepSpeedConfigError(f"config must be a dict or JSON path, got {type(config)}")
        self._param_dict = copy.deepcopy(config)
        self.world_size = world_size

        d = self._param_dict
        # -- batch sizes (resolved below) --
        self.train_batch_size: Optional[int] = d.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu: Optional[int] = d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps: Optional[int] = d.get(C.GRADIENT_ACCUMULATION_STEPS)

        # -- sub-configs --
        opt = d.get(C.OPTIMIZER)
        self.optimizer: Optional[OptimizerConfig] = (
            _from_dict(OptimizerConfig, opt, "optimizer") if opt is not None else None)
        sched = d.get(C.SCHEDULER)
        self.scheduler: Optional[SchedulerConfig] = (
            _from_dict(SchedulerConfig, sched, "scheduler") if sched is not None else None)
        self.fp16 = _from_dict(FP16Config, d.get(C.FP16), "fp16")
        bf16_dict = d.get(C.BFLOAT16, d.get(C.BFLOAT16_OLD))
        self.bf16 = _from_dict(BF16Config, bf16_dict, "bf16")
        self.torch_autocast = _from_dict(TorchAutocastConfig,
                                         d.get("torch_autocast"),
                                         "torch_autocast")
        if self.torch_autocast.enabled:
            if self.fp16.enabled or self.bf16.enabled:
                raise DeepSpeedConfigError(
                    "torch_autocast cannot be combined with an explicit "
                    "fp16/bf16 block (ref runtime/torch_autocast.py)")
            # autocast selects the compute dtype (per-op fp32 islands are
            # the built-in policy of the functional model)
            dt = self.torch_autocast.dtype
            if dt in ("bfloat16", "bf16"):
                self.bf16 = BF16Config(enabled=True)
            elif dt in ("float16", "fp16", "half"):
                self.fp16 = _from_dict(FP16Config, {"enabled": True},
                                       "fp16")
            else:
                raise DeepSpeedConfigError(
                    f"torch_autocast.dtype must be bfloat16 or float16, "
                    f"got {dt!r}")
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        self.zero_config = _from_dict(ZeroConfig, d.get(C.ZERO_OPTIMIZATION), "zero_optimization")
        self.activation_checkpointing = _from_dict(
            ActivationCheckpointingConfig, d.get(C.ACTIVATION_CHECKPOINTING), "activation_checkpointing")
        self.tensorboard = _from_dict(MonitorBackendConfig, d.get(C.TENSORBOARD), "tensorboard")
        self.wandb = _from_dict(MonitorBackendConfig, d.get(C.WANDB), "wandb")
        self.csv_monitor = _from_dict(MonitorBackendConfig, d.get(C.CSV_MONITOR), "csv_monitor")
        self.comet = _from_dict(MonitorBackendConfig, d.get(C.COMET), "comet")
        self.flops_profiler = _from_dict(FlopsProfilerConfig, d.get(C.FLOPS_PROFILER), "flops_profiler")
        self.profiler = _from_dict(ProfilerConfig, d.get(C.PROFILER), "profiler")
        self.comms_logger = _from_dict(CommsLoggerConfig, d.get(C.COMMS_LOGGER), "comms_logger")
        self.comm_quantization = _from_dict(
            CommQuantizationConfig, d.get("comm_quantization"),
            "comm_quantization")
        self.step_schedule = _from_dict(
            StepScheduleConfig, d.get("step_schedule"), "step_schedule")
        self.telemetry = _from_dict(TelemetryConfig, d.get(C.TELEMETRY), "telemetry")
        self.serving = _from_dict(ServingTierConfig, d.get("serving"),
                                  "serving")
        self.tensor_parallel = _from_dict(TensorParallelConfig, d.get(C.TENSOR_PARALLEL), "tensor_parallel")
        self.pipeline = _from_dict(PipelineConfig, d.get(C.PIPELINE), "pipeline")
        self.checkpoint_config = _from_dict(CheckpointConfig, d.get(C.CHECKPOINT), "checkpoint")
        self.aio_config = _from_dict(AIOConfig, d.get("aio"), "aio")
        de = d.get(C.DATA_EFFICIENCY)
        if de is None and d.get(C.CURRICULUM_LEARNING_LEGACY, {}).get("enabled"):
            # legacy top-level curriculum_learning block → wrap it
            de = {"enabled": True,
                  "data_sampling": {"curriculum_learning":
                                    d[C.CURRICULUM_LEARNING_LEGACY]}}
        self.data_efficiency = _from_dict(DataEfficiencyConfig, de,
                                          "data_efficiency")

        # -- mesh --
        mesh_dict = dict(d.get(C.MESH) or {})
        if "tensor" not in mesh_dict and self.tensor_parallel.size > 1:
            mesh_dict["tensor"] = self.tensor_parallel.size
        if "seq" not in mesh_dict and d.get(C.SEQUENCE_PARALLEL_SIZE):
            mesh_dict["seq"] = int(d[C.SEQUENCE_PARALLEL_SIZE])
        if "pipe" not in mesh_dict and self.pipeline.stages > 1:
            mesh_dict["pipe"] = self.pipeline.stages
        if "expert" not in mesh_dict and d.get(C.EXPERT_PARALLEL_SIZE):
            mesh_dict["expert"] = int(d[C.EXPERT_PARALLEL_SIZE])
        self.mesh = _from_dict(MeshConfig, mesh_dict, "mesh")

        # -- scalars --
        self.gradient_clipping: float = float(d.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients: bool = bool(d.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT))
        self.gradient_predivide_factor: float = float(
            d.get(C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT))
        self.steps_per_print: int = int(d.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT))
        self.wall_clock_breakdown: bool = bool(d.get(C.WALL_CLOCK_BREAKDOWN, False))
        self.memory_breakdown: bool = bool(d.get(C.MEMORY_BREAKDOWN, False))
        self.dump_state: bool = bool(d.get(C.DUMP_STATE, False))
        self.zero_allow_untested_optimizer: bool = bool(d.get(C.ZERO_ALLOW_UNTESTED_OPTIMIZER, False))
        self.communication_data_type: Optional[str] = d.get(C.COMMUNICATION_DATA_TYPE)
        self.sparse_gradients_enabled: bool = bool(d.get(C.SPARSE_GRADIENTS, False))
        self.load_universal_checkpoint: bool = bool(
            d.get(C.LOAD_UNIVERSAL_CHECKPOINT, self.checkpoint_config.load_universal))
        self.dataloader_drop_last: bool = bool(d.get(C.DATALOADER_DROP_LAST, False))
        self.seed: int = int(d.get("seed", 42))
        self.gradient_accumulation_dtype: str = d.get("data_types", {}).get(
            "grad_accum_dtype", "fp32") if isinstance(d.get("data_types"), dict) else "fp32"

        # world_size=None defers batch resolution until the topology is known
        # (engine calls resolve_world()).
        if world_size is not None:
            self._resolve_batch_sizes()

    def resolve_world(self, world_size: int) -> None:
        """Set the data-parallel world and resolve batch sizes (deferred)."""
        self.world_size = world_size
        self._resolve_batch_sizes()

    # ------------------------------------------------------------------
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def _resolve_batch_sizes(self) -> None:
        """Same resolution rules as ref runtime/config.py batch assertions:
        train == micro * gas * dp_world; any one may be inferred."""
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        ws = max(1, self.world_size)

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            if train % (micro * ws) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} not divisible by micro_batch*world {micro * ws}")
            gas = train // (micro * ws)
        elif train is not None and gas is not None:
            if train % (gas * ws) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} not divisible by gas*world {gas * ws}")
            micro = train // (gas * ws)
        elif micro is not None:
            gas = gas or C.GRADIENT_ACCUMULATION_STEPS_DEFAULT
            train = micro * gas * ws
        elif train is not None:
            gas = 1
            if train % ws != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} not divisible by world size {ws}")
            micro = train // ws
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu must be set")

        if train != micro * gas * ws:
            raise DeepSpeedConfigError(
                f"Inconsistent batch config: train_batch_size={train} != "
                f"micro({micro}) * gas({gas}) * dp_world({ws})")
        self.train_batch_size = int(train)
        self.train_micro_batch_size_per_gpu = int(micro)
        self.gradient_accumulation_steps = int(gas)

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._param_dict)

    def __repr__(self) -> str:  # pragma: no cover
        parts = [f"train_batch_size={self.train_batch_size}",
                 f"micro={self.train_micro_batch_size_per_gpu}",
                 f"gas={self.gradient_accumulation_steps}",
                 f"zero_stage={self.zero_config.stage}",
                 f"bf16={self.bf16.enabled}", f"fp16={self.fp16.enabled}"]
        return "DeepSpeedConfig(" + ", ".join(parts) + ")"


def load_plan(plan: Union[str, Dict[str, Any]],
              world_size: Optional[int] = 1,
              rank: int = 0) -> "DeepSpeedConfig":
    """Load a planner-emitted plan file (``dstpu-plan --json``) as a
    validated ``DeepSpeedConfig`` — the round-trip half of the plan
    contract (docs/PLANNER.md "Plan files"): the ``rank``-th ranked
    entry's config fragment parses with no edits, or this raises
    ``DeepSpeedConfigError``.  Accepts a path, a plan dict, or a bare
    config fragment (a dict without a ``ranked`` list)."""
    if isinstance(plan, str):
        with open(plan, "r") as f:
            plan = json.load(f)
    if not isinstance(plan, dict):
        raise DeepSpeedConfigError(
            f"plan must be a dict or JSON path, got {type(plan)}")
    if "ranked" in plan:
        ranked = plan["ranked"]
        if not ranked:
            raise DeepSpeedConfigError("plan ranked no candidates")
        if not 0 <= rank < len(ranked):
            raise DeepSpeedConfigError(
                f"plan has {len(ranked)} ranked entries; no rank {rank}")
        fragment = ranked[rank]["config"]
    else:
        fragment = plan
    return DeepSpeedConfig(copy.deepcopy(fragment),
                           world_size=world_size)
