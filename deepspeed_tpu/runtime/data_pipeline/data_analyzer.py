"""Dataset analysis → per-sample curriculum metric files.

Analog of ``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer``): maps a dataset once (parallelizable by worker shards),
computing per-sample difficulty metrics (seqlen, vocab rarity, custom fns),
writes them as ``.npy`` metric files plus a sorted index-by-metric, which
``DeepSpeedDataSampler`` consumes as its ``difficulties``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


def metric_seqlen(sample) -> float:
    return float(len(sample["input_ids"] if isinstance(sample, dict)
                     else sample))


def metric_vocab_rarity(sample, token_freq: Optional[np.ndarray] = None) -> float:
    """Mean negative log-frequency of the sample's tokens (rarer = harder)."""
    toks = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                      else sample)
    if token_freq is None:
        return float(len(toks))
    f = token_freq[np.clip(toks, 0, len(token_freq) - 1)]
    return float(-np.log(np.maximum(f, 1e-12)).mean())


class DataAnalyzer:
    """Map a dataset to metric files (ref DataAnalyzer.run_map/run_reduce).

    ``metrics``: {name: fn(sample) -> float}.  ``num_workers``/``worker_id``
    shard the map phase; ``run_reduce`` merges shard files.
    """

    def __init__(self, dataset, output_dir: str,
                 metrics: Optional[Dict[str, Callable]] = None,
                 num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.output_dir = output_dir
        self.metrics = metrics or {"seqlen": metric_seqlen}
        self.num_workers = num_workers
        self.worker_id = worker_id
        os.makedirs(output_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def _shard_indices(self) -> np.ndarray:
        n = len(self.dataset)
        return np.arange(self.worker_id, n, self.num_workers)

    def run_map(self) -> Dict[str, str]:
        """Compute this worker's metric shard → file paths."""
        idx = self._shard_indices()
        out = {}
        for name, fn in self.metrics.items():
            vals = np.asarray([fn(self.dataset[int(i)]) for i in idx],
                              np.float64)
            path = os.path.join(self.output_dir,
                                f"{name}.worker{self.worker_id}.npy")
            np.save(path, np.stack([idx.astype(np.float64), vals], axis=1))
            out[name] = path
        return out

    def run_reduce(self) -> Dict[str, str]:
        """Merge all worker shards into ``<metric>_values.npy`` (dense,
        index-aligned) + ``<metric>_index_sorted.npy`` (sample indices
        sorted by metric) + a JSON summary."""
        n = len(self.dataset)
        results = {}
        for name in self.metrics:
            dense = np.zeros(n, np.float64)
            seen = np.zeros(n, bool)
            for w in range(self.num_workers):
                path = os.path.join(self.output_dir, f"{name}.worker{w}.npy")
                if not os.path.exists(path):
                    raise RuntimeError(
                        f"metric {name}: worker {w} shard missing ({path}) — "
                        "did every worker run_map?")
                pairs = np.load(path)
                ii = pairs[:, 0].astype(np.int64)
                dense[ii] = pairs[:, 1]
                seen[ii] = True
            if not seen.all():
                raise RuntimeError(
                    f"metric {name}: {int((~seen).sum())} samples missing — "
                    "did every worker run_map?")
            vpath = os.path.join(self.output_dir, f"{name}_values.npy")
            spath = os.path.join(self.output_dir, f"{name}_index_sorted.npy")
            np.save(vpath, dense)
            np.save(spath, np.argsort(dense, kind="stable"))
            results[name] = vpath
        summary = {name: {"min": float(np.load(p).min()),
                          "max": float(np.load(p).max()),
                          "mean": float(np.load(p).mean())}
                   for name, p in results.items()}
        with open(os.path.join(self.output_dir, "analysis_summary.json"),
                  "w") as f:
            json.dump(summary, f, indent=2)
        return results


def load_metric(output_dir: str, name: str = "seqlen") -> np.ndarray:
    """Load a reduced metric as the sampler's ``difficulties`` array."""
    return np.load(os.path.join(output_dir, f"{name}_values.npy"))


class DistributedDataAnalyzer:
    """Multi-process map-reduce dataset analysis (ref
    ``DistributedDataAnalyzer``, data_sampling/data_analyzer.py:455).

    Each ``jax.distributed`` process maps a CONTIGUOUS split of the
    dataset (the reference's ``split_dataset`` semantics), shards merge
    over the DCN host-object collectives
    (:func:`deepspeed_tpu.comm.comm.all_gather_object` — the analog of
    the reference's gather_v/file_write_ordered, which also funnel to
    rank 0 for writing), and rank 0 writes the merged index files the
    reference emits per metric, under ``save_path/<metric>/``:

    * ``<metric>_sample_to_metric.npy`` — value per sample id (dense)
    * ``<metric>_index_to_metric.npy`` — sorted unique metric values
    * ``<metric>_index_to_sample.npz`` — per-value sample-id lists as
      ``ids`` (concatenated) + ``offsets`` (row starts) — the ragged
      layout the reference's mmap builder stores row-per-value
    * ``<metric>_index_to_sample_percentile_merged.npz`` — ~100 merged
      buckets of ids in metric order (ref
      output_index_to_sample_percentile, data_analyzer.py:415)
    * ``<metric>_metric_value.npy`` — for ``accumulate_value_over_samples``
      metrics: the elementwise sum over all workers (e.g. vocab counts)

    plus the flat ``<metric>_values.npy`` / ``<metric>_index_sorted.npy``
    files :class:`DataAnalyzer` writes, so curriculum samplers consume
    either analyzer's output interchangeably.

    The reference sorts via a distributed sample-sort because per-rank
    tensors live on GPU; here metric shards are small host arrays, so
    the merge sorts on rank 0 after the DCN gather — same outputs.

    ``metric_types``: {name: "single_value_per_sample" (default) |
    "accumulate_value_over_samples"}.  ``sample_indices`` optionally maps
    iteration order to user-defined sample ids.
    """

    def __init__(self, dataset, save_path: str,
                 metrics: Optional[Dict[str, Callable]] = None,
                 metric_types: Optional[Dict[str, str]] = None,
                 sample_indices: Optional[Sequence[int]] = None):
        import jax

        self.dataset = dataset
        self.save_path = save_path
        self.metrics = metrics or {"seqlen": metric_seqlen}
        self.metric_types = dict(metric_types or {})
        for name, t in self.metric_types.items():
            if t not in ("single_value_per_sample",
                         "accumulate_value_over_samples"):
                raise ValueError(f"metric_type {t!r} for {name!r} not "
                                 "implemented")
        self.sample_indices = sample_indices
        self.num_workers = jax.process_count()
        self.worker_id = jax.process_index()
        os.makedirs(save_path, exist_ok=True)

    def _worker_split(self) -> range:
        """Contiguous split (ref split_dataset): worker w gets
        [w*n//W, (w+1)*n//W)."""
        n = len(self.dataset)
        w, nw = self.worker_id, self.num_workers
        return range(n * w // nw, n * (w + 1) // nw)

    def run_map_reduce(self) -> Dict[str, str]:
        from deepspeed_tpu.comm import comm

        split = self._worker_split()
        local: Dict[str, Any] = {}
        for name, fn in self.metrics.items():
            mtype = self.metric_types.get(name, "single_value_per_sample")
            if mtype == "single_value_per_sample":
                pairs = []
                for i in split:
                    sid = (int(self.sample_indices[i])
                           if self.sample_indices is not None else i)
                    pairs.append((sid, float(fn(self.dataset[i]))))
                local[name] = pairs
            else:
                acc = None
                for i in split:
                    v = np.asarray(fn(self.dataset[i]), np.float64)
                    acc = v if acc is None else acc + v
                local[name] = (None if acc is None else acc.tolist())

        gathered = comm.all_gather_object(local)
        # Validate on EVERY rank: duplicate ids would silently keep
        # whichever worker's value scattered last — and a rank-0-only
        # raise would leave the other ranks hung at the closing barrier.
        for name in self.metrics:
            if self.metric_types.get(name, "single_value_per_sample") \
                    != "single_value_per_sample":
                continue
            all_ids = np.asarray([p[0] for g in gathered for p in g[name]],
                                 np.int64)
            uniq_ids, id_counts = np.unique(all_ids, return_counts=True)
            if np.any(id_counts > 1):
                dups = uniq_ids[id_counts > 1][:8]
                raise ValueError(
                    f"metric {name!r}: duplicate sample_indices "
                    f"{dups.tolist()} across workers (each sample id "
                    "must map to exactly one value)")
        results: Dict[str, str] = {}
        if self.worker_id == 0:
            n = len(self.dataset)
            for name in self.metrics:
                mdir = os.path.join(self.save_path, name)
                os.makedirs(mdir, exist_ok=True)
                mtype = self.metric_types.get(name,
                                              "single_value_per_sample")
                if mtype == "accumulate_value_over_samples":
                    parts = [np.asarray(g[name], np.float64)
                             for g in gathered if g[name] is not None]
                    # every worker's split was empty (empty dataset):
                    # np.sum([], axis=0) would collapse to scalar 0.0 and
                    # save a shapeless value where callers expect the
                    # metric's accumulator shape
                    total = (np.sum(parts, axis=0) if parts
                             else np.zeros(0, np.float64))
                    path = os.path.join(mdir, f"{name}_metric_value.npy")
                    np.save(path, total)
                    results[name] = path
                    continue
                pairs = np.asarray(
                    [p for g in gathered for p in g[name]], np.float64)
                if pairs.size:
                    ids = pairs[:, 0].astype(np.int64)
                    vals = pairs[:, 1]
                else:
                    ids = np.zeros(0, np.int64)
                    vals = np.zeros(0, np.float64)
                # (duplicate ids already rejected on every rank above)
                # sample_indices may map into a larger corpus id space;
                # size the dense table by the largest id seen.  Ids absent
                # from the gather stay NaN so a missing metric is
                # distinguishable from a measured 0.0.
                size = max(n, int(ids.max()) + 1 if len(ids) else 0)
                dense = np.full(size, np.nan, np.float64)
                dense[ids] = vals
                np.save(os.path.join(mdir, f"{name}_sample_to_metric.npy"),
                        dense)
                # merged metric→samples index: sorted unique values with
                # their (metric-sorted) sample-id rows
                order = np.lexsort((ids, vals))
                sv, si = vals[order], ids[order]
                uniq, starts = np.unique(sv, return_index=True)
                offsets = np.append(starts, len(si)).astype(np.int64)
                np.save(os.path.join(mdir, f"{name}_index_to_metric.npy"),
                        uniq)
                np.savez(os.path.join(mdir, f"{name}_index_to_sample.npz"),
                         ids=si, offsets=offsets)
                # ~100 percentile-merged buckets in metric order
                step = max(1, len(uniq) // 100)
                b_off = [0]
                b_ids = []
                for v_idx in range(0, len(uniq), step):
                    lo = offsets[v_idx]
                    hi = offsets[min(v_idx + step, len(uniq))]
                    b_ids.append(si[lo:hi])
                    b_off.append(b_off[-1] + (hi - lo))
                np.savez(os.path.join(
                    mdir, f"{name}_index_to_sample_percentile_merged.npz"),
                    ids=np.concatenate(b_ids) if b_ids else
                    np.zeros(0, np.int64),
                    offsets=np.asarray(b_off, np.int64))
                # flat sampler-compatible files (DataAnalyzer layout).
                # The NaN missing-id sentinel stays in the merge table
                # above; the sampler's difficulties array must be finite
                # (NaN fails every `difficulty <= threshold` test and
                # would silently drop those samples from the curriculum),
                # so absent ids fall back to 0.0 here.
                finite = np.nan_to_num(dense, nan=0.0)
                np.save(os.path.join(self.save_path, f"{name}_values.npy"),
                        finite)
                np.save(os.path.join(self.save_path,
                                     f"{name}_index_sorted.npy"),
                        np.argsort(finite, kind="stable"))
                results[name] = mdir
        comm.barrier()
        return results
