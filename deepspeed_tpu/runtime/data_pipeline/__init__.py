"""Data efficiency: curriculum learning, curriculum-capable sampling,
random-LTD token dropping, variable-batch-size-and-LR.

Analog of ``deepspeed/runtime/data_pipeline/`` (curriculum_scheduler.py,
data_sampling/data_sampler.py, data_routing/, variable_batch_size_and_lr.py).
"""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.data_routing import (RandomLTDScheduler,
                                                              random_ltd_drop,
                                                              random_ltd_restore)
from deepspeed_tpu.runtime.data_pipeline.variable_batch import (
    batch_by_token_budget, scale_lr_by_batch_size)
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    IndexedDataset, IndexedDatasetBuilder)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalyzer, DistributedDataAnalyzer, load_metric)

__all__ = [
    "CurriculumScheduler", "DeepSpeedDataSampler", "RandomLTDScheduler",
    "random_ltd_drop", "random_ltd_restore", "batch_by_token_budget",
    "scale_lr_by_batch_size", "IndexedDataset", "IndexedDatasetBuilder",
    "DataAnalyzer", "DistributedDataAnalyzer", "load_metric",
]
