"""Hybrid engine — RLHF train ↔ generate on shared weights.

Analog of ``deepspeed/runtime/hybrid_engine.py`` (``DeepSpeedHybridEngine``
:30): during RLHF, the actor model alternates between generation (rollout)
and training (PPO update).  The reference re-wires ZeRO-3-partitioned
weights into inference kernel containers and back.  On TPU there is nothing
to re-wire: training params are a sharded pytree, and generation jits a
decode step over the *same* arrays — mode switching is free, which is the
whole point of keeping both paths functional over one param tree.

Latency bookkeeping mirrors the reference's generate/train timers.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import transformer as tf_model
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine:
    """Wraps a training engine; ``generate`` reads its live params.

    Usage: ``he = DeepSpeedHybridEngine(engine)``; rollout with
    ``he.eval(); he.generate(...)``; then ``he.train();
    he.train_batch(...)`` — weights stay shared throughout.
    """

    def __init__(self, engine, inference_tp_size: Optional[int] = None):
        self.engine = engine
        self.model_config = engine.model_config
        if self.model_config is None:
            raise ValueError("hybrid engine requires an engine built from a "
                             "TransformerConfig model")
        self.inference_tp_size = inference_tp_size
        self._training = True
        self._generate_latency = 0.0
        self._train_latency = 0.0
        self._generate_tokens = 0
        self._kv_gen = None

    # -- mode switches (ref eval()/train() container swap) --------------
    def eval(self) -> None:
        self._training = False

    def train(self, mode: bool = True) -> None:
        self._training = mode

    def release_inference_cache(self) -> None:
        """Parity no-op: there is no separate inference weight cache — the
        decode path reads the training arrays directly."""

    # -- training delegate ----------------------------------------------
    def train_batch(self, data):
        t0 = time.perf_counter()
        loss = self.engine.train_batch(data)
        self._train_latency += time.perf_counter() - t0
        return loss

    def __getattr__(self, name):
        return getattr(self.engine, name)

    # -- generation ------------------------------------------------------
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0, top_k: int = 0,
                 top_p: float = 1.0) -> np.ndarray:
        """KV-cached rollout on the live training weights (ref generate,
        hybrid_engine.py:30: the reference shares ZeRO-3 weights with
        kernel-injected inference containers precisely so RLHF rollouts get
        a KV cache).  Paged prefill + fused decode loop from inference/v2
        jitted over ``engine.params`` — per-token cost is O(S), not the
        O(S²) full-recompute of a naive loop, and mode switching stays
        free because both paths read the same arrays."""
        if self._training:
            log_dist("hybrid engine: generate() called in train mode; "
                     "switching to eval", level="warning")
            self.eval()
        t0 = time.perf_counter()
        if self._kv_gen is None:
            from deepspeed_tpu.inference.kv_generate import KVCachedGenerator

            self._kv_gen = KVCachedGenerator(self.model_config)
        ids = self._kv_gen.generate(self.engine.params, input_ids,
                                    max_new_tokens, temperature=temperature,
                                    seed=seed, top_k=top_k, top_p=top_p)
        self._generate_latency += time.perf_counter() - t0
        self._generate_tokens += max_new_tokens * ids.shape[0]
        return ids

    # -- stats (ref _generate_latency/_training_latency reporting) -------
    def stats(self) -> dict:
        return {"generate_seconds": self._generate_latency,
                "train_seconds": self._train_latency,
                "generated_tokens": self._generate_tokens,
                "tokens_per_sec": (self._generate_tokens / self._generate_latency
                                   if self._generate_latency else 0.0)}
