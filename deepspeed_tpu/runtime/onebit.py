"""1-bit optimizers: OnebitAdam / OnebitLamb / ZeroOneAdam.

TPU-native analog of ``runtime/fp16/onebit/{adam,lamb,zoadam}.py`` and the
compressed backends they ride (``runtime/comm/compressed.py``).  The
reference keeps an eager torch optimizer that calls a hand-written
compressed allreduce; here the WHOLE step — local grads, error-feedback
1-bit momentum exchange, Adam/LAMB update — is one jitted ``shard_map``
program over the data axis (the explicit-collectives "engine-managed" mode,
SURVEY §7).

Algorithm (ref onebit/adam.py):
* warmup (``step < freeze_step``): exact ``psum`` gradient averaging, plain
  Adam — momentum AND variance learn.
* compression stage: variance is FROZEN; each worker folds its local grads
  into its momentum, then momenta are mean-allreduced with 1-bit sign
  compression + worker/server error feedback; the update uses the averaged
  momentum over the frozen ``sqrt(v)``.

OnebitLamb layers the lamb trust ratio on the same compressed momentum
(ref onebit/lamb.py); ZeroOneAdam adds learning-rate/variance freeze
policies with periodic sync intervals (ref onebit/zoadam.py).

qgZ gradient compression (``zero_quantized_gradients``) reuses the same
step shape with int8 block quantization instead of 1-bit signs
(``all_to_all_quant_reduce``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.compressed import compressed_allreduce
from deepspeed_tpu.parallel.topology import DATA_AXIS, MeshTopology
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.jax_compat import shard_map

ONEBIT_OPTIMIZERS = ("onebitadam", "onebitlamb", "zerooneadam")


def _flatten(tree) -> Tuple[jnp.ndarray, list, list]:
    leaves = jax.tree.leaves(tree)
    shapes = [x.shape for x in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    return jnp.concatenate([jnp.ravel(x).astype(jnp.float32) for x in leaves]), shapes, sizes


def _unflatten(flat: jnp.ndarray, treedef, shapes, sizes):
    out, off = [], 0
    for shape, size in zip(shapes, sizes):
        out.append(flat[off:off + size].reshape(shape))
        off += size
    return jax.tree.unflatten(treedef, out)


class OnebitConfig:
    def __init__(self, params: Dict[str, Any], variant: str):
        self.variant = variant
        self.lr = float(params.get("lr", 1e-3))
        betas = params.get("betas", (0.9, 0.999))
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(params.get("eps", 1e-8))
        self.weight_decay = float(params.get("weight_decay", 0.0))
        self.freeze_step = int(params.get("freeze_step", 100))
        # ZeroOneAdam policies (ref zoadam.py): variance update/local-step
        # intervals — exponentially growing sync periods
        self.var_freeze_step = int(params.get("var_freeze_step", self.freeze_step))
        self.var_update_scaler = int(params.get("var_update_scaler", 16))
        self.local_step_scaler = int(params.get("local_step_scaler", 32678))
        self.local_step_clipper = int(params.get("local_step_clipper", 16))
        # Lamb extras (ref onebit/lamb.py)
        self.max_coeff = float(params.get("max_coeff", 10.0))
        self.min_coeff = float(params.get("min_coeff", 0.01))


class OnebitTrainStep:
    """Builds and owns the jitted compressed-DP train step.

    Supports pure data-parallel meshes (the reference's 1-bit optimizers are
    likewise DP-only — incompatible with ZeRO≥2/TP/PP).  Params and
    optimizer state are replicated; error-feedback state is per-rank.
    """

    def __init__(self, topology: MeshTopology, loss_fn: Callable,
                 params: Any, cfg: OnebitConfig, gas: int,
                 grad_clip: float = 0.0):
        if topology.tp_size > 1 or topology.pp_size > 1 or topology.sp_size > 1:
            raise ValueError("1-bit optimizers support data-parallel meshes only "
                             "(ref: 1-bit Adam is incompatible with ZeRO>=2/TP/PP)")
        self.topo = topology
        self.cfg = cfg
        self.world = topology.sizes[DATA_AXIS] * topology.sizes["subdata"] \
            * topology.sizes["expert"]
        self.gas = gas
        self.loss_fn = loss_fn
        self.grad_clip = grad_clip

        flat, shapes, sizes = _flatten(params)
        self._treedef = jax.tree.structure(params)
        self._shapes, self._sizes = shapes, sizes
        n = flat.size
        # pad so chunks divide evenly into world ranks × 8-bit packing
        self._n = n
        self._padded = int(-(-n // (self.world * 8)) * self.world * 8)
        self._built = False
        log_dist(f"1-bit {cfg.variant}: world={self.world} params={n} "
                 f"freeze_step={cfg.freeze_step}")

    # ------------------------------------------------------------------
    def init_state(self, params) -> Dict[str, Any]:
        flat, _, _ = _flatten(params)
        pad = self._padded
        world = self.world
        mesh = self.topo.mesh
        rep = NamedSharding(mesh, P())
        shard0 = NamedSharding(mesh, P((DATA_AXIS, "subdata", "expert")))
        return {
            "m": jax.device_put(jnp.zeros((pad,), jnp.float32), rep),
            "v": jax.device_put(jnp.zeros((pad,), jnp.float32), rep),
            "step": jax.device_put(jnp.int32(0), rep),
            "worker_err": jax.device_put(jnp.zeros((world, pad), jnp.float32), shard0),
            "server_err": jax.device_put(jnp.zeros((world, pad // world), jnp.float32),
                                         shard0),
        }

    # ------------------------------------------------------------------
    def build(self, param_shardings, batch_shardings_fn):
        cfg = self.cfg
        world = self.world
        gas = self.gas
        n, pad = self._n, self._padded
        treedef, shapes, sizes = self._treedef, self._shapes, self._sizes
        loss_fn = self.loss_fn
        clip = self.grad_clip
        axes = (DATA_AXIS, "subdata", "expert")

        def local_step(params, m, v, step, werr, serr, batch_stack, lr):
            """Runs per-device inside shard_map: local grads → compressed
            momentum exchange → replicated update."""
            def body(acc, mb):
                loss, g = jax.value_and_grad(loss_fn)(params, mb)
                return jax.tree.map(lambda a, b: a + b, acc, g), loss

            zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            grads, losses = lax.scan(body, zeros, batch_stack)
            loss = lax.pmean(jnp.mean(losses), axes)
            gflat, _, _ = _flatten(grads)
            gflat = jnp.pad(gflat, (0, pad - n)) / gas

            step = step + 1

            if cfg.variant == "qgz":
                # qgZ: int8 block-quantized hierarchical gradient allreduce
                # (ref all_to_all_quant_reduce, coalesced_collectives.py:31);
                # m and v both learn from the dequantized average.
                from deepspeed_tpu.comm.coalesced_collectives import \
                    _quant_chunked_reduce

                inner = self.topo.sizes["subdata"] * self.topo.sizes["expert"]
                outer = self.topo.sizes[DATA_AXIS]
                inner_axes = ("subdata", "expert")
                if inner > 1:
                    shard = _quant_chunked_reduce(gflat, inner_axes, inner,
                                                  8, 2048)
                    if outer > 1:
                        shard = _quant_chunked_reduce(shard, DATA_AXIS, outer,
                                                      8, 2048)
                        shard = lax.all_gather(shard, DATA_AXIS, axis=0,
                                               tiled=True)
                    g = lax.all_gather(shard, inner_axes, axis=0, tiled=True)
                else:
                    shard = _quant_chunked_reduce(gflat, axes, world, 8, 2048)
                    g = lax.all_gather(shard, axes, axis=0, tiled=True)
                m = cfg.beta1 * m + (1 - cfg.beta1) * g
                v = cfg.beta2 * v + (1 - cfg.beta2) * g * g
            else:
                warm = step <= cfg.freeze_step

                def warmup_branch(args):
                    m, v, werr, serr = args
                    g = lax.pmean(gflat, axes)
                    m2 = cfg.beta1 * m + (1 - cfg.beta1) * g
                    v2 = cfg.beta2 * v + (1 - cfg.beta2) * g * g
                    return m2, v2, werr, serr

                def compressed_branch(args):
                    m, v, werr, serr = args
                    m_local = cfg.beta1 * m + (1 - cfg.beta1) * gflat
                    m_avg, werr2, serr2 = compressed_allreduce(
                        m_local, werr[0], serr[0], axes, world)
                    return m_avg, v, werr2[None], serr2[None]

                m, v, werr, serr = lax.cond(warm, warmup_branch,
                                            compressed_branch,
                                            (m, v, werr[0:1] * 1.0,
                                             serr[0:1] * 1.0))

            # bias correction on momentum only during warmup (ref adam.py
            # keeps torch Adam bias correction; compression stage uses raw m)
            bc1 = 1 - cfg.beta1 ** step.astype(jnp.float32)
            bc2 = 1 - cfg.beta2 ** step.astype(jnp.float32)
            update = (m / bc1) / (jnp.sqrt(v / bc2) + cfg.eps)
            if clip and clip > 0:
                gnorm = jnp.linalg.norm(update)
                update = update * jnp.minimum(1.0, clip / (gnorm + 1e-6))

            upd_tree = _unflatten(update[:n], treedef, shapes, sizes)
            if cfg.variant == "onebitlamb":
                def lamb_scale(p, u):
                    wn = jnp.linalg.norm(p.astype(jnp.float32))
                    un = jnp.linalg.norm(u + cfg.weight_decay * p.astype(jnp.float32))
                    ratio = jnp.clip(wn / (un + 1e-12), cfg.min_coeff, cfg.max_coeff)
                    return jnp.where(wn > 0, ratio, 1.0)

                new_params = jax.tree.map(
                    lambda p, u: (p.astype(jnp.float32)
                                  - lr * lamb_scale(p, u)
                                  * (u + cfg.weight_decay * p.astype(jnp.float32))
                                  ).astype(p.dtype),
                    params, upd_tree)
            else:
                new_params = jax.tree.map(
                    lambda p, u: (p.astype(jnp.float32) * (1 - lr * cfg.weight_decay)
                                  - lr * u).astype(p.dtype),
                    params, upd_tree)
            return new_params, m, v, step, werr, serr, loss

        mesh = self.topo.mesh
        rep = P()
        err_spec = P(axes)
        param_specs = jax.tree.map(lambda s: s.spec, param_shardings)
        batch_specs = batch_shardings_fn

        mapped = shard_map(
            local_step, mesh=mesh,
            in_specs=(param_specs, rep, rep, rep, err_spec, err_spec,
                      batch_specs, rep),
            out_specs=(param_specs, rep, rep, rep, err_spec, err_spec, rep),
            check_vma=False)
        self._jitted = jax.jit(mapped, donate_argnums=(0, 1, 2, 4, 5))
        self._built = True

    def __call__(self, params, state, batch_stack, lr):
        new_params, m, v, step, werr, serr, loss = self._jitted(
            params, state["m"], state["v"], state["step"],
            state["worker_err"], state["server_err"], batch_stack, lr)
        new_state = {"m": m, "v": v, "step": step,
                     "worker_err": werr, "server_err": serr}
        return new_params, new_state, loss
