"""Chunk-granular optimizer-state stores for the chunked host Adam step.

The chunked host optimizer (``runtime/offload.ChunkedHostOptimizer``) views
the whole parameter tree as one flat fp32 vector cut into fixed-size chunks;
each chunk's state is a single contiguous ``(3, n)`` fp32 array (rows
master | exp_avg | exp_avg_sq).  These stores own those arrays between
steps:

* ``HostChunkStore`` — the ``offload_optimizer.device == "cpu"`` tier:
  chunks live as host numpy arrays; get/put are reference moves.
* ``NVMeChunkStore`` — the ``offload_optimizer.device == "nvme"`` tier
  (ref ZeRO-Infinity partitioned_optimizer_swapper.py + AsyncTensorSwapper):
  one ``chunk_<k>.bin`` file per chunk behind two native AIO handles
  (``ops/aio``), reads double-buffered ahead of the consumer and writes
  drained behind it, so host residency is O(buffers x chunk) while the
  full state lives on disk.

Both expose the same five-method protocol (``put`` / ``prefetch`` /
``get`` / ``release`` / ``flush``) plus ``close``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np


class HostChunkStore:
    """RAM tier: chunk arrays are held by reference, no copies."""

    nvme = False

    def __init__(self):
        self._chunks: Dict[int, np.ndarray] = {}

    def put(self, k: int, arr: np.ndarray) -> None:
        self._chunks[k] = arr

    def prefetch(self, k: int) -> None:
        pass

    def get(self, k: int) -> np.ndarray:
        return self._chunks[k]

    def release(self, k: int, arr: np.ndarray) -> None:
        # the store still owns the array it handed out — nothing to recycle
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._chunks.clear()


class NVMeChunkStore:
    """File-backed chunk tier with double-buffered async IO.

    ``put`` issues an async write and keeps the buffer alive until the
    write handle drains (at ``buffer_count`` outstanding writes, or at
    ``flush``); drained buffers are recycled into a small free pool.
    ``prefetch`` issues an async read into a pooled buffer; ``get`` joins
    it (the AIO handle's ``wait`` drains every in-flight read, so the
    consumer keeps at most one chunk of read-ahead — classic double
    buffering).  Reading a chunk whose write has not committed yet drains
    the write handle first (same-file read-after-write hazard; distinct
    chunks never alias files, so the steady-state pipeline never stalls
    on this).
    """

    nvme = True

    def __init__(self, swap_dir: str, aio_config=None, buffer_count: int = 2,
                 prefix: str = "opt_chunk"):
        from deepspeed_tpu.ops.aio import AsyncIOHandle

        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.prefix = prefix
        self.buffer_count = max(2, int(buffer_count))
        cfg = aio_config
        kw = dict(block_size=getattr(cfg, "block_size", 1 << 20),
                  queue_depth=getattr(cfg, "queue_depth", 8),
                  thread_count=getattr(cfg, "thread_count", 4),
                  use_direct=getattr(cfg, "use_direct", False))
        # separate handles: wait() drains a whole handle, and the read-ahead
        # must not have to wait for the write-behind (and vice versa)
        self._read = AsyncIOHandle(**kw)
        self._write = AsyncIOHandle(**kw)
        self._shapes: Dict[int, Tuple[int, ...]] = {}
        self._pending: Dict[int, np.ndarray] = {}   # reads in flight
        self._ready: Dict[int, np.ndarray] = {}     # reads joined, unclaimed
        self._writing: List[np.ndarray] = []        # writes in flight
        self._dirty: set = set()                    # chunk ids being written
        self._free: List[np.ndarray] = []           # recycled buffers

    def _path(self, k: int) -> str:
        return os.path.join(self.swap_dir, f"{self.prefix}_{k}.bin")

    def _alloc(self, shape) -> np.ndarray:
        for i, b in enumerate(self._free):
            if b.shape == tuple(shape):
                return self._free.pop(i)
        return np.empty(shape, np.float32)

    def _recycle(self, arr: np.ndarray) -> None:
        self._free.append(arr)
        del self._free[self.buffer_count:]  # pool stays O(buffers x chunk)

    def _drain_writes(self) -> None:
        errs = self._write.wait()
        if errs:
            raise IOError(f"NVMe chunk store: {errs} failed write chunks")
        for a in self._writing:
            self._recycle(a)
        self._writing = []
        self._dirty.clear()

    def put(self, k: int, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr, np.float32)
        self._shapes[k] = arr.shape
        self._write.async_pwrite(arr, self._path(k))
        self._writing.append(arr)
        self._dirty.add(k)
        if len(self._writing) >= self.buffer_count:
            self._drain_writes()

    def prefetch(self, k: int) -> None:
        if k in self._pending or k in self._ready:
            return
        if k not in self._shapes:
            raise KeyError(f"NVMe chunk store: chunk {k} was never written")
        if k in self._dirty:
            self._drain_writes()
        buf = self._alloc(self._shapes[k])
        self._read.async_pread(buf, self._path(k))
        self._pending[k] = buf

    def get(self, k: int) -> np.ndarray:
        if k in self._ready:
            return self._ready.pop(k)
        if k not in self._pending:
            self.prefetch(k)
        errs = self._read.wait()
        if errs:
            raise IOError(f"NVMe chunk store: {errs} failed read chunks")
        self._ready.update(self._pending)
        self._pending.clear()
        return self._ready.pop(k)

    def release(self, k: int, arr: np.ndarray) -> None:
        self._recycle(arr)

    def flush(self) -> None:
        self._drain_writes()

    def close(self) -> None:
        self.flush()
        self._read.wait()
        self._pending.clear()
        self._ready.clear()
        self._free.clear()
