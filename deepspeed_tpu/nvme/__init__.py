"""NVMe/AIO performance tuning (ref deepspeed/nvme/)."""

from deepspeed_tpu.nvme.chunk_store import HostChunkStore, NVMeChunkStore
from deepspeed_tpu.nvme.perf_sweep import run_sweep, sweep_main

__all__ = ["HostChunkStore", "NVMeChunkStore", "run_sweep", "sweep_main"]
