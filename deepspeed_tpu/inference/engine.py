"""Inference engine (v1-equivalent).

Analog of ``deepspeed.init_inference`` → ``InferenceEngine``
(ref inference/engine.py:40): wraps a model config + params, applies TP
sharding via the same ShardingRules as training (AutoTP-equivalent), and
serves greedy/sampled generation with a static KV cache that keeps shapes
fixed for XLA.  The FastGen-equivalent ragged/continuous-batching engine
lives in ``inference/v2`` (blocked KV cache + scheduler).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.models import transformer as tf_model
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.resilience.oracle import PartitionOracle
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.utils.logging import log_dist


class InferenceConfig:
    def __init__(self, d: Optional[Dict[str, Any]] = None, **kw):
        d = dict(d or {})
        d.update(kw)
        self.tensor_parallel = d.get("tensor_parallel", {})
        if isinstance(self.tensor_parallel, dict):
            self.tp_size = int(self.tensor_parallel.get("tp_size", 1))
        else:
            self.tp_size = int(self.tensor_parallel)
        self.dtype = d.get("dtype", "bfloat16")
        self.max_tokens = int(d.get("max_tokens", d.get("max_out_tokens", 1024)))
        self.max_batch = int(d.get("max_batch", 8))
        self.replace_with_kernel_inject = bool(d.get("replace_with_kernel_inject", True))


class InferenceEngine:
    """Greedy/temperature generation over the functional model zoo."""

    def __init__(self, model: TransformerConfig, config=None,
                 model_params: Optional[Any] = None, seed: int = 0, **kwargs):
        self.cfg = InferenceConfig(config if isinstance(config, dict) else None, **kwargs)
        dt = jnp.bfloat16 if "bf" in str(self.cfg.dtype) else jnp.float32
        self.model_config = model.replace(dtype=dt)
        mesh_sizes = {"tensor": self.cfg.tp_size} if self.cfg.tp_size > 1 else None
        self.topology = MeshTopology(mesh_sizes)
        set_topology(self.topology)
        self.oracle = PartitionOracle(self.topology, zero_stage=0)
        self.rules = self.oracle
        if model_params is None:
            shapes = jax.eval_shape(partial(tf_model.init_params, self.model_config),
                                    jax.random.PRNGKey(seed))
            shardings = self.rules.tree_shardings(shapes)
            self.params = jax.jit(partial(tf_model.init_params, self.model_config),
                                  out_shardings=shardings)(jax.random.PRNGKey(seed))
        else:
            self.params = jax.device_put(
                model_params, self.rules.tree_shardings(model_params))
        self._kv_gen = None
        log_dist(f"InferenceEngine: tp={self.cfg.tp_size} dtype={dt.__name__}")

    # ------------------------------------------------------------------
    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None) -> jnp.ndarray:
        """Full-sequence logits.  ``token_type_ids``/``attention_mask``
        serve the encoder (bert/distilbert fill-mask/classify) families —
        ref v1 injection bert containers."""
        out = tf_model.forward(
            self.params, jnp.asarray(input_ids), self.model_config,
            token_type_ids=None if token_type_ids is None
            else jnp.asarray(token_type_ids),
            attention_mask=None if attention_mask is None
            else jnp.asarray(attention_mask))
        return out[0] if isinstance(out, tuple) else out

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0, top_k: int = 0,
                 top_p: float = 1.0) -> np.ndarray:
        """KV-cached paged generation — O(S) per emitted token: one ragged
        prefill writes the prompt into KV pages, then a fused on-device
        decode loop samples the rest (shares inference/v2's model path; ref
        inference/engine.py:40 generate + FastGen KV semantics).  Greedy
        when temperature == 0."""
        if not self.model_config.causal:
            raise ValueError(
                "generate() requires a causal (decoder) model; "
                f"{self.model_config.arch} is a bidirectional encoder — "
                "use forward() for fill-mask/classification logits")
        if self._kv_gen is None:
            from deepspeed_tpu.inference.kv_generate import KVCachedGenerator

            self._kv_gen = KVCachedGenerator(self.model_config)
        return self._kv_gen.generate(self.params, input_ids, max_new_tokens,
                                     temperature=temperature, seed=seed,
                                     top_k=top_k, top_p=top_p)
