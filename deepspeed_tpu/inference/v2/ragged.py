"""Ragged batching state: blocked KV allocator, sequence manager, batch builder.

TPU-native redesign of the reference FastGen ragged layer
(ref inference/v2/ragged/: ``BlockedAllocator`` blocked_allocator.py:11,
``BlockedKVCache`` kv_cache.py:40, ``DSSequenceDescriptor``/``DSStateManager``
ragged_manager.py:19, ``RaggedBatchWrapper`` ragged_wrapper.py:31).

Differences forced by XLA (fixed shapes, no host pointers on device):

* The device never sees Python sequence objects — each engine step receives a
  ``RaggedBatch`` of FIXED-shape int32 arrays (token ids, per-token sequence
  slot / position / KV-cache destination, block tables, sequence lengths),
  padded up to (token_budget, max_seqs, max_blocks_per_seq). One executable
  serves every prefill/decode mix — the padding discipline replaces the
  reference's variable-size CUDA launches.
* KV "pages" are rows of one flat device array per layer; the block table is
  data, not pointers, and paged attention is a gather over it.
* Block 0 is reserved as a garbage page: padded tokens scatter their KV
  there and padded table entries point at it, so no masking is needed on the
  write path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np


class KVCacheExhausted(RuntimeError):
    """Allocation failed for want of free KV pages.

    A typed subclass so the serving layer can tell "preempt someone and
    retry" (this) apart from genuine config errors (plain RuntimeError,
    e.g. a sequence exceeding max_blocks_per_seq)."""


class BlockedAllocator:
    """Refcounted free-list page allocator (ref blocked_allocator.py:11).

    Block 0 is reserved (garbage page for padding); valid handles are
    1..num_blocks-1.  ``free()`` rejects double-frees and out-of-range
    handles — a double-freed page would be handed to two live sequences
    and silently cross-write their KV.

    Pages are **refcounted** so the serving layer's paged prefix cache
    can share read-only KV pages between sequences: ``allocate`` hands a
    page out at refcount 1, ``acquire`` adds an owner, and ``free``
    drops one owner — the page returns to the free list only when the
    LAST owner releases it.  A caller that never shares pages sees the
    pre-refcount semantics unchanged.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}        # handle -> owner count
        self.num_blocks = num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        """Current owner count (0 = on the free list)."""
        return self._refs.get(block, 0)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise KVCacheExhausted(f"KV cache exhausted: want {n} blocks, "
                                   f"have {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def _validate(self, blocks: Sequence[int], op: str) -> None:
        # Validate the whole batch before mutating: a partially-applied
        # free()/acquire() would leave the caller unable to retry safely.
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"duplicate handles in {op}(): {list(blocks)}")
        for b in blocks:
            if b == 0:
                raise ValueError("block 0 is reserved")
            if not (0 < b < self.num_blocks):
                raise ValueError(f"block {b} out of range "
                                 f"(1..{self.num_blocks - 1})")
            if b not in self._refs:
                raise ValueError(f"block {b} is not allocated "
                                 f"({op} of a free page"
                                 f"{' — double free?' if op == 'free' else ''})")

    def acquire(self, blocks: Sequence[int]) -> None:
        """Add one owner to each live page (prefix-cache sharing: a
        sequence adopting cached pages, or the cache pinning a donor's
        pages past the donor's flush)."""
        self._validate(blocks, "acquire")
        for b in blocks:
            self._refs[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one owner per handle; pages return to the free list at
        owner count zero."""
        self._validate(blocks, "free")
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)


@dataclass
class SequenceDescriptor:
    """Host-side state of one in-flight sequence (ref ragged_manager.py:19)."""
    uid: int
    slot: int                       # row in the device block table
    tokens: List[int] = field(default_factory=list)   # full known token ids
    num_cached: int = 0             # tokens whose KV is already in cache
    blocks: List[int] = field(default_factory=list)

    @property
    def uncached(self) -> int:
        return len(self.tokens) - self.num_cached


class DSStateManager:
    """Tracks live sequences, their slots and KV pages (ref ragged_manager.py).

    ``max_seqs`` bounds concurrent sequences (device block-table rows);
    ``max_blocks_per_seq`` bounds context length per sequence.
    """

    def __init__(self, max_seqs: int, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int):
        self.max_seqs = max_seqs
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.allocator = BlockedAllocator(num_blocks)
        self._seqs: Dict[int, SequenceDescriptor] = {}
        self._free_slots = list(range(max_seqs - 1, -1, -1))

    def __contains__(self, uid: int) -> bool:
        return uid in self._seqs

    def get(self, uid: int) -> SequenceDescriptor:
        return self._seqs[uid]

    @property
    def n_active(self) -> int:
        return len(self._seqs)

    def open(self, uid: int, tokens: Sequence[int],
             cached_blocks: Sequence[int] = (),
             num_cached: int = 0) -> SequenceDescriptor:
        """Open a sequence, optionally seeded with **pre-owned** KV pages.

        ``cached_blocks`` are prefix-cache pages whose KV already holds
        the first ``num_cached`` tokens (the caller must have ``acquire``d
        one owner per page for this sequence — ownership transfers here,
        and ``flush`` releases it).  ``num_cached`` must be block-aligned
        and strictly smaller than ``len(tokens)`` so at least one token
        remains to prefill (the step that samples needs a real row).
        Adopted pages are never written: the first uncached token lands
        at position ``num_cached``, which block-aligns to a FRESH page.
        """
        if uid in self._seqs:
            raise ValueError(f"uid {uid} already active")
        if not self._free_slots:
            raise RuntimeError("no free sequence slots")
        if num_cached:
            if num_cached % self.block_size != 0:
                raise ValueError(
                    f"uid {uid}: num_cached {num_cached} not aligned to "
                    f"block_size {self.block_size} — a partially-filled "
                    "shared page would be appended into by this sequence")
            if num_cached >= len(tokens):
                raise ValueError(
                    f"uid {uid}: num_cached {num_cached} >= prompt length "
                    f"{len(tokens)}; at least one token must prefill")
            if len(cached_blocks) * self.block_size != num_cached:
                raise ValueError(
                    f"uid {uid}: {len(cached_blocks)} cached blocks cover "
                    f"{len(cached_blocks) * self.block_size} tokens, "
                    f"num_cached says {num_cached}")
        elif cached_blocks:
            raise ValueError(f"uid {uid}: cached_blocks without num_cached")
        seq = SequenceDescriptor(uid=uid, slot=self._free_slots.pop(),
                                 tokens=list(tokens),
                                 num_cached=int(num_cached),
                                 blocks=list(cached_blocks))
        self._seqs[uid] = seq
        return seq

    def extend(self, uid: int, token: int) -> None:
        self._seqs[uid].tokens.append(token)

    def ensure_capacity(self, seq: SequenceDescriptor, upto_tokens: int) -> None:
        """Allocate pages so the first ``upto_tokens`` tokens fit."""
        need = -(-upto_tokens // self.block_size)  # ceil
        if need > self.max_blocks_per_seq:
            raise RuntimeError(
                f"sequence {seq.uid} needs {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq}")
        if need > len(seq.blocks):
            seq.blocks.extend(self.allocator.allocate(need - len(seq.blocks)))

    def flush(self, uid: int) -> None:
        """Release a finished sequence (ref ragged_manager flush path)."""
        seq = self._seqs.pop(uid)
        if seq.blocks:
            self.allocator.free(seq.blocks)
        self._free_slots.append(seq.slot)


@dataclass
class RaggedBatch:
    """Fixed-shape device inputs for one engine step
    (ref RaggedBatchWrapper, ragged_wrapper.py:31).

    All arrays are host numpy; the engine ships them to device unchanged
    every step, so shapes never vary and XLA compiles the step once.
    """
    token_ids: np.ndarray       # [T] int32, 0-padded
    token_slot: np.ndarray      # [T] int32; max_seqs = padding slot
    token_pos: np.ndarray       # [T] int32 absolute position in sequence
    token_dest: np.ndarray      # [T] int32 flat KV-cache index (0 = garbage)
    block_tables: np.ndarray    # [max_seqs+1, max_blocks_per_seq] int32
    ctx_lens: np.ndarray        # [max_seqs+1] int32 tokens in cache AFTER step
    logits_idx: np.ndarray      # [max_seqs+1] int32 row in T of final token
    sample_mask: np.ndarray     # [max_seqs+1] bool — sample this slot?
    n_tokens: int               # real (unpadded) token count
    uids_by_slot: Dict[int, int]  # slot → uid for sampled slots


def build_ragged_batch(schedule: "List[tuple]", mgr: DSStateManager,
                       token_budget: int) -> RaggedBatch:
    """Assemble device arrays from (seq, n_new_tokens) work items.

    ``schedule`` holds (SequenceDescriptor, n_tokens) pairs; the last
    scheduled token of a sequence is sampled only if it is the sequence's
    final known token (i.e. the prompt chunk completes the prompt).
    """
    bs = mgr.block_size
    t = token_budget
    pad_slot = mgr.max_seqs
    token_ids = np.zeros((t,), np.int32)
    token_slot = np.full((t,), pad_slot, np.int32)
    token_pos = np.zeros((t,), np.int32)
    token_dest = np.zeros((t,), np.int32)
    block_tables = np.zeros((mgr.max_seqs + 1, mgr.max_blocks_per_seq), np.int32)
    ctx_lens = np.zeros((mgr.max_seqs + 1,), np.int32)
    logits_idx = np.zeros((mgr.max_seqs + 1,), np.int32)
    sample_mask = np.zeros((mgr.max_seqs + 1,), bool)
    uids_by_slot: Dict[int, int] = {}

    total = sum(n_new for _, n_new in schedule)
    if total > t:
        raise RuntimeError(f"schedule ({total} tokens) exceeds budget {t}")

    # Reserve all pages up front so an allocator failure leaves every
    # sequence untouched (no num_cached advance without a KV write).
    for seq, n_new in schedule:
        mgr.ensure_capacity(seq, seq.num_cached + n_new)

    cursor = 0
    for seq, n_new in schedule:
        start = seq.num_cached
        end = start + n_new
        sl = seq.slot
        rows = np.arange(start, end, dtype=np.int32)
        pos_block = rows // bs
        dest = np.asarray(seq.blocks, np.int32)[pos_block] * bs + rows % bs
        token_ids[cursor:cursor + n_new] = seq.tokens[start:end]
        token_slot[cursor:cursor + n_new] = sl
        token_pos[cursor:cursor + n_new] = rows
        token_dest[cursor:cursor + n_new] = dest
        block_tables[sl, :len(seq.blocks)] = seq.blocks
        ctx_lens[sl] = end
        logits_idx[sl] = cursor + n_new - 1
        sample_mask[sl] = (end == len(seq.tokens))
        if sample_mask[sl]:
            uids_by_slot[sl] = seq.uid
        cursor += n_new
        seq.num_cached = end

    return RaggedBatch(token_ids=token_ids, token_slot=token_slot,
                       token_pos=token_pos, token_dest=token_dest,
                       block_tables=block_tables, ctx_lens=ctx_lens,
                       logits_idx=logits_idx, sample_mask=sample_mask,
                       n_tokens=cursor, uids_by_slot=uids_by_slot)
