"""InferenceEngineV2 — continuous-batching serve engine (FastGen analog).

Ref: ``InferenceEngineV2`` (inference/v2/engine_v2.py:30) +
``build_hf_engine`` (engine_factory.py:69). The engine owns the paged KV
cache, the sequence state manager and the SplitFuse scheduler; ``put()``
schedules one ragged step; ``generate()`` runs full continuous-batching
text generation with per-call sampling params (greedy / temperature /
top-k / top-p, sampled on device).

TPU specifics: the ragged step is ONE jitted function with donated KV-cache
buffers (no copies between steps) and fixed shapes — every prefill/decode
mix replays the same executable; tensor-parallel serving reuses the training
ShardingRules so weights shard over the "tensor" mesh axis and XLA inserts
the same collectives AutoTP injection produces in the reference.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.model import (check_sampling_params,
                                              ragged_decode_loop,
                                              ragged_forward,
                                              ragged_forward_sampled,
                                              ragged_forward_verify)
from deepspeed_tpu.inference.v2.ragged import (DSStateManager,
                                               KVCacheExhausted,
                                               build_ragged_batch)
from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
from deepspeed_tpu.models import transformer as tf_model
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.resilience.oracle import PartitionOracle
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.utils.logging import log_dist


class RaggedInferenceEngineConfig:
    """Engine knobs (ref inference/v2/config_v2.py RaggedInferenceEngineConfig)."""

    def __init__(self, d: Optional[Dict[str, Any]] = None, **kw):
        d = {**(d or {}), **kw}
        self.tp_size = int(d.get("tensor_parallel", {}).get("tp_size", 1)
                           if isinstance(d.get("tensor_parallel"), dict)
                           else d.get("tp_size", 1))
        state = d.get("state_manager", {})
        self.max_tracked_sequences = int(state.get("max_tracked_sequences", 64))
        self.max_ragged_batch_size = int(state.get("max_ragged_batch_size", 256))
        self.memory_config = d.get("memory_config", {})
        self.num_blocks = int(self.memory_config.get("num_blocks", 512))
        self.block_size = int(self.memory_config.get("block_size", 16))
        # "int8": blockwise-quantized KV pages (one fp32 scale per
        # (head, row)) — halves decode's KV bandwidth, the bound resource
        # (ref KV-block layout inference/v2/ragged/kv_cache.py:40)
        self.kv_dtype = str(self.memory_config.get("kv_dtype", "auto"))
        if self.kv_dtype not in ("auto", "int8", "bf16", "bfloat16"):
            raise ValueError(f"memory_config.kv_dtype={self.kv_dtype!r}: "
                             "expected 'auto', 'int8', or 'bf16'")
        self.max_context = int(d.get("max_context", 2048))
        # Compile-time guard: the paged decode kernel's per-token page loop
        # is ceil(max_context / block_size) long, and Mosaic compile time
        # grows sharply with it — observed >880 s at 512 blocks/seq on v5e
        # (r04, block_size=64 at 32k context) where a user would assume a
        # hang.  A config error beats a silent 15-minute compile; opt in
        # with {"allow_slow_compile": true} if the one-off compile is
        # acceptable (executions are cached afterwards).
        blocks_per_seq = -(-self.max_context // self.block_size)
        if blocks_per_seq > 256 and not bool(d.get("allow_slow_compile")):
            raise ValueError(
                f"max_context={self.max_context} / block_size="
                f"{self.block_size} = {blocks_per_seq} blocks per sequence: "
                "TPU compile time grows sharply past ~256 (observed >880 s "
                "at 512 on v5e). Raise memory_config.block_size, lower "
                "max_context, or set allow_slow_compile=true to proceed.")
        if blocks_per_seq > 128:
            log_dist(
                f"inference v2: {blocks_per_seq} KV blocks per sequence — "
                "first-compile time on TPU may reach minutes; larger "
                "memory_config.block_size compiles faster", level="warning")
        # longest fused multi-step decode dispatch (one host round-trip
        # runs up to this many steps on device); latency-sensitive hosts
        # raise it to amortize dispatch overhead.  Rounded down to a power
        # of two so the chunk round-up in _fused_decode can never exceed
        # the configured bound (chunk sizes are pow2 compile buckets).
        mdc = max(1, int(d.get("max_decode_chunk", 32)))
        self.max_decode_chunk = 1 << (mdc.bit_length() - 1)
        self.dtype = d.get("dtype", "bfloat16")
        ep = d.get("expert_parallel", {})
        self.ep_size = int(ep.get("ep_size", 1) if isinstance(ep, dict)
                           else ep)
        # module-implementation overrides, e.g. {"attention": "paged_xla"}
        # (ref inference/v2/modules: ConfigBundle names); resolved through
        # inference/v2/modules.py at each attention call.  Validate names
        # NOW — a typo surfacing as a KeyError inside jit tracing at the
        # first generate() would point nowhere near the config
        from deepspeed_tpu.inference.v2 import model as _model  # registers
        from deepspeed_tpu.inference.v2.modules import (available,
                                                        module_overrides)

        self.modules = module_overrides(d)
        for kind, name in self.modules.items():
            if name != "auto" and name not in available(kind):
                raise ValueError(
                    f"unknown {kind} implementation '{name}' "
                    f"(available: {', '.join(available(kind)) or 'none'})")


def _kv_scatter(cache_k, cache_v, rows, k, v):
    """Write handed-off KV page rows into the paged caches (both cache
    layouts: plain array [L, nkv, P, d], or the int8 quantized dict
    {"q": [L, nkv, P, d] int8, "s": [L, nkv, P] fp32})."""
    if isinstance(cache_k, dict):
        cache_k = {"q": cache_k["q"].at[:, :, rows, :].set(k["q"]),
                   "s": cache_k["s"].at[:, :, rows].set(k["s"])}
        cache_v = {"q": cache_v["q"].at[:, :, rows, :].set(v["q"]),
                   "s": cache_v["s"].at[:, :, rows].set(v["s"])}
    else:
        cache_k = cache_k.at[:, :, rows, :].set(k.astype(cache_k.dtype))
        cache_v = cache_v.at[:, :, rows, :].set(v.astype(cache_v.dtype))
    return cache_k, cache_v


def _kv_gather(cache, rows):
    """Read page rows out of either cache layout (host numpy)."""
    if isinstance(cache, dict):
        return {"q": np.asarray(jnp.take(cache["q"], rows, axis=2)),
                "s": np.asarray(jnp.take(cache["s"], rows, axis=2))}
    return np.asarray(jnp.take(cache, rows, axis=2))


def _payload_nbytes(part) -> int:
    if isinstance(part, dict):
        return sum(int(a.nbytes) for a in part.values())
    return int(part.nbytes)


class InferenceEngineV2:
    def __init__(self, model: TransformerConfig,
                 config: Optional[Dict[str, Any]] = None,
                 model_params: Optional[Any] = None, seed: int = 0,
                 devices: Optional[Sequence[Any]] = None, **kw):
        self.cfg = RaggedInferenceEngineConfig(config, **kw)
        dt = jnp.bfloat16 if "bf" in str(self.cfg.dtype) else jnp.float32
        self.model_config = model.replace(dtype=dt)
        if self.cfg.modules:
            self.model_config = self.model_config.replace(
                v2_modules=tuple(sorted(self.cfg.modules.items())))
        mesh_sizes = {}
        if self.cfg.tp_size > 1:
            mesh_sizes["tensor"] = self.cfg.tp_size
        if self.cfg.ep_size > 1:
            mesh_sizes["expert"] = self.cfg.ep_size
        # `devices` pins this engine to a mesh SLICE — the replica tier
        # (serving/replica.py) builds N engines on disjoint slices of one
        # host's devices.  None keeps the whole-world default.
        self.topology = MeshTopology(mesh_sizes or None, devices=devices)
        set_topology(self.topology)
        self.oracle = PartitionOracle(self.topology, zero_stage=0)
        self.rules = self.oracle

        if model_params is None:
            shapes = jax.eval_shape(partial(tf_model.init_params, self.model_config),
                                    jax.random.PRNGKey(seed))
            shardings = self.rules.tree_shardings(shapes)
            self.params = jax.jit(partial(tf_model.init_params, self.model_config),
                                  out_shardings=shardings)(jax.random.PRNGKey(seed))
        else:
            self.params = jax.device_put(model_params,
                                         self.rules.tree_shardings(model_params))

        mc = self.model_config
        max_blocks_per_seq = -(-self.cfg.max_context // self.cfg.block_size)
        self.state_manager = DSStateManager(
            max_seqs=self.cfg.max_tracked_sequences,
            num_blocks=self.cfg.num_blocks,
            block_size=self.cfg.block_size,
            max_blocks_per_seq=max_blocks_per_seq)
        self.scheduler = SplitFuseScheduler(self.state_manager,
                                            token_budget=self.cfg.max_ragged_batch_size)
        self._step_key = jax.random.PRNGKey(seed ^ 0x57E9)  # step() default
        # software-span tracer (telemetry/tracing.py) — the serving layer
        # injects both so ragged dispatches appear in the request trace
        # under the serve loop's trace id instead of one-off orphan ids
        self.tracer = None
        self.trace_id = ""
        # fault injection (resilience/chaos.py ChaosInjector): attached by
        # attach_chaos; None keeps step() at one attribute check per call
        self.chaos = None

        pages = self.cfg.num_blocks * self.cfg.block_size
        # [L, nkv, P, d]: kv-head-major so the paged-attention kernel's page
        # blocks have (rows, head_dim) as their minor dims (lane-aligned).
        kv_shape = (mc.num_layers, mc.kv_heads, pages, mc.dim_per_head)
        if self.cfg.kv_dtype == "int8":
            # quantized cache: int8 payload + one fp32 scale per (head,
            # row) — decode reads half the KV bytes (bandwidth-bound)
            sc_shape = kv_shape[:-1]
            self.cache_k = {"q": jnp.zeros(kv_shape, jnp.int8),
                            "s": jnp.zeros(sc_shape, jnp.float32)}
            self.cache_v = {"q": jnp.zeros(kv_shape, jnp.int8),
                            "s": jnp.zeros(sc_shape, jnp.float32)}
        else:
            kv_dt = (jnp.bfloat16 if self.cfg.kv_dtype in ("bf16", "bfloat16")
                     else dt)
            self.cache_k = jnp.zeros(kv_shape, dtype=kv_dt)
            self.cache_v = jnp.zeros(kv_shape, dtype=kv_dt)

        self._step = jax.jit(
            partial(ragged_forward, cfg=mc, block_size=self.cfg.block_size),
            donate_argnums=(1, 2))
        # sampled variant: mixed prefill/decode steps fetch [max_seqs] int32
        # tokens instead of full [max_seqs, V] logits (ref Weak: v2 prefill
        # loop host-bound — sampling now happens on device for BOTH phases)
        self._step_sampled = jax.jit(
            partial(ragged_forward_sampled, cfg=mc,
                    block_size=self.cfg.block_size),
            static_argnames=("greedy", "top_k"),
            donate_argnums=(1, 2))
        self._decode_loop = jax.jit(
            partial(ragged_decode_loop, cfg=mc, block_size=self.cfg.block_size),
            static_argnames=("n_steps", "greedy", "top_k"),
            donate_argnums=(1, 2))
        # speculative-decoding verify-k: same argument tuple as _step, but
        # the greedy argmax comes back for EVERY token row ([T] int32), so
        # one ragged dispatch scores a whole batch of draft proposals
        self._verify = jax.jit(
            partial(ragged_forward_verify, cfg=mc,
                    block_size=self.cfg.block_size),
            donate_argnums=(1, 2))
        # disaggregated-serving KV import: scatter handed-off page rows
        # into the donated caches in place (rows padded to a pow2 bucket
        # of block rows; padding points at the reserved garbage block 0)
        self._kv_write = jax.jit(_kv_scatter, donate_argnums=(0, 1))
        log_dist(f"InferenceEngineV2: budget={self.cfg.max_ragged_batch_size} "
                 f"blocks={self.cfg.num_blocks}×{self.cfg.block_size} "
                 f"max_seqs={self.cfg.max_tracked_sequences} tp={self.cfg.tp_size}")

    # ------------------------------------------------------------------
    def _ragged_step(self, batch_uids: Sequence[int],
                     batch_tokens: Sequence[Sequence[int]],
                     sample: Optional[Dict[str, Any]] = None):
        """Admit prompts and run ONE ragged step; returns (rb, result) where
        result is the full logits array (sample=None) or on-device-sampled
        tokens [max_seqs] (sample={'key','temperature'} with optional
        'top_k'/'top_p' — see check_sampling_params for their contract)."""
        # Validate the whole batch before touching any state, so a bad entry
        # cannot leave earlier prompts half-admitted.
        if len(batch_uids) != len(batch_tokens):
            raise ValueError(f"{len(batch_uids)} uids vs {len(batch_tokens)} "
                             "token lists")
        seen = set()
        for uid, toks in zip(batch_uids, batch_tokens):
            if uid in self.state_manager or uid in seen:
                raise ValueError(f"uid {uid} already active")
            if not len(toks):
                raise ValueError(f"uid {uid}: empty prompt")
            seen.add(uid)
        for uid, toks in zip(batch_uids, batch_tokens):
            self.admit(uid, toks)
        schedule = self.scheduler.next_schedule()
        if not schedule:
            return None, None
        try:
            rb = build_ragged_batch(schedule, self.state_manager,
                                    self.scheduler.token_budget)
        except KVCacheExhausted:
            # Nothing ran: no num_cached advanced, no KV written.  But
            # next_schedule already promoted prompts whose FINAL chunk was
            # scheduled into the decode set — roll mid-prefill ones back to
            # the head of the prefill queue so they keep chunked prefill
            # (a wrongly-"decoding" prompt would creep 1 token/step).
            # Pages allocated for earlier schedule entries stay attached
            # to their sequences (used next step or freed at flush).
            # Reversed: each demote lands at the queue head, so walking
            # the schedule backwards keeps the original relative order.
            for seq, _n in reversed(schedule):
                if seq.uncached > 1:
                    self.scheduler.demote(seq.uid)
            raise
        # Bucket the step's shapes (power-of-two token count and context
        # width) so decode-heavy steps don't pay the full prefill budget:
        # a 16-seq decode step runs [16, ctx] work, not [budget, max_ctx].
        # A handful of bucket shapes → a handful of cached compilations
        # (the shape discipline the reference gets from its CUDA kernels'
        # ragged launch geometry).
        t_bucket = 16
        while t_bucket < rb.n_tokens:
            t_bucket *= 2
        t_bucket = min(t_bucket, self.scheduler.token_budget)
        bs = self.cfg.block_size
        nb_real = max(1, -(-int(rb.ctx_lens.max()) // bs))
        nb_bucket = 1
        while nb_bucket < nb_real:
            nb_bucket *= 2
        nb_bucket = min(nb_bucket, self.state_manager.max_blocks_per_seq)
        args = (self.params, self.cache_k, self.cache_v,
                jnp.asarray(rb.token_ids[:t_bucket]),
                jnp.asarray(rb.token_slot[:t_bucket]),
                jnp.asarray(rb.token_pos[:t_bucket]),
                jnp.asarray(rb.token_dest[:t_bucket]),
                jnp.asarray(rb.block_tables[:, :nb_bucket]),
                jnp.asarray(rb.ctx_lens),
                jnp.asarray(rb.logits_idx))
        if sample is None:
            logits, self.cache_k, self.cache_v = self._step(*args)
            return rb, logits
        toks, self.cache_k, self.cache_v = self._step_sampled(
            *args, key=sample["key"],
            temperature=jnp.float32(max(sample["temperature"], 1e-6)),
            greedy=(sample["temperature"] <= 0),
            top_k=sample.get("top_k", 0),
            top_p=sample.get("top_p"))
        return rb, toks

    def audit_step_args(self, phase: str = "decode"):
        """``(jitted ragged step, example args)`` for the static graph
        auditor (``analysis/auditor.py``): the decode-shaped (16-token
        bucket), prefill-shaped (full token budget), or speculative
        verify-k (full budget, per-row argmax) step, buildable
        without admitting any sequence.  Zero-filled index arrays are
        fine — the auditor lowers and compiles, never executes, so the
        donated KV caches are not consumed."""
        if phase not in ("decode", "prefill", "verify"):
            raise ValueError(f"audit_step_args: unknown phase {phase!r} "
                             "(decode|prefill|verify)")
        sm = self.state_manager
        t = (min(16, self.scheduler.token_budget) if phase == "decode"
             else self.scheduler.token_budget)
        ids = jnp.zeros((t,), jnp.int32)
        rows = jnp.zeros((sm.max_seqs + 1,), jnp.int32)
        tables = jnp.zeros((sm.max_seqs + 1, sm.max_blocks_per_seq),
                           jnp.int32)
        args = (self.params, self.cache_k, self.cache_v,
                ids, ids, ids, ids, tables, rows, rows)
        return (self._verify if phase == "verify" else self._step), args

    def audit_arg_categories(self):
        """Memory-class manifest for the ``audit_step_args`` tuple (one
        ``analysis.MEMORY_CLASSES`` entry per top-level argument): the
        weights, the two paged KV pools (state, not step-local —
        classed ``other``), and the ragged index arrays."""
        return ("params", "other", "other",
                "activations", "activations", "activations", "activations",
                "other", "other", "other")

    def put(self, batch_uids: Sequence[int],
            batch_tokens: Sequence[Sequence[int]]) -> Dict[int, np.ndarray]:
        """Admit prompts and run ONE ragged step (ref engine_v2.py:30 put).

        Returns {uid: next-token logits} for sequences whose full prompt (or
        pending decode token) was processed this step; uids mid-prefill
        return nothing yet — call put([], []) again to continue.
        """
        rb, logits = self._ragged_step(batch_uids, batch_tokens)
        if rb is None:
            return {}
        logits_np = np.asarray(logits)
        return {uid: logits_np[slot] for slot, uid in rb.uids_by_slot.items()}

    def admit(self, uid: int, tokens: Sequence[int], priority: int = 0,
              front: bool = False, cached_blocks: Sequence[int] = (),
              num_cached: int = 0) -> None:
        """Open a sequence and schedule it WITHOUT running a step.

        The serving layer's admission controller decides *when* to call
        this; ``step()`` decides when work runs.  ``priority`` orders the
        SplitFuse queues (higher first); ``front=True`` requeues ahead of
        every waiting prompt (preempted-request requeue).

        ``cached_blocks``/``num_cached`` seed the sequence with adopted
        prefix-cache pages whose KV already holds the first ``num_cached``
        tokens (pre-acquired by the caller; ownership transfers to the
        sequence — see ``DSStateManager.open``).  Prefill then starts at
        ``num_cached`` instead of 0: the adopted tokens never re-run.
        """
        if uid in self.state_manager:
            raise ValueError(f"uid {uid} already active")
        if not len(tokens):
            raise ValueError(f"uid {uid}: empty prompt")
        self.state_manager.open(uid, [int(x) for x in tokens],
                                cached_blocks=cached_blocks,
                                num_cached=num_cached)
        self.scheduler.add(uid, priority=priority, front=front)

    def step(self, temperature: float = 0.0, key: Optional[Any] = None,
             top_k: int = 0, top_p: float = 1.0,
             return_logits: bool = False) -> Dict[int, Any]:
        """Run ONE ragged step over currently-scheduled work.

        The reusable core of ``generate()`` (factored out for the serving
        loop): returns ``{uid: sampled_token}`` for every sequence whose
        pending work completed this step (``{uid: logits_row}`` with
        ``return_logits=True`` — the serving layer's heterogeneous-
        sampling path), or ``{}`` when nothing is scheduled.  The caller
        owns the extend-or-flush decision per sampled uid.  Raises
        ``KVCacheExhausted`` (with scheduler state rolled back, nothing
        run) when the step needs more KV pages than remain — preempt a
        victim and retry.
        """
        if self.chaos is not None:
            # "engine.step" injection point: specs pinned here (see
            # resilience/chaos.py FaultSpec.point) delay or kill the
            # ragged dispatch itself rather than the serve loop around it
            for f in self.chaos.fire("engine.step"):
                if f.kind == "slow_replica":
                    time.sleep(float(f.params.get("delay_ms", 50.0)) / 1e3)
                elif f.kind == "replica_crash":
                    from deepspeed_tpu.resilience.chaos import ChaosError
                    raise ChaosError("injected replica_crash (engine.step)")
        tr = self.tracer
        sp = None
        if tr is not None and tr.enabled:
            if not self.trace_id:   # standalone use: one stable id
                self.trace_id = tr.new_trace_id()
            sp = tr.span("v2.ragged_step", self.trace_id)
        try:
            return self._step_impl(temperature, key, top_k, top_p,
                                   return_logits)
        finally:
            if sp is not None:
                sp.end()

    def _step_impl(self, temperature: float, key: Optional[Any],
                   top_k: int, top_p: float,
                   return_logits: bool) -> Dict[int, Any]:
        if return_logits:
            rb, logits = self._ragged_step([], [])
            if rb is None:
                return {}
            logits_np = np.asarray(logits)
            return {uid: logits_np[slot]
                    for slot, uid in rb.uids_by_slot.items()}
        top_k, top_p = check_sampling_params(top_k, top_p,
                                             self.model_config.vocab_size)
        if key is None:
            # fresh subkey per call — a fixed key would correlate every
            # non-greedy step's draws (deterministic per engine seed)
            self._step_key, key = jax.random.split(self._step_key)
        rb, toks = self._ragged_step(
            [], [], sample={"key": key, "temperature": temperature,
                            "top_k": top_k, "top_p": top_p})
        if rb is None:
            return {}
        toks_np = np.asarray(toks)
        return {uid: int(toks_np[slot])
                for slot, uid in rb.uids_by_slot.items()}

    def extend(self, uid: int, token: int) -> None:
        """Append a sampled token so the next step decodes it."""
        self.state_manager.extend(uid, int(token))

    def flush(self, uid: int) -> None:
        """Free a finished sequence's slot and KV pages (ref flush)."""
        self.scheduler.retire(uid)
        self.state_manager.flush(uid)

    def preempt(self, uid: int) -> List[int]:
        """Evict a live sequence, returning every token it knows
        (prompt + generated-so-far, including any still-uncached sampled
        token).  Recompute-style preemption: the caller requeues the
        returned list as a fresh prompt; re-prefill rebuilds the KV and
        greedy decoding continues bit-identically.  Slot and pages are
        freed immediately."""
        seq = self.state_manager.get(uid)
        tokens = list(seq.tokens)
        self.flush(uid)
        return tokens

    # -- disaggregated serving: KV-block handoff -----------------------
    def kv_geometry(self) -> tuple:
        """Layout fingerprint a handoff payload must match to be
        importable: two engines with the same geometry (and the shared
        same-seed weight contract) hold interchangeable KV pages."""
        mc = self.model_config
        return (mc.num_layers, mc.kv_heads, self.cfg.block_size,
                mc.dim_per_head, str(self.cfg.kv_dtype),
                str(self.model_config.dtype))

    def export_kv_chain(self, uid: int) -> Optional[Dict[str, Any]]:
        """Read the FULL KV pages of a live sequence's written prefix —
        the prefill half of a prefill→decode handoff.

        Returns a host payload {tokens, k, v, geom, nbytes, export_ms}
        covering ``num_cached // block_size`` full blocks (a partial
        last block is never transferable: adopted pages are read-only
        and the adopter would have to append into it), or None when not
        even one full block is written.  Must run on the thread that
        owns the engine — the gather reads the live donated caches.
        """
        import time as _time

        t0 = _time.perf_counter()
        seq = self.state_manager.get(uid)
        bs = self.cfg.block_size
        n_full = min(seq.num_cached // bs, len(seq.blocks))
        if n_full < 1:
            return None
        rows = np.concatenate(
            [np.arange(b * bs, (b + 1) * bs, dtype=np.int32)
             for b in seq.blocks[:n_full]])
        rows = jnp.asarray(rows)
        k = _kv_gather(self.cache_k, rows)
        v = _kv_gather(self.cache_v, rows)
        return {"tokens": list(seq.tokens[:n_full * bs]), "k": k, "v": v,
                "geom": self.kv_geometry(),
                "nbytes": _payload_nbytes(k) + _payload_nbytes(v),
                "export_ms": (_time.perf_counter() - t0) * 1e3}

    def import_kv_chain(self, payload: Dict[str, Any],
                        skip_blocks: int = 0) -> tuple:
        """Write a handoff payload's pages into THIS engine's cache — the
        decode half of the handoff.  ``skip_blocks`` leading blocks are
        already covered locally (a prefix-cache hit on the same chain:
        the zero-copy ref acquire); only the tail is allocated and
        written.  Returns ``(blocks, n_tokens, bytes_moved)`` where
        ``blocks`` are freshly-allocated pages (refcount 1, ownership
        passes to the caller) holding tokens ``[skip·bs, n_tokens)``.
        Raises ``ValueError`` on a geometry mismatch (caller falls back
        to re-running prefill) and ``KVCacheExhausted`` when the pool
        cannot host the tail.  Engine-owning thread only.
        """
        if tuple(payload["geom"]) != self.kv_geometry():
            raise ValueError(
                f"handoff payload geometry {payload['geom']} does not "
                f"match this engine's {self.kv_geometry()}; the decode "
                "tier must share the prefill tier's model + KV layout")
        bs = self.cfg.block_size
        n_total = len(payload["tokens"]) // bs
        n_new = n_total - int(skip_blocks)
        if n_new <= 0:
            return [], n_total * bs, 0
        blocks = self.state_manager.allocator.allocate(n_new)
        # pow2-bucket the scatter width so a serve lifetime compiles a
        # handful of import shapes; padding rows land in garbage block 0
        nb_bucket = 1
        while nb_bucket < n_new:
            nb_bucket *= 2
        rows = np.zeros((nb_bucket * bs,), np.int32)
        for i, b in enumerate(blocks):
            rows[i * bs:(i + 1) * bs] = np.arange(b * bs, (b + 1) * bs)
        lo, hi = skip_blocks * bs, (skip_blocks + n_new) * bs

        def _cut(part):
            if isinstance(part, dict):
                return {key: _pad(a[:, :, lo:hi]) for key, a in part.items()}
            return _pad(part[:, :, lo:hi])

        def _pad(a):
            width = nb_bucket * bs
            if a.shape[2] == width:
                return a
            pad = [(0, 0)] * a.ndim
            pad[2] = (0, width - a.shape[2])
            return np.pad(a, pad)

        try:
            k, v = _cut(payload["k"]), _cut(payload["v"])
            self.cache_k, self.cache_v = self._kv_write(
                self.cache_k, self.cache_v, jnp.asarray(rows), k, v)
        except BaseException:
            # a failed scatter must not leak the freshly-allocated pages
            # (the donated caches are only rebound on success)
            self.state_manager.allocator.free(blocks)
            raise
        moved = _payload_nbytes(k) + _payload_nbytes(v)
        return blocks, n_total * bs, moved

    # -- speculative decoding: verify-k + draft rewind -----------------
    def verify_step(self, proposals: Dict[int, Sequence[int]]
                    ) -> Dict[int, List[int]]:
        """One ragged verify-k step (greedy only).

        Each uid must be a live sequence with exactly one pending
        sampled token (``uncached == 1``); its ``proposals`` are the
        draft model's guesses for the next k tokens (k may vary per uid,
        and may be 0 — the degenerate case is a plain greedy step).  The
        pending token plus the proposals run as one prefill-style chunk;
        the per-row argmax accepts the longest agreeing proposal prefix
        and appends the target's own argmax after it (the bonus token),
        so the returned ``{uid: accepted_tokens}`` — always ≥ 1 token —
        is bit-identical to one-at-a-time greedy decoding.

        Sequence state advances by the accepted tokens only; KV rows
        written for rejected proposals are dead weight that the next
        write to those positions overwrites (destinations are derived
        from absolute positions, and attention masks by ``ctx_lens``).
        Raises ``KVCacheExhausted`` with every sequence rolled back.
        """
        mgr = self.state_manager
        # validate the WHOLE batch before touching any state: a bad
        # entry must not leave earlier sequences carrying unverified
        # draft tokens (same discipline as _ragged_step admission)
        total = 0
        for uid, props in proposals.items():
            if mgr.get(uid).uncached != 1:
                raise ValueError(
                    f"verify_step: uid {uid} has "
                    f"{mgr.get(uid).uncached} uncached tokens; "
                    "speculative verification needs exactly the one "
                    "pending sampled token")
            total += 1 + len(props)
        if total > self.scheduler.token_budget:
            raise ValueError(
                f"verify_step: {total} tokens exceed the ragged budget "
                f"{self.scheduler.token_budget}; lower spec_k")
        schedule = []
        saved: Dict[int, tuple] = {}
        for uid, props in proposals.items():
            seq = mgr.get(uid)
            saved[uid] = (len(seq.tokens), seq.num_cached)
            seq.tokens.extend(int(t) for t in props)
            schedule.append((seq, 1 + len(props)))
        try:
            rb = build_ragged_batch(schedule, mgr,
                                    self.scheduler.token_budget)
        except KVCacheExhausted:
            for uid, (n_tok, _nc) in saved.items():
                del mgr.get(uid).tokens[n_tok:]
            raise
        t_bucket = 16
        while t_bucket < rb.n_tokens:
            t_bucket *= 2
        t_bucket = min(t_bucket, self.scheduler.token_budget)
        bs = self.cfg.block_size
        nb_real = max(1, -(-int(rb.ctx_lens.max()) // bs))
        nb_bucket = 1
        while nb_bucket < nb_real:
            nb_bucket *= 2
        nb_bucket = min(nb_bucket, self.state_manager.max_blocks_per_seq)
        nxt, self.cache_k, self.cache_v = self._verify(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(rb.token_ids[:t_bucket]),
            jnp.asarray(rb.token_slot[:t_bucket]),
            jnp.asarray(rb.token_pos[:t_bucket]),
            jnp.asarray(rb.token_dest[:t_bucket]),
            jnp.asarray(rb.block_tables[:, :nb_bucket]),
            jnp.asarray(rb.ctx_lens), jnp.asarray(rb.logits_idx))
        nxt = np.asarray(nxt)
        out: Dict[int, List[int]] = {}
        cursor = 0
        for seq, n_new in schedule:
            rows = nxt[cursor:cursor + n_new]
            cursor += n_new
            n_tok, nc0 = saved[seq.uid]
            props = seq.tokens[n_tok:]
            m = 0
            while m < len(props) and int(props[m]) == int(rows[m]):
                m += 1
            accepted = [int(t) for t in props[:m]] + [int(rows[m])]
            # rewind: keep the accepted prefix + bonus; positions
            # nc0..nc0+m ran with correct inputs, the rest is garbage
            del seq.tokens[n_tok + m:]
            seq.tokens.append(int(rows[m]))
            seq.num_cached = nc0 + m + 1
            out[seq.uid] = accepted
        return out

    def rewind(self, uid: int, tokens: Sequence[int],
               num_cached: int) -> None:
        """Reset a live sequence's host-side view (draft-model rewind
        after speculative rejection): ``tokens`` becomes the full known
        stream and ``num_cached`` the count of leading positions whose
        KV was computed from correct inputs.  ``num_cached`` may only
        shrink — garbage KV beyond it is overwritten when those
        positions are legitimately re-run.  Allocated pages stay with
        the sequence (capacity, not content)."""
        seq = self.state_manager.get(uid)
        if num_cached > seq.num_cached:
            raise ValueError(
                f"rewind: num_cached {num_cached} > written "
                f"{seq.num_cached} — rewind cannot invent KV")
        seq.tokens = [int(t) for t in tokens]
        seq.num_cached = int(num_cached)
        if seq.uncached > 1:
            # more than one pending token decodes 1/step from the decode
            # set; chunked prefill catches the stream up in one step
            self.scheduler.demote(uid)

    @property
    def free_blocks(self) -> int:
        return self.state_manager.allocator.free_blocks

    def seq_blocks(self, n_tokens: int) -> int:
        """KV pages a sequence of ``n_tokens`` tokens occupies — THE page
        accounting rule; admission layers must use it rather than re-derive
        it so engine and admission can never disagree."""
        return -(-int(n_tokens) // self.cfg.block_size)

    @property
    def max_seq_blocks(self) -> int:
        """Hard per-sequence page cap (pool size and block-table width)."""
        return min(self.cfg.num_blocks - 1,
                   self.state_manager.max_blocks_per_seq)

    # ------------------------------------------------------------------
    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0,
                 eos_token_id: Optional[int] = None, top_k: int = 0,
                 top_p: float = 1.0) -> List[List[int]]:
        """Continuous-batching generation loop over token prompts.
        ``top_k``/``top_p`` restrict temperature sampling to the top-k
        logits / the top-p nucleus (ref FastGen logits processors);
        0 / 1.0 disable them."""
        top_k, top_p = check_sampling_params(top_k, top_p,
                                             self.model_config.vocab_size)
        uids = list(range(len(prompts)))
        remaining = {u: max_new_tokens for u in uids}
        outputs: Dict[int, List[int]] = {u: [] for u in uids}
        pending = list(zip(uids, prompts))
        step_key = jax.random.PRNGKey(seed)

        decode_key = jax.random.PRNGKey(seed ^ 0x5EED)
        while pending or any(u in self.state_manager for u in uids):
            # Pure-decode phase: every live sequence is waiting on exactly
            # its one pending sampled token -> run a fused multi-step decode
            # on device (one dispatch + one [chunk, S] int32 fetch instead
            # of a full-logits transfer per token).
            active_uids = [u for u in uids if u in self.state_manager]
            if (not pending and active_uids
                    and all(self.state_manager.get(u).uncached == 1
                            for u in active_uids)):
                decode_key, sub = jax.random.split(decode_key)
                self._fused_decode(active_uids, remaining, outputs,
                                   temperature, sub, eos_token_id,
                                   top_k=top_k, top_p=top_p)
                continue
            admit_uids, admit_toks = [], []
            # Active sequences will still claim pages as they decode: reserve
            # their remaining future blocks so admission never overcommits.
            reserved = 0
            for u in uids:
                if u in self.state_manager:
                    seq = self.state_manager.get(u)
                    final = self.seq_blocks(len(seq.tokens) + remaining[u])
                    reserved += max(0, final - len(seq.blocks))
            # Admit while slots and KV pages allow (continuous batching).
            while pending and (self.state_manager.n_active + len(admit_uids)
                               < self.state_manager.max_seqs):
                u, toks = pending[0]
                need = self.seq_blocks(len(toks) + max_new_tokens)
                if need > self.max_seq_blocks:
                    raise RuntimeError(
                        f"prompt uid {u} needs {need} KV blocks but the cache "
                        f"allows {self.max_seq_blocks} per sequence; "
                        "raise num_blocks/max_context or shorten the prompt")
                if need + reserved > self.state_manager.allocator.free_blocks:
                    break
                pending.pop(0)
                reserved += need
                admit_uids.append(u)
                admit_toks.append(toks)
            if pending and not admit_uids and self.state_manager.n_active == 0:
                raise RuntimeError("cannot admit any pending prompt: KV cache "
                                   "too fragmented/small for the workload")
            # mixed prefill/decode step with ON-DEVICE sampling: only
            # [max_seqs] int32 tokens cross to the host, not [seqs, V]
            # logits (the decode-phase discipline applied to prefill too)
            step_key, sub = jax.random.split(step_key)
            rb, toks = self._ragged_step(
                admit_uids, admit_toks,
                sample={"key": sub, "temperature": temperature,
                        "top_k": top_k, "top_p": top_p})
            toks_np = np.asarray(toks) if rb is not None else None
            results = ({} if rb is None
                       else {uid: int(toks_np[slot])
                             for slot, uid in rb.uids_by_slot.items()})
            for uid, nxt in results.items():
                outputs[uid].append(nxt)
                remaining[uid] -= 1
                done = remaining[uid] <= 0 or (eos_token_id is not None
                                               and nxt == eos_token_id)
                if done:
                    self.flush(uid)
                else:
                    self.extend(uid, nxt)
        return [outputs[u] for u in uids]

    # ------------------------------------------------------------------
    def _fused_decode(self, uids: List[int], remaining: Dict[int, int],
                      outputs: Dict[int, List[int]], temperature: float,
                      key, eos_token_id: Optional[int], top_k: int = 0,
                      top_p: float = 1.0) -> None:
        """One fused on-device decode chunk for all live sequences
        (ragged_decode_loop): chunk sizes are power-of-two bucketed so a
        generation run compiles at most a handful of loop lengths."""
        mgr = self.state_manager
        chunk = min(min(remaining[u] for u in uids),
                    self.cfg.max_decode_chunk)
        if chunk > 1:  # round UP to a power of two (compile-cache bound).
            # Up, not down: a 31-token budget then costs one 32-step
            # dispatch instead of a 16/8/4/2/1 ladder — each dispatch is a
            # host round-trip, and overshot tokens are just masked off
            # below (their KV writes die with the flushed sequence).
            chunk = 1 << (chunk - 1).bit_length()
        # ...but the overshoot must stay within every sequence's block
        # table: a prompt near max_context has fewer than `chunk` KV slots
        # left, and ensure_capacity raises rather than clamps.
        cap_tokens = mgr.max_blocks_per_seq * mgr.block_size
        headroom = min(cap_tokens - mgr.get(u).num_cached for u in uids)
        chunk = max(1, min(chunk, headroom))
        # ...and within the shared POOL: the round-up would allocate pages
        # past the admission reservation (overshot tokens are masked, but
        # their pages are real) — on a tight cache that's an exhaustion
        # crash mid-decode.  Halve back until the whole chunk's new pages
        # fit; chunk=1 always fits the reservation.
        bs2 = mgr.block_size

        def _pages_needed(c: int) -> int:
            return sum(max(0, -(-(mgr.get(u).num_cached + c) // bs2)
                           - len(mgr.get(u).blocks)) for u in uids)

        while chunk > 1 and _pages_needed(chunk) > mgr.allocator.free_blocks:
            chunk //= 2
        s_rows = mgr.max_seqs
        tokens0 = np.zeros((s_rows,), np.int32)
        ctx0 = np.zeros((s_rows,), np.int32)
        active = np.zeros((s_rows,), bool)
        nb_needed = 1
        for u in uids:
            seq = mgr.get(u)
            mgr.ensure_capacity(seq, seq.num_cached + chunk)
            tokens0[seq.slot] = seq.tokens[-1]
            ctx0[seq.slot] = seq.num_cached
            active[seq.slot] = True
            nb_needed = max(nb_needed, len(seq.blocks))
        nb_bucket = 1
        while nb_bucket < nb_needed:
            nb_bucket *= 2
        nb_bucket = min(nb_bucket, mgr.max_blocks_per_seq)
        tables = np.zeros((s_rows, nb_bucket), np.int32)
        for u in uids:
            seq = mgr.get(u)
            tables[seq.slot, :len(seq.blocks)] = seq.blocks

        sampled, _, self.cache_k, self.cache_v = self._decode_loop(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(tokens0), jnp.asarray(ctx0), jnp.asarray(active),
            jnp.asarray(tables), key, jnp.float32(max(temperature, 1e-6)),
            n_steps=chunk, greedy=(temperature <= 0),
            top_k=top_k, top_p=top_p)
        sampled = np.asarray(sampled)  # [chunk, s_rows]
        for u in uids:
            seq = mgr.get(u)
            toks = [int(x) for x in sampled[:, seq.slot]]
            take = min(chunk, remaining[u])  # overshoot from round-up
            cut = take
            if eos_token_id is not None and eos_token_id in toks[:take]:
                cut = toks.index(eos_token_id) + 1
            seq.tokens.extend(toks)
            seq.num_cached += chunk
            outputs[u].extend(toks[:cut])
            remaining[u] -= cut
            if cut < take or remaining[u] <= 0:
                self.flush(u)


def build_engine(model: TransformerConfig, engine_config: Optional[Dict] = None,
                 model_params: Optional[Any] = None, **kw) -> InferenceEngineV2:
    """Factory (ref build_hf_engine, inference/v2/engine_factory.py:69)."""
    return InferenceEngineV2(model, engine_config, model_params=model_params, **kw)
