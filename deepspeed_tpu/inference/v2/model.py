"""Ragged (paged-KV) transformer forward for continuous batching.

TPU-native replacement for the reference's blocked-flash-attention kernels
(ref inference/v2/kernels/ragged_ops/: blocked flash attn w/ KV-block table,
linear+blocked-KV rotary, logits_gather, embed): one forward processes an
arbitrary prefill/decode mix as a flat token list with per-token metadata.

Design (vs the reference's CUDA kernels):
* KV cache pages are rows of a flat per-layer array ``[L, P, kv_heads, d]``
  (P = num_blocks·block_size). Token KV is *scattered* to its page slot and
  context KV is *gathered* through the block table — both are XLA
  scatter/gather ops on static shapes, which XLA fuses around the attention
  einsums; a Pallas kernel can later replace the gather+einsum pair without
  changing this interface.
* Every shape is fixed by (token_budget, max_seqs, max_ctx): one compiled
  executable serves all batch mixes (the reference re-launches variable-size
  kernels instead).
* The layer loop is ``lax.scan`` threading the cache as scan xs/ys, matching
  the training forward's stacked-parameter layout.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.inference.v2.modules import register_module, resolve
from deepspeed_tpu.models.transformer import (TransformerConfig, _mlp_block,
                                              _norm)
from deepspeed_tpu.ops.pallas.paged_attention import paged_decode_attention


def _rope_tok(x, positions, cfg: TransformerConfig):
    """Rotary embedding over per-token positions. x: [T, H, D], positions:
    [T].  Honors ``rotary_pct`` (Phi partial rotary) like models._rope."""
    d = cfg.dim_per_head
    rot_d = d if cfg.rotary_pct >= 1.0 else max(2, int(d * cfg.rotary_pct) // 2 * 2)
    freqs = 1.0 / (cfg.rope_theta ** (jnp.arange(0, rot_d, 2, dtype=jnp.float32) / rot_d))
    angles = positions[:, None].astype(jnp.float32) * freqs  # [T, rot_d/2]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    xf = x.astype(jnp.float32)
    xr, x_pass = xf[..., :rot_d], xf[..., rot_d:]
    if cfg.rope_interleaved:
        # GPT-J "rotate every two" pairing
        x1, x2 = xr[..., 0::2], xr[..., 1::2]
        xr = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                       axis=-1).reshape(xr.shape)
    else:
        x1, x2 = jnp.split(xr, 2, axis=-1)
        xr = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                             axis=-1)
    return jnp.concatenate([xr, x_pass], axis=-1).astype(x.dtype)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _is_quant_cache(pages) -> bool:
    """Int8 KV cache layout: {"q": int8 payload, "s": fp32 per-row scales}
    (ref KV-block layout inference/v2/ragged/kv_cache.py:40; quantization
    per (head, row) over head_dim)."""
    return isinstance(pages, dict)


def _kv_append(pages, x, token_dest):
    """Scatter this step's KV rows [T, nkv, d] into the page pool —
    quantizing on append when the cache is int8."""
    xh = x.swapaxes(0, 1)                                # [nkv, T, d]
    if _is_quant_cache(pages):
        xf = xh.astype(jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
        q8 = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
        return {"q": pages["q"].at[:, token_dest].set(q8.astype(jnp.int8)),
                "s": pages["s"].at[:, token_dest].set(scale)}
    return pages.at[:, token_dest].set(xh.astype(pages.dtype))


def _paged_attention_xla(q, k_pages, v_pages, gather_idx, token_pos,
                         token_ctx_len, cfg: TransformerConfig):
    """Gather-based fallback (non-TPU backends / oversize shapes).

    q: [T, nh, d]; k_pages/v_pages: [nkv, P, d] (or int8 dict caches);
    gather_idx: [T, C] flat page-row indices of each token's context.
    GQA-native: queries are grouped by KV head instead of repeating KV.
    """
    t, nh, d = q.shape
    if _is_quant_cache(k_pages):
        nkv = k_pages["q"].shape[0]
        k_ctx = (k_pages["q"][:, gather_idx].astype(q.dtype)
                 * k_pages["s"][:, gather_idx, None].astype(q.dtype))
        v_ctx = (v_pages["q"][:, gather_idx].astype(q.dtype)
                 * v_pages["s"][:, gather_idx, None].astype(q.dtype))
    else:
        nkv = k_pages.shape[0]
        k_ctx = k_pages[:, gather_idx]  # [nkv, T, C, d]
        v_ctx = v_pages[:, gather_idx]
    g = nh // nkv
    qg = q.reshape(t, nkv, g, d)
    scale = (cfg.attn_scale if cfg.attn_scale is not None
             else 1.0 / math.sqrt(cfg.dim_per_head))
    scores = jnp.einsum("tkgd,ktcd->tkgc", qg, k_ctx) * scale
    c_pos = jnp.arange(scores.shape[-1], dtype=jnp.int32)
    if cfg.use_alibi:
        # Bloom ALiBi (key-position form; softmax-shift equivalent)
        from deepspeed_tpu.models.transformer import alibi_slopes

        sl = alibi_slopes(nh).reshape(nkv, g)
        scores = scores + (sl[None, :, :, None]
                           * c_pos.astype(jnp.float32)[None, None, None, :]
                           ).astype(scores.dtype)
    valid = (c_pos[None, :] <= token_pos[:, None]) & \
            (c_pos[None, :] < token_ctx_len[:, None])       # [T, C]
    if cfg.sliding_window:
        valid = valid & (token_pos[:, None] - c_pos[None, :]
                         < cfg.sliding_window)
    scores = jnp.where(valid[:, None, None, :], scores.astype(jnp.float32),
                       -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("tkgc,ktcd->tkgd", probs, v_ctx)
    return out.reshape(t, nh, d)


def _pallas_attn_default(block_size=0, head_dim=0, on_tpu=False,
                         has_tables=False, use_alibi=False, **_):
    if not (has_tables and on_tpu) or use_alibi:
        # alibi rides the XLA gather path (the Pallas kernel has no
        # score-bias lane)
        return False
    from deepspeed_tpu.ops.pallas.paged_attention import supports

    return supports(block_size, head_dim)


@register_module("attention", "paged_pallas",
                 default_for=_pallas_attn_default)
def _attn_impl_pallas(q, k_pages, v_pages, gather_idx, token_pos,
                      token_ctx_len, cfg, block_tables, token_slot,
                      block_size):
    """Pallas block-table kernel (ops/pallas/paged_attention.py: page walk
    with online softmax — no [T, C, ...] gather materialisation).
    Ref kernel: inference/v2/kernels/ragged_ops/blocked_flash."""
    if block_tables is None:
        raise ValueError(
            "attention='paged_pallas' needs block tables (the prefill "
            "mixed path carries none) — use 'auto' or 'paged_xla'")
    if cfg.use_alibi:
        raise ValueError(
            "attention='paged_pallas' has no ALiBi score-bias lane — use "
            "'auto' or 'paged_xla' for bloom-class models")
    pages = block_tables[token_slot]  # [T, NB]
    scale = (cfg.attn_scale if cfg.attn_scale is not None
             else 1.0 / math.sqrt(cfg.dim_per_head))
    if _is_quant_cache(k_pages):
        return paged_decode_attention(
            q, k_pages["q"], v_pages["q"], pages, token_pos, token_ctx_len,
            block_size, scale, window=cfg.sliding_window or None,
            k_scales=k_pages["s"], v_scales=v_pages["s"])
    return paged_decode_attention(
        q, k_pages, v_pages, pages, token_pos, token_ctx_len,
        block_size, scale, window=cfg.sliding_window or None)


@register_module("attention", "paged_xla")
def _attn_impl_xla(q, k_pages, v_pages, gather_idx, token_pos,
                   token_ctx_len, cfg, block_tables, token_slot,
                   block_size):
    return _paged_attention_xla(q, k_pages, v_pages, gather_idx, token_pos,
                                token_ctx_len, cfg)


def _paged_attention(q, k_pages, v_pages, gather_idx, token_pos, token_ctx_len,
                     cfg: TransformerConfig, block_tables=None, token_slot=None,
                     block_size: int = 0):
    """Attention of T query tokens against their sequences' KV pages,
    resolved through the module registry (modules.py — ref
    inference/v2/modules/heuristics.py): 'auto' picks the Pallas
    block-table kernel on TPU when the geometry is servable, the XLA
    gather path elsewhere; ``cfg.v2_modules`` pins a name explicitly."""
    name = dict(cfg.v2_modules or ()).get("attention", "auto")
    impl = resolve("attention", name, block_size=block_size,
                   head_dim=cfg.dim_per_head, on_tpu=_on_tpu(),
                   has_tables=block_tables is not None,
                   use_alibi=cfg.use_alibi)
    return impl(q, k_pages, v_pages, gather_idx, token_pos, token_ctx_len,
                cfg, block_tables, token_slot, block_size)


def _ragged_layer(x, lp, k_pages, v_pages, meta, cfg: TransformerConfig,
                  layer_is_moe=False):
    """One block over flat tokens [T, H]; scatters KV, attends via pages."""
    (token_pos, token_dest, gather_idx, token_ctx_len, token_slot,
     block_tables, block_size) = meta
    t = x.shape[0]
    nh, nkv, d = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    dt = x.dtype

    h = _norm(x, lp["ln1"], cfg)

    def proj(w, b_):
        y = h @ w.astype(dt)
        return y + b_.astype(dt) if b_ is not None else y

    q = proj(lp["attn"]["wq"], lp["attn"].get("bq")).reshape(t, nh, d)
    k = proj(lp["attn"]["wk"], lp["attn"].get("bk")).reshape(t, nkv, d)
    v = proj(lp["attn"]["wv"], lp["attn"].get("bv")).reshape(t, nkv, d)
    if cfg.use_rope:
        q = _rope_tok(q, token_pos, cfg)
        k = _rope_tok(k, token_pos, cfg)

    # Write this step's KV to its pages (padding tokens target page 0 =
    # garbage, so no mask needed; ref: linear_blocked_kv_copy). Cache layout
    # is [nkv, P, d] (kv-head-major for the Pallas kernel's page blocks),
    # quantized on append when the cache is int8 (_kv_append).
    k_pages = _kv_append(k_pages, k, token_dest)
    v_pages = _kv_append(v_pages, v, token_dest)

    attn = _paged_attention(q, k_pages, v_pages, gather_idx, token_pos,
                            token_ctx_len, cfg, block_tables=block_tables,
                            token_slot=token_slot, block_size=block_size)
    attn = attn.reshape(t, nh * d) @ lp["attn"]["wo"].astype(dt)
    if lp["attn"].get("bo") is not None:
        attn = attn + lp["attn"]["bo"].astype(dt)

    if cfg.parallel_block:
        # Falcon/Phi: attention and MLP read the shared input norm;
        # Falcon-40B/GPT-NeoX (parallel_norms): the MLP gets its own
        # ln2 on the same residual input (HF use_parallel_residual)
        h_mlp = _norm(x, lp["ln2"], cfg) if cfg.parallel_norms else h
        return x + attn + _mlp_block(h_mlp, lp["mlp"], cfg), k_pages, v_pages

    x = x + attn

    h2 = _norm(x, lp["ln2"], cfg)
    if "moe" not in lp:
        return x + _mlp_block(h2, lp["mlp"], cfg), k_pages, v_pages

    from deepspeed_tpu.moe.sharded_moe import moe_forward, moe_forward_ep
    from deepspeed_tpu.parallel.topology import get_topology

    def moe_branch(hh):
        topo = get_topology()
        tt = hh.shape[0]
        # expert-parallel ragged step: tokens split over the expert axis,
        # explicit all_to_all dispatch (ref mixtral model_implementations +
        # _AllToAll).  Needs a static branch (shard_map under lax.cond is
        # unsafe), hence the moe_every == 1 static selection above.
        if (isinstance(layer_is_moe, bool) and topo is not None
                and topo.ep_size > 1 and tt % topo.ep_size == 0):
            ep = topo.ep_size
            out, _ = moe_forward_ep(hh.reshape(ep, tt // ep, hh.shape[1]),
                                    lp["moe"], cfg, topo)
            return out.reshape(tt, -1)
        if topo is not None and topo.ep_size > 1:
            from deepspeed_tpu.utils.logging import log_dist

            log_dist(
                f"expert_parallel requested (ep={topo.ep_size}) but the "
                f"ragged step fell back to the single-group MoE "
                f"(tokens={tt} not divisible, or moe_layer_freq > 1 makes "
                "the selection traced) — dispatch will be auto-partitioned",
                level="warning")
        out, _ = moe_forward(hh[None], lp["moe"], cfg)
        return out[0]

    def dense_branch(hh):
        return _mlp_block(hh, lp["mlp"], cfg)

    if isinstance(layer_is_moe, bool):
        y = moe_branch(h2) if layer_is_moe else dense_branch(h2)
    else:
        y = lax.cond(layer_is_moe, moe_branch, dense_branch, h2)
    return x + y, k_pages, v_pages


def ragged_forward(params, cache_k, cache_v, token_ids, token_slot, token_pos,
                   token_dest, block_tables, ctx_lens, logits_idx,
                   cfg: TransformerConfig,
                   block_size: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One ragged step.

    cache_k/cache_v: [L, P, nkv, d]; block_tables: [S+1, NB]; returns
    (logits [S+1, V], cache_k', cache_v').
    """
    dt = cfg.dtype
    x = params["embed"]["tokens"].astype(dt)[token_ids]  # [T, H]
    if cfg.has_learned_positions and "positions" in params["embed"]:
        # gpt2/opt/gpt-neo learned positions (OPT's +2 offset is already
        # stripped at conversion, so token_pos indexes directly)
        x = x + params["embed"]["positions"].astype(dt)[token_pos]
    if cfg.embed_norm:
        x = _norm(x, params["embed"]["norm"], cfg)  # Bloom embedding LN

    # Context gather indices, shared by all layers (ref: atom_builder).
    nb = block_tables.shape[1]
    c = jnp.arange(nb * block_size, dtype=jnp.int32)
    ctx_idx = block_tables[:, c // block_size] * block_size + c % block_size  # [S+1, C]
    gather_idx = ctx_idx[token_slot]          # [T, C]
    token_ctx_len = ctx_lens[token_slot]      # [T]
    meta = (token_pos, token_dest, gather_idx, token_ctx_len, token_slot,
            block_tables, block_size)

    moe_every = max(1, cfg.moe_layer_freq)

    if cfg.alt_window:
        # GPT-Neo alternating global/local: scan layer PAIRS so each
        # member's window is static (see models/transformer scan_segment)
        if cfg.is_moe:
            raise NotImplementedError("alt_window + MoE not supported")
        if cfg.num_layers % 2:
            raise NotImplementedError(
                "alt_window needs an even layer count (the ragged path "
                f"scans layer pairs; got {cfg.num_layers})")
        pairs = cfg.num_layers // 2

        def body2(h, scanned):
            lp, ck_l, cv_l, idx = scanned
            ck_out, cv_out = [], []
            for j in range(2):
                sub = jax.tree.map(lambda p, j=j: p[j], lp)
                lcfg = cfg if j % 2 else cfg.replace(sliding_window=None)
                h, ck_j, cv_j = _ragged_layer(
                    h, sub, jax.tree.map(lambda c, j=j: c[j], ck_l),
                    jax.tree.map(lambda c, j=j: c[j], cv_l), meta, lcfg)
                ck_out.append(ck_j)
                cv_out.append(cv_j)
            stack = lambda xs: jax.tree.map(
                lambda *ys: jnp.stack(ys, axis=0), *xs)
            return h, (stack(ck_out), stack(cv_out))

        pair = lambda tree: jax.tree.map(
            lambda a: a.reshape((pairs, 2) + a.shape[1:]), tree)
        unpair = lambda tree: jax.tree.map(
            lambda a: a.reshape((cfg.num_layers,) + a.shape[2:]), tree)
        x, (cache_k, cache_v) = lax.scan(
            body2, x, (pair(params["layers"]), pair(cache_k),
                       pair(cache_v), jnp.arange(pairs)))
        cache_k, cache_v = unpair(cache_k), unpair(cache_v)
    else:
        def body(h, scanned):
            lp, ck_l, cv_l, idx = scanned
            if not cfg.is_moe:
                is_moe_layer = False
            elif moe_every == 1:
                # static: every layer is MoE — keeps the selection out of
                # lax.cond so the expert-parallel shard_map path can apply
                is_moe_layer = True
            else:
                is_moe_layer = (idx % moe_every) == (moe_every - 1)
            h, ck_l, cv_l = _ragged_layer(h, lp, ck_l, cv_l, meta, cfg,
                                          layer_is_moe=is_moe_layer)
            return h, (ck_l, cv_l)

        layer_idx = jnp.arange(cfg.num_layers)
        x, (cache_k, cache_v) = lax.scan(
            body, x, (params["layers"], cache_k, cache_v, layer_idx))

    x = _norm(x, params["final_norm"], cfg)
    last = x[logits_idx]  # [S+1, H] — ref: logits_gather
    if cfg.tie_embeddings:
        logits = last @ params["embed"]["tokens"].astype(dt).T
    else:
        logits = last @ params["lm_head"].astype(dt)
    return logits.astype(jnp.float32), cache_k, cache_v


def ragged_forward_verify(params, cache_k, cache_v, token_ids, token_slot,
                          token_pos, token_dest, block_tables, ctx_lens,
                          logits_idx, cfg: TransformerConfig,
                          block_size: int
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative-decoding verify-k step: the same ragged trunk, but the
    greedy argmax is taken at EVERY token row — [T] int32 — instead of
    only at each sequence's final row.

    Feeding a sequence's pending token plus its k draft proposals as one
    "prefill chunk" makes row j's argmax the target model's greedy
    next-token after the prefix ending at that row, which is exactly the
    acceptance oracle: proposal i is accepted iff it equals the argmax
    at the row of proposal i-1 (row of the pending token for i=1), and
    the argmax at the last accepted row is the free bonus token.  The
    head matmul contracts the same hidden dimension as the per-sequence
    gather path, so the emitted chain is bit-identical to one-token-at-
    a-time greedy decoding (pinned by the spec-decode parity tests).

    ``logits_idx`` is accepted (unused) so the verify step shares the
    exact argument tuple — and therefore the audit/bench plumbing — of
    ``ragged_forward``.
    """
    del logits_idx
    dt = cfg.dtype
    x = params["embed"]["tokens"].astype(dt)[token_ids]
    if cfg.has_learned_positions and "positions" in params["embed"]:
        x = x + params["embed"]["positions"].astype(dt)[token_pos]
    if cfg.embed_norm:
        x = _norm(x, params["embed"]["norm"], cfg)

    nb = block_tables.shape[1]
    c = jnp.arange(nb * block_size, dtype=jnp.int32)
    ctx_idx = block_tables[:, c // block_size] * block_size + c % block_size
    gather_idx = ctx_idx[token_slot]
    token_ctx_len = ctx_lens[token_slot]
    meta = (token_pos, token_dest, gather_idx, token_ctx_len, token_slot,
            block_tables, block_size)

    if cfg.alt_window or cfg.is_moe:
        raise NotImplementedError(
            "speculative verify step supports the plain scanned-layer "
            "ragged path only (no alt_window, no MoE)")

    def body(h, scanned):
        lp, ck_l, cv_l, _idx = scanned
        h, ck_l, cv_l = _ragged_layer(h, lp, ck_l, cv_l, meta, cfg)
        return h, (ck_l, cv_l)

    layer_idx = jnp.arange(cfg.num_layers)
    x, (cache_k, cache_v) = lax.scan(
        body, x, (params["layers"], cache_k, cache_v, layer_idx))

    x = _norm(x, params["final_norm"], cfg)
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["tokens"].astype(dt).T
    else:
        logits = x @ params["lm_head"].astype(dt)
    nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    return nxt, cache_k, cache_v


def check_sampling_params(top_k: int, top_p, vocab_size: int):
    """API-boundary validation + normalization (outside jit): rejects
    degenerate values that would silently emit token 0 (top_p <= 0) or
    crash deep inside lax.top_k (top_k > vocab).  Returns the
    ``(top_k_static, top_p_traced)`` pair the jitted samplers take —
    top_k clamped to vocab, top_p None when disabled (>= 1.0) else a
    traced fp32 scalar (so per-request values never recompile)."""
    if top_p is not None and not (0.0 < float(top_p) <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    tp = None if top_p is None or float(top_p) >= 1.0 else jnp.float32(top_p)
    return min(int(top_k), vocab_size), tp


def sample_tokens(logits, key, temperature, greedy: bool,
                  top_k: int = 0, top_p=None) -> jnp.ndarray:
    """On-device token sampling with FastGen-style logit processing
    (ref inference/v2/model_implementations sampler + logits processors):
    greedy argmax, or temperature categorical restricted to the top-k
    logits and/or the top-p nucleus.  ``top_k`` is static per compile
    (0 disables); ``top_p`` is a TRACED scalar (None disables) so
    per-request nucleus values never recompile."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p is not None:
        # nucleus: keep the smallest prefix of desc-sorted tokens whose
        # cumulative probability reaches top_p (first always kept)
        order = jnp.argsort(-logits, axis=-1)
        sorted_p = jax.nn.softmax(
            jnp.take_along_axis(logits, order, axis=-1), axis=-1)
        keep_sorted = (jnp.cumsum(sorted_p, axis=-1) - sorted_p) < top_p
        inv = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def ragged_forward_sampled(params, cache_k, cache_v, token_ids, token_slot,
                           token_pos, token_dest, block_tables, ctx_lens,
                           logits_idx, key, temperature,
                           cfg: TransformerConfig, block_size: int,
                           greedy: bool, top_k: int = 0, top_p=None
                           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Ragged step + ON-DEVICE sampling: the host receives [S+1] int32
    tokens instead of [S+1, V] logits.  Same sampling semantics as the
    fused decode loop (greedy argmax / temperature categorical with
    optional top-k/top-p), so a generation that alternates prefill and
    decode phases stays consistent.
    """
    logits, cache_k, cache_v = ragged_forward(
        params, cache_k, cache_v, token_ids, token_slot, token_pos,
        token_dest, block_tables, ctx_lens, logits_idx, cfg=cfg,
        block_size=block_size)
    nxt = sample_tokens(logits, key, temperature, greedy, top_k, top_p)
    return nxt, cache_k, cache_v


def ragged_decode_loop(params, cache_k, cache_v, tokens0, ctx_lens0,
                       active, block_tables, key, temperature,
                       cfg: TransformerConfig, block_size: int,
                       n_steps: int, greedy: bool, top_k: int = 0,
                       top_p=None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                  jnp.ndarray]:
    """Fused multi-step decode: ``lax.scan`` over ``n_steps`` single-token
    steps with on-device sampling — ONE dispatch for the whole decode
    phase, so per-step host/driver latency (the dominant cost on remote
    TPU relays) is paid once instead of per token.

    tokens0 [S]: each slot's current last token; ctx_lens0 [S]: tokens
    already in cache; active [S] bool; block_tables [S, NB] preallocated
    for the full horizon.  Returns (sampled [n_steps, S], ctx_lens',
    cache_k', cache_v').  Slot s's row in ``sampled`` is garbage where
    ``active[s]`` is False.
    """
    s_rows = block_tables.shape[0]
    slots = jnp.arange(s_rows, dtype=jnp.int32)
    act_i = active.astype(jnp.int32)

    def step(carry, step_key):
        tokens, ctx_lens, ck, cv = carry
        pos = ctx_lens  # 0-based position of the incoming token
        dest = block_tables[slots, pos // block_size] * block_size \
            + pos % block_size
        dest = jnp.where(active, dest, 0)  # inactive → garbage page 0
        ctx_after = ctx_lens + act_i
        logits, ck, cv = ragged_forward(
            params, ck, cv, tokens, slots, pos, dest, block_tables,
            ctx_after, slots, cfg=cfg, block_size=block_size)
        nxt = sample_tokens(logits, step_key, temperature, greedy, top_k,
                            top_p)
        nxt = jnp.where(active, nxt, 0)
        return (nxt, ctx_after, ck, cv), nxt

    keys = jax.random.split(key, n_steps)
    (tokens, ctx_lens, cache_k, cache_v), sampled = lax.scan(
        step, (tokens0, ctx_lens0, cache_k, cache_v), keys)
    return sampled, ctx_lens, cache_k, cache_v
