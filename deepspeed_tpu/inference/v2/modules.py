"""Pluggable inference-module registry + heuristics.

TPU analog of the reference's v2 module system
(``inference/v2/modules/module_registry.py`` — ConfigBundle-keyed
implementation registry — and ``modules/heuristics.py`` — "pick the best
impl for this config/hardware").  The registry maps a module *kind*
("attention", "mlp", "embed", "sampler") to named implementations; the
serve engine resolves each kind once at engine build:

* explicit override: ``InferenceEngineV2(model, {"modules":
  {"attention": "paged_xla"}})`` pins an implementation by name
  (ref ConfigBundle(name=...)), or
* heuristic default (``name="auto"``): the registered ``default_for``
  predicates pick by hardware/shape — the Pallas block-table kernel on
  TPU when the geometry is servable, the XLA gather fallback elsewhere
  (ref heuristics.instantiate_attn).

Implementations self-register via :func:`register_module` at import of
their defining module (model.py for the built-ins), so external code can
add implementations without touching the engine.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

_REGISTRY: Dict[str, Dict[str, Dict[str, Any]]] = {}


def register_module(kind: str, name: str,
                    default_for: Optional[Callable[..., bool]] = None):
    """Decorator: register ``fn`` as implementation ``name`` of ``kind``.

    ``default_for(**ctx) -> bool``: heuristic predicate consulted (in
    registration order) when resolving ``"auto"`` — first True wins; a
    registration without a predicate is the fallback.
    """

    def deco(fn):
        _REGISTRY.setdefault(kind, {})[name] = {
            "impl": fn, "default_for": default_for}
        return fn

    return deco


def available(kind: str):
    """Registered implementation names for ``kind``."""
    return tuple(_REGISTRY.get(kind, {}))


def resolve(kind: str, name: str = "auto", **ctx):
    """Resolve ``kind`` to an implementation callable.

    ``name="auto"`` walks the heuristics; an explicit name must exist in
    the registry (ref module_registry raises on unknown ConfigBundle).
    """
    impls = _REGISTRY.get(kind)
    if not impls:
        raise KeyError(f"no implementations registered for '{kind}'")
    if name != "auto":
        if name not in impls:
            raise KeyError(
                f"unknown {kind} implementation '{name}' "
                f"(available: {', '.join(impls)})")
        return impls[name]["impl"]
    fallback = None
    for entry in impls.values():
        pred = entry["default_for"]
        if pred is None:
            fallback = entry["impl"] if fallback is None else fallback
        elif pred(**ctx):
            return entry["impl"]
    if fallback is None:
        raise KeyError(f"no default implementation for '{kind}'")
    return fallback


def module_overrides(config: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """Normalize the engine config's ``"modules"`` block to kind→name."""
    out = {}
    for kind, name in ((config or {}).get("modules") or {}).items():
        out[str(kind)] = str(name)
    return out
