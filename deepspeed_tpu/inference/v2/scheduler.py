"""Dynamic SplitFuse scheduler.

Analog of the reference's FastGen scheduling
(ref inference/v2/scheduling_utils.py + the Dynamic SplitFuse policy,
blogs/deepspeed-fastgen): every engine step runs a FIXED token budget;
running (decode) sequences contribute one token each, and waiting prompts
fill the remaining budget — long prompts are *split* across steps, short
prompts *fuse* into one step. This keeps every forward the same shape
(compiled once) and latency flat.

Serving extensions: sequences carry a priority (higher runs earlier when
the budget is short), ``add(front=True)`` requeues a preempted sequence
ahead of every waiting prompt (preempted work already paid its queue
wait once), and ``demote()`` rolls a sequence back from the decode set to
the head of the prefill queue when a scheduled step could not run (KV
exhaustion caught before any state advanced).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from deepspeed_tpu.inference.v2.ragged import DSStateManager, SequenceDescriptor


class SplitFuseScheduler:
    def __init__(self, mgr: DSStateManager, token_budget: int = 256):
        self.mgr = mgr
        self.token_budget = token_budget
        self._decode: List[int] = []          # uids generating tokens
        self._prefill: List[int] = []         # uids with uncached prompt tokens
        # (-priority, arrival) sort key per uid: higher priority first,
        # FIFO within a priority class; front-requeues get arrival numbers
        # below every live entry so they re-enter at the head.
        self._key: Dict[int, Tuple[int, int]] = {}
        self._arrival = 0
        self._front_arrival = 0

    def add(self, uid: int, priority: int = 0, front: bool = False) -> None:
        if front:
            self._front_arrival -= 1
            arrival = self._front_arrival
        else:
            self._arrival += 1
            arrival = self._arrival
        self._key[uid] = (-int(priority), arrival)
        self._prefill.append(uid)
        self._prefill.sort(key=self._key.__getitem__)

    def retire(self, uid: int) -> None:
        if uid in self._decode:
            self._decode.remove(uid)
        if uid in self._prefill:
            self._prefill.remove(uid)
        self._key.pop(uid, None)

    def demote(self, uid: int) -> None:
        """Move a decode-set sequence back to the head of the prefill queue
        (its scheduled chunk never ran — see engine step() rollback)."""
        if uid in self._decode:
            self._decode.remove(uid)
        if uid not in self._prefill:
            self._front_arrival -= 1
            prio = self._key.get(uid, (0, 0))[0]
            self._key[uid] = (prio, self._front_arrival)
            self._prefill.append(uid)
            self._prefill.sort(key=self._key.__getitem__)

    @property
    def has_work(self) -> bool:
        return bool(self._decode or self._prefill)

    def next_schedule(self) -> List[Tuple[SequenceDescriptor, int]]:
        """(sequence, n_tokens) items for one step, ≤ token_budget total.

        Decode sequences first (1 token each — they bound latency), then
        prompt chunks; both sets walk in priority order. A prompt whose
        remaining tokens exceed the leftover budget is split; its
        unsampled chunk stays queued.
        """
        budget = self.token_budget
        schedule: List[Tuple[SequenceDescriptor, int]] = []
        for uid in sorted(self._decode, key=self._key.__getitem__):
            if budget == 0:
                break
            seq = self.mgr.get(uid)
            if seq.uncached <= 0:
                continue
            schedule.append((seq, 1))
            budget -= 1

        finished_prefill = []
        for uid in list(self._prefill):
            if budget == 0:
                break
            seq = self.mgr.get(uid)
            n = min(seq.uncached, budget)
            if n <= 0:
                finished_prefill.append(uid)
                continue
            schedule.append((seq, n))
            budget -= n
            if n == seq.uncached:
                finished_prefill.append(uid)
        for uid in finished_prefill:
            self._prefill.remove(uid)
            if uid not in self._decode:
                self._decode.append(uid)
        return schedule
