from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig,
                                                  build_engine)
from deepspeed_tpu.inference.v2.ragged import (BlockedAllocator, DSStateManager,
                                               KVCacheExhausted, RaggedBatch,
                                               SequenceDescriptor,
                                               build_ragged_batch)
from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
