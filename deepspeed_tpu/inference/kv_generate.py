"""KV-cached generation over a live parameter tree.

Shared by the v1 ``InferenceEngine`` and the RLHF ``DeepSpeedHybridEngine``
(ref deepspeed/runtime/hybrid_engine.py:30 — the reference re-wires ZeRO-3
weights into kernel-injected inference containers precisely so RLHF
rollouts get a KV cache).  Here the paged prefill/decode functions of
``inference/v2/model.py`` are jitted directly over the caller's param tree
(the training arrays themselves, for the hybrid engine), so per-token cost
is O(S) instead of the O(S²) full-recompute loop: one ragged prefill step
writes the whole prompt into pages, then ONE fused ``lax.scan`` decode
dispatch samples the remaining tokens on device.

Sampling semantics (greedy argmax / temperature categorical) are the
``ragged_forward_sampled`` / ``ragged_decode_loop`` ones, so outputs match
InferenceEngineV2 token-for-token under the same key discipline.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig


class KVCachedGenerator:
    """Jit-cached paged generate.  One instance per model config; repeated
    calls with the same (batch, prompt-len, new-tokens) shapes reuse the
    compiled prefill/decode executables."""

    def __init__(self, cfg: TransformerConfig, block_size: int = 64):
        from deepspeed_tpu.inference.v2.model import (ragged_decode_loop,
                                                      ragged_forward_sampled)

        self.cfg = cfg
        self.block_size = int(block_size)
        self._prefill = jax.jit(
            partial(ragged_forward_sampled, cfg=cfg,
                    block_size=self.block_size),
            static_argnames=("greedy", "top_k"),
            donate_argnums=(1, 2))
        self._decode = jax.jit(
            partial(ragged_decode_loop, cfg=cfg, block_size=self.block_size),
            static_argnames=("n_steps", "greedy", "top_k"),
            donate_argnums=(1, 2))

    def generate(self, params: Any, input_ids, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0, top_k: int = 0,
                 top_p: float = 1.0) -> np.ndarray:
        cfg, bs = self.cfg, self.block_size
        ids = np.asarray(input_ids, dtype=np.int32)
        if ids.ndim == 1:
            ids = ids[None, :]
        b, s0 = ids.shape
        total = s0 + max_new_tokens
        if total > cfg.max_seq_len:
            raise ValueError(f"prompt ({s0}) + max_new_tokens "
                             f"({max_new_tokens}) = {total} exceeds "
                             f"max_seq_len {cfg.max_seq_len}")
        if max_new_tokens < 1:
            return ids

        nb = -(-total // bs)
        n_blocks = b * nb
        tables_np = np.arange(n_blocks, dtype=np.int32).reshape(b, nb)
        tables = jnp.asarray(tables_np)
        # cache rows = blocks × block_size (page-row granularity)
        kv_shape = (cfg.num_layers, cfg.kv_heads, n_blocks * bs,
                    cfg.dim_per_head)
        cache_k = jnp.zeros(kv_shape, dtype=cfg.dtype)
        cache_v = jnp.zeros(kv_shape, dtype=cfg.dtype)

        # One ragged prefill over all B*S0 prompt tokens (causal via
        # token_pos masking in _paged_attention) + on-device first sample.
        token_slot = np.repeat(np.arange(b, dtype=np.int32), s0)
        token_pos = np.tile(np.arange(s0, dtype=np.int32), b)
        token_dest = (tables_np[token_slot, token_pos // bs] * bs
                      + token_pos % bs).astype(np.int32)
        ctx_lens = np.full((b,), s0, dtype=np.int32)
        logits_idx = (np.arange(b, dtype=np.int32) * s0 + s0 - 1)
        from deepspeed_tpu.inference.v2.model import check_sampling_params

        top_k, tp = check_sampling_params(top_k, top_p, cfg.vocab_size)
        greedy = temperature <= 0.0
        temp = jnp.float32(max(temperature, 1e-6))
        key = jax.random.PRNGKey(seed)
        key, kp, kd = jax.random.split(key, 3)
        first, cache_k, cache_v = self._prefill(
            params, cache_k, cache_v, jnp.asarray(ids.reshape(-1)),
            jnp.asarray(token_slot), jnp.asarray(token_pos),
            jnp.asarray(token_dest), tables, jnp.asarray(ctx_lens),
            jnp.asarray(logits_idx), kp, temp, greedy=greedy,
            top_k=top_k, top_p=tp)

        n_rest = max_new_tokens - 1
        if n_rest == 0:
            return np.concatenate([ids, np.asarray(first)[:, None]], axis=1)

        active = jnp.ones((b,), dtype=bool)
        sampled, _, cache_k, cache_v = self._decode(
            params, cache_k, cache_v, first, jnp.asarray(ctx_lens),
            active, tables, kd, temp, n_steps=n_rest, greedy=greedy,
            top_k=top_k, top_p=tp)
        return np.concatenate(
            [ids, np.asarray(first)[:, None], np.asarray(sampled).T], axis=1)
