"""deepspeed_tpu — a TPU-native large-scale training & inference framework
with the capabilities of DeepSpeed, built on JAX/XLA/Pallas/pjit.

Top-level API mirrors the reference (``deepspeed/__init__.py``):

    import deepspeed_tpu as ds
    engine, optimizer, dataloader, lr_scheduler = ds.initialize(
        model=ds.models.get_model_config("gpt2-125m"),
        config="ds_config.json")
    loss = engine.train_batch(batch)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

__version__ = "0.1.0"
__git_branch__ = "main"

from deepspeed_tpu.runtime.config import DeepSpeedConfig, load_plan
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.topology import MeshTopology, get_topology, set_topology
from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.utils.logging import logger, log_dist  # noqa: F401


def initialize(args=None,
               model: Any = None,
               optimizer: Any = None,
               model_parameters: Any = None,
               training_data: Any = None,
               lr_scheduler: Any = None,
               distributed_port: Optional[int] = None,
               mpu: Any = None,
               dist_init_required: Optional[bool] = None,
               collate_fn: Any = None,
               config: Union[str, Dict[str, Any], None] = None,
               config_params: Union[str, Dict[str, Any], None] = None,
               mesh_param=None,
               seed: Optional[int] = None):
    """Initialize the engine. Ref: ``deepspeed.initialize`` (__init__.py:78).

    Returns the reference's 4-tuple ``(engine, optimizer, dataloader,
    lr_scheduler)``.  ``model`` is a :class:`TransformerConfig` from the model
    zoo or any object with ``init(rng)``/``loss(params, batch)``;
    ``model_parameters`` may carry a pre-built param pytree.
    """
    from deepspeed_tpu.comm.comm import init_distributed

    config = config if config is not None else config_params
    if args is not None and config is None:
        config = getattr(args, "deepspeed_config", None)

    if mpu is not None and get_topology() is None:
        # Megatron-style caller: derive the mesh from the mpu's sizes
        # (ref engine._configure_distributed_model mpu path)
        from deepspeed_tpu.utils.mpu_adapter import topology_from_mpu

        set_topology(topology_from_mpu(mpu))
    init_distributed()
    engine = DeepSpeedEngine(model=model,
                             config=config,
                             model_params=model_parameters,
                             optimizer=optimizer,
                             lr_scheduler=lr_scheduler,
                             seed=seed)

    dataloader = None
    if training_data is not None:
        from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

        dataloader = DeepSpeedDataLoader(
            training_data,
            batch_size=engine.train_batch_size_value,
            collate_fn=collate_fn,
            drop_last=engine.config.dataloader_drop_last)

    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Ref: ``deepspeed.init_inference`` (__init__.py:302)."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    return InferenceEngine(model=model, config=config, **kwargs)


def tp_model_init(model=None, tp_size: int = 1, dtype=None, config=None,
                  **kwargs):
    """AutoTP training init: shard a param tree over the "tensor" mesh axis.
    Ref: ``deepspeed.tp_model_init`` (deepspeed/__init__.py:380).

    ``config`` may carry a ``tensor_parallel.autotp_size`` override (the
    reference reads the same key). An existing topology with other mesh axes
    (pipe/expert/seq) is an error if its tp size conflicts — rebuilding the
    mesh here would silently drop those axes.
    """
    from deepspeed_tpu.comm.comm import init_distributed
    from deepspeed_tpu.module_inject.auto_tp import tp_model_init as _tp_init
    from deepspeed_tpu.parallel.topology import get_topology

    if config:
        tp_size = (config.get("tensor_parallel", {}) or {}).get(
            "autotp_size", tp_size)
    topo = get_topology()
    if topo is None:
        topo = init_distributed(mesh_sizes={"tensor": tp_size} if tp_size > 1
                                else None)
    elif tp_size > 1 and topo.tp_size != tp_size:
        extra = {a: s for a, s in topo.sizes.items()
                 if a not in ("data", "tensor") and s > 1}
        if extra:
            raise ValueError(
                f"tp_model_init(tp_size={tp_size}) conflicts with existing "
                f"topology {topo.sizes}; re-run init_distributed with the "
                f"full mesh instead of rebuilding it here")
        topo = init_distributed(mesh_sizes={"tensor": tp_size})
    params = model
    if dtype is not None:
        import jax

        params = jax.tree.map(lambda x: x.astype(dtype), params)
    return _tp_init(params, topo, **kwargs)


# subpackage conveniences
from deepspeed_tpu.models import registry as models  # noqa: E402
from deepspeed_tpu.models.registry import get_model_config  # noqa: E402
from deepspeed_tpu import zero  # noqa: E402
from deepspeed_tpu import checkpointing  # noqa: E402
from deepspeed_tpu.utils.init_on_device import OnDevice  # noqa: E402
from deepspeed_tpu.utils.mpu_adapter import MpuAdapter  # noqa: E402
from deepspeed_tpu.utils.tensor_fragment import (  # noqa: E402
    safe_get_full_fp32_param, safe_get_full_grad,
    safe_get_full_optimizer_state, safe_get_local_fp32_param,
    safe_get_local_grad, safe_get_local_optimizer_state,
    safe_set_full_fp32_param, safe_set_full_optimizer_state)
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine  # noqa: E402
