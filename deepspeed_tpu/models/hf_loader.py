"""Hugging Face checkpoint import.

The reference consumes HF models directly (module_inject/replace_module.py
kernel injection, inference/v2/model_implementations per-arch containers +
``flat_model_helpers``).  Here the equivalent surface is a *weight
converter*: ``config_from_hf`` maps an HF config to a
:class:`TransformerConfig` and ``params_from_hf`` maps an HF state dict to
the stacked functional param tree, after which every subsystem (engine,
AutoTP, ZeRO, inference v1/v2) consumes the model like any other.

Supported families: gpt2, llama, mistral, qwen, qwen2, mixtral, qwen2_moe,
opt, falcon, phi, phi3 — the same set as the reference's v2 model
implementations (MoE included) — plus the v1-injection families
bloom (ALiBi), gptj (interleaved rotary), gpt_neox, and the encoder
family bert/distilbert (ref module_inject/containers/);
:func:`register_converter` adds new families without touching this module
(the analog of the v2 registry).

Conventions handled per family:
* HF ``nn.Linear`` stores [out, in] → transposed to our [in, out];
  GPT-2's Conv1D already stores [in, out].
* Fused projections are split (GPT-2 ``c_attn`` 3-way; Falcon
  ``query_key_value`` MQA layout [(nh + 2·nkv)·d, h]).
* OPT's learned positions carry a +2 row offset.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _map_hf_activation(mt: str, act_name) -> str:
    """HF activation names → the functional vocabulary ("gelu" in HF
    BERT/NeoX is the exact erf form; gelu_new/_fast/_tanh are the tanh
    approximation the decoder families use)."""
    table = {"gelu": "gelu_exact", "gelu_new": "gelu",
             "gelu_fast": "gelu", "gelu_pytorch_tanh": "gelu",
             "relu": "relu"}
    name = str(act_name)
    if name not in table:
        raise ValueError(f"{mt}: unsupported hidden_act {name!r} "
                         f"(supported: {sorted(table)})")
    return table[name]


def config_from_hf(hf_config) -> TransformerConfig:
    """HF PretrainedConfig → TransformerConfig (ref engine_factory arch
    dispatch, inference/v2/engine_factory.py:69)."""
    mt = getattr(hf_config, "model_type", "")
    if mt == "gpt2":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.n_embd,
            intermediate_size=4 * hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions, arch="gpt2",
            norm="layernorm", activation="gelu",
            layernorm_eps=hf_config.layer_norm_epsilon)
    if mt in ("llama", "mistral", "qwen2", "mixtral", "qwen2_moe"):
        # one llama-family block; MoE variants add routing fields.
        # Dropless capacity: C = cf*k*T/E = T exactly at cf = E/k (HF MoE
        # blocks never drop tokens; larger cf inflates [E,C,H] buffers).
        moe_kw = {}
        if mt == "mixtral":
            e, k = hf_config.num_local_experts, hf_config.num_experts_per_tok
            moe_kw = dict(num_experts=e, top_k=k, moe_layer_freq=1,
                          moe_norm_topk=True, capacity_factor=float(e / k))
        elif mt == "qwen2_moe":
            e, k = hf_config.num_experts, hf_config.num_experts_per_tok
            moe_kw = dict(
                num_experts=e, top_k=k, capacity_factor=float(e / k),
                moe_layer_freq=int(getattr(hf_config, "decoder_sparse_step",
                                           1) or 1),
                moe_norm_topk=bool(getattr(hf_config, "norm_topk_prob",
                                           False)),
                moe_intermediate_size=hf_config.moe_intermediate_size,
                moe_shared_expert_size=getattr(
                    hf_config, "shared_expert_intermediate_size", 0))
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            max_seq_len=hf_config.max_position_embeddings,
            arch="llama" if mt in ("mixtral", "qwen2_moe") else mt,
            norm="rmsnorm", activation="swiglu", use_rope=True,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
            qkv_bias=(mt in ("qwen2", "qwen2_moe")),
            sliding_window=getattr(hf_config, "sliding_window", None)
            if mt == "mistral" else None,
            layernorm_eps=hf_config.rms_norm_eps, **moe_kw)
    if mt == "phi3":
        # llama-family numerics with fused qkv_proj / gate_up_proj weights
        # (ref inference/v2/model_implementations/phi3)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            max_seq_len=hf_config.max_position_embeddings,
            arch="phi3", norm="rmsnorm", activation="swiglu", use_rope=True,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                        False)),
            sliding_window=getattr(hf_config, "sliding_window", None),
            layernorm_eps=hf_config.rms_norm_eps)
    if mt == "qwen":
        # Qwen v1 (remote-code modeling_qwen.py; ref
        # inference/v2/model_implementations/qwen): fused biased c_attn,
        # RMSNorm, SwiGLU where w2 gates and the HF intermediate_size is
        # 2x the actual FFN width (the modeling code splits it in half)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size // 2,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=getattr(hf_config, "seq_length", 2048),
            arch="qwen", norm="rmsnorm", activation="swiglu", use_rope=True,
            rope_theta=getattr(hf_config, "rotary_emb_base", 10000.0),
            qkv_bias=True, tie_embeddings=False,
            layernorm_eps=getattr(hf_config, "layer_norm_epsilon", 1e-6))
    if mt == "opt":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.ffn_dim,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=hf_config.max_position_embeddings,
            arch="opt", norm="layernorm", activation="relu",
            learned_positions=True, use_bias=True, tie_embeddings=True)
    if mt == "falcon":
        # HF falcon precedence (modeling_falcon): new_decoder_architecture
        # reads num_kv_heads; legacy multi_query means exactly 1 KV head.
        if getattr(hf_config, "new_decoder_architecture", False):
            nkv = getattr(hf_config, "num_kv_heads", None) \
                or hf_config.num_attention_heads
        elif getattr(hf_config, "multi_query", True):
            nkv = 1
        else:
            nkv = hf_config.num_attention_heads
        new_arch = bool(getattr(hf_config, "new_decoder_architecture", False))
        n_ln = getattr(hf_config, "num_ln_in_parallel_attn", None)
        if n_ln is None and new_arch:
            n_ln = 2  # HF FalconDecoderLayer default for the new arch
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=4 * hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads, num_kv_heads=nkv,
            max_seq_len=getattr(hf_config, "max_position_embeddings", 2048),
            arch="falcon", norm="layernorm", activation="gelu",
            use_rope=getattr(hf_config, "rotary", True),
            parallel_block=bool(getattr(hf_config, "parallel_attn", True)),
            parallel_norms=(new_arch and n_ln == 2),
            use_bias=bool(getattr(hf_config, "bias", False)),
            tie_embeddings=True,
            layernorm_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if mt == "bloom":
        # ALiBi attention, embedding LayerNorm, BloomGelu = tanh approx
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=4 * hf_config.hidden_size,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            max_seq_len=getattr(hf_config, "seq_length", 2048),
            arch="bloom", norm="layernorm", activation="gelu",
            use_alibi=True, embed_norm=True, use_bias=True,
            tie_embeddings=True,
            layernorm_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if mt == "gptj":
        # interleaved partial rotary, parallel block with ONE shared norm,
        # biasless attention + biased MLP, gelu_new = tanh approx
        d = hf_config.n_embd // hf_config.n_head
        return TransformerConfig(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.n_embd,
            intermediate_size=(hf_config.n_inner
                               or 4 * hf_config.n_embd),
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions, arch="gptj",
            norm="layernorm", activation="gelu", use_rope=True,
            rope_interleaved=True,
            # rotary_dim=None = full-head rotary (HF GPTJAttention)
            rotary_pct=(hf_config.rotary_dim or d) / d,
            parallel_block=True, use_bias=False, mlp_bias=True,
            tie_embeddings=False, lm_head_bias=True,
            layernorm_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if mt == "gpt_neo":
        # alternating global/local attention, learned positions, NO
        # sqrt(d) score scaling, biasless q/k/v with biased out/mlp
        layers = list(getattr(hf_config, "attention_layers", []))
        alt = (len(layers) == hf_config.num_layers and all(
            p == ("global" if i % 2 == 0 else "local")
            for i, p in enumerate(layers)))
        all_global = all(p == "global" for p in layers)
        if not (alt or all_global):
            raise ValueError(
                f"gpt_neo: unsupported attention_layers pattern {layers} "
                "(supported: all-global, or alternating global/local)")
        if alt and hf_config.num_layers % 2:
            raise ValueError(
                "gpt_neo: alternating attention needs an even layer count "
                f"(got {hf_config.num_layers}) — the alt-window paths scan "
                "layer pairs")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=(hf_config.intermediate_size
                               or 4 * hf_config.hidden_size),
            num_layers=hf_config.num_layers,
            num_heads=hf_config.num_heads,
            max_seq_len=hf_config.max_position_embeddings, arch="gptneo",
            norm="layernorm",
            activation=_map_hf_activation(
                mt, getattr(hf_config, "activation_function", "gelu_new")),
            learned_positions=True, use_bias=False, mlp_bias=True,
            attn_out_bias=True, alt_window=alt,
            sliding_window=(hf_config.window_size if alt else None),
            attn_scale=1.0, tie_embeddings=True,
            layernorm_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5))
    if mt == "gpt_neox":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=hf_config.max_position_embeddings, arch="gptneox",
            norm="layernorm",
            activation=_map_hf_activation(mt, hf_config.hidden_act),
            use_rope=True, rotary_pct=hf_config.rotary_pct,
            rope_theta=float(getattr(hf_config, "rope_theta", None)
                             or getattr(hf_config, "rotary_emb_base",
                                        10000.0)),
            parallel_block=bool(getattr(hf_config, "use_parallel_residual",
                                        True)),
            parallel_norms=bool(getattr(hf_config, "use_parallel_residual",
                                        True)),
            use_bias=True, tie_embeddings=False,
            layernorm_eps=getattr(hf_config, "layer_norm_eps", 1e-5))
    if mt in ("bert", "distilbert"):
        # map HF activation names onto the functional vocabulary ("gelu"
        # in HF BERT is the exact erf form; gelu_new/_tanh are the tanh
        # approximation the decoder families use)
        act_name = (getattr(hf_config, "hidden_act", None)
                    or getattr(hf_config, "activation", "gelu"))
        enc_kw = dict(
            arch=mt, norm="layernorm",
            activation=_map_hf_activation(mt, act_name),
            causal=False, norm_position="post", embed_norm=True,
            mlm_head=True, tie_embeddings=True)
        if mt == "bert":
            return TransformerConfig(
                vocab_size=hf_config.vocab_size,
                hidden_size=hf_config.hidden_size,
                intermediate_size=hf_config.intermediate_size,
                num_layers=hf_config.num_hidden_layers,
                num_heads=hf_config.num_attention_heads,
                max_seq_len=hf_config.max_position_embeddings,
                type_vocab_size=getattr(hf_config, "type_vocab_size", 2),
                dropout=getattr(hf_config, "hidden_dropout_prob", 0.1),
                layernorm_eps=getattr(hf_config, "layer_norm_eps", 1e-12),
                **enc_kw)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.dim,
            intermediate_size=hf_config.hidden_dim,
            num_layers=hf_config.n_layers,
            num_heads=hf_config.n_heads,
            max_seq_len=hf_config.max_position_embeddings,
            dropout=getattr(hf_config, "dropout", 0.1),
            layernorm_eps=1e-12, **enc_kw)
    if mt == "phi":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=hf_config.max_position_embeddings,
            arch="phi", norm="layernorm", activation="gelu", use_rope=True,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rotary_pct=getattr(hf_config, "partial_rotary_factor", 0.5),
            parallel_block=True, use_bias=True, tie_embeddings=False,
            layernorm_eps=getattr(hf_config, "layer_norm_eps", 1e-5))
    raise ValueError(f"unsupported HF model_type {mt!r}")


# ----------------------------------------------------------------------
#: arch → converter registry (the analog of inference/v2's pluggable
#: model-implementation registry, engine_factory.py:69 — register a new
#: family without touching this module)
_CONVERTERS: Dict[str, Any] = {}


def register_converter(arch: str, fn) -> None:
    """Register ``fn(state_dict, cfg) -> param tree`` for ``cfg.arch``."""
    _CONVERTERS[arch] = fn


def params_from_hf(model_or_state_dict, cfg: TransformerConfig,
                   dtype=None) -> Dict[str, Any]:
    """HF model / state dict → stacked functional param tree."""
    sd = (model_or_state_dict if isinstance(model_or_state_dict, dict)
          else model_or_state_dict.state_dict())
    sd = {k: _np(v) for k, v in sd.items()}
    dt = dtype or cfg.param_dtype
    if cfg.arch not in _CONVERTERS:
        raise KeyError(f"no converter for arch {cfg.arch!r}; known: "
                       f"{sorted(_CONVERTERS)} (register_converter to add)")
    params = _CONVERTERS[cfg.arch](sd, cfg)
    return {k: _cast_tree(v, dt) for k, v in params.items()}


def _cast_tree(x, dt):
    if isinstance(x, dict):
        return {k: _cast_tree(v, dt) for k, v in x.items()}
    return jnp.asarray(x, dt)


def _stack(layer_dicts):
    out: Dict[str, Any] = {}
    for key in layer_dicts[0]:
        if isinstance(layer_dicts[0][key], dict):
            out[key] = _stack([ld[key] for ld in layer_dicts])
        else:
            out[key] = np.stack([ld[key] for ld in layer_dicts], axis=0)
    return out


def _convert_gpt2(sd, cfg):
    h = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        ca_w = sd[p + "attn.c_attn.weight"]  # Conv1D: [in, 3h]
        ca_b = sd[p + "attn.c_attn.bias"]
        wq, wk, wv = np.split(ca_w, 3, axis=1)
        bq, bk, bv = np.split(ca_b, 3, axis=0)
        layers.append({
            "attn": {"wq": wq, "wk": wk, "wv": wv,
                     "wo": sd[p + "attn.c_proj.weight"],
                     "bq": bq, "bk": bk, "bv": bv,
                     "bo": sd[p + "attn.c_proj.bias"]},
            "mlp": {"wi": sd[p + "mlp.c_fc.weight"],
                    "bi": sd[p + "mlp.c_fc.bias"],
                    "wo": sd[p + "mlp.c_proj.weight"],
                    "bo": sd[p + "mlp.c_proj.bias"]},
            "ln1": {"scale": sd[p + "ln_1.weight"], "bias": sd[p + "ln_1.bias"]},
            "ln2": {"scale": sd[p + "ln_2.weight"], "bias": sd[p + "ln_2.bias"]},
        })
    return {
        "embed": {"tokens": sd["transformer.wte.weight"],
                  "positions": sd["transformer.wpe.weight"]},
        "layers": _stack(layers),
        "final_norm": {"scale": sd["transformer.ln_f.weight"],
                       "bias": sd["transformer.ln_f.bias"]},
    }


def _convert_llama(sd, cfg):
    layers = []
    qkv_b = cfg.qkv_bias
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        attn = {"wq": sd[p + "self_attn.q_proj.weight"].T,
                "wk": sd[p + "self_attn.k_proj.weight"].T,
                "wv": sd[p + "self_attn.v_proj.weight"].T,
                "wo": sd[p + "self_attn.o_proj.weight"].T}
        if qkv_b:
            attn["bq"] = sd[p + "self_attn.q_proj.bias"]
            attn["bk"] = sd[p + "self_attn.k_proj.bias"]
            attn["bv"] = sd[p + "self_attn.v_proj.bias"]
        block = {
            "attn": attn,
            "ln1": {"scale": sd[p + "input_layernorm.weight"]},
            "ln2": {"scale": sd[p + "post_attention_layernorm.weight"]},
        }
        if p + "block_sparse_moe.gate.weight" in sd:
            # Mixtral: w1=gate, w3=up, w2=down per expert (ref
            # inference/v2/model_implementations/mixtral)
            ep = p + "block_sparse_moe.experts."
            e = cfg.num_experts
            block["moe"] = {
                "router": sd[p + "block_sparse_moe.gate.weight"].T,
                "wg": np.stack([sd[f"{ep}{j}.w1.weight"].T
                                for j in range(e)]),
                "wi": np.stack([sd[f"{ep}{j}.w3.weight"].T
                                for j in range(e)]),
                "wo": np.stack([sd[f"{ep}{j}.w2.weight"].T
                                for j in range(e)]),
            }
        elif p + "mlp.gate.weight" in sd:
            # Qwen2-MoE: routed experts + gated shared expert
            ep = p + "mlp.experts."
            e = cfg.num_experts
            block["moe"] = {
                "router": sd[p + "mlp.gate.weight"].T,
                "wg": np.stack([sd[f"{ep}{j}.gate_proj.weight"].T
                                for j in range(e)]),
                "wi": np.stack([sd[f"{ep}{j}.up_proj.weight"].T
                                for j in range(e)]),
                "wo": np.stack([sd[f"{ep}{j}.down_proj.weight"].T
                                for j in range(e)]),
                "shared": {
                    "wg": sd[p + "mlp.shared_expert.gate_proj.weight"].T,
                    "wi": sd[p + "mlp.shared_expert.up_proj.weight"].T,
                    "wo": sd[p + "mlp.shared_expert.down_proj.weight"].T},
                "shared_gate": sd[p + "mlp.shared_expert_gate.weight"].T,
            }
        else:
            block["mlp"] = {"wg": sd[p + "mlp.gate_proj.weight"].T,
                            "wi": sd[p + "mlp.up_proj.weight"].T,
                            "wo": sd[p + "mlp.down_proj.weight"].T}
        layers.append(block)
    if cfg.is_moe and any("moe" not in b for b in layers):
        raise NotImplementedError(
            "mixed dense/MoE layer stacks (decoder_sparse_step > 1 or "
            "mlp_only_layers) are not supported by the stacked-layer scan")
    out = {"embed": {"tokens": sd["model.embed_tokens.weight"]},
           "layers": _stack(layers),
           "final_norm": {"scale": sd["model.norm.weight"]}}
    if not cfg.tie_embeddings:
        lm = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
        out["lm_head"] = lm.T
    return out


def _convert_opt(sd, cfg):
    layers = []
    for i in range(cfg.num_layers):
        p = f"model.decoder.layers.{i}."
        layers.append({
            "attn": {"wq": sd[p + "self_attn.q_proj.weight"].T,
                     "wk": sd[p + "self_attn.k_proj.weight"].T,
                     "wv": sd[p + "self_attn.v_proj.weight"].T,
                     "wo": sd[p + "self_attn.out_proj.weight"].T,
                     "bq": sd[p + "self_attn.q_proj.bias"],
                     "bk": sd[p + "self_attn.k_proj.bias"],
                     "bv": sd[p + "self_attn.v_proj.bias"],
                     "bo": sd[p + "self_attn.out_proj.bias"]},
            "mlp": {"wi": sd[p + "fc1.weight"].T, "bi": sd[p + "fc1.bias"],
                    "wo": sd[p + "fc2.weight"].T, "bo": sd[p + "fc2.bias"]},
            "ln1": {"scale": sd[p + "self_attn_layer_norm.weight"],
                    "bias": sd[p + "self_attn_layer_norm.bias"]},
            "ln2": {"scale": sd[p + "final_layer_norm.weight"],
                    "bias": sd[p + "final_layer_norm.bias"]},
        })
    # OPT's learned positions skip the first 2 rows (padding offset)
    pos = sd["model.decoder.embed_positions.weight"][2:]
    return {
        "embed": {"tokens": sd["model.decoder.embed_tokens.weight"],
                  "positions": pos},
        "layers": _stack(layers),
        "final_norm": {"scale": sd["model.decoder.final_layer_norm.weight"],
                       "bias": sd["model.decoder.final_layer_norm.bias"]},
    }


def _convert_falcon(sd, cfg):
    nh, nkv, d = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    ln_attn = "transformer.h.0.ln_attn.weight" in sd
    if ln_attn:
        ln2_key = "ln_mlp"
    elif "transformer.h.0.post_attention_layernorm.weight" in sd:
        ln2_key = "post_attention_layernorm"  # parallel_attn=False layout
    else:
        ln2_key = "input_layernorm"
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        qkv = sd[p + "self_attention.query_key_value.weight"].T  # [h, (nh+2nkv)d]
        # HF Falcon's fused layout is per-KV-group in every variant:
        # nkv groups of (nh/nkv query heads, one k, one v).  nkv==nh reduces
        # to per-head [q,k,v] interleave (Falcon-RW), nkv==1 to [all-q, k, v]
        # (7B multi-query), and 1<nkv<nh is the new_decoder_architecture
        # interleave (40B/180B — the reference handles it via
        # GQAMegatronQKVParameter, module_inject/layers.py).
        hdim = qkv.shape[0]
        qkv = qkv.reshape(hdim, nkv, nh // nkv + 2, d)
        wq = qkv[:, :, :-2, :].reshape(hdim, nh * d)
        wk = qkv[:, :, -2, :].reshape(hdim, nkv * d)
        wv = qkv[:, :, -1, :].reshape(hdim, nkv * d)
        layers.append({
            "attn": {"wq": wq, "wk": wk, "wv": wv,
                     "wo": sd[p + "self_attention.dense.weight"].T},
            "mlp": {"wi": sd[p + "mlp.dense_h_to_4h.weight"].T,
                    "wo": sd[p + "mlp.dense_4h_to_h.weight"].T},
            # new_decoder_architecture: separate ln_attn/ln_mlp parallel
            # norms; legacy sequential (parallel_attn=False): ln2 is the
            # post-attention norm; legacy parallel: one shared input norm
            # (ln2 mirrors it so the tree keeps the slot).
            "ln1": {"scale": sd[p + ("ln_attn.weight" if ln_attn
                                     else "input_layernorm.weight")],
                    "bias": sd[p + ("ln_attn.bias" if ln_attn
                                    else "input_layernorm.bias")]},
            "ln2": {"scale": sd[p + ln2_key + ".weight"],
                    "bias": sd[p + ln2_key + ".bias"]},
        })
    return {
        "embed": {"tokens": sd["transformer.word_embeddings.weight"]},
        "layers": _stack(layers),
        "final_norm": {"scale": sd["transformer.ln_f.weight"],
                       "bias": sd["transformer.ln_f.bias"]},
    }


def _convert_phi(sd, cfg):
    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": {"wq": sd[p + "self_attn.q_proj.weight"].T,
                     "wk": sd[p + "self_attn.k_proj.weight"].T,
                     "wv": sd[p + "self_attn.v_proj.weight"].T,
                     "wo": sd[p + "self_attn.dense.weight"].T,
                     "bq": sd[p + "self_attn.q_proj.bias"],
                     "bk": sd[p + "self_attn.k_proj.bias"],
                     "bv": sd[p + "self_attn.v_proj.bias"],
                     "bo": sd[p + "self_attn.dense.bias"]},
            "mlp": {"wi": sd[p + "mlp.fc1.weight"].T,
                    "bi": sd[p + "mlp.fc1.bias"],
                    "wo": sd[p + "mlp.fc2.weight"].T,
                    "bo": sd[p + "mlp.fc2.bias"]},
            "ln1": {"scale": sd[p + "input_layernorm.weight"],
                    "bias": sd[p + "input_layernorm.bias"]},
            "ln2": {"scale": sd[p + "input_layernorm.weight"],
                    "bias": sd[p + "input_layernorm.bias"]},
        })
    out = {"embed": {"tokens": sd["model.embed_tokens.weight"]},
           "layers": _stack(layers),
           "final_norm": {"scale": sd["model.final_layernorm.weight"],
                          "bias": sd["model.final_layernorm.bias"]},
           "lm_head": sd["lm_head.weight"].T}
    if "lm_head.bias" in sd and np.abs(sd["lm_head.bias"]).max() > 0:
        logger.warning("phi lm_head bias dropped (functional head has no "
                       "output bias)")
    return out


def _convert_phi3(sd, cfg):
    """Phi-3: fused qkv_proj ([q;k;v] rows) and gate_up_proj ([gate;up])
    split into the functional layout (ref phi3 layer containers)."""
    nh, nkv, d = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    ffn = cfg.intermediate_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        qkv = sd[p + "self_attn.qkv_proj.weight"]        # [(nh+2nkv)d, h]
        wq = qkv[:nh * d].T
        wk = qkv[nh * d:nh * d + nkv * d].T
        wv = qkv[nh * d + nkv * d:].T
        gu = sd[p + "mlp.gate_up_proj.weight"]           # [2*ffn, h]
        layers.append({
            "attn": {"wq": wq, "wk": wk, "wv": wv,
                     "wo": sd[p + "self_attn.o_proj.weight"].T},
            "mlp": {"wg": gu[:ffn].T, "wi": gu[ffn:].T,
                    "wo": sd[p + "mlp.down_proj.weight"].T},
            "ln1": {"scale": sd[p + "input_layernorm.weight"]},
            "ln2": {"scale": sd[p + "post_attention_layernorm.weight"]},
        })
    out = {"embed": {"tokens": sd["model.embed_tokens.weight"]},
           "layers": _stack(layers),
           "final_norm": {"scale": sd["model.norm.weight"]}}
    if not cfg.tie_embeddings:
        out["lm_head"] = sd.get("lm_head.weight",
                                sd["model.embed_tokens.weight"]).T
    return out


def _convert_qwen(sd, cfg):
    """Qwen v1 (remote-code modeling_qwen.py layout): transformer.h.*,
    fused biased c_attn, and the w1/w2/c_proj MLP where out =
    c_proj(w1(x) * silu(w2(x))) — w2 is the gate, w1 the up projection."""
    h = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        ca_w = sd[p + "attn.c_attn.weight"].T            # [h, 3h]
        ca_b = sd[p + "attn.c_attn.bias"]
        wq, wk, wv = np.split(ca_w, 3, axis=1)
        bq, bk, bv = np.split(ca_b, 3, axis=0)
        layers.append({
            "attn": {"wq": wq, "wk": wk, "wv": wv,
                     "bq": bq, "bk": bk, "bv": bv,
                     "wo": sd[p + "attn.c_proj.weight"].T},
            "mlp": {"wg": sd[p + "mlp.w2.weight"].T,
                    "wi": sd[p + "mlp.w1.weight"].T,
                    "wo": sd[p + "mlp.c_proj.weight"].T},
            "ln1": {"scale": sd[p + "ln_1.weight"]},
            "ln2": {"scale": sd[p + "ln_2.weight"]},
        })
    return {"embed": {"tokens": sd["transformer.wte.weight"]},
            "layers": _stack(layers),
            "final_norm": {"scale": sd["transformer.ln_f.weight"]},
            "lm_head": sd["lm_head.weight"].T}


def _split_headwise_qkv(w, b, nh, d):
    """Bloom/GPT-NeoX fused query_key_value: rows are grouped PER HEAD as
    [nh, (q|k|v), d] (ref GQAMegatronQKVParameter, module_inject/layers.py).
    Returns ((wq, wk, wv), (bq, bk, bv)) in the functional [in, out]
    layout."""
    h_in = w.shape[1]
    wg = w.reshape(nh, 3, d, h_in)
    ws = tuple(wg[:, j].reshape(nh * d, h_in).T for j in range(3))
    if b is None:
        return ws, (None, None, None)
    bg = b.reshape(nh, 3, d)
    return ws, tuple(bg[:, j].reshape(nh * d) for j in range(3))


def _convert_bloom(sd, cfg):
    """HF BloomForCausalLM → functional tree (ref
    module_inject/containers/bloom.py)."""
    nh, d = cfg.num_heads, cfg.dim_per_head
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        (wq, wk, wv), (bq, bk, bv) = _split_headwise_qkv(
            sd[p + "self_attention.query_key_value.weight"],
            sd[p + "self_attention.query_key_value.bias"], nh, d)
        layers.append({
            "attn": {"wq": wq, "wk": wk, "wv": wv,
                     "bq": bq, "bk": bk, "bv": bv,
                     "wo": sd[p + "self_attention.dense.weight"].T,
                     "bo": sd[p + "self_attention.dense.bias"]},
            "mlp": {"wi": sd[p + "mlp.dense_h_to_4h.weight"].T,
                    "bi": sd[p + "mlp.dense_h_to_4h.bias"],
                    "wo": sd[p + "mlp.dense_4h_to_h.weight"].T,
                    "bo": sd[p + "mlp.dense_4h_to_h.bias"]},
            "ln1": {"scale": sd[p + "input_layernorm.weight"],
                    "bias": sd[p + "input_layernorm.bias"]},
            "ln2": {"scale": sd[p + "post_attention_layernorm.weight"],
                    "bias": sd[p + "post_attention_layernorm.bias"]},
        })
    return {
        "embed": {
            "tokens": sd["transformer.word_embeddings.weight"],
            "norm": {
                "scale": sd["transformer.word_embeddings_layernorm.weight"],
                "bias": sd["transformer.word_embeddings_layernorm.bias"]}},
        "layers": _stack(layers),
        "final_norm": {"scale": sd["transformer.ln_f.weight"],
                       "bias": sd["transformer.ln_f.bias"]},
    }


def _convert_gptj(sd, cfg):
    """HF GPTJForCausalLM → functional tree (ref
    module_inject/containers/gptj.py).  The checkpoint's lm_head.bias
    (nonzero in the released EleutherAI weights) maps to the functional
    head's optional vocab-size output bias — served logits match HF
    per-token."""
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        ln1 = {"scale": sd[p + "ln_1.weight"], "bias": sd[p + "ln_1.bias"]}
        layers.append({
            "attn": {"wq": sd[p + "attn.q_proj.weight"].T,
                     "wk": sd[p + "attn.k_proj.weight"].T,
                     "wv": sd[p + "attn.v_proj.weight"].T,
                     "wo": sd[p + "attn.out_proj.weight"].T},
            "mlp": {"wi": sd[p + "mlp.fc_in.weight"].T,
                    "bi": sd[p + "mlp.fc_in.bias"],
                    "wo": sd[p + "mlp.fc_out.weight"].T,
                    "bo": sd[p + "mlp.fc_out.bias"]},
            # one shared input norm (parallel_norms=False): ln2 mirrors
            # ln1 to keep the stacked tree shape
            "ln1": ln1, "ln2": dict(ln1),
        })
    out = {"embed": {"tokens": sd["transformer.wte.weight"]},
           "layers": _stack(layers),
           "final_norm": {"scale": sd["transformer.ln_f.weight"],
                          "bias": sd["transformer.ln_f.bias"]},
           "lm_head": sd["lm_head.weight"].T}
    if "lm_head.bias" in sd:
        out["lm_head_bias"] = sd["lm_head.bias"]
    return out


def _convert_gptneo(sd, cfg):
    """HF GPTNeoForCausalLM → functional tree (ref
    module_inject/containers/gptneo.py).  q/k/v carry no bias; out_proj
    and the MLP do."""
    layers = []
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        layers.append({
            "attn": {"wq": sd[p + "attn.attention.q_proj.weight"].T,
                     "wk": sd[p + "attn.attention.k_proj.weight"].T,
                     "wv": sd[p + "attn.attention.v_proj.weight"].T,
                     "wo": sd[p + "attn.attention.out_proj.weight"].T,
                     "bo": sd[p + "attn.attention.out_proj.bias"]},
            "mlp": {"wi": sd[p + "mlp.c_fc.weight"].T,
                    "bi": sd[p + "mlp.c_fc.bias"],
                    "wo": sd[p + "mlp.c_proj.weight"].T,
                    "bo": sd[p + "mlp.c_proj.bias"]},
            "ln1": {"scale": sd[p + "ln_1.weight"],
                    "bias": sd[p + "ln_1.bias"]},
            "ln2": {"scale": sd[p + "ln_2.weight"],
                    "bias": sd[p + "ln_2.bias"]},
        })
    return {
        "embed": {"tokens": sd["transformer.wte.weight"],
                  "positions": sd["transformer.wpe.weight"]},
        "layers": _stack(layers),
        "final_norm": {"scale": sd["transformer.ln_f.weight"],
                       "bias": sd["transformer.ln_f.bias"]},
    }


def _convert_gptneox(sd, cfg):
    """HF GPTNeoXForCausalLM → functional tree (ref
    module_inject/containers/gptneox.py)."""
    nh, d = cfg.num_heads, cfg.dim_per_head
    layers = []
    for i in range(cfg.num_layers):
        p = f"gpt_neox.layers.{i}."
        (wq, wk, wv), (bq, bk, bv) = _split_headwise_qkv(
            sd[p + "attention.query_key_value.weight"],
            sd.get(p + "attention.query_key_value.bias"), nh, d)
        attn = {"wq": wq, "wk": wk, "wv": wv,
                "wo": sd[p + "attention.dense.weight"].T}
        if bq is not None:
            attn.update(bq=bq, bk=bk, bv=bv,
                        bo=sd[p + "attention.dense.bias"])
        layers.append({
            "attn": attn,
            "mlp": {"wi": sd[p + "mlp.dense_h_to_4h.weight"].T,
                    "bi": sd[p + "mlp.dense_h_to_4h.bias"],
                    "wo": sd[p + "mlp.dense_4h_to_h.weight"].T,
                    "bo": sd[p + "mlp.dense_4h_to_h.bias"]},
            "ln1": {"scale": sd[p + "input_layernorm.weight"],
                    "bias": sd[p + "input_layernorm.bias"]},
            "ln2": {"scale": sd[p + "post_attention_layernorm.weight"],
                    "bias": sd[p + "post_attention_layernorm.bias"]},
        })
    return {"embed": {"tokens": sd["gpt_neox.embed_in.weight"]},
            "layers": _stack(layers),
            "final_norm": {"scale": sd["gpt_neox.final_layer_norm.weight"],
                           "bias": sd["gpt_neox.final_layer_norm.bias"]},
            "lm_head": sd["embed_out.weight"].T}


def _convert_bert(sd, cfg):
    """HF BertForMaskedLM → functional tree (ref v1 injection
    module_inject/containers/bert.py; post-LN handled by norm_position)."""
    h = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"bert.encoder.layer.{i}."
        layers.append({
            "attn": {"wq": sd[p + "attention.self.query.weight"].T,
                     "bq": sd[p + "attention.self.query.bias"],
                     "wk": sd[p + "attention.self.key.weight"].T,
                     "bk": sd[p + "attention.self.key.bias"],
                     "wv": sd[p + "attention.self.value.weight"].T,
                     "bv": sd[p + "attention.self.value.bias"],
                     "wo": sd[p + "attention.output.dense.weight"].T,
                     "bo": sd[p + "attention.output.dense.bias"]},
            "mlp": {"wi": sd[p + "intermediate.dense.weight"].T,
                    "bi": sd[p + "intermediate.dense.bias"],
                    "wo": sd[p + "output.dense.weight"].T,
                    "bo": sd[p + "output.dense.bias"]},
            # post-LN: ln1 = attention.output.LayerNorm, ln2 = output.LayerNorm
            "ln1": {"scale": sd[p + "attention.output.LayerNorm.weight"],
                    "bias": sd[p + "attention.output.LayerNorm.bias"]},
            "ln2": {"scale": sd[p + "output.LayerNorm.weight"],
                    "bias": sd[p + "output.LayerNorm.bias"]},
        })
    out = {
        "embed": {
            "tokens": sd["bert.embeddings.word_embeddings.weight"],
            "positions": sd["bert.embeddings.position_embeddings.weight"],
            "token_types": sd["bert.embeddings.token_type_embeddings.weight"],
            "norm": {"scale": sd["bert.embeddings.LayerNorm.weight"],
                     "bias": sd["bert.embeddings.LayerNorm.bias"]}},
        "layers": _stack(layers),
        # post-LN stacks never apply final_norm; identity keeps the tree
        # shape every subsystem (sharding, checkpoints) expects
        "final_norm": {"scale": np.ones((h,), np.float32),
                       "bias": np.zeros((h,), np.float32)},
    }
    # classification checkpoints (BertForSequenceClassification) carry a
    # pooler + classifier instead of the MLM head; convert them so
    # models.encoder_heads.bert_pooled_classify can serve the logits
    if "bert.pooler.dense.weight" in sd:
        out["pooler"] = {"w": sd["bert.pooler.dense.weight"].T,
                         "b": sd["bert.pooler.dense.bias"]}
    if "classifier.weight" in sd:
        out["classifier"] = {"w": sd["classifier.weight"].T,
                             "b": sd["classifier.bias"]}
    if not cfg.mlm_head:
        return out  # headless encoder (hidden states / classification)
    if "cls.predictions.transform.dense.weight" not in sd:
        raise KeyError(
            "bert checkpoint carries no MLM head (cls.predictions.*): "
            "convert a BertForMaskedLM model, or build the config with "
            "mlm_head=False for headless encoders")
    out["mlm_head"] = {
        "w": sd["cls.predictions.transform.dense.weight"].T,
        "b": sd["cls.predictions.transform.dense.bias"],
        "ln": {"scale": sd["cls.predictions.transform.LayerNorm.weight"],
               "bias": sd["cls.predictions.transform.LayerNorm.bias"]},
        "bias": sd["cls.predictions.bias"]}
    return out


def _convert_distilbert(sd, cfg):
    """HF DistilBertForMaskedLM → functional tree (ref
    module_inject/containers/distil_bert.py).  No token-type table; the
    vocab_projector weight is tied to the embeddings."""
    h = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f"distilbert.transformer.layer.{i}."
        layers.append({
            "attn": {"wq": sd[p + "attention.q_lin.weight"].T,
                     "bq": sd[p + "attention.q_lin.bias"],
                     "wk": sd[p + "attention.k_lin.weight"].T,
                     "bk": sd[p + "attention.k_lin.bias"],
                     "wv": sd[p + "attention.v_lin.weight"].T,
                     "bv": sd[p + "attention.v_lin.bias"],
                     "wo": sd[p + "attention.out_lin.weight"].T,
                     "bo": sd[p + "attention.out_lin.bias"]},
            "mlp": {"wi": sd[p + "ffn.lin1.weight"].T,
                    "bi": sd[p + "ffn.lin1.bias"],
                    "wo": sd[p + "ffn.lin2.weight"].T,
                    "bo": sd[p + "ffn.lin2.bias"]},
            "ln1": {"scale": sd[p + "sa_layer_norm.weight"],
                    "bias": sd[p + "sa_layer_norm.bias"]},
            "ln2": {"scale": sd[p + "output_layer_norm.weight"],
                    "bias": sd[p + "output_layer_norm.bias"]},
        })
    return {
        "embed": {
            "tokens": sd["distilbert.embeddings.word_embeddings.weight"],
            "positions": sd["distilbert.embeddings.position_embeddings.weight"],
            "norm": {"scale": sd["distilbert.embeddings.LayerNorm.weight"],
                     "bias": sd["distilbert.embeddings.LayerNorm.bias"]}},
        "layers": _stack(layers),
        "final_norm": {"scale": np.ones((h,), np.float32),
                       "bias": np.zeros((h,), np.float32)},
        "mlm_head": {
            "w": sd["vocab_transform.weight"].T,
            "b": sd["vocab_transform.bias"],
            "ln": {"scale": sd["vocab_layer_norm.weight"],
                   "bias": sd["vocab_layer_norm.bias"]},
            "bias": sd["vocab_projector.bias"]},
    }


def load_hf_model(name_or_model, dtype=None):
    """AutoModel / checkpoint path → (TransformerConfig, params).  The
    one-call porting path for reference users (ref build_hf_engine)."""
    if isinstance(name_or_model, str):
        from transformers import AutoConfig

        conf = AutoConfig.from_pretrained(name_or_model)
        if getattr(conf, "model_type", "") in ("bert", "distilbert"):
            from transformers import AutoModelForMaskedLM as Auto
        else:
            from transformers import AutoModelForCausalLM as Auto
        model = Auto.from_pretrained(name_or_model)
    else:
        model = name_or_model
    cfg = config_from_hf(model.config)
    return cfg, params_from_hf(model, cfg, dtype=dtype)


for _arch, _fn in (("gpt2", _convert_gpt2), ("llama", _convert_llama),
                   ("mistral", _convert_llama), ("qwen2", _convert_llama),
                   ("opt", _convert_opt), ("falcon", _convert_falcon),
                   ("phi", _convert_phi), ("phi3", _convert_phi3),
                   ("qwen", _convert_qwen), ("bert", _convert_bert),
                   ("distilbert", _convert_distilbert),
                   ("bloom", _convert_bloom), ("gptj", _convert_gptj),
                   ("gptneox", _convert_gptneox),
                   ("gptneo", _convert_gptneo)):
    register_converter(_arch, _fn)
