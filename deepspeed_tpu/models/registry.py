"""Model presets (flagship + test-scale configs)."""

from __future__ import annotations

import jax.numpy as jnp

from deepspeed_tpu.models.transformer import TransformerConfig

_REGISTRY = {}


def register(name: str, cfg: TransformerConfig) -> TransformerConfig:
    _REGISTRY[name] = cfg
    return cfg


def get_model_config(name: str, **overrides) -> TransformerConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model '{name}'; known: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]
    return cfg.replace(**overrides) if overrides else cfg


def list_models():
    return sorted(_REGISTRY)


# -- GPT-2 family ------------------------------------------------------
register("gpt2-125m", TransformerConfig(
    vocab_size=50304,  # padded to 128 multiple for MXU tiling
    hidden_size=768, intermediate_size=3072, num_layers=12, num_heads=12,
    max_seq_len=1024, arch="gpt2", norm="layernorm", activation="gelu"))

register("gpt2-350m", TransformerConfig(
    vocab_size=50304, hidden_size=1024, intermediate_size=4096, num_layers=24,
    num_heads=16, max_seq_len=1024, arch="gpt2"))

register("gpt2-1.3b", TransformerConfig(
    vocab_size=50304, hidden_size=2048, intermediate_size=8192, num_layers=24,
    num_heads=32, max_seq_len=2048, arch="gpt2"))

# GPT-3 6.7B-class geometry — the peak_params ladder's chunked-offload
# rung builds this shape from gpt2-1.3b overrides; registered so the
# plan compiler (tools/plan.py --model gpt2-6.7b) can name it directly
register("gpt2-6.7b", TransformerConfig(
    vocab_size=50304, hidden_size=4096, intermediate_size=16384,
    num_layers=32, num_heads=32, max_seq_len=2048, arch="gpt2"))

# ~1B-total MoE with 8 routed experts: the planner's expert-parallel
# sight-unseen target (moe_1b_ep8) — experts dominate the param count,
# so expert-parallel meshes beat replicated-expert DP on wire bytes
register("moe-1b-ep8", TransformerConfig(
    vocab_size=32000, hidden_size=1024, intermediate_size=2816,
    num_layers=12, num_heads=16, num_kv_heads=8, max_seq_len=2048,
    arch="llama", norm="rmsnorm", activation="swiglu", use_rope=True,
    tie_embeddings=False, num_experts=8, top_k=2, moe_layer_freq=1))

# -- Llama family ------------------------------------------------------
_llama = dict(arch="llama", norm="rmsnorm", activation="swiglu", use_rope=True,
              tie_embeddings=False, rope_theta=500000.0)

register("llama3-8b", TransformerConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, max_seq_len=8192, **_llama))

register("llama3-70b", TransformerConfig(
    vocab_size=128256, hidden_size=8192, intermediate_size=28672, num_layers=80,
    num_heads=64, num_kv_heads=8, max_seq_len=8192, **_llama))

register("llama-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=256, arch="llama", norm="rmsnorm",
    activation="swiglu", use_rope=True, tie_embeddings=False, rope_theta=10000.0))

# -- Mixtral-style MoE -------------------------------------------------
register("mixtral-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=256, arch="llama", norm="rmsnorm",
    activation="swiglu", use_rope=True, tie_embeddings=False,
    num_experts=4, top_k=2, moe_layer_freq=1))

# Qwen2-MoE style: narrower routed experts + a gated shared expert
register("qwen2moe-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=256, arch="llama",
    norm="rmsnorm", activation="swiglu", use_rope=True,
    tie_embeddings=False, qkv_bias=True, num_experts=4, top_k=2,
    moe_layer_freq=1, moe_intermediate_size=64, moe_shared_expert_size=128))

register("qwen2moe-a14b", TransformerConfig(  # Qwen2-57B-A14B geometry
    vocab_size=151936, hidden_size=3584, intermediate_size=18944,
    num_layers=28, num_heads=28, num_kv_heads=4, max_seq_len=32768,
    arch="llama", norm="rmsnorm", activation="swiglu", use_rope=True,
    tie_embeddings=False, qkv_bias=True, num_experts=64, top_k=8,
    moe_layer_freq=1, moe_intermediate_size=2560,
    moe_shared_expert_size=20480))

register("mixtral-8x7b", TransformerConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, max_seq_len=8192, arch="llama", norm="rmsnorm",
    activation="swiglu", use_rope=True, tie_embeddings=False, rope_theta=1e6,
    num_experts=8, top_k=2, moe_layer_freq=1))

# -- OPT family (ref inference/v2/model_implementations/opt) -----------
_opt = dict(arch="opt", norm="layernorm", activation="relu",
            learned_positions=True, use_bias=True, tie_embeddings=True)

register("opt-125m", TransformerConfig(
    vocab_size=50272, hidden_size=768, intermediate_size=3072, num_layers=12,
    num_heads=12, max_seq_len=2048, **_opt))

register("opt-1.3b", TransformerConfig(
    vocab_size=50272, hidden_size=2048, intermediate_size=8192, num_layers=24,
    num_heads=32, max_seq_len=2048, **_opt))

# -- Mistral (ref v2 mistral: llama + sliding window) ------------------
register("mistral-7b", TransformerConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, max_seq_len=8192, arch="mistral",
    norm="rmsnorm", activation="swiglu", use_rope=True, tie_embeddings=False,
    rope_theta=10000.0, sliding_window=4096))

# -- Qwen2 (ref v2 qwen_v2: llama + qkv bias) --------------------------
register("qwen2-7b", TransformerConfig(
    vocab_size=152064, hidden_size=3584, intermediate_size=18944,
    num_layers=28, num_heads=28, num_kv_heads=4, max_seq_len=8192,
    arch="qwen2", norm="rmsnorm", activation="swiglu", use_rope=True,
    tie_embeddings=False, rope_theta=1e6, qkv_bias=True))

# -- Falcon (ref v2 falcon: multi-query + parallel block) --------------
register("falcon-7b", TransformerConfig(
    vocab_size=65024, hidden_size=4544, intermediate_size=18176,
    num_layers=32, num_heads=71, num_kv_heads=1, max_seq_len=2048,
    arch="falcon", norm="layernorm", activation="gelu", use_rope=True,
    tie_embeddings=True, parallel_block=True, use_bias=False))

# -- Phi (ref v2 phi: parallel block + partial rotary + biases) --------
register("phi-2", TransformerConfig(
    vocab_size=51200, hidden_size=2560, intermediate_size=10240,
    num_layers=32, num_heads=32, max_seq_len=2048, arch="phi",
    norm="layernorm", activation="gelu", use_rope=True, rotary_pct=0.4,
    tie_embeddings=False, parallel_block=True, use_bias=True))

# -- test-scale --------------------------------------------------------
register("gpt2-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, arch="gpt2"))

register("opt-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, **_opt))

register("mistral-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=256, arch="mistral",
    norm="rmsnorm", activation="swiglu", use_rope=True, tie_embeddings=False,
    sliding_window=32))

register("qwen2-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=256, arch="qwen2",
    norm="rmsnorm", activation="swiglu", use_rope=True, tie_embeddings=False,
    qkv_bias=True))

register("falcon-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=8, num_kv_heads=1, max_seq_len=256, arch="falcon",
    norm="layernorm", activation="gelu", use_rope=True, tie_embeddings=True,
    parallel_block=True, use_bias=False))

register("phi-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, arch="phi", norm="layernorm",
    activation="gelu", use_rope=True, rotary_pct=0.5, tie_embeddings=False,
    parallel_block=True, use_bias=True))


# -- Encoder (BERT-class) family ---------------------------------------
# Ref: the reference trains these through its fused transformer kernel
# (ops/transformer/transformer.py:296) and serves them via the
# bert/distil_bert v1 injection containers (module_inject/containers).
_bert = dict(arch="bert", norm="layernorm", activation="gelu_exact",
             causal=False, norm_position="post", embed_norm=True,
             mlm_head=True, tie_embeddings=True, layernorm_eps=1e-12)

register("bert-base-uncased", TransformerConfig(
    vocab_size=30522, hidden_size=768, intermediate_size=3072,
    num_layers=12, num_heads=12, max_seq_len=512, type_vocab_size=2,
    dropout=0.1, **_bert))

register("bert-large-uncased", TransformerConfig(
    vocab_size=30522, hidden_size=1024, intermediate_size=4096,
    num_layers=24, num_heads=16, max_seq_len=512, type_vocab_size=2,
    dropout=0.1, **_bert))

register("bert-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, type_vocab_size=2, **_bert))

register("distilbert-base-uncased", TransformerConfig(
    vocab_size=30522, hidden_size=768, intermediate_size=3072,
    num_layers=6, num_heads=12, max_seq_len=512, dropout=0.1,
    **{**_bert, "arch": "distilbert"}))

register("distilbert-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, **{**_bert, "arch": "distilbert"}))


# -- Bloom / GPT-J / GPT-NeoX (v1 injection breadth) -------------------
# Ref containers: module_inject/containers/{bloom,gptj,gptneox}.py
register("bloom-560m", TransformerConfig(
    vocab_size=250880, hidden_size=1024, intermediate_size=4096,
    num_layers=24, num_heads=16, max_seq_len=2048, arch="bloom",
    norm="layernorm", activation="gelu", use_alibi=True, embed_norm=True,
    use_bias=True, tie_embeddings=True))

register("bloom-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, arch="bloom", norm="layernorm",
    activation="gelu", use_alibi=True, embed_norm=True, use_bias=True,
    tie_embeddings=True))

register("gptj-6b", TransformerConfig(
    vocab_size=50400, hidden_size=4096, intermediate_size=16384,
    num_layers=28, num_heads=16, max_seq_len=2048, arch="gptj",
    norm="layernorm", activation="gelu", use_rope=True,
    rope_interleaved=True, rotary_pct=64 / 256, parallel_block=True,
    use_bias=False, mlp_bias=True, tie_embeddings=False))

register("gptj-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, arch="gptj", norm="layernorm",
    activation="gelu", use_rope=True, rope_interleaved=True,
    rotary_pct=0.5, parallel_block=True, use_bias=False, mlp_bias=True,
    tie_embeddings=False))

register("gptneox-20b", TransformerConfig(
    vocab_size=50432, hidden_size=6144, intermediate_size=24576,
    num_layers=44, num_heads=64, max_seq_len=2048, arch="gptneox",
    norm="layernorm", activation="gelu_exact", use_rope=True,
    rotary_pct=0.25, parallel_block=True, parallel_norms=True,
    use_bias=True, tie_embeddings=False))

register("gptneox-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, arch="gptneox", norm="layernorm",
    activation="gelu_exact", use_rope=True, rotary_pct=0.25,
    parallel_block=True, parallel_norms=True, use_bias=True,
    tie_embeddings=False))


register("gptneo-1.3b", TransformerConfig(
    vocab_size=50257, hidden_size=2048, intermediate_size=8192,
    num_layers=24, num_heads=16, max_seq_len=2048, arch="gptneo",
    norm="layernorm", activation="gelu", learned_positions=True,
    use_bias=False, mlp_bias=True, attn_out_bias=True, alt_window=True,
    sliding_window=256,
    attn_scale=1.0, tie_embeddings=True))

register("gptneo-tiny", TransformerConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, max_seq_len=256, arch="gptneo", norm="layernorm",
    activation="gelu", learned_positions=True, use_bias=False,
    mlp_bias=True, attn_out_bias=True, alt_window=True,
    sliding_window=16, attn_scale=1.0,
    tie_embeddings=True))
