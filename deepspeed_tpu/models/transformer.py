"""Functional transformer model family (GPT-2 and Llama class).

TPU-first design notes (vs the reference's per-module eager torch models):

* Parameters are a plain pytree (nested dicts of jnp arrays); the per-layer
  params are **stacked along a leading layer axis** and the forward is a
  ``lax.scan`` over layers — one compiled layer body regardless of depth,
  which is the idiomatic XLA replacement for DeepSpeed's per-module hook
  machinery (SURVEY §7 hard part (a)).
* Activation checkpointing is ``jax.checkpoint`` with a configurable policy
  (ref: runtime/activation_checkpointing/checkpointing.py:948 — here the
  compiler does the re-materialisation).
* Compute runs in ``config.dtype`` (bf16 by default), master params stay in
  ``param_dtype`` (fp32) — the engine's mixed-precision contract.
* Param paths are stable strings (e.g. ``layers/attn/wq``) so parallelism
  sharding rules can be expressed as path-pattern → PartitionSpec maps
  (AutoTP-equivalent, ref module_inject/auto_tp.py:193).
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    """Architecture hyperparameters covering GPT-2 and Llama families."""
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # < num_heads → GQA (Llama-3)
    head_dim: Optional[int] = None
    max_seq_len: int = 1024
    # architecture switches
    arch: str = "gpt2"  # "gpt2" | "llama" | "opt" | "mistral" | "qwen2" | "falcon" | "phi"
    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    activation: str = "gelu"  # "gelu" | "swiglu" | "relu"
    use_rope: bool = False
    rope_theta: float = 10000.0
    rotary_pct: float = 1.0  # Phi-style partial rotary (fraction of head dim)
    tie_embeddings: bool = True
    # family features (ref inference/v2/model_implementations/{opt,phi,qwen,
    # falcon,mistral}): learned absolute positions, projection biases,
    # sliding-window attention, parallel attn+MLP residual blocks
    learned_positions: Optional[bool] = None  # None → arch == "gpt2"/"opt"
    use_bias: Optional[bool] = None  # all proj biases; None → gpt2/opt
    qkv_bias: bool = False  # qkv-only bias (Qwen2)
    sliding_window: Optional[int] = None  # Mistral
    # GPT-Neo attention_types: ODD global layer indices use
    # sliding_window ("local"), even ones attend globally.  Realized by
    # scanning layer PAIRS with a static per-member config — no dynamic
    # masks (ref module_inject/containers/gptneo.py)
    alt_window: bool = False
    # attention score scale; None → 1/sqrt(head_dim).  GPT-Neo famously
    # omits the sqrt(d) scaling (scale = 1.0)
    attn_scale: Optional[float] = None
    # ALiBi positional bias (Bloom): score += slope[h] · key_position —
    # used instead of rope/learned positions
    use_alibi: bool = False
    # GPT-J rotary layout: dims pair as (2i, 2i+1) ("rotate every two")
    # instead of the llama/neox half-split
    rope_interleaved: bool = False
    # MLP bias independent of attention bias (GPT-J: biasless attention,
    # biased MLP); None → follows has_bias
    mlp_bias: Optional[bool] = None
    # attention OUT-projection bias independent of q/k/v bias (GPT-Neo:
    # biasless q/k/v, biased out_proj); None → follows has_bias
    attn_out_bias: Optional[bool] = None
    # False = bidirectional (encoder/BERT-class) attention.  The reference
    # trains encoders through its fused transformer kernel
    # (ops/transformer/transformer.py:296 DeepSpeedTransformerLayer) and
    # serves bert/distilbert via v1 injection containers.
    causal: bool = True
    # "pre" (GPT/llama) | "post" (BERT: residual-add then LayerNorm; the
    # final norm is per-layer, so no final_norm is applied)
    norm_position: str = "pre"
    # BERT segment embeddings: 0 = none; batch may carry "token_type_ids"
    type_vocab_size: int = 0
    # BERT: LayerNorm (+dropout) applied to the summed embeddings
    embed_norm: bool = False
    # BERT MLM head: LN(gelu(h @ W + b)) @ embed.T + bias instead of the
    # plain lm_head matmul (HF BertLMPredictionHead)
    mlm_head: bool = False
    # vocab-size output bias added to the logits (GPT-J ships a nonzero
    # lm_head.bias; HF applies it, so serving parity requires it too)
    lm_head_bias: bool = False
    parallel_block: bool = False  # Falcon/Phi: x + attn(n) + mlp(n)
    # Falcon new_decoder_architecture (40B/180B, num_ln_in_parallel_attn=2):
    # the parallel block gets separate input norms — attn uses ln1 (HF
    # ln_attn) and the MLP uses ln2 (HF ln_mlp) on the same residual input.
    parallel_norms: bool = False
    # MoE (0 ⇒ dense; ref deepspeed/moe)
    num_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 1.25
    # routed-expert FFN width when it differs from the dense layers'
    # intermediate_size (HF qwen2_moe moe_intermediate_size); None → same
    moe_intermediate_size: Optional[int] = None
    # Qwen2-MoE shared expert: a dense FFN of this width added to the
    # routed output, gated by sigmoid(x @ shared_gate); 0 = none
    moe_shared_expert_size: int = 0
    # True: renormalize top-k weights to sum to 1 (HF mixtral
    # norm_topk_prob); False: deepspeed top2gating drop-aware scaling
    moe_norm_topk: bool = False
    # Residual MoE (PR-MoE, ref moe/layer.py:29 use_residual /
    # arXiv:2201.05596): a dense expert-shaped MLP runs every token and a
    # learned 2-way coefficient softmax mixes it with the routed output
    moe_use_residual: bool = False
    # "auto" | "einsum" | "sorted": [T,E,C] one-hot einsum dispatch vs
    # argsort-by-expert gather dispatch (auto switches on one-hot size)
    moe_dispatch: str = "auto"
    # "1f1b" (training loss runs the interleaved schedule with O(pp) live
    # microbatches, ref runtime/pipe/schedule.py:189) | "gpipe" (fill-drain
    # forward scan differentiated by AD)
    pipeline_schedule: str = "1f1b"
    # ZeRO-Infinity: stacked layer params live in pinned host memory and
    # stream one layer at a time through the scan, fwd and bwd
    # (runtime/infinity.py; set by the engine from offload_param config)
    param_stream: bool = False
    moe_layer_freq: int = 2  # every Nth layer is MoE, matching ref PR-MoE style
    # pipeline parallelism: microbatches per forward call, i.e. per
    # gradient-accumulation micro-step (0 → pp size); must divide the
    # per-call batch dim
    pipeline_microbatches: int = 0
    # random-LTD (ref data_routing/basic_layer.py): a band of middle layers
    # [ltd_start, ltd_end) runs on ltd_kept random tokens; 0 = disabled.
    # ltd_kept is static per compile — the engine re-jits when the
    # schedule raises it (same recompile cadence as the reference's
    # shape changes).
    ltd_kept: int = 0
    ltd_start: int = 1
    ltd_end: Optional[int] = None
    # reference noisy gating (TopKGate noisy_gate_policy): 'RSample' |
    # 'Jitter' | None; active only while training threads a dropout/noise
    # key through the batch
    moe_noisy_gate_policy: Optional[str] = None
    # sequence-tiled logits+loss (ALST, sequence/alst.py): never
    # materialises [B, S, V]; 0 = full logits
    loss_tiles: int = 0
    # sequence-parallel attention form over the "seq" mesh axis:
    # "ulysses" (all-to-all head exchange; needs heads % (tp·sp) == 0) |
    # "ring" (K/V blocks rotate the ring with online softmax; no head
    # divisibility requirement — sequence/ring.py)
    seq_impl: str = "ulysses"
    # ring attention block placement over the seq mesh: "contiguous"
    # (shard r owns rows [r·S_l, (r+1)·S_l)) | "striped" (shard r owns
    # rows r, r+sp, … — Striped Attention causal load balancing: every
    # hop is ~half-masked on every rank, so the flash kernel's tile skip
    # halves causal compute uniformly instead of idling early ranks).
    # Striped feeds require stripe-permuted ids/labels; the engine
    # applies the permutation host-side and forward() derives matching
    # positions, so training is turnkey (sequence/ring.py helpers).
    ring_placement: str = "contiguous"
    # ring hop/compute interleave depth (step_schedule.ring_interleave;
    # sequence/ring.py): 1 = attend then rotate, 2 = rotate-ahead (next
    # hop's ppermute issued before the current hop's attend so the
    # transfer overlaps the hop's kernels)
    ring_interleave: int = 1
    # ring rotation wire dtype (comm_quantization.ring_rotation; set by
    # the engine): "fp32" | "int8" | "fp8" — quantized payloads + fp32
    # per-row scales travel every ring hop, dequantized in the consuming
    # flash kernel's epilogue (sequence/ring.py)
    ring_wire_dtype: str = "fp32"
    # layer-scan unroll factor (XLA overlaps across unrolled iterations)
    scan_unroll: int = 1
    # ZeRO-3 fused gather-matmul (step_schedule.fused_gather_matmul;
    # ops/pallas/gather_matmul.py): the MLP matmuls run inside an
    # explicit shard_map over `fused_gather_axes` that issues the
    # following matmul's param all-gather ahead of the current one.  Set
    # by the engine after it verifies the MLP weights actually carry the
    # expected fsdp sharding pattern.
    fused_gather_matmul: bool = False
    fused_gather_axes: Tuple[str, ...] = ()
    # residual/embedding dropout rate (GPT-2/BERT-class training; llama
    # pretraining leaves it 0).  Applied when the engine threads a
    # per-step PRNG key through the batch ("dropout_key"); inference and
    # eval paths pass no key, so dropout is identically off there.
    # Attention-probability dropout is folded into the residual drops
    # (the flash kernel keeps its probabilities in VMEM).  Under remat,
    # explicit keys make the recompute bitwise-identical — the property
    # the reference's CudaRNGStatesTracker exists to enforce.
    dropout: float = 0.0
    # numerics
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32  # master dtype
    layernorm_eps: float = 1e-5
    # per-op autocast policy (ref runtime/torch_autocast.py): which op
    # classes stay fp32 regardless of the compute dtype.  None → the safe
    # default below.  Configured via the "torch_autocast" config block
    # ("fp32_ops"); dropping entries is the aggressive full-low-precision
    # mode.  NOTE: the Pallas flash kernels always accumulate softmax in
    # fp32 (hardware-right on TPU) — "softmax" here gates the XLA path.
    fp32_ops: Optional[Tuple[str, ...]] = None
    # module classes allowed to run in the low compute dtype; None → all.
    # Modules NOT listed are promoted to fp32 (the torch autocast
    # "lower_precision_safe_modules" contract).
    autocast_safe_modules: Optional[Tuple[str, ...]] = None
    # remat policy name: none|full|nothing_saveable|dots_saveable|dots_with_no_batch_dims_saveable
    remat_policy: str = "nothing_saveable"
    attn_impl: str = "auto"  # "auto" | "xla" | "pallas_flash" | "sparse"
    # block-sparse attention config (ref ops/sparse_attention sparsity
    # configs): {"mode": "fixed"|"bigbird"|"bslongformer"|"variable",
    # "block": 16, ...mode kwargs}; selected when attn_impl == "sparse"
    sparse_attention: Optional[Any] = None
    # inference-v2 module overrides as (kind, name) pairs — resolved via
    # inference/v2/modules.py (ref inference/v2/modules/heuristics.py)
    v2_modules: Optional[Tuple[Tuple[str, str], ...]] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dim_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def has_learned_positions(self) -> bool:
        if self.learned_positions is not None:
            return self.learned_positions
        return self.arch in ("gpt2", "opt", "bert", "distilbert")

    @property
    def has_bias(self) -> bool:
        if self.use_bias is not None:
            return self.use_bias
        return self.arch in ("gpt2", "opt", "phi", "bert", "distilbert")

    @property
    def has_mlp_bias(self) -> bool:
        return self.has_bias if self.mlp_bias is None else self.mlp_bias

    @property
    def has_attn_out_bias(self) -> bool:
        return (self.has_bias if self.attn_out_bias is None
                else self.attn_out_bias)

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)


# ----------------------------------------------------------------------
# Init
# ----------------------------------------------------------------------
def _dense_init(key, shape, scale, dtype):
    return (jax.random.normal(key, shape) * scale).astype(dtype)


def init_layer_params(cfg: TransformerConfig, key) -> Params:
    """One transformer block's params (unstacked)."""
    h, ffn = cfg.hidden_size, cfg.intermediate_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    keys = jax.random.split(key, 8)
    scale = 1.0 / math.sqrt(h)
    out_scale = scale / math.sqrt(2 * cfg.num_layers)  # GPT-2 style residual scaling
    pd = cfg.param_dtype

    attn = {
        "wq": _dense_init(keys[0], (h, nh * hd), scale, pd),
        "wk": _dense_init(keys[1], (h, nkv * hd), scale, pd),
        "wv": _dense_init(keys[2], (h, nkv * hd), scale, pd),
        "wo": _dense_init(keys[3], (nh * hd, h), out_scale, pd),
    }
    if cfg.has_bias or cfg.qkv_bias:
        attn["bq"] = jnp.zeros((nh * hd,), pd)
        attn["bk"] = jnp.zeros((nkv * hd,), pd)
        attn["bv"] = jnp.zeros((nkv * hd,), pd)
    if cfg.has_attn_out_bias:
        attn["bo"] = jnp.zeros((h,), pd)

    def mlp_params(k1, k2, k3):
        if cfg.activation == "swiglu":
            return {
                "wi": _dense_init(k1, (h, ffn), scale, pd),
                "wg": _dense_init(k2, (h, ffn), scale, pd),
                "wo": _dense_init(k3, (ffn, h), out_scale, pd),
            }
        mlp = {
            "wi": _dense_init(k1, (h, ffn), scale, pd),
            "wo": _dense_init(k3, (ffn, h), out_scale, pd),
        }
        if cfg.has_mlp_bias:
            mlp["bi"] = jnp.zeros((ffn,), pd)
            mlp["bo"] = jnp.zeros((h,), pd)
        return mlp

    block: Params = {"attn": attn}
    if not (cfg.is_moe and cfg.moe_layer_freq == 1):
        # all-MoE stacks (freq 1, mixtral/qwen2moe style) carry no dense
        # FFN at all — a zero/random filler would cost real HBM and
        # optimizer state (e.g. ~22GB of dead fp32 on mixtral-8x7b)
        block["mlp"] = mlp_params(keys[4], keys[5], keys[6])

    if cfg.is_moe:
        # Expert weights stacked on a leading expert axis (sharded over the
        # "expert" mesh axis); router is replicated. Ref: moe/experts.py +
        # sharded_moe.py TopKGate.
        ek = jax.random.split(keys[7], 12)
        e = cfg.num_experts
        mffn = cfg.moe_intermediate_size or ffn
        block["moe"] = {
            "router": _dense_init(ek[0], (h, e), scale, pd),
            "wi": _dense_init(ek[1], (e, h, mffn), scale, pd),
            "wg": _dense_init(ek[2], (e, h, mffn), scale, pd) if cfg.activation == "swiglu" else None,
            "wo": _dense_init(ek[3], (e, mffn, h), out_scale, pd),
        }
        if cfg.moe_use_residual:
            # PR-MoE (ref moe/layer.py:83-86): the residual branch is an
            # expert-shaped dense MLP plus a Linear(h, 2) mixing head
            block["moe"]["residual"] = {
                k: v for k, v in {
                    "wi": _dense_init(ek[8], (h, mffn), scale, pd),
                    "wg": _dense_init(ek[9], (h, mffn), scale, pd)
                    if cfg.activation == "swiglu" else None,
                    "wo": _dense_init(ek[10], (mffn, h), out_scale, pd),
                }.items() if v is not None}
            block["moe"]["coef_w"] = _dense_init(ek[11], (h, 2), scale, pd)
            block["moe"]["coef_b"] = jnp.zeros((2,), pd)
        if cfg.moe_shared_expert_size:
            sf = cfg.moe_shared_expert_size
            block["moe"]["shared"] = {
                "wi": _dense_init(ek[4], (h, sf), scale, pd),
                "wg": _dense_init(ek[5], (h, sf), scale, pd)
                if cfg.activation == "swiglu" else None,
                "wo": _dense_init(ek[6], (sf, h), out_scale, pd),
            }
            block["moe"]["shared"] = {k: v for k, v
                                      in block["moe"]["shared"].items()
                                      if v is not None}
            block["moe"]["shared_gate"] = _dense_init(ek[7], (h, 1), scale,
                                                      pd)
        block["moe"] = {k: v for k, v in block["moe"].items() if v is not None}

    def norm_params():
        p = {"scale": jnp.ones((h,), pd)}
        if cfg.norm == "layernorm":
            p["bias"] = jnp.zeros((h,), pd)
        return p

    block["ln1"] = norm_params()
    block["ln2"] = norm_params()
    return block


def init_params(cfg: TransformerConfig, key) -> Params:
    """Full model params with per-layer params stacked on axis 0."""
    # nl+5 keys: rows are counter-derived, so rows nl..nl+2 keep the same
    # values the old nl+3 split produced (init stays bit-stable for
    # existing archs); the encoder-only params use the two new rows.
    nl = cfg.num_layers
    keys = jax.random.split(key, nl + 5)
    scale = 1.0 / math.sqrt(cfg.hidden_size)
    pd = cfg.param_dtype
    h = cfg.hidden_size

    layer_list = [init_layer_params(cfg, keys[i]) for i in range(nl)]
    layers = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *layer_list)

    params: Params = {
        "embed": {"tokens": _dense_init(keys[nl], (cfg.vocab_size, h), scale, pd)},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((h,), pd)},
    }
    if cfg.norm == "layernorm":
        params["final_norm"]["bias"] = jnp.zeros((h,), pd)
    if cfg.has_learned_positions:
        params["embed"]["positions"] = _dense_init(
            keys[nl + 1], (cfg.max_seq_len, h), scale, pd)
    if cfg.type_vocab_size:
        params["embed"]["token_types"] = _dense_init(
            keys[nl + 3], (cfg.type_vocab_size, h), scale, pd)
    if cfg.embed_norm:
        params["embed"]["norm"] = {"scale": jnp.ones((h,), pd),
                                   "bias": jnp.zeros((h,), pd)}
    if cfg.mlm_head:
        # BERT MLM head (HF BertLMPredictionHead): transform dense + LN,
        # decoder tied to the token embeddings, per-vocab output bias
        params["mlm_head"] = {
            "w": _dense_init(keys[nl + 4], (h, h), scale, pd),
            "b": jnp.zeros((h,), pd),
            "ln": {"scale": jnp.ones((h,), pd), "bias": jnp.zeros((h,), pd)},
            "bias": jnp.zeros((cfg.vocab_size,), pd),
        }
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(keys[nl + 2], (h, cfg.vocab_size), scale, pd)
    if cfg.lm_head_bias and not cfg.mlm_head:
        params["lm_head_bias"] = jnp.zeros((cfg.vocab_size,), pd)
    return params


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ----------------------------------------------------------------------
# Forward pieces
# ----------------------------------------------------------------------
_DEFAULT_FP32_OPS = ("layernorm", "softmax", "rope", "router", "loss")


def op_fp32(cfg, op: str) -> bool:
    """Whether op class ``op`` runs in fp32 under the autocast policy.
    getattr: callers (moe/sharded_moe) pass duck-typed configs in tests."""
    ops = getattr(cfg, "fp32_ops", None)
    return op in (ops if ops is not None else _DEFAULT_FP32_OPS)


def _module_dtype(cfg: TransformerConfig, name: str, default_dt):
    """Compute dtype for module class ``name``: safe-listed (or no list →
    everything) runs in the low dtype, the rest is promoted to fp32."""
    if cfg.autocast_safe_modules is None:
        return default_dt
    if any(pat in name for pat in cfg.autocast_safe_modules):
        return default_dt
    return jnp.float32


def _norm(x, p, cfg: TransformerConfig):
    dt = x.dtype
    ct = jnp.float32 if op_fp32(cfg, "layernorm") else dt
    xc = x.astype(ct)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(jnp.square(xc), axis=-1, keepdims=True)
        out = xc * lax.rsqrt(var + cfg.layernorm_eps) * p["scale"].astype(ct)
    else:
        mean = jnp.mean(xc, axis=-1, keepdims=True)
        var = jnp.var(xc, axis=-1, keepdims=True)
        out = (xc - mean) * lax.rsqrt(var + cfg.layernorm_eps)
        out = out * p["scale"].astype(ct) + p["bias"].astype(ct)
    return out.astype(dt)


def _rope(q, k, positions, cfg: TransformerConfig):
    """Rotary embeddings (Llama). q,k: [B, S, H, D].  ``rotary_pct`` < 1
    rotates only the leading fraction of the head dim (Phi partial rotary,
    ref inference/v2 phi containers)."""
    d = cfg.dim_per_head
    rot_d = d if cfg.rotary_pct >= 1.0 else max(2, int(d * cfg.rotary_pct) // 2 * 2)
    freqs = 1.0 / (cfg.rope_theta ** (jnp.arange(0, rot_d, 2, dtype=jnp.float32) / rot_d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, rot_d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    ct = jnp.float32 if op_fp32(cfg, "rope") else q.dtype
    cos, sin = cos.astype(ct), sin.astype(ct)

    def rot(x):
        xf = x.astype(ct)
        xr, x_pass = xf[..., :rot_d], xf[..., rot_d:]
        if cfg.rope_interleaved:
            # GPT-J "rotate every two": dims pair as (2i, 2i+1)
            x1, x2 = xr[..., 0::2], xr[..., 1::2]
            xr = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1).reshape(xr.shape)
        else:
            x1, x2 = jnp.split(xr, 2, axis=-1)
            xr = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                                 axis=-1)
        return jnp.concatenate([xr, x_pass], axis=-1)

    return rot(q).astype(q.dtype), rot(k).astype(k.dtype)


def alibi_slopes(nh: int) -> jnp.ndarray:
    """ALiBi head slopes (Press et al.; HF build_alibi_tensor semantics,
    including the non-power-of-two head interleave)."""
    cp2 = 2 ** math.floor(math.log2(nh))
    base = 2.0 ** (-(2.0 ** -(math.log2(cp2) - 3)))
    slopes = [base ** (i + 1) for i in range(cp2)]
    if cp2 != nh:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * cp2) - 3)))
        slopes += [extra_base ** (i + 1)
                   for i in range(0, 2 * (nh - cp2), 2)]
    return jnp.asarray(slopes, jnp.float32)


def _attention_scores(q, k, v, cfg: TransformerConfig, segment_pos=None,
                      attention_mask=None):
    """MHA/GQA over [B, S, H, D] via XLA einsums (MXU-friendly) — causal
    or bidirectional per ``cfg.causal``.  ``attention_mask``: [B, S] 1 =
    attend / 0 = padding key (HF convention).  Pallas flash attention is
    selected by the engine when attn_impl allows."""
    b, s, nh, d = q.shape
    nkv = k.shape[2]
    if nkv != nh:  # GQA: repeat kv heads
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = cfg.attn_scale if cfg.attn_scale is not None else 1 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if cfg.use_alibi:
        # Bloom ALiBi: slope[h] · key_position added to the scores (HF's
        # key-position form — per-query-row softmax shift makes it
        # equivalent to the distance form)
        if attention_mask is not None:
            # HF build_alibi_tensor derives key positions from the padding
            # mask (cumsum - 1 over the kept keys), so LEFT-padded batches
            # bias by the token's position within the real sequence, not
            # its slot index.  Padding slots get position 0; their scores
            # are masked below anyway.
            am = attention_mask.astype(jnp.float32)
            kpos = (jnp.cumsum(am, axis=-1) - 1.0) * am      # [B, S]
            scores = scores + (alibi_slopes(nh)[None, :, None, None]
                               * kpos[:, None, None, :]).astype(scores.dtype)
        else:
            kpos = jnp.arange(s, dtype=jnp.float32)
            scores = scores + (alibi_slopes(nh)[:, None, None]
                               * kpos[None, None, :]).astype(scores.dtype)
    if cfg.causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        if cfg.sliding_window:
            # Mistral sliding-window: key within the last `window` positions
            qpos = lax.broadcasted_iota(jnp.int32, (s, s), 0)
            kpos = lax.broadcasted_iota(jnp.int32, (s, s), 1)
            mask = mask & (qpos - kpos < cfg.sliding_window)
        mask = mask[None, None, :, :]
    else:
        mask = jnp.ones((1, 1, s, s), dtype=bool)
    if attention_mask is not None:
        mask = mask & attention_mask[:, None, None, :].astype(bool)
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    ct = jnp.float32 if op_fp32(cfg, "softmax") else scores.dtype
    probs = jax.nn.softmax(scores.astype(ct), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _sparse_attn(q, k, v, cfg: TransformerConfig):
    """Block-sparse attention path (ref ops/sparse_attention configs);
    causal composes with the layout."""
    from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                    BSLongformerSparsityConfig,
                                                    DenseSparsityConfig,
                                                    FixedSparsityConfig,
                                                    VariableSparsityConfig,
                                                    sparse_attention)

    sc = dict(cfg.sparse_attention or {})
    mode = sc.pop("mode", "fixed")
    cls = {"fixed": FixedSparsityConfig, "bigbird": BigBirdSparsityConfig,
           "bslongformer": BSLongformerSparsityConfig,
           "variable": VariableSparsityConfig,
           "dense": DenseSparsityConfig}[mode]
    sparsity = cls(num_heads=q.shape[2], **sc)
    return sparse_attention(q, k, v, sparsity, causal=cfg.causal)


def _attn_block(x, p, positions, cfg: TransformerConfig,
                attention_mask=None):
    b, s, h = x.shape
    nh, nkv, d = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    dt0 = x.dtype  # residual-stream dtype: restored at the block boundary
    dt = _module_dtype(cfg, "attn", dt0)
    x = x.astype(dt)

    def proj(w, b_, out_dim):
        y = x @ w.astype(dt)
        if b_ is not None:
            y = y + b_.astype(dt)
        return y

    q = proj(p["wq"], p.get("bq"), nh * d).reshape(b, s, nh, d)
    k = proj(p["wk"], p.get("bk"), nkv * d).reshape(b, s, nkv, d)
    v = proj(p["wv"], p.get("bv"), nkv * d).reshape(b, s, nkv, d)
    if cfg.use_rope:
        q, k = _rope(q, k, positions, cfg)

    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    if cfg.seq_impl not in ("ulysses", "ring"):
        raise ValueError(f"seq_impl={cfg.seq_impl!r}: expected 'ulysses' "
                         "or 'ring'")
    if (topo is not None and topo.sp_size > 1 and cfg.seq_impl == "ring"):
        # Ring attention: K/V blocks rotate the seq ring (nearest-
        # neighbour ppermute + online softmax) — no heads % sp
        # requirement, unlike the Ulysses all-to-all below.
        if attention_mask is not None:
            raise NotImplementedError(
                "attention_mask + ring sequence parallelism not supported")
        if cfg.use_alibi:
            raise NotImplementedError(
                "alibi + ring sequence parallelism not supported (the "
                "ring hop has no score-bias lane yet)")
        if cfg.attn_impl == "sparse":
            raise NotImplementedError(
                "attn_impl='sparse' + ring sequence parallelism not "
                "supported (dense ring hops would silently replace the "
                "block-sparse layout's semantics)")
        from deepspeed_tpu.sequence.ring import ring_attention

        out = ring_attention(q, k, v, topo, causal=cfg.causal,
                             sm_scale=cfg.attn_scale,
                             window=cfg.sliding_window or None,
                             placement=cfg.ring_placement,
                             interleave=cfg.ring_interleave,
                             wire_dtype=cfg.ring_wire_dtype)
        out = out.reshape(b, s, nh * d)
        out = out @ p["wo"].astype(dt)
        if p.get("bo") is not None:
            out = out + p["bo"].astype(dt)
        return out.astype(dt0)

    # Ulysses SP: re-shard seq-sharded q/k/v to head-sharded (XLA lowers the
    # layout switch to all-to-all over ICI; ref sequence/layer.py:331).
    from deepspeed_tpu.sequence.layer import (ulysses_output_constraint,
                                              ulysses_qkv_constraint)

    q, k, v = ulysses_qkv_constraint(q, k, v)

    if attention_mask is not None or cfg.use_alibi:
        if cfg.attn_impl == "sparse":
            raise NotImplementedError(
                "attention_mask/alibi + attn_impl='sparse' not supported "
                "(the padding mask would silently replace the block-sparse "
                "layout's semantics)")
        # key-padding masks and the ALiBi score bias thread only through
        # the XLA scores path (the flash kernel has neither lane; padded
        # serving is the encoder case, alibi the bloom family)
        out = _attention_scores(q, k, v, cfg, attention_mask=attention_mask)
    elif cfg.attn_impl == "sparse":
        out = _sparse_attn(q, k, v, cfg)
    elif cfg.attn_impl in ("pallas_flash", "auto"):
        # flash_attention dispatches: Pallas kernel on TPU (tiled online
        # softmax, no [S,S] materialisation; sliding windows skip dead
        # tiles at the grid level), equivalent XLA math elsewhere.
        from deepspeed_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=cfg.causal,
                              sm_scale=cfg.attn_scale,
                              window=cfg.sliding_window or None)
    else:
        out = _attention_scores(q, k, v, cfg)
    out = ulysses_output_constraint(out.reshape(b, s, nh * d))
    out = out @ p["wo"].astype(dt)
    if p.get("bo") is not None:
        out = out + p["bo"].astype(dt)
    return out.astype(dt0)


def _mlp_block(x, p, cfg: TransformerConfig):
    dt0 = x.dtype
    dt = _module_dtype(cfg, "mlp", dt0)
    x = x.astype(dt)
    if cfg.fused_gather_matmul and cfg.fused_gather_axes:
        # ZeRO-3 fused gather-matmul (step_schedule.fused_gather_matmul;
        # ops/pallas/gather_matmul.py): explicit shard_map over the fsdp
        # axes — the following matmul's param all-gather issues inside
        # the current matmul's epilogue region instead of wherever GSPMD
        # scheduled it.  The engine verified the weight sharding pattern
        # before setting the flag; the tiny output bias stays on the
        # implicit path (bi rides the fused region — it must add before
        # the activation).
        from deepspeed_tpu.ops.pallas.gather_matmul import fused_gather_mlp

        y = fused_gather_mlp(x, p, cfg)
        if p.get("bo") is not None:
            y = y + p["bo"].astype(dt)
        return y.astype(dt0)
    if cfg.activation == "swiglu":
        gate = jax.nn.silu(x @ p["wg"].astype(dt))
        up = x @ p["wi"].astype(dt)
        return ((gate * up) @ p["wo"].astype(dt)).astype(dt0)
    y = x @ p["wi"].astype(dt)
    if p.get("bi") is not None:
        y = y + p["bi"].astype(dt)
    # "gelu_exact" = erf gelu (HF BERT's hidden_act="gelu"); "gelu" keeps
    # the tanh approximation the decoder families use
    y = jax.nn.relu(y) if cfg.activation == "relu" \
        else jax.nn.gelu(y, approximate=cfg.activation != "gelu_exact")
    y = y @ p["wo"].astype(dt)
    if p.get("bo") is not None:
        y = y + p["bo"].astype(dt)
    return y.astype(dt0)


def _moe_block(x, p, cfg: TransformerConfig, allow_ep: bool = True,
               noise_key=None):
    """MoE block used inside the scan.  With an expert mesh axis of size
    > 1 the explicit shard_map + all_to_all expert-parallel path runs
    (deepspeed_tpu/moe/sharded_moe.moe_forward_ep — the reference's
    `_AllToAll` dispatch on ICI); otherwise the single-group path.

    ``allow_ep=False`` is passed from ``lax.cond`` call sites: a shard_map
    collective inside a cond branch crashes XLA's backward pass, so traced
    MoE-vs-dense selection keeps the auto-partitioned formulation (the
    grouped scan in :func:`forward` makes the selection static precisely
    so the EP path applies on aligned configs)."""
    from deepspeed_tpu.moe.sharded_moe import moe_forward, moe_forward_ep
    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    if allow_ep and topo is not None and topo.ep_size > 1:
        return moe_forward_ep(x, p, cfg, topo, noise_key=noise_key)
    return moe_forward(x, p, cfg, noise_key=noise_key)


def _select_ffn(h, layer_params, cfg: TransformerConfig, layer_is_moe,
                noise_key=None):
    """MoE-vs-dense FFN selection on normed input ``h`` → (y, aux).

    A static ``layer_is_moe`` keeps the choice out of the compiled graph
    (and lets the expert-parallel shard_map path apply); a traced one
    lowers to ``lax.cond`` with the auto-partitioned MoE (a shard_map
    collective under cond crashes XLA backward)."""
    def dense_branch(h):
        return _mlp_block(h, layer_params["mlp"], cfg), jnp.zeros((), jnp.float32)

    if "moe" not in layer_params:
        return dense_branch(h)
    if isinstance(layer_is_moe, bool):
        return (_moe_block(h, layer_params["moe"], cfg, noise_key=noise_key)
                if layer_is_moe else dense_branch(h))

    def moe_branch(h):
        return _moe_block(h, layer_params["moe"], cfg, allow_ep=False,
                          noise_key=noise_key)

    return lax.cond(layer_is_moe, moe_branch, dense_branch, h)


def _dropout(x, rate: float, key):
    """Inverted dropout; identity when no key is threaded (eval/serve)."""
    if key is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def transformer_layer(x, layer_params, positions, cfg: TransformerConfig,
                      layer_is_moe=False, dropout_key=None,
                      attention_mask=None):
    """One transformer block (pre- or post-norm). Returns (x, moe_aux_loss).

    ``layer_is_moe`` may be a traced bool (layer index inside a scan): the
    MoE-vs-dense choice then lowers to ``lax.cond``, which is how the
    reference's per-layer MoE placement (PR-MoE, moe_layer_freq) maps onto a
    uniform scan-over-layers body.  ``dropout_key``: this layer's PRNG key
    for residual dropout (None → off).  ``attention_mask``: [B, S] key
    padding mask (encoder serving).
    """
    dk = (lambda i: jax.random.fold_in(dropout_key, i)) \
        if dropout_key is not None else (lambda i: None)
    if cfg.parallel_block:
        # Falcon/Phi residual form: shared (or, with parallel_norms, per-
        # branch) input norms feed attention and MLP in parallel (ref
        # falcon/phi v2 containers).
        n = _norm(x, layer_params["ln1"], cfg)
        n_mlp = _norm(x, layer_params["ln2"], cfg) if cfg.parallel_norms else n
        attn_out = _attn_block(n, layer_params["attn"], positions, cfg,
                               attention_mask=attention_mask)
        y, aux = _select_ffn(n_mlp, layer_params, cfg, layer_is_moe,
                             noise_key=dk(2))
        return x + _dropout(attn_out, cfg.dropout, dk(0)) \
            + _dropout(y, cfg.dropout, dk(1)), aux
    if cfg.norm_position == "post":
        # BERT-class post-LN (HF BertLayer): residual add THEN LayerNorm —
        # ln1 is attention.output.LayerNorm, ln2 is output.LayerNorm
        attn_out = _attn_block(x, layer_params["attn"], positions, cfg,
                               attention_mask=attention_mask)
        x = _norm(x + _dropout(attn_out, cfg.dropout, dk(0)),
                  layer_params["ln1"], cfg)
        y, aux = _select_ffn(x, layer_params, cfg, layer_is_moe,
                             noise_key=dk(2))
        return _norm(x + _dropout(y, cfg.dropout, dk(1)),
                     layer_params["ln2"], cfg), aux
    attn_out = _attn_block(_norm(x, layer_params["ln1"], cfg),
                           layer_params["attn"], positions, cfg,
                           attention_mask=attention_mask)
    x = x + _dropout(attn_out, cfg.dropout, dk(0))
    h = _norm(x, layer_params["ln2"], cfg)
    y, aux = _select_ffn(h, layer_params, cfg, layer_is_moe,
                         noise_key=dk(2))
    return x + _dropout(y, cfg.dropout, dk(1)), aux


_REMAT_POLICIES = {
    "none": None,
    "full": None,
    "nothing_saveable": "nothing_saveable",
    "dots_saveable": "dots_saveable",
    # dots + the repo flash kernel's named residuals (flash_out/flash_lse):
    # the backward then never re-runs the attention forward kernel.
    "dots_flash_saveable": "dots_flash_saveable",
    # ONLY the flash residuals: at long sequence the per-layer matmul
    # outputs dots_saveable keeps are O(S·ffn) and dominate HBM (seq 32k:
    # ~640MB/layer); saving just flash_out/flash_lse keeps the backward
    # from re-running the attention kernel while everything else remats.
    "flash_saveable": "flash_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    # CPU activation checkpointing (ref checkpointing.py:474): matmul
    # outputs are saved to pinned host memory instead of rematerialised —
    # trades PCIe/DMA bandwidth for recompute, like the reference's
    # cpu_checkpointing flag.
    "offload_dots": "offload_dot_with_no_batch_dims",
}


def _maybe_remat(fn, cfg: TransformerConfig):
    if cfg.remat_policy in ("none",):
        return fn
    policy = None
    name = _REMAT_POLICIES.get(cfg.remat_policy)
    if name == "offload_dot_with_no_batch_dims":
        # factory: activations saved to pinned host instead of recomputed
        policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    elif name == "dots_flash_saveable":
        policy = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
    elif name == "flash_saveable":
        policy = jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    elif name:
        policy = getattr(jax.checkpoint_policies, name)
    return jax.checkpoint(fn, policy=policy, prevent_cse=False)


def make_pipeline_stage_fn(cfg: TransformerConfig, topo):
    """Per-stage layer applier for the SPMD pipeline: scans this stage's
    ``L/pp`` stacked layers, returns ``(h, aux)``.

    MoE placement must be static inside the pipe shard_map (the stage
    index is a traced ``axis_index``, so a global-layer-index predicate
    would put the MoE collective under a traced cond — see
    :func:`_select_ffn`): with ``layers_per_stage % moe_layer_freq == 0``
    every stage has the same local pattern — groups of f layers whose last
    member is MoE.  Ref: MoE+PP composition, utils/groups.py:384.
    """
    pp = topo.pp_size
    if cfg.num_layers % pp:
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by "
                         f"pipeline stages ({pp})")
    if cfg.alt_window:
        raise NotImplementedError(
            "alt_window (GPT-Neo alternating local attention) + pipeline "
            "parallelism not supported (stage fns scan a uniform body)")
    lp_count = cfg.num_layers // pp
    f = max(1, cfg.moe_layer_freq) if cfg.is_moe else 1
    if cfg.is_moe and lp_count % f != 0:
        raise NotImplementedError(
            f"MoE + pipeline requires layers_per_stage ({lp_count}) "
            f"divisible by moe_layer_freq ({f}) so expert placement is "
            "static per stage")

    def stage_fn(stage_params, h, extras_mb):
        # extras carry (positions, per-microbatch dropout key rows) when
        # training threads randomness — the key rides the same per-
        # microbatch slicing as positions, so the 1F1B backward tick
        # replays the identical mask (remat-bit-exact, like the dense
        # path's keyed dropout).  Bare positions = no randomness.
        pos_mb, keys_mb = (extras_mb if isinstance(extras_mb, tuple)
                           else (extras_mb, None))
        mb_key = keys_mb[0] if keys_mb is not None else None
        from deepspeed_tpu.parallel.topology import PIPE_AXIS
        stage0 = lax.axis_index(PIPE_AXIS) * lp_count

        def layer_key(li):
            # fold the GLOBAL layer index so stages draw distinct masks,
            # mirroring the dense path's fold_in(key, layer_idx)
            return jax.random.fold_in(mb_key, stage0 + li) \
                if mb_key is not None else None

        zero = jnp.zeros((), jnp.float32)
        if f > 1:
            steps = lp_count // f

            def body(carry, xs):
                h, aux_acc = carry
                glp, g = xs
                for j in range(f):
                    lp = jax.tree.map(lambda p, j=j: p[j], glp)
                    h, aux = transformer_layer(h, lp, pos_mb, cfg,
                                               layer_is_moe=(j == f - 1),
                                               dropout_key=layer_key(g * f + j))
                    aux_acc = aux_acc + aux
                return (h, aux_acc), None

            body = _maybe_remat(body, cfg)
            grouped = jax.tree.map(
                lambda p: p.reshape((steps, f) + p.shape[1:]), stage_params)
            (h, aux), _ = lax.scan(body, (h, zero),
                                   (grouped, jnp.arange(steps)))
        else:
            def body(carry, xs):
                h, aux_acc = carry
                lp, li = xs
                h, aux = transformer_layer(h, lp, pos_mb, cfg,
                                           layer_is_moe=cfg.is_moe,
                                           dropout_key=layer_key(li))
                return (h, aux_acc + aux), None

            body = _maybe_remat(body, cfg)
            (h, aux), _ = lax.scan(body, (h, zero),
                                   (stage_params, jnp.arange(lp_count)))
        return h, aux

    return stage_fn


def _pipeline_key_rows(dropout_key, b: int, n_micro: int):
    """Expand a per-step PRNG key into per-example rows [B, 2] where every
    row of microbatch ``m`` holds ``fold_in(step_key, m)`` — the shape the
    pipeline's per-microbatch extras slicing expects (row 0 of a microbatch
    slice is its key)."""
    mb = b // n_micro
    mb_keys = jax.vmap(lambda m: jax.random.fold_in(dropout_key, m))(
        jnp.arange(n_micro))
    return jnp.repeat(mb_keys, mb, axis=0)


def forward(params: Params, input_ids, cfg: TransformerConfig,
            positions=None, pld_theta=None,
            return_hidden: bool = False, token_embeds=None,
            dropout_key=None, token_type_ids=None,
            attention_mask=None) -> jnp.ndarray:
    """Token ids [B, S] → logits [B, S, V]. lax.scan over stacked layers.
    ``pld_theta``: progressive-layer-drop keep prob (traced scalar or None).
    ``return_hidden``: final-norm hidden states instead of logits (tiled
    loss path).  ``dropout_key``: per-step PRNG key enabling
    ``cfg.dropout`` (None → dropout off, the eval/serve contract).
    ``token_type_ids``/``attention_mask``: encoder (BERT-class) segment
    ids and [B, S] key-padding mask."""
    b, s = input_ids.shape
    dt = cfg.dtype
    if positions is None:
        pos_row = jnp.arange(s, dtype=jnp.int32)
        if cfg.seq_impl == "ring" and cfg.ring_placement == "striped":
            from deepspeed_tpu.parallel.topology import get_topology as _gt
            from deepspeed_tpu.sequence.ring import ring_position_map

            topo_ = _gt()
            if topo_ is not None and topo_.sp_size > 1:
                # striped ring: the engine feeds stripe-permuted ids, so
                # slot j of shard r holds token r + sp*j — positions must
                # follow (RoPE/learned embeddings stay exact)
                pos_row = ring_position_map(s, topo_.sp_size, "striped")
        positions = jnp.broadcast_to(pos_row[None, :], (b, s))
    if dropout_key is not None and cfg.param_stream:
        raise NotImplementedError(
            "dropout / noisy MoE gating + param streaming not supported "
            "(the streamed scan's custom VJP does not thread per-layer "
            "keys)")
    if attention_mask is not None and cfg.param_stream:
        raise NotImplementedError(
            "attention_mask + param streaming not supported (the streamed "
            "scan does not thread the mask)")
    if attention_mask is not None and 0 < cfg.ltd_kept < s:
        raise NotImplementedError(
            "attention_mask + random-LTD not supported (the LTD band's "
            "reduced token subset would need the mask gathered by the "
            "kept indices)")

    x = _embed(params, input_ids, positions, cfg, token_embeds,
               token_type_ids=token_type_ids)
    if dropout_key is not None and cfg.dropout > 0:
        x = _dropout(x, cfg.dropout, jax.random.fold_in(dropout_key, 10_000))

    moe_every = max(1, cfg.moe_layer_freq)

    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    moe_aux = jnp.zeros((), jnp.float32)
    if topo is not None and topo.pp_size > 1:
        # Pipeline path: layers circulate microbatches over the "pipe" axis
        # (ref runtime/pipe/engine.py TrainSchedule → spmd_pipeline here).
        if pld_theta is not None:
            raise NotImplementedError(
                "progressive layer drop + pipeline parallelism not supported")
        if 0 < cfg.ltd_kept < s:
            raise NotImplementedError(
                "random-LTD + pipeline parallelism not supported")
        if cfg.param_stream:
            raise NotImplementedError(
                "param streaming + pipeline parallelism not supported "
                "(the pipe axis already partitions layers pp-ways)")
        if attention_mask is not None:
            raise NotImplementedError(
                "attention_mask + pipeline parallelism not supported "
                "(masks do not ride the pipeline extras yet)")
        from deepspeed_tpu.parallel.pipeline import spmd_pipeline

        stage_fn = make_pipeline_stage_fn(cfg, topo)
        n_micro = cfg.pipeline_microbatches or topo.pp_size
        extras = positions
        if dropout_key is not None:
            # per-microbatch keys ride the extras so every stage/layer/
            # microbatch draws a distinct, replay-stable mask
            extras = (positions, _pipeline_key_rows(dropout_key, b, n_micro))
        x, moe_aux = spmd_pipeline(stage_fn, params["layers"], x, topo=topo,
                                   n_micro=n_micro, extras=extras)
    else:
        def scan_segment(x, pos, layers_slice, idx0, n_layers):
            """Scan a contiguous slice of the stacked layers.

            MoE placement is kept **static** so the expert-parallel
            shard_map path applies: with moe_layer_freq f, the f-aligned
            middle of the segment scans *groups* of f layers whose last
            member is statically MoE (no lax.cond in the scan body — a
            shard_map collective under a traced cond crashes XLA
            backward), and the unaligned head/tail layers (e.g. where a
            random-LTD band cuts through a group) run unrolled with their
            static global indices.
            """
            if cfg.alt_window:
                # GPT-Neo alternating global/local attention: scan layer
                # PAIRS so each member's window is STATIC (even global
                # index → global, odd → cfg.sliding_window)
                if cfg.is_moe:
                    raise NotImplementedError(
                        "alt_window + MoE not supported")
                f = 2
            else:
                f = moe_every if cfg.is_moe else 1
            if n_layers == 0:
                return x, jnp.zeros((), jnp.float32)

            def member_cfg(parity: int):
                """Per-layer static config: alt_window strips the local
                window from even global indices."""
                if not cfg.alt_window or parity % 2:
                    return cfg
                return cfg.replace(sliding_window=None)

            def apply_layer(h, aux_acc, lp, layer_idx, is_moe_layer,
                            lcfg=cfg):
                # keys serve dropout AND noisy MoE gating — thread whenever
                # one is present (each consumer no-ops when its rate/policy
                # is off)
                lk = jax.random.fold_in(dropout_key, layer_idx) \
                    if dropout_key is not None else None
                h2, aux = transformer_layer(h, lp, pos, lcfg,
                                            layer_is_moe=is_moe_layer,
                                            dropout_key=lk,
                                            attention_mask=attention_mask)
                if pld_theta is not None:
                    # progressive layer drop (ref progressive_layer_drop.py
                    # + stochastic depth): deeper layers drop more; batch
                    # content seeds the per-step coin so the step stays a
                    # single compile.
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(17),
                        (jnp.sum(input_ids) % 100003).astype(jnp.int32)
                        * 1000 + layer_idx)
                    depth_frac = (layer_idx + 1) / cfg.num_layers
                    p_keep = 1.0 - (1.0 - pld_theta) * depth_frac
                    coin = jax.random.bernoulli(key, p_keep)
                    h2 = jnp.where(coin, h2, h)
                return h2, aux_acc + aux

            aux0 = jnp.zeros((), jnp.float32)
            head = min((-idx0) % f, n_layers)
            mid = (n_layers - head) // f * f

            if cfg.param_stream:
                # ZeRO-Infinity: layer slices stream host→device inside the
                # scan; the custom VJP (runtime/infinity.streamed_scan)
                # parks each layer's gradient back to a host accumulator so
                # neither params nor their grads are ever device-resident in
                # full. Placement must be static end to end.
                if head or mid != n_layers:
                    raise NotImplementedError(
                        "param streaming requires moe_layer_freq-aligned "
                        "segments (no random-LTD bands)")
                if pld_theta is not None:
                    raise NotImplementedError(
                        "param streaming + progressive layer drop "
                        "not supported")
                from deepspeed_tpu.runtime.infinity import streamed_scan

                if f > 1:
                    steps = n_layers // f
                    stacked = jax.tree.map(
                        lambda p: p.reshape((steps, f) + p.shape[1:]),
                        layers_slice)
                else:
                    stacked = layers_slice

                def step_fn(lp, h, pos_, i):
                    aux_acc = jnp.zeros((), jnp.float32)
                    if f > 1:
                        for j in range(f):
                            sub = jax.tree.map(lambda p, j=j: p[j], lp)
                            h, aux = transformer_layer(
                                h, sub, pos_, member_cfg(j % 2),
                                layer_is_moe=(cfg.is_moe and j == f - 1))
                            aux_acc = aux_acc + aux
                    else:
                        h, aux = transformer_layer(
                            h, lp, pos_, cfg, layer_is_moe=cfg.is_moe)
                        aux_acc = aux_acc + aux
                    return h, aux_acc

                return streamed_scan(step_fn, stacked, x, extras=pos)
            # head/tail: static global indices → static MoE placement
            def run_unrolled(x, aux, lo, hi):
                for j in range(lo, hi):
                    lp = jax.tree.map(lambda p, j=j: p[j], layers_slice)
                    is_moe = cfg.is_moe and ((idx0 + j) % f == f - 1)
                    lcfg = member_cfg((idx0 + j) % 2)
                    step = _maybe_remat(
                        lambda h, a, lp, j=j, m=is_moe, c=lcfg:
                        apply_layer(h, a, lp, idx0 + j, m, lcfg=c), cfg)
                    x, aux = step(x, aux, lp)
                return x, aux

            x, aux0 = run_unrolled(x, aux0, 0, head)
            if mid > 0:
                grouped = f > 1

                def body(carry, scanned):
                    h, aux_acc = carry
                    layer_params, i = scanned
                    if grouped:
                        for j in range(f):
                            lp = jax.tree.map(lambda p, j=j: p[j],
                                              layer_params)
                            # group starts are ≡ 0 mod f, so the member's
                            # global parity is j's — static
                            h, aux_acc = apply_layer(
                                h, aux_acc, lp, i * f + j,
                                cfg.is_moe and j == f - 1,
                                lcfg=member_cfg(j % 2))
                    else:
                        h, aux_acc = apply_layer(h, aux_acc, layer_params, i,
                                                 cfg.is_moe and f == 1)
                    return (h, aux_acc), None

                body = _maybe_remat(body, cfg)
                mid_slice = jax.tree.map(lambda p: p[head:head + mid],
                                         layers_slice)
                if grouped:
                    steps = mid // f
                    layers_scan = jax.tree.map(
                        lambda p: p.reshape((steps, f) + p.shape[1:]),
                        mid_slice)
                    idxs = jnp.arange((idx0 + head) // f,
                                      (idx0 + head) // f + steps)
                else:
                    steps = mid
                    layers_scan = mid_slice
                    idxs = jnp.arange(idx0 + head, idx0 + head + mid)
                unroll = max(1, cfg.scan_unroll)
                if steps % unroll != 0:
                    unroll = 1
                (x, aux_mid), _ = lax.scan(
                    body, (x, jnp.zeros((), jnp.float32)),
                    (layers_scan, idxs), unroll=unroll)
                aux0 = aux0 + aux_mid
            x, aux0 = run_unrolled(x, aux0, head + mid, n_layers)
            return x, aux0

        def layer_slice(a, b_):
            return jax.tree.map(lambda p: p[a:b_], params["layers"])

        ltd_on = 0 < cfg.ltd_kept < s
        if ltd_on:
            # random-LTD: middle band runs on a random token subset
            # (ref RandomLayerTokenDrop; gather/scatter = csrc/random_ltd)
            from deepspeed_tpu.runtime.data_pipeline.data_routing import (
                random_ltd_drop, random_ltd_indices, random_ltd_restore)

            a = max(0, min(cfg.ltd_start, cfg.num_layers))
            z = cfg.ltd_end if cfg.ltd_end is not None else cfg.num_layers - 1
            z = max(a, min(z, cfg.num_layers))
            x, aux0 = scan_segment(x, positions, layer_slice(0, a), 0, a)
            key = jax.random.fold_in(jax.random.PRNGKey(23),
                                     jnp.sum(input_ids[:, :1]).astype(jnp.int32))
            idx = random_ltd_indices(key, s, cfg.ltd_kept, b)
            x_kept = random_ltd_drop(x, idx)
            pos_kept = jnp.take_along_axis(positions, idx, axis=1)
            x_kept, aux1 = scan_segment(x_kept, pos_kept, layer_slice(a, z),
                                        a, z - a)
            x = random_ltd_restore(x, x_kept, idx)
            x, aux2 = scan_segment(x, positions, layer_slice(z, cfg.num_layers),
                                   z, cfg.num_layers - z)
            moe_aux = aux0 + aux1 + aux2
        else:
            x, moe_aux = scan_segment(x, positions, params["layers"], 0,
                                      cfg.num_layers)

    if cfg.norm_position != "post":
        # post-LN stacks (BERT) normalise inside every layer — no final norm
        x = _norm(x, params["final_norm"], cfg)
    if return_hidden:
        return (x, moe_aux) if cfg.is_moe else x
    # honor the autocast safe-module list for the output head: an unlisted
    # lm_head is promoted to fp32 like any other module class.
    ht = _module_dtype(cfg, "lm_head", dt)
    if cfg.mlm_head:
        # BERT MLM head: LN(gelu(h W + b)) @ embed.T + vocab bias (HF
        # BertLMPredictionHead; decoder tied to the token embeddings)
        mh = params["mlm_head"]
        t = x.astype(ht) @ mh["w"].astype(ht) + mh["b"].astype(ht)
        # transform activation follows cfg.activation like the MLP blocks
        # (HF BertPredictionHeadTransform uses config.hidden_act)
        t = jax.nn.relu(t) if cfg.activation == "relu" else \
            jax.nn.gelu(t, approximate=cfg.activation != "gelu_exact")
        t = _norm(t, mh["ln"], cfg)
        logits = t.astype(ht) @ params["embed"]["tokens"].astype(ht).T \
            + mh["bias"].astype(ht)
    elif cfg.tie_embeddings:
        logits = x.astype(ht) @ params["embed"]["tokens"].astype(ht).T
    else:
        logits = x.astype(ht) @ params["lm_head"].astype(ht)
    if not cfg.mlm_head and params.get("lm_head_bias") is not None:
        # GPT-J-style per-vocab output bias (HF applies lm_head.bias)
        logits = logits + params["lm_head_bias"].astype(ht)
    if cfg.is_moe:
        # stash aux loss on the fwd for the engine loss fn via closure return
        return logits, moe_aux
    return logits


MOE_AUX_COEF = 0.01


def _nll_sum(logits32, labels_mb):
    """Summed token NLL with -100 = ignore (HF convention)."""
    m = labels_mb != -100
    safe = jnp.where(m, labels_mb, 0)
    logz = jax.nn.logsumexp(logits32, axis=-1)
    gold = jnp.take_along_axis(logits32, safe[..., None], axis=-1)[..., 0]
    return jnp.sum((logz - gold) * m)


def _embed(params: Params, input_ids, positions, cfg: TransformerConfig,
           token_embeds=None, token_type_ids=None):
    """Embedding prologue shared by forward() and the 1F1B loss path.
    ``token_embeds``: precomputed table rows [B,S,H] — the sparse-gradient
    path (runtime/sparse.py) hoists the lookup out of the differentiated
    function so the table cotangent stays (ids, values)-sparse.
    ``token_type_ids``: BERT segment ids (default segment 0)."""
    et = _module_dtype(cfg, "embed", cfg.dtype)
    x = (params["embed"]["tokens"].astype(et)[input_ids]
         if token_embeds is None else token_embeds.astype(et))
    if cfg.has_learned_positions:
        x = x + params["embed"]["positions"].astype(et)[positions]
    if cfg.type_vocab_size:
        tt = (token_type_ids if token_type_ids is not None
              else jnp.zeros_like(input_ids))
        x = x + params["embed"]["token_types"].astype(et)[tt]
    if cfg.embed_norm:
        x = _norm(x.astype(cfg.dtype), params["embed"]["norm"], cfg)
    return x.astype(cfg.dtype)


def _pipeline_1f1b_loss(params, batch, cfg: TransformerConfig, topo,
                        labels_eff, denom):
    """Training loss through the 1F1B pipeline schedule (the head + NLL run
    per microbatch on the last stage, ref runtime/pipe/engine.py:337)."""
    from deepspeed_tpu.parallel.pipeline import make_pipeline_train_loss

    input_ids = batch["input_ids"]
    b, s = input_ids.shape
    dt = cfg.dtype
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                                 (b, s))

    def tail_fn(tp, h, labels_mb):
        h = _norm(h, tp["final_norm"], cfg)
        ht = _module_dtype(cfg, "lm_head", dt)
        w = tp["w"].astype(ht)
        logits = h.astype(ht) @ (w.T if cfg.tie_embeddings else w)
        lt = jnp.float32 if op_fp32(cfg, "loss") else logits.dtype
        return _nll_sum(logits.astype(lt), labels_mb)

    def embed_fn(ep, ids_mb, extras_mb):
        # runs inside the pipelined region: stage 0 embeds per microbatch
        # and its backward folds the input cotangent straight into these
        # tables (no O(batch) dx stash — see make_pipeline_train_loss)
        pos_mb, keys_mb = (extras_mb if isinstance(extras_mb, tuple)
                           else (extras_mb, None))
        x = _embed(ep, ids_mb, pos_mb, cfg)
        if keys_mb is not None and cfg.dropout > 0:
            # embedding dropout, keyed per microbatch (dense path uses
            # fold_in(step_key, 10_000) — same sentinel here)
            x = _dropout(x, cfg.dropout,
                         jax.random.fold_in(keys_mb[0], 10_000))
        return x

    tail_params = {"final_norm": params["final_norm"],
                   "w": params["embed"]["tokens"] if cfg.tie_embeddings
                   else params["lm_head"]}
    stage_fn = make_pipeline_stage_fn(cfg, topo)
    n_micro = cfg.pipeline_microbatches or topo.pp_size
    dropout_key = batch.get("dropout_key")
    extras = positions if dropout_key is None else (
        positions, _pipeline_key_rows(dropout_key, b, n_micro))
    f = make_pipeline_train_loss(
        stage_fn, tail_fn, topo, n_micro,
        aux_coef=MOE_AUX_COEF if cfg.is_moe else 0.0, embed_fn=embed_fn)
    return f(params["layers"], tail_params, {"embed": params["embed"]},
             input_ids, labels_eff, extras, denom)


def loss_fn(params: Params, batch: Dict[str, jnp.ndarray], cfg: TransformerConfig,
            token_embeds=None):
    """Causal LM cross-entropy. ``batch``: input_ids [B,S], labels [B,S]
    (-100 = ignore, HF convention), optional loss_mask, optional pld_theta
    (progressive layer drop keep prob, passed through the batch so the
    schedule never forces a recompile).

    With ``cfg.loss_tiles`` set (and dividing S), the loss is computed in
    sequence tiles (ALST, sequence/alst.py) so [B, S, V] logits are never
    materialised.
    """
    labels = batch["labels"]
    mask = (labels != -100)
    if "loss_mask" in batch:
        mask = mask & (batch["loss_mask"] > 0)

    s = batch["input_ids"].shape[1]
    tiled = cfg.loss_tiles and s % cfg.loss_tiles == 0
    if tiled and cfg.mlm_head:
        raise NotImplementedError(
            "loss_tiles + mlm_head not supported (the tiled loss computes "
            "logits directly against the embedding table, bypassing the "
            "MLM transform head); encoder sequences are short — drop "
            "loss_tiles")

    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    if (topo is not None and topo.pp_size > 1
            and cfg.pipeline_schedule == "1f1b" and not tiled
            and not cfg.param_stream   # forward() raises for pp+streaming
            and batch.get("pld_theta") is None
            and not (0 < cfg.ltd_kept < s)      # forward() raises for pp+LTD
            # encoder stacks: the 1F1B tail applies final_norm + the plain
            # tied head — post-LN/MLM-head models keep the AD GPipe path
            and not cfg.mlm_head and cfg.norm_position != "post"
            and batch.get("attention_mask") is None
            # fp16 needs the dynamic loss scale inside the backward, but the
            # 1F1B custom VJP computes grads in its forward before the scale
            # cotangent exists — fp16 stays on the AD-differentiated GPipe
            # path (bf16 shares f32's exponent range; no scaling needed)
            and cfg.dtype != jnp.float16):
        labels_eff = jnp.where(mask, labels, -100)
        denom = jnp.maximum(mask.sum(), 1).astype(jnp.float32)
        return _pipeline_1f1b_loss(params, batch, cfg, topo, labels_eff,
                                   denom)
    out = forward(params, batch["input_ids"], cfg,
                  pld_theta=batch.get("pld_theta"), return_hidden=bool(tiled),
                  token_embeds=token_embeds,
                  dropout_key=batch.get("dropout_key"),
                  token_type_ids=batch.get("token_type_ids"),
                  attention_mask=batch.get("attention_mask"))
    moe_aux = jnp.zeros((), jnp.float32)
    if isinstance(out, tuple):
        out, moe_aux = out

    if tiled:
        from deepspeed_tpu.sequence.alst import tiled_logits_loss

        w = params["embed"]["tokens"] if cfg.tie_embeddings \
            else params["lm_head"].T
        loss, _ = tiled_logits_loss(out, w.astype(cfg.dtype),
                                    jnp.where(mask, labels, -100),
                                    cfg.loss_tiles)
    else:
        lt = jnp.float32 if op_fp32(cfg, "loss") else out.dtype
        loss = _nll_sum(out.astype(lt),
                        jnp.where(mask, labels, -100)) \
            / jnp.maximum(mask.sum(), 1)
    if cfg.is_moe:
        loss = loss + MOE_AUX_COEF * moe_aux
    return loss
