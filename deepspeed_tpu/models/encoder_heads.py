"""Encoder task heads: BERT pooling + sequence classification.

Analog of the reference's bert-injection serving surface
(module_inject/containers/bert.py — HF BertPooler + the classification
head): ``bert_pooled_classify`` consumes the encoder's hidden states
(``forward(..., return_hidden=True)``) and produces [B, num_labels]
logits through tanh-pooled [CLS] + the classifier linear.
"""

from __future__ import annotations

import jax.numpy as jnp


def bert_pool(params, hidden) -> jnp.ndarray:
    """HF BertPooler: tanh(dense([CLS])) — ``hidden`` [B, S, H] → [B, H].
    ``params["pooler"]`` = {"w": [H, H], "b": [H]}."""
    p = params["pooler"]
    cls = hidden[:, 0]
    return jnp.tanh(cls @ p["w"].astype(cls.dtype)
                    + p["b"].astype(cls.dtype))


def bert_pooled_classify(params, hidden) -> jnp.ndarray:
    """Pooled classification logits [B, num_labels] (HF
    BertForSequenceClassification head; eval path — dropout between the
    pooler and classifier is a train-time-only op)."""
    pooled = bert_pool(params, hidden)
    c = params["classifier"]
    return pooled @ c["w"].astype(pooled.dtype) + c["b"].astype(pooled.dtype)
