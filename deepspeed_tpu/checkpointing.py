"""Activation checkpointing API surface.

Analog of ``deepspeed.checkpointing`` (runtime/activation_checkpointing/
checkpointing.py: ``checkpoint`` :948, ``configure`` , partitioned/CPU
variants :377/:474).  On TPU the machinery is ``jax.checkpoint``; this
module keeps the reference's call signatures so ported Megatron-style code
runs unchanged, mapping its knobs onto remat policies:

* ``partition_activations`` → handled by GSPMD sharding (activations are
  already sharded over the mesh; nothing to split by hand)
* ``cpu_checkpointing`` → ``offload_dots`` policy (save matmul outputs to
  pinned host memory)
* ``contiguous_memory_optimization``/``synchronize`` → no-ops (XLA owns
  layout and scheduling)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax

_CONFIG: Dict[str, Any] = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "policy": "nothing_saveable",
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None) -> None:
    """Ref checkpointing.configure — records knobs; ``checkpoint_in_cpu``
    selects the host-offload remat policy."""
    if partition_activations is not None:
        _CONFIG["partition_activations"] = bool(partition_activations)
    if checkpoint_in_cpu is not None:
        _CONFIG["cpu_checkpointing"] = bool(checkpoint_in_cpu)
        _CONFIG["policy"] = "offload_dots" if checkpoint_in_cpu \
            else "nothing_saveable"
    if contiguous_checkpointing is not None:
        _CONFIG["contiguous_memory_optimization"] = bool(contiguous_checkpointing)
    if synchronize is not None:
        _CONFIG["synchronize_checkpoint_boundary"] = bool(synchronize)
    if profile is not None:
        _CONFIG["profile"] = bool(profile)


def _policy():
    name = _CONFIG["policy"]
    if name == "offload_dots":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    if name and name != "nothing_saveable":
        return getattr(jax.checkpoint_policies, name, None)
    return None


def checkpoint(function: Callable, *args):
    """Ref checkpointing.checkpoint(function, *args): run ``function`` under
    rematerialisation and return its output."""
    return jax.checkpoint(function, policy=_policy(), prevent_cse=False)(*args)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form."""
    return jax.checkpoint(function, policy=_policy(), prevent_cse=False)


def is_configured() -> bool:
    return True


def get_config() -> Dict[str, Any]:
    return dict(_CONFIG)


def reset() -> None:
    """Ref checkpointing.reset — clears buffers; here: restore defaults."""
    _CONFIG.update(partition_activations=False, cpu_checkpointing=False,
                   contiguous_memory_optimization=False,
                   synchronize_checkpoint_boundary=False, profile=False,
                   policy="nothing_saveable")


class CheckpointFunction:
    """Name-parity shim (ref CheckpointFunction autograd.Function): calling
    applies :func:`checkpoint`."""

    @staticmethod
    def apply(function, *args):
        return checkpoint(function, *args)


# ----------------------------------------------------------------------
# RNG state tracker (ref CudaRNGStatesTracker, activation_checkpointing/
# checkpointing.py:124 + get_cuda_rng_tracker/model_parallel_cuda_
# manual_seed).  The reference maintains named CUDA RNG states so
# tensor-parallel ranks draw different dropout masks inside TP regions
# and identical ones outside, and so recompute replays the same masks.
# Under JAX, keys are VALUES: recompute-consistency is automatic (the
# model threads explicit keys — see models/transformer dropout), and this
# tracker provides the named-stream API for ported Megatron-style code.
# ----------------------------------------------------------------------
class _ForkedKey:
    """A forked subkey usable BOTH as a key value (``np.asarray``/
    ``.key``) and as the reference's context-manager idiom
    (``with tracker.fork(): ...`` — Megatron code ported unchanged)."""

    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key

    def __array__(self, dtype=None):
        import numpy as _np

        a = _np.asarray(self.key)
        return a.astype(dtype) if dtype is not None else a

    def __enter__(self):
        return self.key

    def __exit__(self, *exc):
        return False


class RNGStatesTracker:
    """Named jax.random key streams with fork semantics."""

    def __init__(self):
        self._states: Dict[str, Any] = {}

    def reset(self) -> None:
        self._states.clear()

    def add(self, name: str, seed: int) -> None:
        if name in self._states:
            raise ValueError(f"rng state '{name}' already exists")
        self._states[name] = jax.random.PRNGKey(int(seed))

    def get_states(self) -> Dict[str, Any]:
        return dict(self._states)

    def set_states(self, states: Dict[str, Any]) -> None:
        self._states = dict(states)

    def fork(self, name: str = "model-parallel-rng"):
        """Split the named stream and return a fresh subkey.

        Dual-use for ported code: the reference forks inside a
        ``with get_cuda_rng_tracker().fork():`` block, so the returned
        object is also a no-op context manager (functionally the caller
        passes the key — or the yielded value — to its dropout)."""
        if name not in self._states:
            raise KeyError(f"rng state '{name}' not added")
        self._states[name], sub = jax.random.split(self._states[name])
        return _ForkedKey(sub)


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    """Ref get_cuda_rng_tracker (checkpointing.py:225)."""
    return _RNG_TRACKER


# reference-name alias for ported code
get_cuda_rng_tracker = get_rng_state_tracker


def model_parallel_rng_seed(seed: int, tp_rank: int = 0) -> None:
    """Ref model_parallel_cuda_manual_seed (checkpointing.py:235): the
    default stream is identical across TP ranks; the model-parallel stream
    is offset per rank so TP shards draw different dropout masks."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("default", seed)
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718 + int(tp_rank))


model_parallel_cuda_manual_seed = model_parallel_rng_seed
