"""Monitoring fan-out: TensorBoard / WandB / CSV / Comet.

Analog of ``deepspeed/monitor/monitor.py`` (Monitor ABC :13, MonitorMaster
:30).  Events are ``(tag, value, step)`` tuples written at step boundaries
from process 0.
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, cfg):
        self.enabled = cfg.enabled

    def write_events(self, event_list: List[Event]) -> None:  # pragma: no cover
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.summary_writer = None
        if self.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                log_dir = os.path.join(cfg.output_path or "./runs", cfg.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()


class CSVMonitor(Monitor):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.output_path = cfg.output_path or "./csv_monitor"
        self.job_name = cfg.job_name
        if self.enabled and jax.process_index() == 0:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled or jax.process_index() != 0:
            return
        for tag, value, step in event_list:
            fname = os.path.join(self.output_path, self.job_name,
                                 tag.replace("/", "_") + ".csv")
            with open(fname, "a", newline="") as f:
                csv.writer(f).writerow([step, value])


class WandbMonitor(Monitor):
    def __init__(self, cfg):
        super().__init__(cfg)
        self._wandb = None
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb

                wandb.init(project=cfg.project, group=cfg.group, team=cfg.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self._wandb is None:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: value}, step=step)


class CometMonitor(Monitor):
    """Comet backend (ref monitor/comet.py); gated on the comet_ml SDK."""

    def __init__(self, cfg):
        super().__init__(cfg)
        self._exp = None
        if self.enabled and jax.process_index() == 0:
            try:
                import comet_ml

                self._exp = comet_ml.Experiment(
                    project_name=getattr(cfg, "project", None))
            except Exception as e:
                logger.warning(f"comet unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self._exp is None:
            return
        for tag, value, step in event_list:
            self._exp.log_metric(tag, value, step=step)


class MonitorMaster(Monitor):
    """Fans events out to every enabled backend (ref monitor.py:30)."""

    def __init__(self, ds_config):
        self.monitors: List[Monitor] = []
        for cfg, cls in ((ds_config.tensorboard, TensorBoardMonitor),
                         (ds_config.wandb, WandbMonitor),
                         (ds_config.csv_monitor, CSVMonitor),
                         (getattr(ds_config, "comet", None), CometMonitor)):
            if getattr(cfg, "enabled", False):
                self.monitors.append(cls(cfg))
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, event_list: List[Event]) -> None:
        if jax.process_index() != 0:
            return
        for m in self.monitors:
            if not m.enabled:
                continue
            try:
                m.write_events(event_list)
            except Exception as e:
                # one broken backend (full disk, dead wandb socket) must
                # degrade to disabled, not take down the train loop or
                # starve the remaining backends
                m.enabled = False
                logger.warning(
                    f"monitor backend {type(m).__name__} failed and was "
                    f"disabled: {e}")
        self.enabled = any(m.enabled for m in self.monitors)
