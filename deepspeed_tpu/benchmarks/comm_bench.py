"""`dstpu_bench` — collective micro-benchmark.

Analog of the reference's ``ds_bench`` (bin/ds_bench → communication
benchmarks): times all_reduce / all_gather / reduce_scatter / all_to_all
over the active mesh axis and reports algorithmic bandwidth, using the same
busbw conventions as the reference's comms logger
(ref utils/comms_logging.py:34 calc_bw_log).
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional

import numpy as np


def bw_factor(op: str, n: int) -> float:
    """Algorithmic→bus bandwidth factor (ring algorithms).

    Ref: get_bw (utils/comms_logging.py:34): allreduce 2(n-1)/n, allgather /
    reducescatter / alltoall (n-1)/n.
    """
    if n <= 1:
        return 1.0
    if op == "all_reduce":
        return 2.0 * (n - 1) / n
    return (n - 1) / n


def run_bench(sizes_mb: Optional[List[float]] = None, trials: int = 5,
              axis: str = "data", dtype="float32") -> List[dict]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu import comm
    from deepspeed_tpu.parallel.topology import get_topology

    comm.comm.init_distributed()
    topo = get_topology()
    n = topo.axis_size(axis) if hasattr(topo, "axis_size") else 1
    mesh = topo.mesh
    sizes_mb = sizes_mb or [1.0, 16.0, 64.0]
    results = []

    from deepspeed_tpu.utils.jax_compat import shard_map

    for op in ("all_reduce", "all_gather", "reduce_scatter", "all_to_all"):
        for mb in sizes_mb:
            itemsize = np.dtype(dtype).itemsize
            elems = int(mb * 1e6 / itemsize)
            elems = max(n * n, elems - elems % (n * n))  # divisible for rs/a2a
            x = jnp.ones((elems,), dtype=dtype)
            x = jax.device_put(x, NamedSharding(mesh, P()))

            if op == "all_reduce":
                fn = lambda a: jax.lax.psum(a, axis)
                in_spec, out_spec = P(), P()
            elif op == "all_gather":
                fn = lambda a: jax.lax.all_gather(a, axis, tiled=True)
                in_spec, out_spec = P(axis), P()
            elif op == "reduce_scatter":
                fn = lambda a: jax.lax.psum_scatter(a, axis, tiled=True)
                in_spec, out_spec = P(), P(axis)
            else:
                fn = lambda a: jax.lax.all_to_all(
                    a.reshape(n, -1), axis, split_axis=0, concat_axis=0,
                    tiled=False).reshape(-1)
                in_spec, out_spec = P(axis), P(axis)

            jitted = jax.jit(shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                                       out_specs=out_spec,
                                       check_vma=False))
            out = jitted(x)  # compile + warm
            np.asarray(jax.device_get(out)).ravel()[:1]
            t0 = time.perf_counter()
            for _ in range(trials):
                out = jitted(x)
            np.asarray(jax.device_get(out)).ravel()[:1]
            dt = (time.perf_counter() - t0) / trials

            nbytes = elems * itemsize
            algbw = nbytes / dt / 1e9
            results.append({
                "op": op, "size_mb": round(nbytes / 1e6, 2), "axis": axis,
                "world": n, "time_ms": round(dt * 1e3, 3),
                "algbw_gbps": round(algbw, 2),
                "busbw_gbps": round(algbw * bw_factor(op, n), 2),
            })

    # qgZ row: int8 block-quantized gradient reduce (ZeRO++ transport) vs
    # the fp32 reduce-scatter above — wire traffic is s8 + 1/256 scales,
    # so effective bandwidth should approach 4x (ref qgZ claim; the HLO
    # test pins that the payload really is s8)
    from deepspeed_tpu.comm.coalesced_collectives import (
        all_to_all_quant_reduce)

    for mb in sizes_mb:
        itemsize = 4
        elems = int(mb * 1e6 / itemsize)
        elems = max(n * n * 256, elems - elems % (n * n * 256))
        x = jnp.ones((elems,), jnp.float32)
        x = jax.device_put(x, NamedSharding(mesh, P()))

        def qfn(a):
            shard, _ = all_to_all_quant_reduce(
                {"g": a}, axis, axis, inner_size=n, outer_size=1)
            return shard

        jitted = jax.jit(shard_map(qfn, mesh=mesh, in_specs=(P(),),
                                   out_specs=P(axis), check_vma=False))
        out = jitted(x)
        np.asarray(jax.device_get(out)).ravel()[:1]
        t0 = time.perf_counter()
        for _ in range(trials):
            out = jitted(x)
        np.asarray(jax.device_get(out)).ravel()[:1]
        dt = (time.perf_counter() - t0) / trials
        nbytes = elems * itemsize
        algbw = nbytes / dt / 1e9  # logical fp32 bytes reduced per second
        results.append({
            "op": "qgz_quant_reduce", "size_mb": round(nbytes / 1e6, 2),
            "axis": axis, "world": n, "time_ms": round(dt * 1e3, 3),
            "algbw_gbps": round(algbw, 2),
            "busbw_gbps": round(algbw * bw_factor("reduce_scatter", n), 2),
        })
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dstpu_bench")
    p.add_argument("--sizes-mb", type=float, nargs="*", default=[1.0, 16.0])
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--axis", type=str, default="data")
    args = p.parse_args(argv)
    from deepspeed_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    for row in run_bench(args.sizes_mb, args.trials, args.axis):
        print(json.dumps(row))
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(main())
