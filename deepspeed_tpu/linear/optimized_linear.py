"""OptimizedLinear — quantized frozen base weight + trainable LoRA adapter.

Analog of ``deepspeed/linear/optimized_linear.py`` (``OptimizedLinear``
:18, ``LoRAOptimizedLinear`` :76).  The reference shards the frozen base
weight 1/world and all-gathers it per forward; here ``base_weight_sharding``
maps to sharding the dequantized base over the "tensor" mesh axis and
letting XLA keep the matmul sharded (no gather materialisation).

Functional API: params are a dict ``{"base": QuantizedParameter | array,
"lora_A": [in, r], "lora_B": [r, out]}``; :func:`lora_linear` is the
forward.  Only A/B receive gradients — the base is a
``jax.lax.stop_gradient`` leaf, which is how "frozen" is spelled in a
functional framework.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.linear.quantization import QuantizedParameter


def init_lora_params(key, in_dim: int, out_dim: int,
                     lora_config: Optional[LoRAConfig] = None,
                     dtype=jnp.float32) -> Dict[str, Any]:
    """A ~ kaiming-uniform, B = 0 (standard LoRA init; ref
    LoRAOptimizedLinear.init_lora)."""
    lc = lora_config or LoRAConfig()
    bound = math.sqrt(6.0 / in_dim)
    a = jax.random.uniform(key, (in_dim, lc.lora_r), dtype,
                           minval=-bound, maxval=bound)
    b = jnp.zeros((lc.lora_r, out_dim), dtype)
    return {"lora_A": a, "lora_B": b}


def lora_linear(x, base, lora_A=None, lora_B=None,
                lora_alpha: float = 16.0, lora_r: Optional[int] = None,
                bias=None):
    """y = x @ W_base (frozen) + (alpha/r) * (x @ A) @ B.

    Packed bases (FP6 q_bits=6) route through
    :meth:`QuantizedParameter.matmul` so the base product reads only the
    packed bytes; its custom VJP keeps dx flowing to upstream layers
    while the packed ints stay frozen."""
    if isinstance(base, QuantizedParameter) and base.q_bits == 6:
        y = base.matmul(x)
    else:
        w = (base.dequantized() if isinstance(base, QuantizedParameter)
             else base)
        w = jax.lax.stop_gradient(w)
        y = x @ w.astype(x.dtype)
    if lora_A is not None and lora_B is not None:
        r = lora_r or lora_A.shape[-1]
        scale = lora_alpha / r
        y = y + scale * ((x @ lora_A.astype(x.dtype)) @ lora_B.astype(x.dtype))
    if bias is not None:
        y = y + bias.astype(x.dtype)
    return y


class OptimizedLinear:
    """Factory/stateful wrapper (ref OptimizedLinear.__new__ dispatch):
    quantizes the base when a QuantizationConfig is given, attaches LoRA
    when a LoRAConfig is given."""

    def __init__(self, weight, lora_config: Optional[LoRAConfig] = None,
                 quantization_config: Optional[QuantizationConfig] = None,
                 bias=None, key=None):
        self.lora_config = lora_config
        self.bias = bias
        if quantization_config is not None:
            self.base = QuantizedParameter(
                weight, q_bits=quantization_config.q_bits,
                group_size=quantization_config.group_size)
        else:
            self.base = weight
        self.lora_A = self.lora_B = None
        if lora_config is not None and not lora_config.delay_lora_init:
            if key is None:
                key = jax.random.PRNGKey(0)
            p = init_lora_params(key, weight.shape[-2], weight.shape[-1],
                                 lora_config, dtype=weight.dtype)
            self.lora_A, self.lora_B = p["lora_A"], p["lora_B"]

    def trainable_params(self) -> Dict[str, Any]:
        out = {}
        if self.lora_A is not None:
            out = {"lora_A": self.lora_A, "lora_B": self.lora_B}
        return out

    def __call__(self, x, lora_A=None, lora_B=None):
        lc = self.lora_config or LoRAConfig()
        return lora_linear(x, self.base,
                           lora_A if lora_A is not None else self.lora_A,
                           lora_B if lora_B is not None else self.lora_B,
                           lora_alpha=lc.lora_alpha, lora_r=lc.lora_r,
                           bias=self.bias)
