"""QuantizedParameter — quantized storage with on-the-fly dequant.

Analog of ``deepspeed/linear/quantization.py`` (``QuantizedParameter``
:18): a frozen weight stored as int8, packed int4, or packed FP6 +
per-group scales, dequantized inside the jitted forward so the matmul
reads bf16 while HBM holds the compressed bytes.  Built on the blockwise
quantizer kernels in ``deepspeed_tpu.ops.quantizer`` (the TPU analog of
csrc/quantization); ``q_bits=6`` uses the FP6 e3m2 plane packing whose
Pallas GEMM (``ops/pallas/fp6_linear``) reads only the packed bytes —
the reference's cuda_linear weight-only path.
"""

from __future__ import annotations

import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import (dequantize_blockwise, pack_int4,
                                         quantize_blockwise, unpack_int4)


class QuantizedParameter:
    """Quantize once at construction; ``dequantized()`` inside jit.

    q_bits 8 → int8 storage; 4 → two nibbles per byte; 6 → FP6 e3m2
    plane packing (2-D weights, per-output-column scales; ``matmul``
    runs the packed-read Pallas GEMM).  Int grouping is along the last
    dim (``group_size`` clipped to it).
    """

    def __init__(self, weight, q_bits: int = 8, group_size: int = 512):
        if q_bits not in (4, 6, 8):
            raise ValueError(f"q_bits must be 4, 6, or 8, got {q_bits}")
        self.shape = tuple(weight.shape)
        self.dtype = weight.dtype
        self.q_bits = q_bits
        if q_bits == 6:
            from deepspeed_tpu.ops.pallas.fp6_linear import fp6_quantize

            if len(self.shape) != 2:
                raise ValueError("q_bits=6 (FP6 packed) needs a 2-D "
                                 f"weight, got shape {self.shape}")
            self.data, self.scale = fp6_quantize(weight)
            self.zero = None
            self.group_size = self.shape[0]  # per-column (channel) scale
            return
        n = self.shape[-1]
        group_size = min(group_size, n)
        while n % group_size != 0:  # shrink to a divisor of the last dim
            group_size -= 1
        self.group_size = group_size
        q, scale, zero = quantize_blockwise(weight, num_bits=q_bits,
                                            group_size=group_size)
        self.scale = scale
        self.zero = zero
        self.data = pack_int4(q) if q_bits == 4 else q

    def dequantized(self) -> jnp.ndarray:
        if self.q_bits == 6:
            from deepspeed_tpu.ops.pallas.fp6_linear import fp6_dequantize

            return fp6_dequantize(self.data, self.scale, self.dtype)
        q = unpack_int4(self.data) if self.q_bits == 4 else self.data
        w = dequantize_blockwise(q, self.scale, self.zero,
                                 num_bits=self.q_bits)
        return w.astype(self.dtype)

    def matmul(self, x) -> jnp.ndarray:
        """``x @ W`` without materialising the dequantized weight when a
        packed-read kernel exists (FP6); otherwise dequant-then-dot.

        The FP6 path carries a custom VJP: the weight is frozen (packed
        ints take no gradient), but dx = g @ Wᵀ must flow to upstream
        layers — the backward dequantizes (LoRA training is not the
        bandwidth-bound serve case the packed read exists for)."""
        if self.q_bits != 6:
            return x @ self.dequantized()
        import jax

        from deepspeed_tpu.ops.pallas.fp6_linear import (fp6_dequantize,
                                                         fp6_matmul)

        packed, scale = self.data, self.scale

        @jax.custom_vjp
        def mm(xx):
            return fp6_matmul(xx, packed, scale)

        def mm_fwd(xx):
            return mm(xx), None

        def mm_bwd(_, g):
            w = fp6_dequantize(packed, scale, g.dtype)
            return (g @ w.T,)

        mm.defvjp(mm_fwd, mm_bwd)
        return mm(x)

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize
                   + self.scale.size * self.scale.dtype.itemsize)
