"""Per-node launcher: spawn one training process per local slot.

Analog of the reference's ``launcher/launch.py:145 main``: reads the world
layout, computes this node's global rank offsets, exports the rendezvous
env (DSTPU_* for our comm layer + MASTER_*/RANK/LOCAL_RANK for ported
scripts), spawns the user script once per slot, forwards SIGTERM/SIGINT to
children, and writes a pidfile.

On TPU one process usually owns all local chips (PJRT), so the common case
is ``--nproc 1``; ``--nproc N`` with ``TPU_PROCESS_BOUNDS``-style
chip-splitting is supported for megacore-per-process layouts and for CPU
test meshes.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger

PID_FILE_BASENAME = "dstpu_launch.pid"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dstpu-launch")
    p.add_argument("--world_info", type=str, default="",
                   help="base64 JSON {host: [slot ids]} from the runner")
    p.add_argument("--node_rank", type=str, default="0",
                   help="this node's index (pdsh passes %%n)")
    p.add_argument("--nproc", type=int, default=0,
                   help="local processes (overrides world_info slots)")
    p.add_argument("--coordinator_addr", type=str, default="127.0.0.1")
    p.add_argument("--coordinator_port", type=int, default=29500)
    p.add_argument("--pid_dir", type=str, default="/tmp")
    p.add_argument("--bind_cores_to_rank", action="store_true",
                   help="numactl-bind each local rank to its core slice "
                        "(+ membind when the slice fits one NUMA node) — "
                        "ref launcher --bind_cores_to_rank")
    p.add_argument("--bind_core_list", type=str, default=None,
                   help='cores to divide among ranks, e.g. "0-7,16-23" '
                        "(default: one logical CPU per physical core)")
    p.add_argument("user_script", type=str)
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p


def compute_ranks(world_info: "dict[str, List[int]]", node_rank: int):
    """Global rank base + local slot list for this node."""
    hosts = list(world_info)
    if not 0 <= node_rank < len(hosts):
        raise ValueError(f"node_rank {node_rank} out of range ({len(hosts)} hosts)")
    base = sum(len(world_info[h]) for h in hosts[:node_rank])
    return base, world_info[hosts[node_rank]]


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    node_rank = int(args.node_rank)

    if args.world_info:
        world = decode_world_info(args.world_info)
        rank_base, slots = compute_ranks(world, node_rank)
        world_size = sum(len(v) for v in world.values())
    else:
        n = args.nproc or 1
        rank_base, slots, world_size = 0, list(range(n)), n

    coord = f"{args.coordinator_addr}:{args.coordinator_port}"
    # one shm nonce per job: distinguishes this run's shared-memory regions
    # from a crashed predecessor's (comm/shm.py waits on it)
    shm_nonce = str((os.getpid() << 20) | (int(time.time()) & 0xFFFFF))
    procs: List[subprocess.Popen] = []
    for local_rank, slot in enumerate(slots):
        rank = rank_base + local_rank
        env = dict(os.environ)
        env.update({
            "DSTPU_COORDINATOR": coord,
            "DSTPU_NUM_PROCS": str(world_size),
            "DSTPU_PROC_ID": str(rank),
            "DSTPU_SHM_NONCE": shm_nonce,
            # reference-compatible names (launch.py:182 area)
            "MASTER_ADDR": args.coordinator_addr,
            "MASTER_PORT": str(args.coordinator_port),
            "WORLD_SIZE": str(world_size),
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "CROSS_RANK": str(node_rank),
        })
        if len(slots) > 1:
            # Chip-per-process layout on a multi-chip host (or CPU test mesh).
            env.setdefault("TPU_VISIBLE_DEVICES", str(slot))
        prefix: List[str] = []
        if args.bind_cores_to_rank:
            from deepspeed_tpu.utils.numa import get_numactl_cmd

            prefix, cores = get_numactl_cmd(args.bind_core_list,
                                            len(slots), local_rank)
            # cap intra-op host threads to the slice — unconditionally,
            # or an inherited OMP_NUM_THREADS oversubscribes the slice
            # the binding exists to protect (ref launch.py does the same)
            env["OMP_NUM_THREADS"] = str(max(1, len(cores)))
        cmd = prefix + [sys.executable, "-u", args.user_script,
                        f"--local_rank={local_rank}"] + args.user_args
        procs.append(subprocess.Popen(cmd, env=env))

    pid_path = os.path.join(args.pid_dir, f"{PID_FILE_BASENAME}.{node_rank}")
    try:
        with open(pid_path, "w") as f:
            json.dump({"launcher": os.getpid(), "children": [p.pid for p in procs]}, f)
    except OSError:  # pragma: no cover
        pid_path = None

    def _forward(signum, frame):  # pragma: no cover - signal path
        for p in procs:
            if p.poll() is None:
                p.send_signal(signum)

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)

    rc = 0
    try:
        alive = list(procs)
        while alive:
            for p in list(alive):
                ret = p.poll()
                if ret is None:
                    continue
                alive.remove(p)
                if ret != 0:
                    rc = ret
                    logger.error(f"child {p.pid} exited with {ret}; terminating node")
                    for q in alive:
                        q.terminate()
                    for q in alive:
                        try:
                            q.wait(timeout=30)
                        except subprocess.TimeoutExpired:
                            q.kill()
                            q.wait()
                    alive = []
                    break
            time.sleep(0.1)
    finally:
        if pid_path:
            try:
                os.remove(pid_path)
            except OSError:
                pass
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
