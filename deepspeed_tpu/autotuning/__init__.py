"""Autotuning (ref deepspeed/autotuning/) + overlap-driven step scheduling."""

from deepspeed_tpu.autotuning.autotuner import (Autotuner, ModelInfo,
                                                TrialResult,
                                                estimate_memory_breakdown,
                                                estimate_memory_per_device,
                                                generate_tuning_space,
                                                load_memory_calibration,
                                                predict_fit)
from deepspeed_tpu.autotuning.overlap_scheduler import (SCHEDULE_DECISIONS,
                                                        OverlapScheduler,
                                                        ScheduleDecision,
                                                        decide,
                                                        ensure_schedule,
                                                        extract_evidence)

__all__ = ["Autotuner", "ModelInfo", "TrialResult",
           "estimate_memory_breakdown", "estimate_memory_per_device",
           "generate_tuning_space", "load_memory_calibration",
           "predict_fit",
           "OverlapScheduler", "ScheduleDecision", "SCHEDULE_DECISIONS",
           "decide", "ensure_schedule", "extract_evidence"]
