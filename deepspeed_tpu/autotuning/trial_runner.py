"""Autotuner trial body — shared by in-process and subprocess execution.

``run_timed_trial`` is THE definition of a trial (engine build → one
warmup/compile step → timed steps → samples/sec); the subprocess path
(``python -m deepspeed_tpu.autotuning.trial_runner payload.pkl``) and
``Autotuner._run_trial_inprocess`` both call it, so isolated and
in-process scores stay comparable by construction.
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import time

RESULT_PREFIX = "DSTPU_TRIAL "


def run_timed_trial(model_cfg, config, seq_len: int, steps: int) -> dict:
    """→ {"step_seconds", "throughput"} for one candidate config."""
    import numpy as np

    import deepspeed_tpu as ds

    engine, _, _, _ = ds.initialize(model=model_cfg, config=config)
    rng = np.random.default_rng(0)
    rows = engine.train_batch_size_value
    ids = rng.integers(0, model_cfg.vocab_size, size=(rows, seq_len + 1),
                       dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    loss = engine.train_batch(batch)  # compile step (excluded from timing)
    float(np.asarray(loss))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    float(np.asarray(loss))  # sync
    dt = (time.perf_counter() - t0) / steps
    return {"step_seconds": dt, "throughput": rows / dt}


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    # honor the parent's platform choice even when a platform plugin pinned
    # the config (env vars alone don't override a sitecustomize plugin)
    from deepspeed_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    with open(argv[0], "rb") as f:
        p = pickle.load(f)
    r = run_timed_trial(p["model_cfg"], p["config"], p["seq_len"], p["steps"])
    print(RESULT_PREFIX + json.dumps(r), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
