"""Autotuner — memory-model-driven search over ZeRO stage & micro-batch.

Analog of ``deepspeed/autotuning/autotuner.py`` (``Autotuner`` :42,
``model_info_profile_run`` :663, ``get_instantiation_memory_required_per_gpu``
:278) and the grid/random/model-based tuners (``autotuning/tuner/``).  The
reference launches whole subprocess experiment jobs; on TPU a trial is just
building an engine and timing a few compiled steps in-process — rendezvous
and relaunch overhead don't exist under single-controller JAX.

Flow (mirrors Autotuner.tune): estimate per-device memory for each ZeRO
stage → prune stages that can't fit → sweep micro-batch sizes (power-of-2
"model-based" ordering) → run short timed trials → pick best throughput.

Caveat (trial fidelity): trials time the CURRENT backend.  On a real TPU
the ranking is authoritative; on the virtual CPU mesh (CI, or a down
tunnel) the memory-model pruning is still sound, but the throughput
ORDERING reflects the CPU interpreter's cost model, not the chip's — MXU
tiling, ICI bandwidth, and HBM pressure differences do not register.
Treat CPU-mesh tuning results as feasibility screening and re-run the
final sweep on hardware (``bin/dstpu_autotune`` on the pod) before
committing a launch config.
"""

from __future__ import annotations

import copy
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

BYTES_PER_PARAM = {"bf16": 2, "fp16": 2, "fp32": 4}


@dataclass
class ModelInfo:
    """Ref model_info_profile_run: num_params + activation footprint."""
    num_params: int
    hidden_size: int = 0
    num_layers: int = 0
    vocab_size: int = 0


def estimate_memory_breakdown(model_info: ModelInfo, zero_stage: int,
                              dp_size: int, micro_batch: int, seq_len: int,
                              dtype: str = "bf16",
                              optimizer_factor: int = 12,
                              tp_size: int = 1, pp_size: int = 1,
                              sp_size: int = 1,
                              comm_quant: bool = False,
                              comm_group_size: int = 256) -> Dict[str, int]:
    """Per-class bytes per device for params/grads/optimizer/activations/
    logits/comm (+ ``total``) — the ladder predictor reports WHICH class
    blew the budget, not just that it did.

    Ref get_instantiation_memory_required_per_gpu (autotuner.py:278):
    optimizer_factor=12 ≈ fp32 master + two Adam moments + fp16 param/grad
    bookkeeping, partitioned by stage:
      stage 0: all replicated; 1: optimizer/dp; 2: +grads/dp; 3: +params/dp.
    Model-parallel axes shard everything multiplicatively: tensor/pipe split
    params+grads+optimizer; pipe splits resident layers (activations too);
    seq splits the activation sequence dim.

    ``comm_quant`` prices the comm-quantization error-feedback residual:
    the engine rides a ``[world, padded]`` fp32 buffer through the step
    signature (engine.py, quantized-DP grad reduce), sharded over the DP
    axis — per device that is ``padded * 4`` bytes where ``padded`` rounds
    the flat param count up to a multiple of ``world * group_size``, i.e.
    ~4 bytes/param REGARDLESS of dp_size.  It only materializes on the
    eligible path (dp > 1, pure-DP mesh, stage <= 2), matching the
    engine's fallback gate.
    """
    p = model_info.num_params // max(1, tp_size * pp_size)
    b = BYTES_PER_PARAM.get(dtype, 2)
    params_mem = p * b
    grads_mem = p * b
    opt_mem = p * optimizer_factor
    if zero_stage >= 1:
        opt_mem //= dp_size
    if zero_stage >= 2:
        grads_mem //= dp_size
    if zero_stage >= 3:
        params_mem //= dp_size
    # activation estimate: ~ layers * micro_batch * seq * hidden * c bytes.
    # NOT divided by pp: the 1F1B schedule keeps O(pp) microbatches in
    # flight, cancelling the layers/pp split per stage.
    act = (model_info.num_layers * micro_batch * seq_len
           * max(1, model_info.hidden_size) * 2 * 16
           // max(1, sp_size * tp_size))
    # fp32 [B, S, V] logits + their cotangent: dominates small models with
    # big vocabs (r04 on-chip validation: the estimator passed gpt2-125m
    # mb=64 at 11.6GB est but the 6.6GB logits buffer OOM'd the trial —
    # AUTOTUNE_TPU.json).  Sequence-tiled loss (loss_tiles) avoids the
    # buffer, but the tuner prices the default untiled path.
    logits = (micro_batch * seq_len * max(1, model_info.vocab_size) * 4 * 2
              // max(1, sp_size * tp_size))
    comm_mem = 0
    if (comm_quant and dp_size > 1 and zero_stage <= 2
            and tp_size == 1 and pp_size == 1 and sp_size == 1):
        base = dp_size * max(1, comm_group_size)
        padded = -(-model_info.num_params // base) * base
        comm_mem = padded * 4  # fp32 EF residual row per device
    out = {"params": int(params_mem), "grads": int(grads_mem),
           "optimizer": int(opt_mem), "activations": int(act),
           "logits": int(logits), "comm": int(comm_mem)}
    out["total"] = sum(out.values())
    return out


def estimate_memory_per_device(model_info: ModelInfo, zero_stage: int,
                               dp_size: int, micro_batch: int, seq_len: int,
                               dtype: str = "bf16",
                               optimizer_factor: int = 12,
                               tp_size: int = 1, pp_size: int = 1,
                               sp_size: int = 1) -> int:
    """Total bytes per device (see :func:`estimate_memory_breakdown`)."""
    return estimate_memory_breakdown(
        model_info, zero_stage, dp_size, micro_batch, seq_len, dtype,
        optimizer_factor, tp_size, pp_size, sp_size)["total"]


def load_memory_calibration(path: Optional[str] = None,
                            backend: str = "cpu") -> float:
    """The ``model_drift`` calibration ratio (XLA-measured static peak /
    analytic estimate) the memory auditor froze into
    ``tools/memory_baseline.json`` for ``backend`` — 1.0 when the file
    or the backend entry is absent.  Multiplying the analytic estimate
    by this ratio turns the never-validated model into one anchored to
    what XLA actually allocates on this backend."""
    import json

    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "tools", "memory_baseline.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return 1.0
    try:
        return float(data.get("calibration", {}).get(backend, 1.0)) or 1.0
    except (TypeError, ValueError):
        return 1.0


def predict_fit(model_info: ModelInfo, zero_stage: int, dp_size: int,
                micro_batch: int, seq_len: int, hbm_bytes: int,
                dtype: str = "bf16", calibration: float = 1.0,
                tp_size: int = 1, pp_size: int = 1, sp_size: int = 1,
                offload_param: Optional[str] = None,
                offload_optimizer: Optional[str] = None,
                host_bytes: Optional[int] = None,
                chunk_bytes: Optional[int] = None,
                comm_quant: bool = False,
                comm_group_size: int = 256) -> Dict[str, Any]:
    """The OOM-before-you-run gate: calibrated per-device peak estimate
    vs the device budget, with the dominant class and shortfall when it
    does NOT fit — so a too-big ladder rung reports *why* instead of
    dying in RESOURCE_EXHAUSTED.

    ZeRO-Offload re-homes whole classes off the device
    (``offload_param`` / ``offload_optimizer`` take the config's device
    string, e.g. ``"cpu"`` / ``"nvme"``): the optimizer's fp32 masters +
    moments (and the grads that feed them) follow ``offload_optimizer``,
    the param shards follow ``offload_param`` — those classes stop
    counting against ``hbm_bytes``.  Classes homed on ``"cpu"`` are
    instead priced against ``host_bytes`` when the caller provides it
    (the r04 ladder died in HOST resource exhaustion, not HBM); NVMe
    classes are treated as unbounded.

    ``chunk_bytes`` prices the chunked host-step pipeline
    (``offload_optimizer.working_set_bytes > 0``): grads stay
    device-homed (the grads program materializes them in HBM/host-placed
    shardings and only O(chunk) crosses at a time), the cpu tier adds a
    double-buffered working set (grad chunk + the (3,n) state rows, two
    buffers deep) to the host need, and the nvme tier's host need is
    ONLY that working set — the state itself lives in chunk files.

    ``comm_quant`` adds the error-feedback residual under a ``comm``
    class (see :func:`estimate_memory_breakdown`); it is always
    device-homed — offload never re-homes it — so quantized-DP configs
    near the fit boundary stop being under-priced."""
    bd = estimate_memory_breakdown(model_info, zero_stage, dp_size,
                                   micro_batch, seq_len, dtype,
                                   tp_size=tp_size, pp_size=pp_size,
                                   sp_size=sp_size, comm_quant=comm_quant,
                                   comm_group_size=comm_group_size)
    cal = float(calibration) if calibration else 1.0
    home = {k: "device" for k in bd if k != "total"}
    if offload_optimizer:
        home["optimizer"] = offload_optimizer
        home["grads"] = offload_optimizer
    if offload_param:
        home["params"] = offload_param
    chunk_working_set = 0
    if chunk_bytes and offload_optimizer in ("cpu", "nvme"):
        home["grads"] = "device"
        # per buffered chunk: 1 grad row + 3 state rows, double-buffered
        chunk_working_set = int(2 * 4 * chunk_bytes)
    device_classes = [k for k, h in home.items() if h == "device"]
    host_classes = [k for k, h in home.items() if h == "cpu"]
    predicted = int(sum(bd[k] for k in device_classes) * cal)
    host_need = int(sum(bd[k] for k in host_classes) * cal)
    # (nvme-homed state never entered host_classes, so the nvme tier's
    # host need is exactly this working set)
    host_need += chunk_working_set
    fit_device = predicted <= int(hbm_bytes)
    fit_host = host_bytes is None or host_need <= int(host_bytes)
    if not fit_device:
        dominant = max(device_classes, key=lambda k: bd[k])
        shortfall = predicted - int(hbm_bytes)
    elif not fit_host:
        dominant = (max(host_classes, key=lambda k: bd[k])
                    if host_classes else "optimizer")
        shortfall = host_need - int(host_bytes)
    else:
        dominant = max((k for k in bd if k != "total"),
                       key=lambda k: bd[k])
        shortfall = 0
    return {
        "predicted_peak_bytes": predicted,
        "predicted_fit": fit_device and fit_host,
        "hbm_bytes": int(hbm_bytes),
        "host_bytes": None if host_bytes is None else int(host_bytes),
        "host_resident_bytes": host_need,
        "chunk_working_set_bytes": chunk_working_set,
        "calibration": round(cal, 4),
        "breakdown": bd,
        "dominant_class": dominant,
        "shortfall_bytes": max(0, shortfall),
    }


def enumerate_meshes(n_devices: int, model_cfg) -> "List[Dict[str, int]]":
    """All valid mesh factorizations of ``n_devices`` over
    data×tensor×pipe×seq(×expert for MoE), pruned by model divisibility
    (heads % tp, kv_heads % tp, heads % sp, layers % pp, experts % ep) —
    the tp/pp/sp/ep sweep dimension of the reference autotuner's space.
    """
    heads = getattr(model_cfg, "num_heads", 1) or 1
    kv_heads = getattr(model_cfg, "num_kv_heads", None) or heads
    layers = getattr(model_cfg, "num_layers", 1) or 1
    experts = getattr(model_cfg, "num_experts", 0) or 0
    is_moe = experts > 1

    def divisors(n):
        return [d for d in range(1, n + 1) if n % d == 0]

    meshes = []
    for tp in divisors(n_devices):
        if heads % tp or kv_heads % tp:
            continue
        for pp in divisors(n_devices // tp):
            if layers % pp:
                continue
            for sp in divisors(n_devices // (tp * pp)):
                # only query heads constrain sp: the Ulysses layer expands
                # KV for GQA when kv_heads < sp (sequence/layer.py:43)
                if heads % sp:
                    continue
                if tp > 1 and sp > 1 and (pp > 1 or heads % (tp * sp)):
                    # tensor×seq composition shards heads jointly over
                    # both axes (sequence/layer.py) — needs tp·sp | heads,
                    # and adding pipe on top still trips the SPMD
                    # partitioner (XLA abort), so tp×sp×pp stays pruned.
                    # (tp×sp is validated on the XLA attention path; on a
                    # real TPU the Pallas kernel route is covered by the
                    # crash-isolated trial, which scores an abort as 0.)
                    continue
                rem = n_devices // (tp * pp * sp)
                for ep in (divisors(rem) if is_moe else [1]):
                    if is_moe and ep > 1 and experts % ep:
                        continue
                    mesh = {"data": rem // ep}
                    if tp > 1:
                        mesh["tensor"] = tp
                    if pp > 1:
                        mesh["pipe"] = pp
                    if sp > 1:
                        mesh["seq"] = sp
                    if ep > 1:
                        mesh["expert"] = ep
                    meshes.append(mesh)  # every (tp,pp,sp,ep) is distinct
    return meshes


def generate_tuning_space(model_info: ModelInfo, dp_size: int, seq_len: int,
                          hbm_bytes: int, dtype: str = "bf16",
                          stages=(0, 1, 2, 3),
                          max_micro_batch: int = 64,
                          meshes: Optional[List[Dict[str, int]]] = None,
                          calibration: float = 1.0
                          ) -> List[Dict[str, Any]]:
    """Candidate (mesh, zero_stage, micro_batch) configs that fit the
    memory budget (ref tuning-space templates + the mesh sweep).
    ``calibration`` scales the analytic estimate by the memory auditor's
    frozen ``model_drift`` ratio (:func:`load_memory_calibration`) so
    pruning tracks what XLA actually allocates on this backend."""
    space = []
    # mesh=None = "not sweeping": candidates carry no mesh key, so the
    # caller's base_config mesh passes through trials untouched
    for mesh in (meshes if meshes else [None]):
        if mesh is None:
            dp, tp, pp, sp = dp_size, 1, 1, 1
        else:
            dp = mesh.get("data", 1) * mesh.get("expert", 1)
            tp, pp, sp = (mesh.get("tensor", 1), mesh.get("pipe", 1),
                          mesh.get("seq", 1))
        if sp > 1 and seq_len % sp:
            continue
        for stage in stages:
            if pp > 1 and stage >= 2:
                continue  # engine: pipeline composes with ZeRO-0/1 specs
            mb = 1
            while mb <= max_micro_batch:
                need = int(estimate_memory_per_device(
                    model_info, stage, max(1, dp), mb, seq_len, dtype,
                    tp_size=tp, pp_size=pp, sp_size=sp)
                    * (float(calibration) or 1.0))
                if need <= hbm_bytes:
                    cand = {"zero_stage": stage, "micro_batch": mb,
                            "est_bytes": need}
                    if mesh is not None:
                        cand["mesh"] = mesh
                    space.append(cand)
                mb *= 2
    return space


@dataclass
class TrialResult:
    config: Dict[str, Any]
    throughput: float  # samples/sec
    step_seconds: float
    error: Optional[str] = None


class Autotuner:
    """Ref Autotuner (autotuning/autotuner.py:42).

    ``tune`` returns (best_ds_config, results).  ``mode``: "grid" tries the
    whole space; "random" samples ``max_trials``; "model_based" orders by
    estimated memory headroom (bigger batch first) and early-stops after
    ``patience`` non-improving trials; "planner" seeds the space with the
    plan compiler's ranked candidates (deepspeed_tpu.planner — static
    census-priced step-time model) instead of the blind pow2 ladder,
    falling back to model_based ordering if planning fails.
    """

    def __init__(self, model_cfg, base_config: Dict[str, Any],
                 seq_len: int = 64, mode: str = "model_based",
                 max_trials: int = 8, steps_per_trial: int = 3,
                 hbm_bytes: Optional[int] = None, seed: int = 0,
                 tune_mesh: bool = False, n_devices: Optional[int] = None,
                 isolate_trials: bool = True,
                 trial_timeout: Optional[float] = None,
                 calibration: Any = None):
        self.model_cfg = model_cfg
        self.base_config = base_config
        self.seq_len = seq_len
        self.mode = mode
        self.max_trials = max_trials
        self.steps_per_trial = steps_per_trial
        self.hbm_bytes = hbm_bytes or (16 << 30)
        self.seed = seed
        self.tune_mesh = tune_mesh
        self.n_devices = n_devices
        # subprocess isolation (ref: experiments run as separate jobs) —
        # an aborting/OOMing candidate must not kill the tuner itself
        self.isolate_trials = isolate_trials
        # generous default: engine build + XLA compile + timed steps
        self.trial_timeout = trial_timeout or (600.0 + 30.0 * steps_per_trial)
        # memory-model calibration attached to tuning-space pruning:
        # None = uncalibrated (1.0, historical behavior), "auto" = the
        # memory auditor's frozen model_drift ratio for this backend
        # (tools/memory_baseline.json), or an explicit float
        if calibration == "auto":
            import jax

            calibration = load_memory_calibration(
                backend=jax.default_backend())
        self.calibration = float(calibration) if calibration else 1.0
        self.results: List[TrialResult] = []

    # ------------------------------------------------------------------
    def model_info(self) -> ModelInfo:
        from deepspeed_tpu.profiling import get_model_profile

        prof = get_model_profile(self.model_cfg, 1, self.seq_len)
        return ModelInfo(num_params=prof["params"],
                         hidden_size=self.model_cfg.hidden_size,
                         num_layers=self.model_cfg.num_layers,
                         vocab_size=self.model_cfg.vocab_size)

    def _space(self) -> List[Dict[str, Any]]:
        if self.mode == "planner":
            # plan-compiler seeding: ranked candidates from the static
            # planner (census-priced step-time model) replace the blind
            # pow2 enumeration — trials then confirm the analytic ranking
            try:
                import jax

                from deepspeed_tpu.planner import seed_candidates

                n = self.n_devices or len(jax.devices())
                cands = seed_candidates(
                    self.model_cfg, seq_len=self.seq_len, chips=n,
                    hbm_bytes=self.hbm_bytes,
                    calibration=self.calibration, top=self.max_trials)
                if cands:
                    return cands
            except Exception as e:  # planner unavailable → pow2 fallback
                logger.warning(f"planner seeding failed ({e}); "
                               "falling back to model_based space")
        mesh = self.base_config.get("mesh") or {}
        dp = int(mesh.get("data", 1)) * int(mesh.get("expert", 1))
        meshes = None
        if self.tune_mesh:
            import jax

            n = self.n_devices or len(jax.devices())
            meshes = enumerate_meshes(n, self.model_cfg)
        space = generate_tuning_space(self.model_info(), max(1, dp),
                                      self.seq_len, self.hbm_bytes,
                                      meshes=meshes,
                                      calibration=self.calibration)
        if self.mode == "random":
            rng = np.random.default_rng(self.seed)
            rng.shuffle(space)
            return space[:self.max_trials]
        if self.mode in ("model_based", "planner"):
            space.sort(key=lambda c: (-c["micro_batch"], -c["zero_stage"]))
            return space[:self.max_trials]
        return space  # grid

    def _trial_config(self, cand: Dict[str, Any]) -> Dict[str, Any]:
        cfg = copy.deepcopy(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = cand["micro_batch"]
        cfg.setdefault("gradient_accumulation_steps", 1)
        cfg.pop("train_batch_size", None)
        cfg.setdefault("zero_optimization", {})["stage"] = cand["zero_stage"]
        if cand.get("mesh"):
            cfg["mesh"] = dict(cand["mesh"])
        # planner-seeded candidates carry whole config blocks
        # (comm_quantization / step_schedule / offload) as overrides
        for k, v in (cand.get("overrides") or {}).items():
            cfg[k] = copy.deepcopy(v)
        return cfg

    def run_trial(self, cand: Dict[str, Any]) -> TrialResult:
        if self.isolate_trials:
            return self._run_trial_subprocess(cand)
        return self._run_trial_inprocess(cand)

    def _run_trial_subprocess(self, cand: Dict[str, Any]) -> TrialResult:
        """Run one trial in a fresh subprocess (the reference launches whole
        experiment jobs, autotuner.py:404): an OOM, compile failure, or a
        hard XLA abort kills only the trial, never the tuner.  The trial
        body is deepspeed_tpu.autotuning.trial_runner (shared with the
        in-process path)."""
        import json
        import pickle
        import re as _re
        import subprocess
        import sys
        import tempfile

        from deepspeed_tpu.autotuning.trial_runner import RESULT_PREFIX

        payload = {"model_cfg": self.model_cfg,
                   "config": self._trial_config(cand),
                   "seq_len": self.seq_len,
                   "steps": self.steps_per_trial}
        with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as f:
            pickle.dump(payload, f)
            path = f.name
        import deepspeed_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(deepspeed_tpu.__file__)))
        # propagate the parent's LIVE jax setup — it is often configured
        # programmatically (jax.config.update), which env vars alone would
        # not reproduce in the child
        import jax

        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        if jax.default_backend() == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            ndev = self.n_devices or len(jax.devices())
            flags = _re.sub(r"--xla_force_host_platform_device_count=\d+",
                            "", env.get("XLA_FLAGS", ""))
            env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                                f"count={ndev}").strip()
        try:
            out = subprocess.run(
                [sys.executable, "-m",
                 "deepspeed_tpu.autotuning.trial_runner", path],
                capture_output=True, timeout=self.trial_timeout, env=env)
            for line in out.stdout.decode(errors="replace").splitlines():
                if line.startswith(RESULT_PREFIX):
                    r = json.loads(line[len(RESULT_PREFIX):])
                    return TrialResult(cand, throughput=r["throughput"],
                                       step_seconds=r["step_seconds"])
            err = out.stderr.decode(errors="replace")[-300:]
            logger.warning(f"autotuner trial {cand} failed (rc={out.returncode})")
            return TrialResult(cand, throughput=0.0,
                               step_seconds=float("inf"), error=err)
        except subprocess.TimeoutExpired:
            logger.warning(f"autotuner trial {cand} timed out after "
                           f"{self.trial_timeout:.0f}s")
            return TrialResult(cand, throughput=0.0,
                               step_seconds=float("inf"), error="timeout")
        finally:
            os.unlink(path)

    def _run_trial_inprocess(self, cand: Dict[str, Any]) -> TrialResult:
        from deepspeed_tpu.autotuning.trial_runner import run_timed_trial
        from deepspeed_tpu.parallel import topology

        cfg = self._trial_config(cand)
        try:
            r = run_timed_trial(self.model_cfg, cfg, self.seq_len,
                                self.steps_per_trial)
            return TrialResult(cand, throughput=r["throughput"],
                               step_seconds=r["step_seconds"])
        except Exception as e:  # OOM / compile failure → score 0
            logger.warning(f"autotuner trial {cand} failed: {e}")
            return TrialResult(cand, throughput=0.0, step_seconds=float("inf"),
                               error=str(e))
        finally:
            topology._GLOBAL_TOPOLOGY = None

    def tune(self, patience: int = 3):
        """→ (best_config_dict, [TrialResult...])."""
        best: Optional[TrialResult] = None
        stale = 0
        for cand in self._space():
            res = self.run_trial(cand)
            self.results.append(res)
            logger.info(f"autotuner: {cand} → "
                        f"{res.throughput:.2f} samples/s")
            if best is None or res.throughput > best.throughput:
                best, stale = res, 0
            else:
                stale += 1
                if self.mode == "model_based" and stale >= patience:
                    break
        if best is None or best.throughput <= 0:
            raise RuntimeError("autotuning found no runnable config")
        return self._trial_config(best.config), self.results
