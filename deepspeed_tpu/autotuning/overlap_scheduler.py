"""Overlap-driven step scheduling: probe → decide → pin.

Closes the loop left open by the telemetry layer: ``telemetry/capture.py``
auto-captures collective-overlap reports (``overlap_fraction`` +
``top_device_ops`` from ``utils/xplane``), but nothing *acted* on them.
This module runs k probe steps with a forced capture, reads the report,
and picks a **step schedule** — the T3 move (arXiv:2401.16677: fine-grained
compute/collective overlap is the lever once wire bytes are already
quantized) combined with automatic cross-replica weight-update sharding
(arXiv:2004.13336: decompose the optimizer step over the replica axis when
it serializes behind the gradient reduce).

Three knob families are actuated (runtime/engine.py reads the pinned
``step_schedule`` config block):

* ``zero3_prefetch`` — ZeRO-3 gather scheduling: ``gather_prefetch_depth``
  (the layer-scan unroll window XLA's latency-hiding scheduler can hoist a
  parameter all-gather across), ``param_persistence_threshold`` (small
  params stay gathered — fewer per-use all-gathers), and
  ``prefetch_bucket_size`` (recorded with the schedule for launch-config
  parity; under XLA the bucketing itself belongs to the scheduler).
* ``ring_interleave`` — ring-attention hop schedule: depth 2 issues the
  next hop's ``ppermute`` *before* the current hop's attend, so the
  K/V transfer is dataflow-independent of the hop's kernels and the
  compiler can overlap the two (sequence/ring.py).
* ``decomposed_update`` — the 2004.13336 schedule: optimizer state and the
  gradient accumulator shard over the ZeRO axes even at stage 0/1, so the
  gradient all-reduce becomes reduce-scatter + a 1/world optimizer step +
  an all-gather of updated params that XLA overlaps with neighbouring
  update compute (at stage 3 the schedule is already decomposed — the
  re-gather happens lazily at the next step's forward, per layer).

Every decision is a typed :class:`ScheduleDecision` carrying the evidence
that justified it (overlap fraction + its source, dominant collective,
estimated exposed-comm ms, probe step).  The chosen schedule is written
into a frozen ``step_schedule`` config block with ``mode: "pinned"`` —
a tuned run is reproducible without re-probing.

CPU degradation: XPlane captures on the CPU mesh carry no device planes,
so the report's ``spans`` block (software-span overlap estimate from the
PR-4 tracer) feeds the same decision logic — the probe→decide→pin loop is
exercisable end-to-end in CI.  Like the autotuner's trials, a CPU-mesh
probe validates *plumbing*, not chip timings; re-probe on hardware before
committing a launch schedule.
"""

from __future__ import annotations

import copy
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

# Frozen decision vocabulary — linted against docs/AUTOTUNING.md by
# tools/telemetry_check.py (same contract as the telemetry span names).
SCHEDULE_DECISIONS = ("decomposed_update", "fused_gather_matmul", "noop",
                      "ring_interleave", "zero3_prefetch")

# Frozen evidence key set: every ScheduleDecision carries exactly these.
# `static_census` is the graph auditor's per-kind collective rollup and
# `static_memory` the memory-plan auditor's per-device totals rollup
# (analysis/auditor.census_and_memory_engine — docs/STATIC_ANALYSIS.md,
# both off ONE probe-time lowering): pinned evidence records WHAT the
# step's comm and memory plan statically are alongside how well the
# runtime overlapped it; None when the audit was unavailable during the
# probe.
EVIDENCE_KEYS = ("dominant_collective", "exposed_comm_ms",
                 "overlap_fraction", "overlap_source", "probe_step",
                 "static_census", "static_memory")

# param_persistence_threshold rungs (same ladder as the DeepCompile
# SelectiveUnshardPass — compile/backend.py): each step trades spare HBM
# for fewer per-use all-gathers of small ZeRO-3 params.
PERSIST_LADDER = (0, 100_000, 1_000_000, 10_000_000)

MAX_PREFETCH_DEPTH = 4


@dataclass
class ScheduleDecision:
    """One typed scheduling decision with the evidence that justified it.

    ``knobs`` maps ``step_schedule`` keys to their pinned values (empty
    for ``noop``); ``evidence`` carries exactly :data:`EVIDENCE_KEYS`.
    """
    decision: str
    knobs: Dict[str, Any] = field(default_factory=dict)
    evidence: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.decision not in SCHEDULE_DECISIONS:
            raise ValueError(
                f"unknown schedule decision {self.decision!r} "
                f"(known: {list(SCHEDULE_DECISIONS)})")
        missing = set(EVIDENCE_KEYS) - set(self.evidence)
        if missing:
            raise ValueError(
                f"ScheduleDecision {self.decision!r} evidence is missing "
                f"{sorted(missing)} (frozen keys: {list(EVIDENCE_KEYS)})")

    def to_dict(self) -> Dict[str, Any]:
        return {"decision": self.decision, "knobs": dict(self.knobs),
                "evidence": dict(self.evidence)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ScheduleDecision":
        ev = dict(d.get("evidence", {}))
        if ev:
            # configs pinned before the census/memory fields existed must
            # keep loading (pinned-mode reproducibility contract): an
            # absent block is None, the same value a failed audit records
            ev.setdefault("static_census", None)
            ev.setdefault("static_memory", None)
        return cls(decision=d["decision"], knobs=dict(d.get("knobs", {})),
                   evidence=ev)


def extract_evidence(report: Dict[str, Any],
                     context: Dict[str, Any]) -> Dict[str, Any]:
    """Evidence fields from one capture report.

    Prefers the XPlane device-plane numbers (on-chip truth); degrades to
    the report's ``spans`` block (software-span estimate) when the
    capture carried no device planes (CPU mesh).  Raises ``ValueError``
    when the report carries neither — the scheduler refuses to decide on
    no evidence.
    """
    devices = report.get("devices") or {}
    if devices:
        overlap = float(report.get("overlap_fraction", 0.0))
        source = "xplane"
        # per-device MEAN, matching mean_overlap_fraction: summing the
        # planes would scale the evidence with the device (and, on
        # multi-host captures, host-file) count instead of describing
        # one step on one chip
        coll_ms = (sum(float(d.get("collective_ms", 0.0))
                       for d in devices.values()) / len(devices))
        exposed_ms = coll_ms * (1.0 - overlap)
    else:
        spans = report.get("spans") or {}
        if float(spans.get("step_ms", 0.0)) <= 0.0:
            raise ValueError(
                "capture report carries neither device planes nor a spans "
                "block — nothing to schedule on (was tracing enabled "
                "during the probe?)")
        overlap = float(spans.get("overlap_estimate", 0.0))
        source = "spans"
        exposed_ms = float(spans.get("exposed_ms", 0.0))

    dom = report.get("dominant_collective") or {}
    name = dom.get("name", "") if isinstance(dom, dict) else str(dom)
    if not name:
        # No collective op surfaced in the capture (CPU host planes, or
        # post-processing degraded): infer the schedule-implied dominant
        # collective from the config so the decision table still has a
        # gate.  Marked "(inferred)" so pinned evidence is honest.
        if context.get("zero_stage", 0) >= 3:
            name = "all-gather (inferred)"
        elif context.get("sp", 1) > 1 and context.get("seq_impl") == "ring":
            name = "collective-permute (inferred)"
        elif context.get("dp", 1) > 1:
            name = "all-reduce (inferred)"
        else:
            name = "none"
    return {
        "dominant_collective": name,
        "exposed_comm_ms": round(float(exposed_ms), 3),
        "overlap_fraction": round(float(overlap), 4),
        "overlap_source": source,
        "probe_step": int(report.get("step",
                                     report.get("armed_at_step", 0))),
        "static_census": report.get("static_census"),
        "static_memory": report.get("static_memory"),
    }


def _next_persist_rung(current: int) -> int:
    for rung in PERSIST_LADDER:
        if rung > current:
            return rung
    return PERSIST_LADDER[-1]


def decide(report: Dict[str, Any], context: Dict[str, Any],
           overlap_threshold: float = 0.5
           ) -> Tuple[Dict[str, Any], List[ScheduleDecision]]:
    """Pure decision table: capture report + config context → schedule.

    Returns ``(updates, decisions)`` where ``updates`` maps
    ``step_schedule`` keys to their new pinned values.  The three knob
    families are evaluated independently; when nothing fires a single
    ``noop`` decision records the evidence that justified leaving the
    schedule alone.

    ``context``: ``{"zero_stage", "dp", "sp", "seq_impl", "base": {...}}``
    where ``base`` carries the effective pre-decision knob values.
    """
    ev = extract_evidence(report, context)
    base = dict(context.get("base", {}))
    overlap = ev["overlap_fraction"]
    dom = ev["dominant_collective"]
    low = overlap < float(overlap_threshold)
    updates: Dict[str, Any] = {}
    decisions: List[ScheduleDecision] = []

    # (a) ZeRO-3 gather scheduling: exposed param gathers → prefetch
    # deeper and persist more small params.
    if low and context.get("zero_stage", 0) >= 3:
        depth = int(base.get("gather_prefetch_depth", 1))
        persist = int(base.get("param_persistence_threshold") or 0)
        bucket = int(base.get("prefetch_bucket_size") or 50_000_000)
        knobs = {
            "gather_prefetch_depth": min(MAX_PREFETCH_DEPTH, depth * 2),
            "param_persistence_threshold": _next_persist_rung(persist),
            "prefetch_bucket_size": bucket * 2,
        }
        updates.update(knobs)
        decisions.append(ScheduleDecision("zero3_prefetch", knobs, ev))

    # (a') ZeRO-3 fused gather-matmul: the scheduled arm is exhausted
    # (prefetch depth already widened by a previous probe) and the
    # exposed collective is still the param gather → stop scheduling
    # around it and FUSE it — the layer MLP's explicit shard_map region
    # issues the following matmul's all-gather itself
    # (ops/pallas/gather_matmul.py).  Fused vs scheduled is thus one
    # decision table: first probe deepens prefetch, a still-low second
    # probe flips to fused.
    if (low and context.get("zero_stage", 0) >= 3
            and "gather" in dom
            and int(base.get("gather_prefetch_depth", 1)) >= 2
            and not base.get("fused_gather_matmul", False)):
        knobs = {"fused_gather_matmul": True}
        updates.update(knobs)
        decisions.append(ScheduleDecision("fused_gather_matmul", knobs, ev))

    # (b) ring hop/compute interleave: an exposed ring rotation → issue
    # the next hop's permute before the current hop's attend.
    if (low and context.get("sp", 1) > 1
            and context.get("seq_impl") == "ring"
            and int(base.get("ring_interleave", 1)) < 2):
        knobs = {"ring_interleave": 2}
        updates.update(knobs)
        decisions.append(ScheduleDecision("ring_interleave", knobs, ev))

    # (c) decomposed weight update (2004.13336): the optimizer step
    # serializes behind a dominant gradient reduce → shard the update
    # over the ZeRO axes (stage ≥ 2 is already decomposed by layout).
    if (low and context.get("zero_stage", 0) <= 1
            and context.get("dp", 1) > 1
            and ("reduce" in dom)
            and base.get("weight_update", "fused") != "decomposed"):
        knobs = {"weight_update": "decomposed"}
        updates.update(knobs)
        decisions.append(ScheduleDecision("decomposed_update", knobs, ev))

    if not decisions:
        decisions.append(ScheduleDecision("noop", {}, ev))
    return updates, decisions


class OverlapScheduler:
    """The probe→decide→pin driver (wired into ``autotuning/``).

    ``tune(batch)`` builds an engine from ``base_config`` with a forced
    telemetry capture + tracing injected, runs ``probe_steps`` compiled
    steps (plus one compile warmup outside the window), reads the overlap
    report, runs :func:`decide`, and returns the base config with a
    frozen ``step_schedule`` block (``mode: "pinned"``) holding the
    chosen knobs and the full decision records.
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 probe_steps: Optional[int] = None,
                 overlap_threshold: Optional[float] = None,
                 output_dir: Optional[str] = None):
        if not isinstance(base_config, dict):
            raise TypeError("OverlapScheduler needs the config as a dict "
                            "(the pinned schedule is written back into it)")
        self.model = model
        self.base_config = copy.deepcopy(base_config)
        ss = dict(self.base_config.get("step_schedule") or {})
        self.probe_steps = int(probe_steps if probe_steps is not None
                               else ss.get("probe_steps", 3))
        self.overlap_threshold = float(
            overlap_threshold if overlap_threshold is not None
            else ss.get("overlap_threshold", 0.5))
        self.output_dir = output_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "dstpu_overlap_probe")
        self.last_report: Optional[Dict[str, Any]] = None
        self.last_context: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------
    def _probe_config(self) -> Dict[str, Any]:
        cfg = copy.deepcopy(self.base_config)
        tel = dict(cfg.get("telemetry") or {})
        tel["enabled"] = True
        cap = dict(tel.get("capture") or {})
        # the first step pays the XLA compile — capture the LAST probe
        # step so the window sees steady-state scheduling
        cap.update({"enabled": True, "capture_step": self.probe_steps + 1,
                    "num_steps": 1, "budget": 1,
                    "output_dir": self.output_dir})
        tel["capture"] = cap
        tr = dict(tel.get("tracing") or {})
        tr["enabled"] = True   # spans feed the CPU-degraded estimate
        tel["tracing"] = tr
        cfg["telemetry"] = tel
        return cfg

    @staticmethod
    def _context_from_engine(engine) -> Dict[str, Any]:
        cfg = engine.config
        ss = cfg.step_schedule
        zc = cfg.zero_config
        persist = (ss.param_persistence_threshold
                   if ss.param_persistence_threshold is not None
                   else zc.param_persistence_threshold)
        bucket = (ss.prefetch_bucket_size
                  if ss.prefetch_bucket_size is not None
                  else zc.prefetch_bucket_size)
        mc = engine.model_config
        return {
            "zero_stage": engine.zero_stage,
            "dp": engine.topology.dp_size,
            "sp": engine.topology.sp_size,
            "seq_impl": getattr(mc, "seq_impl", "") if mc is not None else "",
            "base": {
                "gather_prefetch_depth": ss.gather_prefetch_depth,
                "param_persistence_threshold": persist,
                "prefetch_bucket_size": bucket,
                "ring_interleave": ss.ring_interleave,
                "weight_update": ss.weight_update,
                "fused_gather_matmul": ss.fused_gather_matmul,
            },
        }

    def probe(self, batch) -> Dict[str, Any]:
        """Run the probe steps under a forced capture; → the report dict.

        Also stashes ``last_context`` (read off the built engine, so the
        decision table sees the *effective* stage/mesh, not the raw
        JSON).
        """
        import deepspeed_tpu as ds
        from deepspeed_tpu.parallel import topology as topo_mod

        engine, _, _, _ = ds.initialize(model=self.model,
                                        config=self._probe_config())
        census = None
        static_memory = None
        try:
            self.last_context = self._context_from_engine(engine)
            for _ in range(self.probe_steps + 1):
                engine.train_batch(batch)
            try:
                # static collective census + memory-plan rollup for the
                # pinned evidence, BOTH off one AOT lower+compile (a
                # one-time probe cost, same class as profile_compiled's);
                # a failed audit must not cost the probe its runtime
                # report
                from deepspeed_tpu.analysis.auditor import \
                    census_and_memory_engine

                census, static_memory = census_and_memory_engine(engine)
            except Exception as e:
                logger.warning(f"overlap_scheduler: static census "
                               f"unavailable ({e})")
        finally:
            # a failed probe step must still release the engine — a
            # leaked armed TraceProfiler would make a RETRIED probe fail
            # with "no capture report" (another profiler owns the
            # backend) instead of the real error.  destroy() also
            # flushes a window cut short + the telemetry exporters.
            try:
                engine.destroy()
            finally:
                topo_mod._GLOBAL_TOPOLOGY = None
        paths = (engine.telemetry.capture.reports
                 if engine.telemetry and engine.telemetry.capture
                 else [])
        if not paths:
            raise RuntimeError(
                "overlap probe produced no capture report "
                f"(output_dir={self.output_dir})")
        with open(paths[-1], "r", encoding="utf-8") as f:
            self.last_report = json.load(f)
        self.last_report["static_census"] = census
        self.last_report["static_memory"] = static_memory
        return self.last_report

    def pin(self, updates: Dict[str, Any],
            decisions: List[ScheduleDecision]) -> Dict[str, Any]:
        """→ the base config with a frozen ``step_schedule`` block."""
        cfg = copy.deepcopy(self.base_config)
        ss = dict(cfg.get("step_schedule") or {})
        ss.update(updates)
        ss["mode"] = "pinned"
        ss["probe_steps"] = self.probe_steps
        ss["overlap_threshold"] = self.overlap_threshold
        ss["decisions"] = [d.to_dict() for d in decisions]
        cfg["step_schedule"] = ss
        return cfg

    def tune(self, batch) -> Tuple[Dict[str, Any], List[ScheduleDecision]]:
        """probe → decide → pin; → (pinned config, decisions)."""
        report = self.probe(batch)
        updates, decisions = decide(report, self.last_context,
                                    overlap_threshold=self.overlap_threshold)
        for d in decisions:
            logger.info(f"overlap_scheduler: {d.decision} knobs={d.knobs} "
                        f"evidence={d.evidence}")
        return self.pin(updates, decisions), decisions


def ensure_schedule(model, config: Dict[str, Any], batch,
                    **scheduler_kwargs
                    ) -> Tuple[Dict[str, Any], List[ScheduleDecision]]:
    """Launch-path entry: honor the config's ``step_schedule.mode``.

    * ``"static"`` (default) and ``"pinned"`` pass through unchanged —
      a pinned config NEVER re-probes, which is what makes a tuned run
      reproducible.
    * ``"probe"`` runs the probe→decide→pin loop and returns the pinned
      config plus the decisions.
    """
    ss = dict((config or {}).get("step_schedule") or {})
    if ss.get("mode", "static") != "probe":
        decisions = [ScheduleDecision.from_dict(d)
                     for d in ss.get("decisions") or []]
        return config, decisions
    sched = OverlapScheduler(model, config, **scheduler_kwargs)
    return sched.tune(batch)
