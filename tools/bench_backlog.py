#!/usr/bin/env python
"""Backlog validator: every queued bench command must still run.

The BENCH_MEASURED_r*.json rounds carry ``queued_measurements_r*``
lists — on-chip commands written rounds ago, waiting for silicon.  Rows
get renamed, flags change, models get re-registered; a queued command
referencing a vanished row name would silently burn its measurement
window.  This tool re-validates the WHOLE queue against the current
tree (run from tier-1 via tests/test_telemetry.py):

- ``python bench.py`` invocations: every ``--flag`` must appear in
  bench.py, ``--row`` names must be registered in ``bench._ROWS``,
  ``--peak-entry`` indices must be inside the ladder.
- ``python tools/<script>.py`` invocations: the script must exist and
  every ``--flag`` must appear in its source.
- ``python -``/``python -c`` snippet bodies are validated leniently:
  any ``get_model_config('name')`` reference must resolve against the
  models registry.
- env-prefixed and ``for ...; do ...; done`` wrapped commands are
  unwrapped first; ``see BENCH_MEASURED_...`` cross-references must
  point at an existing round file.
- staleness: every on-chip row the run ledger flags as ``stale``
  (carried forward since r04 — telemetry/ledger.py
  ``LAST_MEASURED_ROUND``) must have a re-measurement command attached,
  and that command must itself pass the checks above.  The stale set is
  printed with its commands so the next silicon window has a ready-made
  worklist (same view as ``tools/obs_report.py``).

Exit 1 with one line per finding; exit 0 when the queue is clean (the
stale-row worklist is informational, not a finding).
"""

from __future__ import annotations

import glob
import json
import os
import re
import shlex
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# rounds before r07 predate the queued-command grammar (r04 is a
# measurement record, r05/r06 queues were drained and superseded)
ROUND_GLOB = "BENCH_MEASURED_r*.json"
FIRST_VALIDATED_ROUND = 7

_ENV_TOKEN = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*=\S*$")
_MODEL_REF = re.compile(r"get_model_config\(\s*['\"]([^'\"]+)['\"]")
_FOR_LOOP = re.compile(r"^for\s+\w+\s+in\s+[^;]+;\s*do\s+(.*?);?\s*done$")


def _bench_rows():
    """bench._ROWS / ladder length without importing jax eagerly —
    bench.py only touches the backend under --smoke, so a plain import
    from the repo root is safe and keeps the row list authoritative."""
    import bench

    return set(bench._ROWS), len(bench._PEAK_LADDER)


def _strip_comment(cmd: str) -> str:
    # queued cmds annotate with trailing "  # ..." notes; heredoc bodies
    # ('\n' present) keep their hash lines
    if "\n" in cmd:
        return cmd
    return cmd.split("  #", 1)[0].strip()


def _segments(cmd: str) -> List[str]:
    """Unwrap env prefixes / for-loops and split on top-level ``&&``."""
    out = []
    for seg in cmd.split("&&"):
        seg = seg.strip()
        m = _FOR_LOOP.match(seg)
        if m:
            seg = m.group(1).strip()
        try:
            toks = shlex.split(seg.split("\n", 1)[0])
        except ValueError:
            toks = seg.split()
        while toks and _ENV_TOKEN.match(toks[0]):
            toks = toks[1:]
        if toks:
            out.append(" ".join(toks) + ("\n" + seg.split("\n", 1)[1]
                                         if "\n" in seg else ""))
    return out


def _check_snippet(body: str, where: str, errors: List[str]) -> None:
    from deepspeed_tpu.models.registry import list_models

    known = set(list_models())
    for name in _MODEL_REF.findall(body):
        if name not in known:
            errors.append(f"{where}: snippet references unknown model "
                          f"{name!r} (known: {sorted(known)})")


def _check_bench(toks: List[str], where: str, rows, ladder_len,
                 errors: List[str]) -> None:
    src = open(os.path.join(REPO, "bench.py")).read()
    i = 0
    while i < len(toks):
        t = toks[i]
        if t == "--row":
            i += 1
            if i >= len(toks) or toks[i] not in rows:
                errors.append(f"{where}: unknown bench row "
                              f"{toks[i] if i < len(toks) else '<missing>'!r}"
                              f" (known: {sorted(rows)})")
        elif t == "--peak-entry":
            i += 1
            if i >= len(toks) or not toks[i].isdigit() \
                    or int(toks[i]) >= ladder_len:
                errors.append(f"{where}: --peak-entry index out of "
                              f"ladder range (< {ladder_len})")
        elif t.startswith("--") and t not in src:
            errors.append(f"{where}: bench.py has no flag {t!r}")
        i += 1


def _check_tool(toks: List[str], where: str, errors: List[str]) -> None:
    script = os.path.join(REPO, toks[0])
    if not os.path.exists(script):
        errors.append(f"{where}: script {toks[0]!r} does not exist")
        return
    src = open(script).read()
    for t in toks[1:]:
        if t.startswith("--") and t not in src:
            errors.append(f"{where}: {toks[0]} has no flag {t!r}")


def _check_cmd(cmd: str, where: str, rows, ladder_len,
               errors: List[str]) -> None:
    cmd = _strip_comment(cmd)
    if cmd.startswith("see "):
        ref = cmd.split()[1]
        if not os.path.exists(os.path.join(REPO, ref.split(".json")[0]
                                           + ".json")):
            errors.append(f"{where}: cross-reference {ref!r} missing")
        return
    for seg in _segments(cmd):
        toks = seg.split("\n", 1)[0].split()
        if not toks:
            continue
        if toks[0] == "git":
            continue
        if toks[0] != "python" and not toks[0].startswith("python"):
            errors.append(f"{where}: unrecognised command {toks[0]!r}")
            continue
        if len(toks) > 1 and toks[1] in ("-", "-c"):
            _check_snippet(seg, where, errors)
        elif len(toks) > 1 and toks[1] == "bench.py":
            _check_bench(toks[2:], where, rows, ladder_len, errors)
        elif len(toks) > 1 and toks[1].startswith("tools/"):
            _check_tool(toks[1:], where, errors)
        elif len(toks) == 1:
            pass  # bare "python bench.py" variants already matched above
        else:
            errors.append(f"{where}: unrecognised python target "
                          f"{toks[1]!r}")


def check_stale(rows, ladder_len, errors: List[str]):
    """Ledger staleness lint: every row still carrying an on-chip number
    measured at r04 must have a validated re-measurement command.
    Returns {row: cmd} for the worklist printout."""
    from deepspeed_tpu.telemetry import ledger

    history = ledger.load_bench_history(REPO)
    requeue = ledger.attach_requeue_cmds(
        history, ledger.collect_queued_cmds(REPO))
    for row, cmd in sorted(requeue.items()):
        where = f"stale[{row}]"
        if not cmd:
            errors.append(f"{where}: carried since "
                          f"r{ledger.LAST_MEASURED_ROUND:02d} with no "
                          f"re-measurement command attached")
            continue
        _check_cmd(cmd, where, rows, ladder_len, errors)
    return requeue


def run_all() -> List[str]:
    errors: List[str] = []
    rows, ladder_len = _bench_rows()
    seen_any = False
    for path in sorted(glob.glob(os.path.join(REPO, ROUND_GLOB))):
        fname = os.path.basename(path)
        rnum = int(re.search(r"_r(\d+)\.json$", fname).group(1))
        if rnum < FIRST_VALIDATED_ROUND:
            continue
        data = json.load(open(path))
        queued = data.get(f"queued_measurements_r{rnum:02d}")
        if not isinstance(queued, list):
            errors.append(f"{fname}: no queued_measurements_r{rnum:02d} "
                          f"list")
            continue
        for i, entry in enumerate(queued):
            where = f"{fname}[{i}]"
            if not isinstance(entry, dict) or "cmd" not in entry \
                    or "what" not in entry:
                errors.append(f"{where}: entry needs 'what' and 'cmd'")
                continue
            seen_any = True
            _check_cmd(entry["cmd"], where, rows, ladder_len, errors)
    if not seen_any:
        errors.append("no queued commands found — backlog files moved?")
    check_stale(rows, ladder_len, errors)
    return errors


def main() -> int:
    errors = run_all()
    for e in errors:
        print(e)
    rows, ladder_len = _bench_rows()
    stale = check_stale(rows, ladder_len, [])
    if stale:
        print(f"stale rows ({len(stale)} carried forward; re-measure "
              f"with):")
        for row, cmd in sorted(stale.items()):
            print(f"  {row}: {cmd}")
    n = sum(1 for _ in glob.glob(os.path.join(REPO, ROUND_GLOB)))
    print(f"bench_backlog: {len(errors)} finding(s) across {n} round "
          f"file(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
