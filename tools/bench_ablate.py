"""Ablation timings for the train step: fwd / fwd+bwd / full, attention
impls, micro-batch shapes. Run on the real chip. Not part of the suite."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(f, *args, iters=6):
    r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / iters


import jax
import jax.numpy as jnp


def main():
    from deepspeed_tpu.models import get_model_config, init_params
    from deepspeed_tpu.models import transformer as tf

    seq = 1024
    rng = np.random.default_rng(0)

    for label, kw in [
        ("flash", {}),
        ("xla-attn", {"attn_impl": "xla"}),
        ("flash-remat-none", {"remat_policy": "none"}),
    ]:
        for b in (8, 16):
            cfg = get_model_config("gpt2-350m", max_seq_len=seq, **kw)
            params = init_params(cfg, jax.random.PRNGKey(0))
            params = jax.tree.map(lambda x: x, params)  # fresh
            ids = rng.integers(0, cfg.vocab_size, size=(b, seq + 1), dtype=np.int32)
            batch = {"input_ids": jnp.asarray(ids[:, :-1]),
                     "labels": jnp.asarray(ids[:, 1:])}

            fwd = jax.jit(lambda p, bt: tf.loss_fn(p, bt, cfg))
            gfn = jax.jit(lambda p, bt: jax.value_and_grad(
                lambda pp: tf.loss_fn(pp, bt, cfg))(p))
            try:
                t_f = timeit(fwd, params, batch)
            except Exception as e:
                print(f"{label} b={b} fwd FAILED {str(e)[:80]}"); continue
            try:
                t_g = timeit(gfn, params, batch)
            except Exception as e:
                print(f"{label} b={b} fwd={b*seq/t_f:,.0f} tok/s; grad FAILED {str(e)[:80]}")
                continue
            ftok, gtok = b * seq / t_f, b * seq / t_g
            print(f"{label:18s} b={b:2d}: fwd {ftok:9,.0f} tok/s ({t_f*1e3:6.1f} ms)"
                  f" | fwd+bwd {gtok:9,.0f} tok/s ({t_g*1e3:6.1f} ms)", flush=True)


if __name__ == "__main__":
    main()
