"""On-chip micro-benchmark of the Pallas flash attention kernels at long
sequence (the KV-blocked path): fwd and fwd+bwd achieved TFLOP/s vs the
causal-attention flop count.  Quantifies kernel-level MFU separately from
the end-to-end longseq bench row (which folds in dense matmuls + remat).
Not part of the suite."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


ITERS = 8


def timeit(f, *args):
    """f must iterate ITERS times inside one jit AND reduce to a scalar
    (per-call dispatch through the axon tunnel costs ~55 ms and a
    full-array fetch downloads the buffer — either swamps the kernel)."""
    r = f(*args)
    assert getattr(r, "ndim", 0) == 0, "bench fns must reduce to a scalar"
    float(np.asarray(r))
    t0 = time.perf_counter()
    float(np.asarray(f(*args)))
    return (time.perf_counter() - t0) / ITERS


def attn_flops(b, h, s, d, causal=True):
    # scores + pv matmuls: 2 * 2 * B*H*S^2*D, halved by causal skipping
    f = 4 * b * h * s * s * d
    return f / 2 if causal else f


def main():
    # the package re-exports the flash_mha FUNCTION over the submodule
    # name — import the module itself for the _BLK_* knobs
    import importlib

    fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")

    sweep = "--sweep" in sys.argv
    blocks = [(None, None)]  # None → the shipped _choose_blocks heuristic
    if sweep:
        blocks = [(None, None), (512, 512), (512, 1024), (1024, 512),
                  (256, 1024), (1024, 1024), (256, 512)]
    for (b, h, s, d) in [(1, 16, 32768, 64), (1, 8, 32768, 128),
                         (1, 16, 8192, 64)]:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        fl = attn_flops(b, h, s, d)
        for bq, bk in blocks:
            fm._BLK_Q, fm._BLK_K = bq, bk
            try:
                from jax import lax

                @jax.jit
                def fwd(q, k, v):
                    def body(c, _):
                        return fm.flash_mha(c, k, v, True), ()

                    out, _ = lax.scan(body, q, None, length=ITERS)
                    return jnp.sum(out.astype(jnp.float32))

                t_f = timeit(fwd, q, k, v)
                gfn = jax.grad(lambda q, k, v: fm.flash_mha(
                    q, k, v, True).astype(jnp.float32).sum(),
                    argnums=(0, 1, 2))

                @jax.jit
                def grad(q, k, v):
                    # dk/dv must stay LIVE via the carry or XLA dead-code
                    # eliminates the dkv kernel and "fwd+bwd" times only
                    # fwd+dq (r04 review finding)
                    def body(carry, _):
                        c, acc = carry
                        dq, dk, dv = gfn(c, k, v)
                        acc = acc + jnp.sum(dk.astype(jnp.float32)) \
                            + jnp.sum(dv.astype(jnp.float32))
                        return (c - 1e-3 * dq.astype(c.dtype), acc), ()

                    (out, acc), _ = lax.scan(
                        body, (q, jnp.float32(0.0)), None, length=ITERS)
                    return jnp.sum(out.astype(jnp.float32)) + acc

                t_g = timeit(grad, q, k, v)
            except Exception as e:
                lab = "auto" if bq is None else f"({bq},{bk})"
                print(f"S={s} D={d} H={h} blk={lab}: FAILED "
                      f"{str(e)[:200]}")
                continue
            fl_g = fl * 3.5  # bwd ≈ 2.5x fwd (dq + dkv recompute scores)
            lab = "auto" if bq is None else f"({bq},{bk})"
            print(f"S={s} D={d} H={h} blk={lab}: "
                  f"fwd {t_f*1e3:.2f} ms = {fl/t_f/1e12:.1f} TF/s "
                  f"({fl/t_f/197e12:.1%}); fwd+bwd {t_g*1e3:.2f} ms "
                  f"= {fl_g/t_g/1e12:.1f} TF/s ({fl_g/t_g/197e12:.1%})",
                  flush=True)
        fm._BLK_Q = fm._BLK_K = None


if __name__ == "__main__":
    print(f"devices: {jax.devices()}")
    main()
