"""On-chip micro-benchmark of the Pallas flash attention kernels at long
sequence (the KV-blocked path): fwd and fwd+bwd achieved TFLOP/s vs the
causal-attention flop count.  Quantifies kernel-level MFU separately from
the end-to-end longseq bench row (which folds in dense matmuls + remat).
Not part of the suite."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(f, *args, iters=8):
    r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / iters


def attn_flops(b, h, s, d, causal=True):
    # scores + pv matmuls: 2 * 2 * B*H*S^2*D, halved by causal skipping
    f = 4 * b * h * s * s * d
    return f / 2 if causal else f


def main():
    from deepspeed_tpu.ops.pallas.flash_mha import flash_mha

    for (b, h, s, d) in [(1, 16, 32768, 64), (1, 8, 32768, 128),
                         (1, 16, 8192, 64)]:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)

        fwd = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True))
        t_f = timeit(fwd, q, k, v)
        fl = attn_flops(b, h, s, d)
        print(f"S={s} D={d} H={h}: fwd {t_f*1e3:.2f} ms "
              f"= {fl/t_f/1e12:.1f} TF/s ({fl/t_f/197e12:.1%} of peak)")

        grad = jax.jit(jax.grad(
            lambda q, k, v: flash_mha(q, k, v, causal=True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2)))
        t_g = timeit(grad, q, k, v)
        fl_g = fl * 3.5  # bwd ≈ 2.5x fwd (dq + dkv recompute scores)
        print(f"            fwd+bwd {t_g*1e3:.2f} ms "
              f"= {fl_g/t_g/1e12:.1f} TF/s ({fl_g/t_g/197e12:.1%} of peak)")


if __name__ == "__main__":
    print(f"devices: {jax.devices()}")
    main()
