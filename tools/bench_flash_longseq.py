"""On-chip micro-benchmark of the Pallas flash attention kernels at long
sequence (the KV-blocked path): fwd and fwd+bwd achieved TFLOP/s vs the
causal-attention flop count.  Quantifies kernel-level MFU separately from
the end-to-end longseq bench row (which folds in dense matmuls + remat).
Not part of the suite."""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--smoke" in sys.argv:
    # CPU plumbing check — pin the platform BEFORE any backend touch (a
    # down TPU tunnel would otherwise block forever; see bench.py)
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax
import jax.numpy as jnp

if "--smoke" in sys.argv:
    jax.config.update("jax_platforms", "cpu")


ITERS = 8


def timeit(f, *args):
    """f must iterate ITERS times inside one jit AND reduce to a scalar
    (per-call dispatch through the axon tunnel costs ~55 ms and a
    full-array fetch downloads the buffer — either swamps the kernel)."""
    r = f(*args)
    assert getattr(r, "ndim", 0) == 0, "bench fns must reduce to a scalar"
    float(np.asarray(r))
    t0 = time.perf_counter()
    float(np.asarray(f(*args)))
    return (time.perf_counter() - t0) / ITERS


def attn_flops(b, h, s, d, causal=True):
    # scores + pv matmuls: 2 * 2 * B*H*S^2*D, halved by causal skipping
    f = 4 * b * h * s * s * d
    return f / 2 if causal else f


def ring_sweep(fm, smoke: bool):
    """The queued `_RING_BLK` 512-vs-1024 sweep (ROADMAP item 2 /
    BENCH_MEASURED r06-r07): time one ring hop — a fused
    ``flash_carry_block`` online-softmax update of the (m, l, acc) carry
    against a visiting K/V block — at per-shard S_l >= 4k, d=128 GQA
    geometry, per candidate block edge.  ``--smoke`` runs a tiny shape
    through the Pallas interpreter (plumbing check only, no numbers of
    record); on-chip: ``python tools/bench_flash_longseq.py --sweep``."""
    if smoke:
        fm.INTERPRET = True
        cases = [(1, 4, 2, 256, 64)]       # b, hq, hkv, S_l, d
        blocks = [128, 256]
        hops = 2
    else:
        cases = [(1, 16, 8, 4096, 128), (1, 16, 8, 8192, 128)]
        blocks = [512, 1024]
        hops = ITERS
    neg = float(np.finfo(np.float32).min)
    for (b, hq, hkv, s_l, d) in cases:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, hq, s_l, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, hkv, s_l, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, hkv, s_l, d)), jnp.bfloat16)
        for blk in blocks:
            prev = fm._RING_BLK
            fm._RING_BLK = blk
            try:
                s_pad = fm.ring_carry_pad(s_l)
                pad = lambda x: jnp.pad(  # noqa: E731
                    x, ((0, 0), (0, 0), (0, s_pad - s_l), (0, 0)))
                qp, kp, vp = pad(q), pad(k), pad(v)

                @jax.jit
                def one(qp, kp, vp):
                    m0 = jnp.full((b, hq, s_pad, 128), neg, jnp.float32)
                    l0 = jnp.zeros((b, hq, s_pad, 128), jnp.float32)
                    a0 = jnp.zeros((b, hq, s_pad, d), jnp.float32)

                    def hop(carry, src):
                        m, l, acc = carry
                        m, l, acc = fm.flash_carry_block(
                            qp, kp, vp, m, l, acc,
                            jnp.int32((hops - 1) * s_l),  # causally live q
                            src * s_l, s_real=s_l, causal=True)
                        return (m, l, acc), None

                    (m, l, acc), _ = jax.lax.scan(
                        hop, (m0, l0, a0),
                        jnp.arange(hops, dtype=jnp.int32))
                    return jnp.sum(acc) + jnp.sum(l[..., :1]) \
                        + jnp.sum(m[..., :1])

                t = timeit(one, qp, kp, vp) / max(1, hops) * ITERS
            except Exception as e:
                print(f"ring S_l={s_l} d={d} blk={blk}: FAILED "
                      f"{str(e)[:200]}", flush=True)
                fm._RING_BLK = prev
                continue
            fm._RING_BLK = prev
            fl = attn_flops(b, hq, s_l, d, causal=False)  # one full hop
            print(f"ring S_l={s_l} d={d} hq:hkv={hq}:{hkv} blk={blk}: "
                  f"{t*1e3:.2f} ms/hop = {fl/t/1e12:.1f} TF/s "
                  f"({fl/t/197e12:.1%})", flush=True)


def bwd_sweep(fm, smoke: bool):
    """--bwd: per-hop ring BACKWARD timing (ROADMAP item 2 acceptance) —
    the fused offset-aware dq/dkv flash kernels vs the XLA einsum hop of
    the ``sequence/ring.py`` fallback, on the same fully-live causal hop,
    plus an estimated peak per-hop transient-bytes figure for each path:
    SCORE-shaped for the einsums (s/p/dp/ds fp32, 4·S_l²·hkv·rep·4 B) vs
    BLOCK-shaped for the kernels (≈4 fp32 [bq, bk] tiles per program,
    grid-sequential so they never coexist across programs).  One JSON row
    per case with the frozen keys linted by tools/telemetry_check.py
    ``RING_BWD_BENCH_KEYS``.  ``--bwd --smoke`` runs a tiny shape through
    the Pallas interpreter and asserts the fused estimate really is
    block-shaped; on-chip: ``python tools/bench_flash_longseq.py --bwd``."""
    if smoke:
        fm.INTERPRET = True
        cases = [(1, 4, 2, 256, 64)]       # b, hq, hkv, S_l, d
        hops = 2
    else:
        cases = [(1, 16, 8, 4096, 128), (1, 16, 8, 8192, 128)]
        hops = ITERS
    for (b, hq, hkv, s_l, d) in cases:
        rep = hq // hkv
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, hq, s_l, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, hkv, s_l, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, hkv, s_l, d)), jnp.bfloat16)
        do = jnp.asarray(rng.standard_normal((b, hq, s_l, d)), jnp.bfloat16)
        s_pad = fm.ring_carry_pad(s_l)
        assert s_pad == s_l, "bench cases are block-aligned"
        # q one block AHEAD of the visiting K/V block: every tile of the
        # causal hop is live — the worst-case (dense) per-hop cost
        q_off, k_off = jnp.int32(s_l), jnp.int32(0)
        neg = float(np.finfo(np.float32).min)

        # forward residuals the backward consumes: one carry hop -> o, lse
        m0 = jnp.full((b, hq, s_l, 128), neg, jnp.float32)
        l0 = jnp.zeros((b, hq, s_l, 128), jnp.float32)
        a0 = jnp.zeros((b, hq, s_l, d), jnp.float32)
        m, l, acc = jax.jit(fm.flash_carry_block, static_argnames=(
            "q_stride", "k_stride", "s_real", "sm_scale", "causal",
            "window"))(q, k, v, m0, l0, a0, q_off, k_off, s_real=s_l,
                       causal=True)
        l1 = jnp.maximum(l[..., 0], 1e-20)
        o = (acc / l1[..., None]).astype(q.dtype)
        lse = m[..., 0] + jnp.log(l1)
        lsep, deltap = fm.bwd_lane_residuals(o, do, lse, s_l)

        @jax.jit
        def fused(q, k, v, do, lsep, deltap):
            dq0 = jnp.zeros((b, hq, s_l, d), jnp.float32)
            dk0 = jnp.zeros((b, hkv, s_l, d), jnp.float32)
            dv0 = jnp.zeros((b, hkv, s_l, d), jnp.float32)

            def hop(carry, _):
                dq, dk, dv = carry
                dq = fm.flash_ring_dq_block(
                    q, k, v, do, lsep, deltap, dq, q_off, k_off,
                    s_real=s_l, causal=True)
                dk, dv = fm.flash_ring_dkv_block(
                    q, k, v, do, lsep, deltap, dk, dv, q_off, k_off,
                    s_real=s_l, causal=True)
                return (dq, dk, dv), None

            (dq, dk, dv), _ = jax.lax.scan(
                hop, (dq0, dk0, dv0), None, length=hops)
            return jnp.sum(dq) + jnp.sum(dk) + jnp.sum(dv)

        @jax.jit
        def xla(q, k, v, do, lse, o):
            # the einsum hop of sequence/ring.py _ring_bwd_xla, dense
            q5 = q.astype(jnp.float32).reshape(b, hkv, rep, s_l, d)
            do5 = do.astype(jnp.float32).reshape(b, hkv, rep, s_l, d)
            o5 = o.astype(jnp.float32).reshape(b, hkv, rep, s_l, d)
            delta = jnp.sum(do5 * o5, -1)[..., None]
            lse_ = lse.reshape(b, hkv, rep, s_l)[..., None]
            kf = k.astype(jnp.float32).swapaxes(1, 2)     # [b, s, c, d]
            vf = v.astype(jnp.float32).swapaxes(1, 2)
            scale = 1.0 / np.sqrt(d)

            def hop(carry, _):
                dq, dk, dv = carry
                s = jnp.einsum("bcgqd,bscd->bcgqs", q5, kf) * scale
                p = jnp.exp(s - lse_)
                dv_c = jnp.einsum("bcgqs,bcgqd->bscd", p, do5)
                dp = jnp.einsum("bcgqd,bscd->bcgqs", do5, vf)
                ds = p * (dp - delta) * scale
                dq_c = jnp.einsum("bcgqs,bscd->bcgqd", ds, kf)
                dk_c = jnp.einsum("bcgqs,bcgqd->bscd", ds, q5)
                return (dq + dq_c, dk + dk_c, dv + dv_c), None

            z_q = jnp.zeros((b, hkv, rep, s_l, d), jnp.float32)
            z_kv = jnp.zeros((b, s_l, hkv, d), jnp.float32)
            (dq, dk, dv), _ = jax.lax.scan(
                hop, (z_q, z_kv, z_kv), None, length=hops)
            return jnp.sum(dq) + jnp.sum(dk) + jnp.sum(dv)

        try:
            t_f = timeit(fused, q, k, v, do, lsep, deltap) \
                / max(1, hops) * ITERS
            t_x = timeit(xla, q, k, v, do, lse, o) / max(1, hops) * ITERS
        except Exception as e:
            print(f"ring bwd S_l={s_l} d={d}: FAILED {str(e)[:200]}",
                  flush=True)
            continue
        # peak fused transient = the LARGER of the two kernels' tile
        # geometries: dq tiles at the full ring edge, the grouped dkv
        # halves its q-edge under GQA (_ring_bwd_blocks)
        bq_dkv, bk = fm._ring_bwd_blocks(s_l, rep)
        bk_dq = min(fm._RING_BLK, s_l)
        bytes_fused = 4 * max(bk_dq * bk_dq, bq_dkv * bk) * 4
        bytes_xla = 4 * b * s_l * s_l * hkv * rep * 4
        row = {
            "metric": f"ring_bwd_hop_S{s_l}_d{d}_gqa{hq}:{hkv}",
            "bwd_ms_per_hop_fused": round(t_f * 1e3, 3),
            "bwd_ms_per_hop_xla": round(t_x * 1e3, 3),
            "transient_bytes_fused": bytes_fused,
            "transient_bytes_xla": bytes_xla,
            "transient_reduction": round(bytes_xla / bytes_fused, 1),
        }
        assert bytes_fused < bytes_xla, row  # block-shaped, not score-
        print(json.dumps(row), flush=True)


def main():
    # the package re-exports the flash_mha FUNCTION over the submodule
    # name — import the module itself for the _BLK_* knobs
    import importlib

    fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")

    sweep = "--sweep" in sys.argv
    smoke = "--smoke" in sys.argv
    if "--bwd" in sys.argv:
        # backward-hop mode: fused dq/dkv kernels vs the XLA einsum hop
        bwd_sweep(fm, smoke=smoke)
        return
    if sweep and smoke:
        # CPU plumbing check of the ring sweep only (the MHA sweep below
        # needs a real chip; interpreted 32k shapes would run for hours)
        ring_sweep(fm, smoke=True)
        return
    if sweep:
        ring_sweep(fm, smoke=False)
    blocks = [(None, None)]  # None → the shipped _choose_blocks heuristic
    if sweep:
        blocks = [(None, None), (512, 512), (512, 1024), (1024, 512),
                  (256, 1024), (1024, 1024), (256, 512)]
    for (b, h, s, d) in [(1, 16, 32768, 64), (1, 8, 32768, 128),
                         (1, 16, 8192, 64)]:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        fl = attn_flops(b, h, s, d)
        for bq, bk in blocks:
            fm._BLK_Q, fm._BLK_K = bq, bk
            try:
                from jax import lax

                @jax.jit
                def fwd(q, k, v):
                    def body(c, _):
                        return fm.flash_mha(c, k, v, True), ()

                    out, _ = lax.scan(body, q, None, length=ITERS)
                    return jnp.sum(out.astype(jnp.float32))

                t_f = timeit(fwd, q, k, v)
                gfn = jax.grad(lambda q, k, v: fm.flash_mha(
                    q, k, v, True).astype(jnp.float32).sum(),
                    argnums=(0, 1, 2))

                @jax.jit
                def grad(q, k, v):
                    # dk/dv must stay LIVE via the carry or XLA dead-code
                    # eliminates the dkv kernel and "fwd+bwd" times only
                    # fwd+dq (r04 review finding)
                    def body(carry, _):
                        c, acc = carry
                        dq, dk, dv = gfn(c, k, v)
                        acc = acc + jnp.sum(dk.astype(jnp.float32)) \
                            + jnp.sum(dv.astype(jnp.float32))
                        return (c - 1e-3 * dq.astype(c.dtype), acc), ()

                    (out, acc), _ = lax.scan(
                        body, (q, jnp.float32(0.0)), None, length=ITERS)
                    return jnp.sum(out.astype(jnp.float32)) + acc

                t_g = timeit(grad, q, k, v)
            except Exception as e:
                lab = "auto" if bq is None else f"({bq},{bk})"
                print(f"S={s} D={d} H={h} blk={lab}: FAILED "
                      f"{str(e)[:200]}")
                continue
            fl_g = fl * 3.5  # bwd ≈ 2.5x fwd (dq + dkv recompute scores)
            lab = "auto" if bq is None else f"({bq},{bk})"
            print(f"S={s} D={d} H={h} blk={lab}: "
                  f"fwd {t_f*1e3:.2f} ms = {fl/t_f/1e12:.1f} TF/s "
                  f"({fl/t_f/197e12:.1%}); fwd+bwd {t_g*1e3:.2f} ms "
                  f"= {fl_g/t_g/1e12:.1f} TF/s ({fl_g/t_g/197e12:.1%})",
                  flush=True)
        fm._BLK_Q = fm._BLK_K = None


if __name__ == "__main__":
    print(f"devices: {jax.devices()}")
    main()
