"""On-chip micro-benchmark of the Pallas flash attention kernels at long
sequence (the KV-blocked path): fwd and fwd+bwd achieved TFLOP/s vs the
causal-attention flop count.  Quantifies kernel-level MFU separately from
the end-to-end longseq bench row (which folds in dense matmuls + remat).
Not part of the suite."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--smoke" in sys.argv:
    # CPU plumbing check — pin the platform BEFORE any backend touch (a
    # down TPU tunnel would otherwise block forever; see bench.py)
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax
import jax.numpy as jnp

if "--smoke" in sys.argv:
    jax.config.update("jax_platforms", "cpu")


ITERS = 8


def timeit(f, *args):
    """f must iterate ITERS times inside one jit AND reduce to a scalar
    (per-call dispatch through the axon tunnel costs ~55 ms and a
    full-array fetch downloads the buffer — either swamps the kernel)."""
    r = f(*args)
    assert getattr(r, "ndim", 0) == 0, "bench fns must reduce to a scalar"
    float(np.asarray(r))
    t0 = time.perf_counter()
    float(np.asarray(f(*args)))
    return (time.perf_counter() - t0) / ITERS


def attn_flops(b, h, s, d, causal=True):
    # scores + pv matmuls: 2 * 2 * B*H*S^2*D, halved by causal skipping
    f = 4 * b * h * s * s * d
    return f / 2 if causal else f


def ring_sweep(fm, smoke: bool):
    """The queued `_RING_BLK` 512-vs-1024 sweep (ROADMAP item 2 /
    BENCH_MEASURED r06-r07): time one ring hop — a fused
    ``flash_carry_block`` online-softmax update of the (m, l, acc) carry
    against a visiting K/V block — at per-shard S_l >= 4k, d=128 GQA
    geometry, per candidate block edge.  ``--smoke`` runs a tiny shape
    through the Pallas interpreter (plumbing check only, no numbers of
    record); on-chip: ``python tools/bench_flash_longseq.py --sweep``."""
    if smoke:
        fm.INTERPRET = True
        cases = [(1, 4, 2, 256, 64)]       # b, hq, hkv, S_l, d
        blocks = [128, 256]
        hops = 2
    else:
        cases = [(1, 16, 8, 4096, 128), (1, 16, 8, 8192, 128)]
        blocks = [512, 1024]
        hops = ITERS
    neg = float(np.finfo(np.float32).min)
    for (b, hq, hkv, s_l, d) in cases:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, hq, s_l, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, hkv, s_l, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, hkv, s_l, d)), jnp.bfloat16)
        for blk in blocks:
            prev = fm._RING_BLK
            fm._RING_BLK = blk
            try:
                s_pad = fm.ring_carry_pad(s_l)
                pad = lambda x: jnp.pad(  # noqa: E731
                    x, ((0, 0), (0, 0), (0, s_pad - s_l), (0, 0)))
                qp, kp, vp = pad(q), pad(k), pad(v)

                @jax.jit
                def one(qp, kp, vp):
                    m0 = jnp.full((b, hq, s_pad, 128), neg, jnp.float32)
                    l0 = jnp.zeros((b, hq, s_pad, 128), jnp.float32)
                    a0 = jnp.zeros((b, hq, s_pad, d), jnp.float32)

                    def hop(carry, src):
                        m, l, acc = carry
                        m, l, acc = fm.flash_carry_block(
                            qp, kp, vp, m, l, acc,
                            jnp.int32((hops - 1) * s_l),  # causally live q
                            src * s_l, s_real=s_l, causal=True)
                        return (m, l, acc), None

                    (m, l, acc), _ = jax.lax.scan(
                        hop, (m0, l0, a0),
                        jnp.arange(hops, dtype=jnp.int32))
                    return jnp.sum(acc) + jnp.sum(l[..., :1]) \
                        + jnp.sum(m[..., :1])

                t = timeit(one, qp, kp, vp) / max(1, hops) * ITERS
            except Exception as e:
                print(f"ring S_l={s_l} d={d} blk={blk}: FAILED "
                      f"{str(e)[:200]}", flush=True)
                fm._RING_BLK = prev
                continue
            fm._RING_BLK = prev
            fl = attn_flops(b, hq, s_l, d, causal=False)  # one full hop
            print(f"ring S_l={s_l} d={d} hq:hkv={hq}:{hkv} blk={blk}: "
                  f"{t*1e3:.2f} ms/hop = {fl/t/1e12:.1f} TF/s "
                  f"({fl/t/197e12:.1%})", flush=True)


def main():
    # the package re-exports the flash_mha FUNCTION over the submodule
    # name — import the module itself for the _BLK_* knobs
    import importlib

    fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")

    sweep = "--sweep" in sys.argv
    smoke = "--smoke" in sys.argv
    if sweep and smoke:
        # CPU plumbing check of the ring sweep only (the MHA sweep below
        # needs a real chip; interpreted 32k shapes would run for hours)
        ring_sweep(fm, smoke=True)
        return
    if sweep:
        ring_sweep(fm, smoke=False)
    blocks = [(None, None)]  # None → the shipped _choose_blocks heuristic
    if sweep:
        blocks = [(None, None), (512, 512), (512, 1024), (1024, 512),
                  (256, 1024), (1024, 1024), (256, 512)]
    for (b, h, s, d) in [(1, 16, 32768, 64), (1, 8, 32768, 128),
                         (1, 16, 8192, 64)]:
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        fl = attn_flops(b, h, s, d)
        for bq, bk in blocks:
            fm._BLK_Q, fm._BLK_K = bq, bk
            try:
                from jax import lax

                @jax.jit
                def fwd(q, k, v):
                    def body(c, _):
                        return fm.flash_mha(c, k, v, True), ()

                    out, _ = lax.scan(body, q, None, length=ITERS)
                    return jnp.sum(out.astype(jnp.float32))

                t_f = timeit(fwd, q, k, v)
                gfn = jax.grad(lambda q, k, v: fm.flash_mha(
                    q, k, v, True).astype(jnp.float32).sum(),
                    argnums=(0, 1, 2))

                @jax.jit
                def grad(q, k, v):
                    # dk/dv must stay LIVE via the carry or XLA dead-code
                    # eliminates the dkv kernel and "fwd+bwd" times only
                    # fwd+dq (r04 review finding)
                    def body(carry, _):
                        c, acc = carry
                        dq, dk, dv = gfn(c, k, v)
                        acc = acc + jnp.sum(dk.astype(jnp.float32)) \
                            + jnp.sum(dv.astype(jnp.float32))
                        return (c - 1e-3 * dq.astype(c.dtype), acc), ()

                    (out, acc), _ = lax.scan(
                        body, (q, jnp.float32(0.0)), None, length=ITERS)
                    return jnp.sum(out.astype(jnp.float32)) + acc

                t_g = timeit(grad, q, k, v)
            except Exception as e:
                lab = "auto" if bq is None else f"({bq},{bk})"
                print(f"S={s} D={d} H={h} blk={lab}: FAILED "
                      f"{str(e)[:200]}")
                continue
            fl_g = fl * 3.5  # bwd ≈ 2.5x fwd (dq + dkv recompute scores)
            lab = "auto" if bq is None else f"({bq},{bk})"
            print(f"S={s} D={d} H={h} blk={lab}: "
                  f"fwd {t_f*1e3:.2f} ms = {fl/t_f/1e12:.1f} TF/s "
                  f"({fl/t_f/197e12:.1%}); fwd+bwd {t_g*1e3:.2f} ms "
                  f"= {fl_g/t_g/1e12:.1f} TF/s ({fl_g/t_g/197e12:.1%})",
                  flush=True)
        fm._BLK_Q = fm._BLK_K = None


if __name__ == "__main__":
    print(f"devices: {jax.devices()}")
    main()
