#!/usr/bin/env python
"""On-chip Domino overlap measurement: capture an XPlane trace of the
tensor-parallel forward with and without Domino batch chunking and report
how much collective time XLA hid under compute.

Ref claim: blogs/deepspeed-domino/README.md:126 — Domino hides 50-100% of
the TP communication.  On TPU the overlap comes from giving XLA
independent per-chunk chains (runtime/domino.py); this tool turns the
indirect compile-level evidence (test_autotp_domino.py — separate
per-chunk psums) into a measured on-device overlap fraction.

NEEDS >= 2 real TPU devices (a 1-chip mesh has no TP collective to
measure — the current axon tunnel exposes one chip, so this runs when a
multi-chip slice is attached).  Usage:

    python tools/domino_overlap.py [--chunks 2] [--steps 8] [--assert-min 0.3]

Prints one JSON line per variant and a final comparison line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunks", type=int, default=2)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--assert-min", type=float, default=None,
                    help="exit 1 unless domino overlap >= this fraction")
    ap.add_argument("--device-substr", default="TPU")
    args = ap.parse_args()

    from deepspeed_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    import jax
    import numpy as np

    if len(jax.devices()) < 2:
        print(json.dumps({"error": "domino overlap needs >= 2 devices "
                                   f"(have {len(jax.devices())}); the TP "
                                   "collective does not exist on one chip"}))
        return 2

    import jax.numpy as jnp

    from deepspeed_tpu.models import get_model_config, init_params
    from deepspeed_tpu.models import transformer as tf_model
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.domino import domino_forward
    from deepspeed_tpu.utils.xplane import analyze_logdir

    n = len(jax.devices())
    topo = MeshTopology({"tensor": n})
    set_topology(topo)
    cfg = get_model_config("llama-tiny", hidden_size=1024,
                           intermediate_size=2816, num_layers=4,
                           num_heads=16, num_kv_heads=16, max_seq_len=512)
    params = init_params(cfg, jax.random.PRNGKey(0))
    from deepspeed_tpu.parallel.sharding import ShardingRules

    params = jax.device_put(
        params, ShardingRules(topo, zero_stage=0).tree_shardings(params))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(8, 512)), jnp.int32)

    def run(label, fn):
        out = fn(params, ids)          # compile outside the capture
        float(np.asarray(out.sum()))
        logdir = tempfile.mkdtemp(prefix=f"domino_{label}_")
        jax.profiler.start_trace(logdir)
        for _ in range(args.steps):
            out = fn(params, ids)
        float(np.asarray(out.sum()))   # hard device drain
        jax.profiler.stop_trace()
        stats = analyze_logdir(logdir, args.device_substr)
        print(json.dumps({"variant": label, **stats}))
        return stats

    plain = jax.jit(lambda p, i: tf_model.forward(p, i, cfg))
    domino = jax.jit(lambda p, i: domino_forward(p, i, cfg,
                                                 n_chunks=args.chunks))
    s_plain = run("plain_tp", plain)
    s_domino = run(f"domino_{args.chunks}chunk", domino)

    result = {
        "metric": "domino_overlap_fraction",
        "plain": s_plain.get("mean_overlap_fraction"),
        "domino": s_domino.get("mean_overlap_fraction"),
    }
    print(json.dumps(result))
    if args.assert_min is not None:
        ok = (s_domino.get("mean_overlap_fraction") or 0) >= args.assert_min
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
