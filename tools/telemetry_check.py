#!/usr/bin/env python
"""Telemetry docs/schema lint (runs in the tier-1 suite via
tests/test_telemetry.py, and standalone: ``python tools/telemetry_check.py``).

Checks:
1. every MonitorMaster tag the telemetry bridge or the serving metrics
   can emit appears in docs/OBSERVABILITY.md;
2. every Prometheus metric name the train/serving registries create
   appears in the docs;
3. the StepRecord JSONL schema is stable: ``schema: 1``, keys sorted in
   the serialized line, and the top-level key set matches the frozen
   list below (update EXPECTED_RECORD_KEYS *and the docs table* in the
   same commit as any schema change).
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# frozen with schema version 1 — tools/telemetry_check.py is the tripwire
EXPECTED_SCHEMA_VERSION = 1
EXPECTED_RECORD_KEYS = [
    "achieved_flops_per_sec", "comm", "flops_per_step", "flops_source",
    "goodput", "grad_norm", "hbm", "kind", "loss", "loss_scale", "lr",
    "mfu", "peak_flops_per_sec", "schema", "serving", "skipped", "step",
    "tokens", "tokens_per_sec", "wall_time_s",
]


def _exported_monitor_tags() -> List[str]:
    from deepspeed_tpu.serving.metrics import ServingMetrics
    from deepspeed_tpu.telemetry import EXPORT_TAGS

    serving_tags = [tag for tag, _, _ in ServingMetrics().events(0)]
    return sorted(set(EXPORT_TAGS) | set(serving_tags))


def _registry_metric_names() -> List[str]:
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving.metrics import ServingMetrics
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(enabled=True))
    ServingMetrics(registry=tel.registry)
    return [m.name for m in tel.registry.collect()]


def check_tags_documented(docs_path: str = DOCS) -> List[str]:
    """Every exported tag / metric name must appear in the docs tables.
    Suffix-flattened serving distribution tags (serving/ttft_p50 …) are
    accepted via their documented `serving/ttft_*` wildcard row."""
    errors = []
    try:
        with open(docs_path, "r", encoding="utf-8") as f:
            docs = f.read()
    except OSError as e:
        return [f"cannot read {docs_path}: {e}"]
    for tag in _exported_monitor_tags():
        base = tag.rsplit("_", 1)[0]
        if tag not in docs and f"{base}_*" not in docs:
            errors.append(f"monitor tag {tag!r} not documented in "
                          f"{os.path.basename(docs_path)}")
    for name in _registry_metric_names():
        if name not in docs:
            errors.append(f"prometheus metric {name!r} not documented")
    return errors


def check_schema() -> List[str]:
    """JSONL schema stability: versioned, sorted, frozen key set."""
    from deepspeed_tpu.telemetry import StepRecord, record_keys

    errors = []
    rec = StepRecord(step=1, wall_time_s=0.5, tokens=100,
                     flops_per_step=1e9, peak_flops_per_sec=1e12)
    d = json.loads(rec.to_json())
    if d.get("schema") != EXPECTED_SCHEMA_VERSION:
        errors.append(f"schema field is {d.get('schema')!r}, expected "
                      f"{EXPECTED_SCHEMA_VERSION}")
    keys = list(d.keys())
    if keys != sorted(keys):
        errors.append("JSONL keys are not sorted in serialized output")
    if sorted(keys) != EXPECTED_RECORD_KEYS:
        errors.append(
            "StepRecord key set drifted from the frozen schema: "
            f"extra={sorted(set(keys) - set(EXPECTED_RECORD_KEYS))}, "
            f"missing={sorted(set(EXPECTED_RECORD_KEYS) - set(keys))} — "
            "bump SCHEMA_VERSION and update EXPECTED_RECORD_KEYS + docs")
    if record_keys() != EXPECTED_RECORD_KEYS:
        errors.append("telemetry.record.record_keys() disagrees with the "
                      "frozen key list")
    # mfu/goodput invariants the docs promise
    if not (0.0 < d["mfu"] <= 1.0):
        errors.append(f"sample record mfu {d['mfu']} outside (0, 1]")
    return errors


def run_all() -> List[str]:
    return check_tags_documented() + check_schema()


def main() -> int:
    errors = run_all()
    for e in errors:
        print(f"telemetry_check: ERROR: {e}", file=sys.stderr)
    if not errors:
        print("telemetry_check: OK")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
