#!/usr/bin/env python
"""Telemetry docs/schema lint (runs in the tier-1 suite via
tests/test_telemetry.py, and standalone: ``python tools/telemetry_check.py``).

Checks:
1. every MonitorMaster tag the telemetry bridge or the serving metrics
   can emit appears in docs/OBSERVABILITY.md;
2. every Prometheus metric name the train/serving registries create
   appears in the docs;
3. the StepRecord JSONL schema is stable: ``schema: 1``, keys sorted in
   the serialized line, and the top-level key set matches the frozen
   list below (update EXPECTED_RECORD_KEYS *and the docs table* in the
   same commit as any schema change);
4. the tracing vocabulary is stable and documented: span / instant-event
   names (telemetry/tracing.py) and flight-recorder bundle reasons
   (telemetry/flight.py) match the frozen lists below AND appear in the
   docs span table;
5. an exported trace is well-formed Chrome trace-event JSON — a sample
   trace covering every span/event name is generated and validated
   (``validate_chrome_trace`` is also importable for ad-hoc files).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Any, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the shared frozen-vocabulary engine (deepspeed_tpu/analysis/vocab.py):
# every "frozen list == module list, names documented, bench keys
# emitted" contract below is ONE VocabSpec registration, shared with
# tools/graft_lint.py
from deepspeed_tpu.analysis.vocab import VocabSpec  # noqa: E402
from deepspeed_tpu.analysis.vocab import check_all as _vocab_check  # noqa: E402

DOCS = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# frozen with schema version 3 (v2 added offload_overlap_fraction for
# the chunked host-optimizer pipeline; v3 added run_id, the run-ledger
# stitching key) — telemetry_check is the tripwire
EXPECTED_SCHEMA_VERSION = 3
EXPECTED_RECORD_KEYS = [
    "achieved_flops_per_sec", "comm", "flops_per_step", "flops_source",
    "goodput", "grad_norm", "hbm", "kind", "loss", "loss_scale", "lr",
    "mfu", "offload_overlap_fraction", "peak_flops_per_sec", "run_id",
    "schema", "serving", "skipped", "step", "tokens", "tokens_per_sec",
    "wall_time_s",
]

# frozen tracing vocabulary (telemetry/tracing.py SPAN_NAMES/EVENT_NAMES
# and telemetry/flight.py FLIGHT_REASONS must match, and every name must
# appear in the docs span table — same contract as the record keys)
EXPECTED_SPAN_NAMES = [
    "fleet.sample",
    "offload.d2h", "offload.h2d", "offload.host_step",
    "recovery.outage", "router.leg", "router.request",
    "serve.admission_block", "serve.decode", "serve.handoff",
    "serve.prefill", "serve.queue_wait", "serve.request", "serve.step",
    "spec.draft", "spec.verify",
    "train.data_ingest", "train.dispatch", "train.step", "train.sync",
    "train.telemetry", "v2.ragged_step",
]
EXPECTED_EVENT_NAMES = [
    "chaos.inject", "fleet.brownout", "fleet.heal",
    "recovery.detected", "recovery.replan", "recovery.restart",
    "recovery.resumed", "router.dispatch", "router.failover", "serve.emit",
    "serve.enqueue", "serve.finish", "serve.first_token", "serve.preempt",
    "serve.prefix_hit", "slo.violation", "spec.accept", "watchdog.fire",
]
EXPECTED_FLIGHT_REASONS = ["watchdog", "serve_crash", "engine_crash",
                           "manual", "recovery", "fleet"]

# frozen quantized-collective comm-op vocabulary (comm/quantized.py
# QUANT_COMM_OPS): every wire movement of the quantized ZeRO collectives
# is recorded in CommsLogger — and therefore surfaces in the StepRecord
# `comm` field — under one of these names.  Each must be documented in
# docs/QUANTIZED_COMM.md; the bench comm-quant row keys below must appear
# both in bench.py (so the lint trips when the row drifts) and the docs.
QUANT_DOCS = os.path.join(REPO, "docs", "QUANTIZED_COMM.md")
EXPECTED_QUANT_COMM_OPS = ["quant_all_gather", "quant_reduce_scatter"]
QUANT_BENCH_KEYS = ["grad_reduce_bytes_fp32", "grad_reduce_bytes_quant",
                    "bytes_reduction", "loss_delta"]

# frozen ring bench-row vocabulary (same contract as QUANT_BENCH_KEYS):
# the longseq_ring row keys (bench.py) and the fused-backward hop keys
# (tools/bench_flash_longseq.py --bwd) must each be emitted by their
# bench source AND documented in the docs/RING_ATTENTION.md key table —
# the lint trips when either side drifts.
RING_DOCS = os.path.join(REPO, "docs", "RING_ATTENTION.md")
RING_BENCH_KEYS = ["mfu", "placement", "ring_backward", "vs_baseline",
                   "ring_wire_bytes_fp32", "ring_wire_bytes_quant",
                   "ring_wire_reduction", "ring_loss_delta"]
RING_BWD_BENCH_KEYS = ["bwd_ms_per_hop_fused", "bwd_ms_per_hop_xla",
                       "transient_bytes_fused", "transient_bytes_xla",
                       "transient_reduction"]

# frozen overlap-scheduler vocabulary (autotuning/overlap_scheduler.py;
# docs/AUTOTUNING.md): decision names and evidence keys must match the
# module AND be documented; the step_schedule config keys must be
# documented; the autosched bench row keys must be emitted by bench.py
# and documented; and the capture-report keys the scheduler consumes
# (telemetry/capture.py) must be documented too.
AUTOTUNING_DOCS = os.path.join(REPO, "docs", "AUTOTUNING.md")
EXPECTED_SCHEDULE_DECISIONS = ["decomposed_update", "fused_gather_matmul",
                               "noop", "ring_interleave", "zero3_prefetch"]
EXPECTED_EVIDENCE_KEYS = ["dominant_collective", "exposed_comm_ms",
                          "overlap_fraction", "overlap_source",
                          "probe_step", "static_census", "static_memory"]
EXPECTED_STEP_SCHEDULE_KEYS = [
    "decisions", "fused_gather_matmul", "fused_reduce_scatter",
    "gather_prefetch_depth", "mode", "overlap_threshold",
    "param_persistence_threshold", "prefetch_bucket_size", "probe_steps",
    "ring_interleave", "weight_update",
]
AUTOSCHED_BENCH_KEYS = ["mfu_static", "mfu_tuned", "exposed_comm_ms",
                        "schedule_decision", "fused_gather_loss_delta",
                        "fused_gather_wire_bytes"]
CAPTURE_REPORT_SCHED_KEYS = ["dominant_collective", "exposed_ms",
                             "overlap_estimate", "spans", "step"]

# frozen multi-replica serving vocabulary (same contract): the
# serve_load_multi bench row keys must be emitted by bench.py and
# documented in docs/SERVING.md; every router-tier Prometheus metric
# (RouterMetrics over a fresh registry; per-replica counters normalized
# to their documented `router_routed_r*_total` wildcard) must appear in
# docs/SERVING.md too.
SERVING_DOCS = os.path.join(REPO, "docs", "SERVING.md")
SERVE_MULTI_BENCH_KEYS = ["agg_tokens_per_sec", "ttft_p95_ms",
                          "prefix_hit_rate", "prefill_tokens_saved"]

# frozen disaggregated-serving vocabulary (serving/disagg.py;
# docs/SERVING.md "Disaggregated tiers & speculative decoding"): the
# serve_disagg bench row keys, the scenario load generator's traffic-mix
# names (bench.py SCENARIO_MIXES), and the replica tier names must each
# match their module, be documented, and (for bench keys) be literally
# emitted by bench.py.
DISAGG_BENCH_KEYS = ["agg_tokens_per_sec_disagg",
                     "agg_tokens_per_sec_homog", "ttft_p95_ms_disagg",
                     "ttft_p95_ms_homog", "tpot_p95_ms_disagg",
                     "tpot_p95_ms_homog", "handoff_ms_p95",
                     "handoff_bytes_per_req", "spec_accept_rate",
                     "scenario_mix", "slo", "fleet_jsonl"]
EXPECTED_SCENARIO_MIXES = ["burst", "session_heavy",
                           "shared_system_prompt",
                           "long_prompt_short_decode"]
EXPECTED_REPLICA_TIERS = ["prefill", "decode", "unified"]

# frozen static-graph-audit vocabulary (deepspeed_tpu/analysis/report.py;
# docs/STATIC_ANALYSIS.md): finding kinds, severities, and the audit
# report's frozen key sets — same tripwire contract as the StepRecord
# schema, linted through the shared VocabSpec engine.
STATIC_DOCS = os.path.join(REPO, "docs", "STATIC_ANALYSIS.md")
EXPECTED_FINDING_KINDS = [
    "collective_mismatch", "donation_miss", "dtype_promotion",
    "host_callback", "implicit_resharding", "model_drift",
    "peak_regression", "recompile_hazard", "remat_miss",
    "seam_violation", "unsharded_transient", "wire_dtype_mismatch",
]
EXPECTED_AUDIT_SEVERITIES = ["info", "warning", "high"]
EXPECTED_AUDIT_REPORT_KEYS = ["backend", "census", "donation", "findings",
                              "label", "num_partitions", "schema"]
EXPECTED_AUDIT_CENSUS_KEYS = ["count", "dtype", "group_size", "kind",
                              "payload_bytes", "wire_bytes"]
EXPECTED_AUDIT_FINDING_KEYS = ["detail", "fingerprint", "kind", "message",
                               "severity", "where"]
EXPECTED_AUDIT_DONATION_KEYS = ["aliased", "declared", "missed",
                                "missed_bytes"]

# frozen memory-plan-audit vocabulary (analysis/report.py MemoryAuditReport;
# docs/STATIC_ANALYSIS.md): report/totals/buffer/budget/calibration key
# sets and the buffer-classification classes, plus the peak_params
# ladder-prediction bench keys — same tripwire contract as the graph
# audit schema.
EXPECTED_MEMORY_REPORT_KEYS = ["backend", "budget", "buffers",
                               "calibration", "class_bytes", "findings",
                               "label", "num_partitions", "schema",
                               "totals"]
EXPECTED_MEMORY_TOTALS_KEYS = ["alias_bytes", "argument_bytes",
                               "generated_code_bytes", "output_bytes",
                               "peak_bytes", "temp_bytes"]
EXPECTED_BUFFER_KEYS = ["bytes", "category", "dtype", "op", "shape"]
EXPECTED_MEMORY_CLASSES = ["activations", "grads", "opt_state", "other",
                           "params", "transients"]
EXPECTED_BUDGET_KEYS = ["bucketed_peak_bytes", "budget_bytes",
                        "peak_bytes"]
EXPECTED_CALIBRATION_KEYS = ["analytic_bytes", "measured_bytes", "ratio"]
MEMORY_BENCH_KEYS = ["predicted_peak_bytes", "predicted_fit"]

# frozen host-tiered offload vocabulary (runtime/offload.py
# ChunkedHostOptimizer + nvme/chunk_store.py; docs/OFFLOAD.md): the
# peak_params ladder's measured per-rung host keys must be emitted by
# bench.py and documented, and the chunked config knobs must be real
# OffloadOptimizerConfig fields documented in the offload doc — same
# tripwire contract as every other vocabulary.
OFFLOAD_DOCS = os.path.join(REPO, "docs", "OFFLOAD.md")
OFFLOAD_BENCH_KEYS = ["host_peak_bytes", "offload_overlap_fraction"]
OFFLOAD_CONFIG_KEYS = ["buffer_count", "chunk_bytes", "nvme_path",
                       "working_set_bytes"]

# frozen recovery vocabulary (resilience/supervisor.py RECOVERY_STATES;
# docs/ELASTICITY.md): the supervisor's state machine and the chaos
# bench row keys follow the same contract as every other vocabulary —
# frozen list matches the module, every name documented, bench keys
# literally emitted by bench.py.
ELASTICITY_DOCS = os.path.join(REPO, "docs", "ELASTICITY.md")
EXPECTED_RECOVERY_STATES = ["running", "detected", "dumped", "stopped",
                            "replanned", "restarted", "resumed", "failed"]
CHAOS_BENCH_KEYS = ["recovery_s", "loss_gap", "goodput_after",
                    "serve_ttft_p99_ms", "failovers", "regrown"]

# frozen plan-compiler vocabulary (deepspeed_tpu/planner; docs/PLANNER.md):
# the per-candidate evidence keys the planner pins, the link classes its
# cost model prices, the offload tier ladder it enumerates, and the
# plan_validate bench-row keys all follow the standard contract — frozen
# list matches the module, every name documented, bench keys literally
# emitted by bench.py.
PLANNER_DOCS = os.path.join(REPO, "docs", "PLANNER.md")
EXPECTED_PLAN_EVIDENCE_KEYS = [
    "census", "census_mode", "dominant_class", "dominant_cost_term",
    "overlap_fraction", "predicted_peak_bytes", "predicted_step_ms",
    "wire_bytes_total",
]
EXPECTED_LINK_CLASSES = ["ici", "dcn", "pcie", "nvme"]
EXPECTED_OFFLOAD_TIER_NAMES = ["none", "opt_cpu", "cpu", "cpu_chunked",
                               "nvme_chunked", "nvme"]
PLAN_BENCH_KEYS = ["plan_validate_known_good_top3", "known_good_ranks",
                   "proposed_6_7b", "pruned_6_7b", "evidence_keys_ok"]

# frozen fleet-observability vocabulary (serving/fleet.py TierSnapshot,
# telemetry/slo.py SLO ledger, serving/disagg.py request timelines;
# docs/OBSERVABILITY.md "Fleet snapshots & SLO ledger"): snapshot keys,
# SLO block/scenario/ledger/target keys, and stitched-timeline keys each
# follow the standard contract — frozen list matches the module, every
# key documented, and the serve_disagg `slo`/`fleet_jsonl` row keys are
# literally emitted by bench.py (they also ride in DISAGG_BENCH_KEYS).
# Per-tier Prometheus gauges are documented via their `fleet_*_<key>`
# wildcard rows (tiers substitute into the `*`).
EXPECTED_TIER_SNAPSHOT_SCHEMA = 2      # v2 added run_id (run ledger)
EXPECTED_TIER_SNAPSHOT_KEYS = [
    "evictable_headroom_blocks", "handoff_bytes_per_sec",
    "handoffs_per_sec", "kv_utilization", "prefix_hit_rate",
    "queue_depth", "queue_wait_p50_ms", "queue_wait_p95_ms",
    "queue_wait_p99_ms", "replicas_alive", "run_id", "running", "schema",
    "slo_violation", "spec_accept_rate", "tick", "tier",
    "tokens_per_sec", "tpot_p50_ms", "tpot_p95_ms", "tpot_p99_ms", "ts",
    "ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
]
EXPECTED_SLO_TARGET_KEYS = ["queue_wait_p95_ms", "tpot_p95_ms",
                            "ttft_p95_ms"]
EXPECTED_SLO_BLOCK_KEYS = ["attainment", "by_scenario",
                           "error_budget_burn", "objective", "targets",
                           "violations"]
EXPECTED_SLO_SCENARIO_KEYS = ["attainment", "n", "tpot_attainment",
                              "ttft_attainment", "violations"]
EXPECTED_SLO_LEDGER_KEYS = ["attainment", "error_budget_burn", "ticks",
                            "violations"]
EXPECTED_TIMELINE_KEYS = ["decode_ms", "failovers", "handoff_bytes",
                          "handoff_ms", "prefill_ms", "total_ms",
                          "trace_id", "uid"]

# frozen run-ledger vocabulary (telemetry/ledger.py; docs/OBSERVABILITY.md
# "Run ledger & regression sentinel"): manifest / rollup / finding /
# anomaly / drift key sets, the sentinel verdicts, and the anomaly kinds
# each follow the standard contract — frozen list matches the module,
# every name documented, and bench.py literally stamps the run_id +
# manifest keys into every row.
EXPECTED_LEDGER_SCHEMA = 1
EXPECTED_MANIFEST_KEYS = ["artifacts", "created_utc", "ledger_schema",
                          "row", "run_id", "schema_versions", "smoke"]
EXPECTED_MANIFEST_ARTIFACT_KEYS = ["fleet_jsonl", "flight_dir",
                                   "resolved_config", "slo",
                                   "telemetry_jsonl", "trace_json"]
EXPECTED_ROLLUP_KEYS = ["error", "metric", "recovery", "round", "row",
                        "run_id", "serve", "smoke", "source", "stale",
                        "train", "unit", "value", "vs_baseline"]
EXPECTED_ROLLUP_TRAIN_KEYS = ["comm_bytes_by_collective", "goodput",
                              "hbm_peak_bytes", "mfu",
                              "offload_overlap_fraction",
                              "step_time_p50_ms", "step_time_p95_ms",
                              "tokens_per_sec"]
EXPECTED_ROLLUP_SERVE_KEYS = ["error_budget_burn", "handoff_bytes_per_req",
                              "prefix_hit_rate", "queue_wait_p95_ms",
                              "slo_attainment", "spec_accept_rate",
                              "tokens_per_sec", "tpot_p50_ms",
                              "tpot_p95_ms", "ttft_p50_ms", "ttft_p95_ms"]
EXPECTED_ROLLUP_RECOVERY_KEYS = ["goodput_after", "loss_gap", "outage_s"]
EXPECTED_VERDICTS = ["flat", "improved", "missing", "new", "regressed",
                     "stale"]

# frozen chaos / self-healing vocabulary (resilience/chaos.py fault
# kinds + injection points, serving/supervisor.py health states,
# serving/admission.py brownout ladder; docs/SERVING.md "Fault injection
# & self-healing"): each frozen list matches its module, every name is
# documented, and the chaos_serve bench row literally emits the frozen
# keys — the standard vocabulary contract.
EXPECTED_FAULT_KINDS = ["admission_storm", "cancel_storm", "handoff_fail",
                        "replica_crash", "replica_hang", "slow_replica"]
EXPECTED_INJECTION_POINTS = ["engine.step", "router.dispatch",
                             "server.handoff", "server.step", "train.step"]
EXPECTED_HEALTH_STATES = ["healthy", "suspect", "stuck", "straggler",
                          "dead", "quarantined", "respawned", "retired"]
EXPECTED_BROWNOUT_LEVELS = ["normal", "shed_speculation", "cap_decode",
                            "shed_low_priority", "reject_new"]
CHAOS_SERVE_BENCH_KEYS = ["faults_injected", "completed_chaos",
                          "shed_chaos", "failed_chaos", "heals",
                          "time_to_heal_s", "collapses", "restores",
                          "bit_identical", "brownout_peak",
                          "slo_violations_curve"]
EXPECTED_ANOMALY_KINDS = ["goodput_gap", "heal_latency", "mfu_cliff",
                          "slo_burn_spike", "step_time_spike"]
EXPECTED_ANOMALY_KEYS = ["flight_bundle", "kind", "run_id", "step",
                         "threshold", "tier", "trace_span", "value"]
EXPECTED_OBS_FINDING_KEYS = ["baseline", "current", "delta", "fingerprint",
                             "metric", "requeue_cmd", "row", "verdict"]
EXPECTED_DRIFT_KEYS = ["actual", "metric", "predicted", "ratio", "row"]
LEDGER_BENCH_KEYS = ["run_id", "manifest"]


def _exported_monitor_tags() -> List[str]:
    from deepspeed_tpu.serving.metrics import ServingMetrics
    from deepspeed_tpu.telemetry import EXPORT_TAGS

    serving_tags = [tag for tag, _, _ in ServingMetrics().events(0)]
    return sorted(set(EXPORT_TAGS) | set(serving_tags))


def _registry_metric_names() -> List[str]:
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving.metrics import ServingMetrics
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(enabled=True))
    ServingMetrics(registry=tel.registry)
    return [m.name for m in tel.registry.collect()]


def check_tags_documented(docs_path: str = DOCS) -> List[str]:
    """Every exported tag / metric name must appear in the docs tables.
    Suffix-flattened serving distribution tags (serving/ttft_p50 …) are
    accepted via their documented `serving/ttft_*` wildcard row."""
    errors = []
    try:
        with open(docs_path, "r", encoding="utf-8") as f:
            docs = f.read()
    except OSError as e:
        return [f"cannot read {docs_path}: {e}"]
    for tag in _exported_monitor_tags():
        base = tag.rsplit("_", 1)[0]
        if tag not in docs and f"{base}_*" not in docs:
            errors.append(f"monitor tag {tag!r} not documented in "
                          f"{os.path.basename(docs_path)}")
    for name in _registry_metric_names():
        if name not in docs:
            errors.append(f"prometheus metric {name!r} not documented")
    return errors


def check_schema() -> List[str]:
    """JSONL schema stability: versioned, sorted, frozen key set."""
    from deepspeed_tpu.telemetry import StepRecord, record_keys

    errors = []
    rec = StepRecord(step=1, wall_time_s=0.5, tokens=100,
                     flops_per_step=1e9, peak_flops_per_sec=1e12)
    d = json.loads(rec.to_json())
    if d.get("schema") != EXPECTED_SCHEMA_VERSION:
        errors.append(f"schema field is {d.get('schema')!r}, expected "
                      f"{EXPECTED_SCHEMA_VERSION}")
    keys = list(d.keys())
    if keys != sorted(keys):
        errors.append("JSONL keys are not sorted in serialized output")
    if sorted(keys) != EXPECTED_RECORD_KEYS:
        errors.append(
            "StepRecord key set drifted from the frozen schema: "
            f"extra={sorted(set(keys) - set(EXPECTED_RECORD_KEYS))}, "
            f"missing={sorted(set(EXPECTED_RECORD_KEYS) - set(keys))} — "
            "bump SCHEMA_VERSION and update EXPECTED_RECORD_KEYS + docs")
    if record_keys() != EXPECTED_RECORD_KEYS:
        errors.append("telemetry.record.record_keys() disagrees with the "
                      "frozen key list")
    # mfu/goodput invariants the docs promise
    if not (0.0 < d["mfu"] <= 1.0):
        errors.append(f"sample record mfu {d['mfu']} outside (0, 1]")
    return errors


def check_span_names() -> List[str]:
    """Tracing vocabulary: frozen lists match the modules, every name is
    in the docs span table."""
    from deepspeed_tpu.telemetry.flight import FLIGHT_REASONS
    from deepspeed_tpu.telemetry.tracing import EVENT_NAMES, SPAN_NAMES

    errors = []
    if sorted(SPAN_NAMES) != sorted(EXPECTED_SPAN_NAMES):
        errors.append(
            "tracing.SPAN_NAMES drifted from the frozen list: "
            f"extra={sorted(set(SPAN_NAMES) - set(EXPECTED_SPAN_NAMES))}, "
            f"missing={sorted(set(EXPECTED_SPAN_NAMES) - set(SPAN_NAMES))}"
            " — update EXPECTED_SPAN_NAMES + the docs span table together")
    if sorted(EVENT_NAMES) != sorted(EXPECTED_EVENT_NAMES):
        errors.append(
            "tracing.EVENT_NAMES drifted from the frozen list: "
            f"extra={sorted(set(EVENT_NAMES) - set(EXPECTED_EVENT_NAMES))},"
            f" missing="
            f"{sorted(set(EXPECTED_EVENT_NAMES) - set(EVENT_NAMES))}")
    if sorted(FLIGHT_REASONS) != sorted(EXPECTED_FLIGHT_REASONS):
        errors.append("flight.FLIGHT_REASONS drifted from the frozen list")
    try:
        with open(DOCS, "r", encoding="utf-8") as f:
            docs = f.read()
    except OSError as e:
        return errors + [f"cannot read {DOCS}: {e}"]
    for name in list(SPAN_NAMES) + list(EVENT_NAMES):
        if f"`{name}`" not in docs:
            errors.append(f"span/event {name!r} not documented in "
                          f"{os.path.basename(DOCS)}")
    for reason in FLIGHT_REASONS:
        if f"`{reason}`" not in docs:
            errors.append(f"flight reason {reason!r} not documented")
    return errors


def _cross_link(docs_path: str, needle: str, what: str) -> List[str]:
    """A docs file must reference another doc (cross-link contract)."""
    try:
        with open(docs_path, "r", encoding="utf-8") as f:
            if needle not in f.read():
                return [f"{os.path.basename(docs_path)} does not "
                        f"cross-link {needle} from its {what} section"]
    except OSError as e:
        return [f"cannot read {docs_path}: {e}"]
    return []


_BENCH = os.path.join(REPO, "bench.py")


def check_quant_comm() -> List[str]:
    """Quantized-collective telemetry: frozen comm-op vocabulary matches
    the module, every op and bench key is documented, and the bench row
    actually emits the documented keys."""
    def _ops():
        from deepspeed_tpu.comm.quantized import QUANT_COMM_OPS

        return QUANT_COMM_OPS

    return _vocab_check([
        VocabSpec(name="quantized.QUANT_COMM_OPS",
                  expected=EXPECTED_QUANT_COMM_OPS, actual=_ops,
                  docs_path=QUANT_DOCS),
        VocabSpec(name="QUANT_BENCH_KEYS", expected=QUANT_BENCH_KEYS,
                  docs_path=QUANT_DOCS,
                  source_keys=[(_BENCH, QUANT_BENCH_KEYS)]),
    ]) + _cross_link(DOCS, "QUANTIZED_COMM.md", "comm")


def check_ring_bench() -> List[str]:
    """Ring bench-row vocabulary: every frozen longseq_ring / --bwd key
    is emitted by its bench source and documented in the
    docs/RING_ATTENTION.md bench-key table."""
    return _vocab_check([
        VocabSpec(name="RING_BENCH_KEYS", expected=RING_BENCH_KEYS,
                  docs_path=RING_DOCS,
                  source_keys=[(_BENCH, RING_BENCH_KEYS)]),
        VocabSpec(name="RING_BWD_BENCH_KEYS",
                  expected=RING_BWD_BENCH_KEYS, docs_path=RING_DOCS,
                  source_keys=[(os.path.join(REPO, "tools",
                                             "bench_flash_longseq.py"),
                                RING_BWD_BENCH_KEYS)]),
    ])


def check_router_serving() -> List[str]:
    """Router-tier vocabulary: every RouterMetrics Prometheus name is
    documented in docs/SERVING.md (per-replica counters via their
    ``_r*_`` wildcard), and the frozen serve_load_multi bench keys are
    both emitted by bench.py and documented."""
    import re

    from deepspeed_tpu.serving.metrics import RouterMetrics

    names = [m.name for m in
             RouterMetrics(n_replicas=2).registry.collect()]

    def _mixes():
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location("_dstpu_bench", _BENCH)
        # bench.py guards backend setup behind --smoke; importing it for
        # the frozen tuple is safe (no row runs at import)
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.SCENARIO_MIXES

    def _tiers():
        from deepspeed_tpu.serving.disagg import REPLICA_TIERS

        return REPLICA_TIERS

    return _vocab_check([
        # registry-derived, so no frozen list — the docs contract only
        VocabSpec(name="router metrics", doc_names=names,
                  docs_path=SERVING_DOCS,
                  doc_normalize=lambda n: re.sub(r"_r\d+_", "_r*_", n)),
        VocabSpec(name="SERVE_MULTI_BENCH_KEYS",
                  expected=SERVE_MULTI_BENCH_KEYS, docs_path=SERVING_DOCS,
                  source_keys=[(_BENCH, SERVE_MULTI_BENCH_KEYS)]),
        VocabSpec(name="DISAGG_BENCH_KEYS",
                  expected=DISAGG_BENCH_KEYS, docs_path=SERVING_DOCS,
                  source_keys=[(_BENCH, DISAGG_BENCH_KEYS)]),
        VocabSpec(name="bench.SCENARIO_MIXES",
                  expected=EXPECTED_SCENARIO_MIXES, actual=_mixes,
                  docs_path=SERVING_DOCS,
                  source_keys=[(_BENCH, EXPECTED_SCENARIO_MIXES)]),
        VocabSpec(name="disagg.REPLICA_TIERS",
                  expected=EXPECTED_REPLICA_TIERS, actual=_tiers,
                  docs_path=SERVING_DOCS),
    ])


def check_autotuning() -> List[str]:
    """Overlap-scheduler vocabulary: frozen decision/evidence/config key
    lists match the modules, every name is documented in
    docs/AUTOTUNING.md, and the autosched bench row emits the frozen
    keys."""
    from dataclasses import fields as dc_fields

    def _decisions():
        from deepspeed_tpu.autotuning.overlap_scheduler import \
            SCHEDULE_DECISIONS

        return SCHEDULE_DECISIONS

    def _evidence():
        from deepspeed_tpu.autotuning.overlap_scheduler import EVIDENCE_KEYS

        return EVIDENCE_KEYS

    def _ss_keys():
        from deepspeed_tpu.runtime.config import StepScheduleConfig

        return sorted(f.name for f in dc_fields(StepScheduleConfig))

    return _vocab_check([
        VocabSpec(name="overlap_scheduler.SCHEDULE_DECISIONS",
                  expected=EXPECTED_SCHEDULE_DECISIONS, actual=_decisions,
                  docs_path=AUTOTUNING_DOCS),
        VocabSpec(name="overlap_scheduler.EVIDENCE_KEYS",
                  expected=EXPECTED_EVIDENCE_KEYS, actual=_evidence,
                  docs_path=AUTOTUNING_DOCS),
        VocabSpec(name="StepScheduleConfig keys",
                  expected=EXPECTED_STEP_SCHEDULE_KEYS, actual=_ss_keys,
                  docs_path=AUTOTUNING_DOCS),
        VocabSpec(name="AUTOSCHED_BENCH_KEYS",
                  expected=AUTOSCHED_BENCH_KEYS, docs_path=AUTOTUNING_DOCS,
                  source_keys=[(_BENCH, AUTOSCHED_BENCH_KEYS)]),
        VocabSpec(name="capture report scheduler keys",
                  expected=CAPTURE_REPORT_SCHED_KEYS,
                  docs_path=AUTOTUNING_DOCS),
    ]) + _cross_link(DOCS, "AUTOTUNING.md", "capture")


def check_graph_audit() -> List[str]:
    """Static-graph-audit vocabulary: finding kinds / severities / report
    key sets match deepspeed_tpu/analysis/report.py, every name is
    documented in docs/STATIC_ANALYSIS.md, and the autotuning docs
    cross-link the census-in-evidence field."""
    from deepspeed_tpu.analysis import (AUDIT_REPORT_KEYS, CENSUS_KEYS,
                                        DONATION_KEYS, FINDING_KEYS,
                                        FINDING_KINDS, SEVERITIES)

    return _vocab_check([
        VocabSpec(name="analysis.FINDING_KINDS",
                  expected=EXPECTED_FINDING_KINDS,
                  actual=lambda: FINDING_KINDS, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.SEVERITIES",
                  expected=EXPECTED_AUDIT_SEVERITIES,
                  actual=lambda: SEVERITIES, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.AUDIT_REPORT_KEYS",
                  expected=EXPECTED_AUDIT_REPORT_KEYS,
                  actual=lambda: AUDIT_REPORT_KEYS, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.CENSUS_KEYS",
                  expected=EXPECTED_AUDIT_CENSUS_KEYS,
                  actual=lambda: CENSUS_KEYS, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.FINDING_KEYS",
                  expected=EXPECTED_AUDIT_FINDING_KEYS,
                  actual=lambda: FINDING_KEYS, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.DONATION_KEYS",
                  expected=EXPECTED_AUDIT_DONATION_KEYS,
                  actual=lambda: DONATION_KEYS, docs_path=STATIC_DOCS),
    ]) + _cross_link(AUTOTUNING_DOCS, "STATIC_ANALYSIS.md",
                     "census-in-evidence")


def check_memory_audit() -> List[str]:
    """Memory-plan-audit vocabulary: the MemoryAuditReport's frozen key
    sets and classes match deepspeed_tpu/analysis/report.py, every name
    is documented in docs/STATIC_ANALYSIS.md, the peak_params ladder
    emits the frozen prediction keys, and docs/AUTOTUNING.md cross-links
    the model_drift calibration record."""
    from deepspeed_tpu.analysis import (BUDGET_KEYS, BUFFER_KEYS,
                                        CALIBRATION_KEYS, MEMORY_CLASSES,
                                        MEMORY_REPORT_KEYS,
                                        MEMORY_TOTALS_KEYS)

    return _vocab_check([
        VocabSpec(name="analysis.MEMORY_REPORT_KEYS",
                  expected=EXPECTED_MEMORY_REPORT_KEYS,
                  actual=lambda: MEMORY_REPORT_KEYS,
                  docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.MEMORY_TOTALS_KEYS",
                  expected=EXPECTED_MEMORY_TOTALS_KEYS,
                  actual=lambda: MEMORY_TOTALS_KEYS,
                  docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.BUFFER_KEYS",
                  expected=EXPECTED_BUFFER_KEYS,
                  actual=lambda: BUFFER_KEYS, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.MEMORY_CLASSES",
                  expected=EXPECTED_MEMORY_CLASSES,
                  actual=lambda: MEMORY_CLASSES, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.BUDGET_KEYS",
                  expected=EXPECTED_BUDGET_KEYS,
                  actual=lambda: BUDGET_KEYS, docs_path=STATIC_DOCS),
        VocabSpec(name="analysis.CALIBRATION_KEYS",
                  expected=EXPECTED_CALIBRATION_KEYS,
                  actual=lambda: CALIBRATION_KEYS, docs_path=STATIC_DOCS),
        VocabSpec(name="MEMORY_BENCH_KEYS", expected=MEMORY_BENCH_KEYS,
                  docs_path=STATIC_DOCS,
                  source_keys=[(_BENCH, MEMORY_BENCH_KEYS)]),
    ]) + _cross_link(AUTOTUNING_DOCS, "model_drift", "calibration")


def check_recovery() -> List[str]:
    """Recovery vocabulary: the supervisor's frozen state machine matches
    the module and docs/ELASTICITY.md, the chaos bench row emits the
    frozen keys, and the observability doc cross-links the elasticity
    doc from its recovery rows."""
    def _states():
        from deepspeed_tpu.resilience.supervisor import RECOVERY_STATES

        return RECOVERY_STATES

    return _vocab_check([
        VocabSpec(name="supervisor.RECOVERY_STATES",
                  expected=EXPECTED_RECOVERY_STATES, actual=_states,
                  docs_path=ELASTICITY_DOCS),
        VocabSpec(name="CHAOS_BENCH_KEYS", expected=CHAOS_BENCH_KEYS,
                  docs_path=ELASTICITY_DOCS,
                  source_keys=[(_BENCH, CHAOS_BENCH_KEYS)]),
    ]) + _cross_link(DOCS, "ELASTICITY.md", "recovery")


def check_offload() -> List[str]:
    """Host-tiered offload vocabulary: the ladder's measured host keys
    (`host_peak_bytes` next to the predictor's number, plus the overlap
    fraction) are emitted by bench.py and documented in docs/OFFLOAD.md,
    the chunked config knobs are real OffloadOptimizerConfig fields and
    documented, and the observability doc cross-links the offload doc
    from its offload span rows."""
    from dataclasses import fields as dc_fields

    def _cfg_keys():
        from deepspeed_tpu.runtime.config import OffloadOptimizerConfig

        have = {f.name for f in dc_fields(OffloadOptimizerConfig)}
        return sorted(k for k in OFFLOAD_CONFIG_KEYS if k in have)

    return _vocab_check([
        VocabSpec(name="OFFLOAD_BENCH_KEYS", expected=OFFLOAD_BENCH_KEYS,
                  docs_path=OFFLOAD_DOCS,
                  source_keys=[(_BENCH, OFFLOAD_BENCH_KEYS)]),
        VocabSpec(name="OffloadOptimizerConfig chunked keys",
                  expected=OFFLOAD_CONFIG_KEYS, actual=_cfg_keys,
                  docs_path=OFFLOAD_DOCS),
    ]) + _cross_link(DOCS, "OFFLOAD.md", "offload")


def check_planner() -> List[str]:
    """Plan-compiler vocabulary: evidence keys / link classes / offload
    tier names match deepspeed_tpu/planner, every name is documented in
    docs/PLANNER.md, the plan_validate bench keys are emitted by
    bench.py, and the planner and autotuning docs cross-link each
    other (the Autotuner's planner mode consumes seed_candidates)."""
    from deepspeed_tpu.planner import (LINK_CLASSES, OFFLOAD_TIERS,
                                       PLAN_EVIDENCE_KEYS)

    return _vocab_check([
        VocabSpec(name="planner.PLAN_EVIDENCE_KEYS",
                  expected=EXPECTED_PLAN_EVIDENCE_KEYS,
                  actual=lambda: PLAN_EVIDENCE_KEYS,
                  docs_path=PLANNER_DOCS),
        VocabSpec(name="planner.LINK_CLASSES",
                  expected=EXPECTED_LINK_CLASSES,
                  actual=lambda: LINK_CLASSES, docs_path=PLANNER_DOCS),
        VocabSpec(name="planner offload tiers",
                  expected=EXPECTED_OFFLOAD_TIER_NAMES,
                  actual=lambda: [n for n, _ in OFFLOAD_TIERS],
                  docs_path=PLANNER_DOCS),
        VocabSpec(name="PLAN_BENCH_KEYS", expected=PLAN_BENCH_KEYS,
                  docs_path=PLANNER_DOCS,
                  source_keys=[(_BENCH, PLAN_BENCH_KEYS)]),
    ]) + _cross_link(AUTOTUNING_DOCS, "PLANNER.md", "planner mode") \
       + _cross_link(PLANNER_DOCS, "AUTOTUNING.md", "autotuner handoff")


def check_fleet() -> List[str]:
    """Fleet-observability vocabulary: TierSnapshot schema / SLO ledger
    / request-timeline key sets match their modules, every key is
    documented in docs/OBSERVABILITY.md (per-tier gauges via their
    ``fleet_*_<key>`` wildcard rows), and docs/SERVING.md cross-links
    the fleet section as the autoscaler-input feed."""
    import re

    def _snap_keys():
        from deepspeed_tpu.serving.fleet import (TIER_SNAPSHOT_KEYS,
                                                 TIER_SNAPSHOT_SCHEMA)

        if TIER_SNAPSHOT_SCHEMA != EXPECTED_TIER_SNAPSHOT_SCHEMA:
            raise ValueError(
                f"TIER_SNAPSHOT_SCHEMA is {TIER_SNAPSHOT_SCHEMA}, lint "
                f"pins {EXPECTED_TIER_SNAPSHOT_SCHEMA}")
        return TIER_SNAPSHOT_KEYS

    def _slo(name):
        def thunk():
            import deepspeed_tpu.telemetry.slo as slo

            return getattr(slo, name)
        return thunk

    def _timeline_keys():
        from deepspeed_tpu.serving.disagg import REQUEST_TIMELINE_KEYS

        return REQUEST_TIMELINE_KEYS

    # every tier substitutes into the same gauge wildcard rows: document
    # `fleet_*_queue_depth` once, not once per tier (tier/schema/run_id
    # are identity fields, never exported as gauges)
    gauges = [f"fleet_prefill_{k}" for k in EXPECTED_TIER_SNAPSHOT_KEYS
              if k not in ("tier", "schema", "run_id")]
    return _vocab_check([
        VocabSpec(name="fleet.TIER_SNAPSHOT_KEYS",
                  expected=EXPECTED_TIER_SNAPSHOT_KEYS, actual=_snap_keys,
                  docs_path=DOCS),
        VocabSpec(name="fleet gauges", doc_names=gauges, docs_path=DOCS,
                  doc_normalize=lambda n: re.sub(
                      r"^fleet_(prefill|decode|unified)_", "fleet_*_", n)),
        VocabSpec(name="slo.SLO_TARGET_KEYS",
                  expected=EXPECTED_SLO_TARGET_KEYS,
                  actual=_slo("SLO_TARGET_KEYS"), docs_path=DOCS),
        VocabSpec(name="slo.SLO_BLOCK_KEYS",
                  expected=EXPECTED_SLO_BLOCK_KEYS,
                  actual=_slo("SLO_BLOCK_KEYS"), docs_path=DOCS),
        VocabSpec(name="slo.SLO_SCENARIO_KEYS",
                  expected=EXPECTED_SLO_SCENARIO_KEYS,
                  actual=_slo("SLO_SCENARIO_KEYS"), docs_path=DOCS),
        VocabSpec(name="slo.SLO_LEDGER_KEYS",
                  expected=EXPECTED_SLO_LEDGER_KEYS,
                  actual=_slo("SLO_LEDGER_KEYS"), docs_path=DOCS),
        VocabSpec(name="disagg.REQUEST_TIMELINE_KEYS",
                  expected=EXPECTED_TIMELINE_KEYS, actual=_timeline_keys,
                  docs_path=DOCS),
    ]) + _cross_link(SERVING_DOCS, "OBSERVABILITY.md",
                     "fleet snapshots / autoscaler inputs")


def check_obs_ledger() -> List[str]:
    """Run-ledger vocabulary: manifest/rollup/finding/anomaly/drift key
    sets, the sentinel verdicts, and the anomaly kinds match
    telemetry/ledger.py; every name is documented in the
    docs/OBSERVABILITY.md "Run ledger & regression sentinel" section;
    bench.py stamps run_id + manifest into every row; and the ledger
    schema version is pinned."""
    def _led(name):
        def thunk():
            from deepspeed_tpu.telemetry import ledger

            if ledger.LEDGER_SCHEMA != EXPECTED_LEDGER_SCHEMA:
                raise ValueError(
                    f"LEDGER_SCHEMA is {ledger.LEDGER_SCHEMA}, lint pins "
                    f"{EXPECTED_LEDGER_SCHEMA}")
            return getattr(ledger, name)
        return thunk

    return _vocab_check([
        VocabSpec(name="ledger.MANIFEST_KEYS",
                  expected=EXPECTED_MANIFEST_KEYS,
                  actual=_led("MANIFEST_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.MANIFEST_ARTIFACT_KEYS",
                  expected=EXPECTED_MANIFEST_ARTIFACT_KEYS,
                  actual=_led("MANIFEST_ARTIFACT_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.ROLLUP_KEYS",
                  expected=EXPECTED_ROLLUP_KEYS,
                  actual=_led("ROLLUP_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.ROLLUP_TRAIN_KEYS",
                  expected=EXPECTED_ROLLUP_TRAIN_KEYS,
                  actual=_led("ROLLUP_TRAIN_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.ROLLUP_SERVE_KEYS",
                  expected=EXPECTED_ROLLUP_SERVE_KEYS,
                  actual=_led("ROLLUP_SERVE_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.ROLLUP_RECOVERY_KEYS",
                  expected=EXPECTED_ROLLUP_RECOVERY_KEYS,
                  actual=_led("ROLLUP_RECOVERY_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.VERDICTS", expected=EXPECTED_VERDICTS,
                  actual=_led("VERDICTS"), docs_path=DOCS),
        VocabSpec(name="ledger.ANOMALY_KINDS",
                  expected=EXPECTED_ANOMALY_KINDS,
                  actual=_led("ANOMALY_KINDS"), docs_path=DOCS),
        VocabSpec(name="ledger.ANOMALY_KEYS",
                  expected=EXPECTED_ANOMALY_KEYS,
                  actual=_led("ANOMALY_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.FINDING_KEYS",
                  expected=EXPECTED_OBS_FINDING_KEYS,
                  actual=_led("FINDING_KEYS"), docs_path=DOCS),
        VocabSpec(name="ledger.DRIFT_KEYS", expected=EXPECTED_DRIFT_KEYS,
                  actual=_led("DRIFT_KEYS"), docs_path=DOCS),
        VocabSpec(name="LEDGER_BENCH_KEYS", expected=LEDGER_BENCH_KEYS,
                  docs_path=DOCS,
                  source_keys=[(_BENCH, LEDGER_BENCH_KEYS)]),
    ]) + _cross_link(PLANNER_DOCS, "obs_report", "calibration")


def check_chaos_fleet() -> List[str]:
    """Chaos / self-healing vocabulary: fault kinds, injection points,
    health states and brownout levels match their modules and are
    documented in docs/SERVING.md; the chaos_serve bench row emits the
    frozen keys; and docs/ELASTICITY.md cross-links the serving doc
    from its chaos section (the training and serving chaos halves share
    resilience/chaos.py)."""
    def _kinds():
        from deepspeed_tpu.resilience.chaos import FAULT_KINDS

        return FAULT_KINDS

    def _points():
        from deepspeed_tpu.resilience.chaos import INJECTION_POINTS

        return INJECTION_POINTS

    def _states():
        from deepspeed_tpu.serving.supervisor import HEALTH_STATES

        return HEALTH_STATES

    def _levels():
        from deepspeed_tpu.serving.admission import BROWNOUT_LEVELS

        return BROWNOUT_LEVELS

    return _vocab_check([
        VocabSpec(name="chaos.FAULT_KINDS",
                  expected=EXPECTED_FAULT_KINDS, actual=_kinds,
                  docs_path=SERVING_DOCS),
        VocabSpec(name="chaos.INJECTION_POINTS",
                  expected=EXPECTED_INJECTION_POINTS, actual=_points,
                  docs_path=SERVING_DOCS),
        VocabSpec(name="supervisor.HEALTH_STATES",
                  expected=EXPECTED_HEALTH_STATES, actual=_states,
                  docs_path=SERVING_DOCS),
        VocabSpec(name="admission.BROWNOUT_LEVELS",
                  expected=EXPECTED_BROWNOUT_LEVELS, actual=_levels,
                  docs_path=SERVING_DOCS),
        VocabSpec(name="CHAOS_SERVE_BENCH_KEYS",
                  expected=CHAOS_SERVE_BENCH_KEYS, docs_path=SERVING_DOCS,
                  source_keys=[(_BENCH, CHAOS_SERVE_BENCH_KEYS)]),
    ]) + _cross_link(ELASTICITY_DOCS, "SERVING.md", "chaos")


def validate_chrome_trace(obj: Any) -> List[str]:
    """Structural validation of a Chrome trace-event JSON object (pass a
    path or the loaded dict).  Perfetto/chrome://tracing both accept the
    object form: ``{"traceEvents": [...]}`` with per-event ``name``,
    ``ph``, ``ts`` (µs), ``pid``/``tid``, and ``dur`` on complete ("X")
    events."""
    if isinstance(obj, str):
        try:
            with open(obj, "r", encoding="utf-8") as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            return [f"trace file unreadable / not JSON: {e}"]
    errors: List[str] = []
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        return ["trace is not an object with a 'traceEvents' list"]
    for i, ev in enumerate(obj["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing/empty name")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            errors.append(f"{where}: unsupported ph {ph!r}")
        if not isinstance(ev.get("ts"), (int, float)) or ev["ts"] < 0:
            errors.append(f"{where}: bad ts {ev.get('ts')!r}")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                errors.append(f"{where}: bad {key} {ev.get(key)!r}")
        if ph == "X" and (not isinstance(ev.get("dur"), (int, float))
                          or ev["dur"] < 0):
            errors.append(f"{where}: X event without valid dur")
        if ph == "X" and not isinstance(
                ev.get("args", {}).get("trace_id"), str):
            errors.append(f"{where}: span without args.trace_id")
    return errors


def check_trace_export() -> List[str]:
    """Generate a sample trace touching every span/event name and assert
    the exported file is well-formed."""
    from deepspeed_tpu.telemetry.tracing import (EVENT_NAMES, SPAN_NAMES,
                                                 Tracer)

    tracer = Tracer(enabled=True)
    tid = tracer.new_trace_id()
    for name in SPAN_NAMES:
        tracer.span(name, tid).set(sample=True).end()
    for name in EVENT_NAMES:
        tracer.instant(name, tid)
    with tempfile.TemporaryDirectory() as d:
        path = tracer.export_chrome_trace(os.path.join(d, "t.trace.json"))
        errors = validate_chrome_trace(path)
        with open(path, "r", encoding="utf-8") as f:
            seen = {ev["name"] for ev in json.load(f)["traceEvents"]
                    if ev.get("ph") in ("X", "i")}
    missing = (set(SPAN_NAMES) | set(EVENT_NAMES)) - seen
    if missing:
        errors.append(f"exported trace lost events: {sorted(missing)}")
    return errors


def run_all() -> List[str]:
    return (check_tags_documented() + check_schema() + check_span_names()
            + check_quant_comm() + check_ring_bench()
            + check_router_serving() + check_autotuning()
            + check_graph_audit() + check_memory_audit()
            + check_offload() + check_recovery() + check_planner()
            + check_fleet() + check_obs_ledger() + check_chaos_fleet()
            + check_trace_export())


def main() -> int:
    errors = run_all()
    for e in errors:
        print(f"telemetry_check: ERROR: {e}", file=sys.stderr)
    if not errors:
        print("telemetry_check: OK")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
