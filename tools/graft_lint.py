#!/usr/bin/env python
"""Static graph auditor + seam lint CLI (docs/STATIC_ANALYSIS.md).

Runs the ``deepspeed_tpu/analysis`` auditor over the bench-row step
configs on a virtual 8-device CPU mesh (``--rows``) and/or the AST-level
jax-version-seam lint over the production tree (``--seam``); with
neither flag, both run.  Exit status 1 when any HIGH-severity finding is
not suppressed by the baseline file.

Usage::

    python tools/graft_lint.py                   # everything
    python tools/graft_lint.py --rows train_zero3 v2_decode
    python tools/graft_lint.py --seam            # AST lint only
    python tools/graft_lint.py --list            # show row targets
    python tools/graft_lint.py --json out.json   # machine-readable dump
    python tools/graft_lint.py --write-baseline  # accept current highs

The baseline (default ``tools/graft_lint_baseline.json``) holds finding
fingerprints — stable hashes of (kind, where, stable-key), never of
byte counts — so a deliberately accepted finding stays suppressed while
anything NEW still fails the lint.  ``--write-baseline`` records every
currently-unsuppressed high finding; review the diff like code.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "tools", "graft_lint_baseline.json")


def _setup_mesh_backend() -> None:
    """Pin the virtual 8-device CPU mesh BEFORE any backend touch (same
    discipline as ``bench.py --smoke``: a down TPU tunnel must not hang
    the lint, and audits check graph *structure*, which the CPU mesh
    lowers identically)."""
    flags = os.environ.get("XLA_FLAGS", "")
    for flag in ("--xla_force_host_platform_device_count=8",
                 "--xla_backend_optimization_level=0"):
        if flag.split("=")[0] not in flags:
            flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graft_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--rows", nargs="*", default=None, metavar="ROW",
                   help="audit bench-row step configs (all when no names "
                        "are given)")
    p.add_argument("--seam", action="store_true",
                   help="run the AST jax-version-seam lint")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="finding-fingerprint suppression file")
    p.add_argument("--write-baseline", action="store_true",
                   help="append every currently-unsuppressed high "
                        "finding to the baseline")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write full reports + findings as JSON")
    p.add_argument("--list", action="store_true",
                   help="list bench-row audit targets and exit")
    args = p.parse_args(argv)

    sys.path.insert(0, REPO)
    from deepspeed_tpu.analysis.report import load_baseline

    run_rows = args.rows is not None or not args.seam
    run_seam = args.seam or args.rows is None

    if args.list:
        from deepspeed_tpu.analysis.targets import BENCH_AUDIT_TARGETS
        for name in sorted(BENCH_AUDIT_TARGETS):
            print(name)
        return 0

    findings = []
    reports = []
    if run_rows:
        _setup_mesh_backend()
        from deepspeed_tpu.analysis.targets import (BENCH_AUDIT_TARGETS,
                                                    run_audit_target)
        names = args.rows or sorted(BENCH_AUDIT_TARGETS)
        for name in names:
            rep = run_audit_target(name)
            reports.append(rep)
            findings.extend(rep.findings)
            census = ", ".join(f"{k}×{v['count']}"
                               for k, v in rep.census_summary().items())
            print(f"row {name}: {len(rep.findings)} finding(s); "
                  f"donation {rep.donation['aliased']}/"
                  f"{rep.donation['declared']} aliased; "
                  f"census [{census or 'no collectives'}]")
    if run_seam:
        from deepspeed_tpu.analysis.seam import lint_repo
        seam = lint_repo(REPO)
        findings.extend(seam)
        print(f"seam: {len(seam)} violation(s)")

    baseline = load_baseline(args.baseline)
    highs: List = [f for f in findings if f.severity == "high"]
    new_highs = [f for f in highs if f.fingerprint() not in baseline]
    suppressed = len(highs) - len(new_highs)

    for f in findings:
        mark = ("BASELINED" if f.severity == "high"
                and f.fingerprint() in baseline else f.severity.upper())
        print(f"[{mark}] {f.kind} @ {f.where} ({f.fingerprint()})\n"
              f"    {f.message}")

    if args.write_baseline and new_highs:
        data = {"comment": "graft_lint accepted findings — every entry "
                           "is a Finding.fingerprint(); review changes "
                           "to this file like code",
                "suppress": sorted(baseline.union(
                    f.fingerprint() for f in new_highs))}
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2)
            fh.write("\n")
        print(f"baseline: wrote {len(new_highs)} new fingerprint(s) to "
              f"{args.baseline}")
        new_highs = []

    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump({"reports": [r.to_dict() for r in reports],
                       "findings": [f.to_dict() for f in findings],
                       "unbaselined_high": [f.to_dict()
                                            for f in new_highs]},
                      fh, indent=2, sort_keys=True)

    print(f"graft_lint: {len(findings)} finding(s), {len(new_highs)} "
          f"unbaselined high ({suppressed} baselined)")
    return 1 if new_highs else 0


if __name__ == "__main__":
    sys.exit(main())
