#!/usr/bin/env python
"""Static graph + memory-plan auditor and seam lint CLI
(docs/STATIC_ANALYSIS.md).

Runs the ``deepspeed_tpu/analysis`` auditors over the bench-row step
configs on a virtual 8-device CPU mesh (``--rows`` for the collective/
donation graph audit, ``--memory`` for the HBM memory-plan audit — both
families share ONE lowering per target) and/or the AST-level
jax-version-seam lint over the production tree (``--seam``); with no
flags, everything runs.  Exit status 1 when any HIGH-severity finding is
not suppressed by the baseline file.

Usage::

    python tools/graft_lint.py                   # everything
    python tools/graft_lint.py --rows train_zero3 v2_decode
    python tools/graft_lint.py --memory          # memory audits, all rows
    python tools/graft_lint.py --memory --target train_zero3
    python tools/graft_lint.py --seam            # AST lint only
    python tools/graft_lint.py --plan            # audit planner output:
                                                 # top-ranked config per
                                                 # bench-row query must
                                                 # lower clean
    python tools/graft_lint.py --list            # show row targets
    python tools/graft_lint.py --json out.json   # machine-readable dump
    python tools/graft_lint.py --write-baseline  # accept current highs
                                                 # + freeze peak budgets

Two baselines gate the lint:

* ``tools/graft_lint_baseline.json`` — finding fingerprints (stable
  hashes of kind|where|stable-key, never byte counts): a deliberately
  accepted finding stays suppressed while anything NEW fails.
* ``tools/memory_baseline.json`` — frozen per-target peak budgets
  (``{"budgets": {target: {backend: bucketed_bytes}}}``, bytes bucketed
  so CPU-vs-TPU layout jitter never churns the file) plus the
  ``model_drift`` calibration ratios the autotuner consumes.  A >10%
  peak growth past the budget is a high ``peak_regression`` finding;
  ``--write-baseline`` (with memory audits running) re-freezes budgets
  for the current backend.  Review both files' diffs like code.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "tools", "graft_lint_baseline.json")
DEFAULT_MEMORY_BASELINE = os.path.join(REPO, "tools",
                                       "memory_baseline.json")


def _setup_mesh_backend() -> None:
    """Pin the virtual 8-device CPU mesh BEFORE any backend touch (same
    discipline as ``bench.py --smoke``: a down TPU tunnel must not hang
    the lint, and audits check graph *structure*, which the CPU mesh
    lowers identically)."""
    flags = os.environ.get("XLA_FLAGS", "")
    for flag in ("--xla_force_host_platform_device_count=8",
                 "--xla_backend_optimization_level=0"):
        if flag.split("=")[0] not in flags:
            flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graft_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--rows", nargs="*", default=None, metavar="ROW",
                   help="graph-audit bench-row step configs (all when no "
                        "names are given)")
    p.add_argument("--memory", nargs="*", default=None, metavar="ROW",
                   help="memory-plan-audit bench-row step configs (all "
                        "when no names are given); shares one lowering "
                        "per target with --rows")
    p.add_argument("--target", action="append", default=None,
                   metavar="ROW",
                   help="restrict --rows/--memory to these targets "
                        "(repeatable)")
    p.add_argument("--seam", action="store_true",
                   help="run the AST jax-version-seam lint")
    p.add_argument("--plan", action="store_true",
                   help="audit the planner's top-ranked config per "
                        "registered bench-row query (planner/audit.py): "
                        "each must lower with 0 unbaselined graph/memory "
                        "highs — a plan the auditors reject must not "
                        "ship")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="finding-fingerprint suppression file")
    p.add_argument("--memory-baseline", default=DEFAULT_MEMORY_BASELINE,
                   help="frozen per-target peak-budget file")
    p.add_argument("--write-baseline", action="store_true",
                   help="append every currently-unsuppressed high "
                        "finding to the baseline; with memory audits "
                        "running, also freeze peak budgets + calibration "
                        "for the current backend")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write full reports + findings as JSON")
    p.add_argument("--list", action="store_true",
                   help="list bench-row audit targets and exit")
    args = p.parse_args(argv)

    sys.path.insert(0, REPO)
    from deepspeed_tpu.analysis.report import (load_baseline,
                                               load_memory_baseline)

    all_default = (args.rows is None and args.memory is None
                   and not args.seam and not args.plan)
    run_rows = args.rows is not None or all_default
    run_memory = args.memory is not None or all_default
    run_seam = args.seam or all_default

    if args.list:
        from deepspeed_tpu.analysis.targets import TARGET_PREPARERS
        for name in sorted(TARGET_PREPARERS):
            print(name)
        return 0

    findings = []
    reports = []
    mem_reports = []
    if run_rows or run_memory:
        _setup_mesh_backend()
        import jax

        from deepspeed_tpu.analysis.targets import (TARGET_PREPARERS,
                                                    run_target_audits)
        backend = jax.default_backend()
        mem_base = load_memory_baseline(args.memory_baseline)
        row_names = set(args.rows or sorted(TARGET_PREPARERS)) \
            if run_rows else set()
        mem_names = set(args.memory or sorted(TARGET_PREPARERS)) \
            if run_memory else set()
        names = sorted(row_names | mem_names)
        if args.target:
            # a misspelled --target must fail loudly, never shrink the
            # audit set to nothing and exit 0 (a green gate that
            # verified nothing)
            unknown = sorted(set(args.target) - set(TARGET_PREPARERS))
            if unknown:
                p.error(f"unknown --target {unknown}; known targets: "
                        f"{sorted(TARGET_PREPARERS)}")
            names = [n for n in names if n in set(args.target)]
        for name in names:
            budget = mem_base["budgets"].get(name, {}).get(backend)
            rep, mem = run_target_audits(name, memory=name in mem_names,
                                         budget=budget,
                                         graph=name in row_names)
            if name in row_names:
                reports.append(rep)
                findings.extend(rep.findings)
                census = ", ".join(
                    f"{k}×{v['count']}"
                    for k, v in rep.census_summary().items()
                    if k != "fused_collective")
                print(f"row {name}: {len(rep.findings)} finding(s); "
                      f"donation {rep.donation['aliased']}/"
                      f"{rep.donation['declared']} aliased; "
                      f"census [{census or 'no collectives'}]")
            if mem is not None:
                mem_reports.append(mem)
                findings.extend(mem.findings)
                peak = mem.totals["peak_bytes"]
                print(f"memory {name}: peak {peak / (1 << 20):.2f} "
                      f"MiB/device (budget "
                      f"{'—' if budget is None else budget}); "
                      f"{len(mem.findings)} finding(s)")
    if run_seam:
        from deepspeed_tpu.analysis.seam import lint_repo
        seam = lint_repo(REPO)
        findings.extend(seam)
        print(f"seam: {len(seam)} violation(s)")

    plan_reports = []
    if args.plan:
        _setup_mesh_backend()
        from deepspeed_tpu.planner.audit import (PLAN_AUDIT_ROWS,
                                                 audit_planned_config)
        for name in PLAN_AUDIT_ROWS:
            frag, rep, mem = audit_planned_config(name)
            # plan twins join the finding gate but NOT mem_reports —
            # --write-baseline must never freeze budgets for the
            # synthetic plan:* labels
            findings.extend(rep.findings)
            findings.extend(mem.findings)
            plan_reports.append({"name": name, "fragment": frag,
                                 "graph": rep.to_dict(),
                                 "memory": mem.to_dict()})
            mesh = frag.get("mesh") or {}
            mesh_s = "x".join(f"{k}{v}"
                              for k, v in sorted(mesh.items())) or "data1"
            stage = (frag.get("zero_optimization") or {}).get("stage", 0)
            print(f"plan {name}: top-ranked zero{stage} mesh {mesh_s} "
                  f"lowered; {len(rep.findings) + len(mem.findings)} "
                  f"finding(s)")

    baseline = load_baseline(args.baseline)
    highs: List = [f for f in findings if f.severity == "high"]
    new_highs = [f for f in highs if f.fingerprint() not in baseline]
    suppressed = len(highs) - len(new_highs)

    for f in findings:
        mark = ("BASELINED" if f.severity == "high"
                and f.fingerprint() in baseline else f.severity.upper())
        print(f"[{mark}] {f.kind} @ {f.where} ({f.fingerprint()})\n"
              f"    {f.message}")

    if args.write_baseline and mem_reports:
        _write_memory_baseline(args.memory_baseline, mem_reports)
        # budgets just froze: drop the now-stale no-budget warnings and
        # peak regressions from this run's gate — the next run audits
        # against the frozen numbers
        new_highs = [f for f in new_highs if f.kind != "peak_regression"]
    if args.write_baseline and new_highs:
        data = {"comment": "graft_lint accepted findings — every entry "
                           "is a Finding.fingerprint(); review changes "
                           "to this file like code",
                "suppress": sorted(baseline.union(
                    f.fingerprint() for f in new_highs))}
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2)
            fh.write("\n")
        print(f"baseline: wrote {len(new_highs)} new fingerprint(s) to "
              f"{args.baseline}")
        new_highs = []

    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump({"reports": [r.to_dict() for r in reports],
                       "memory_reports": [r.to_dict()
                                          for r in mem_reports],
                       "plan_reports": plan_reports,
                       "findings": [f.to_dict() for f in findings],
                       "unbaselined_high": [f.to_dict()
                                            for f in new_highs]},
                      fh, indent=2, sort_keys=True)

    print(f"graft_lint: {len(findings)} finding(s), {len(new_highs)} "
          f"unbaselined high ({suppressed} baselined)")
    return 1 if new_highs else 0


def _write_memory_baseline(path: str, mem_reports) -> None:
    """Freeze peak budgets (bucketed) + the median model-drift
    calibration ratio for the audited backend, preserving other
    backends' entries (the TPU budgets survive a CPU re-freeze)."""
    from deepspeed_tpu.analysis.report import load_memory_baseline

    data = load_memory_baseline(path)
    ratios = []
    backend = mem_reports[0].backend if mem_reports else "cpu"
    for rep in mem_reports:
        data["budgets"].setdefault(rep.label, {})[rep.backend] = \
            rep.budget["bucketed_peak_bytes"]
        if rep.calibration.get("ratio"):
            ratios.append(float(rep.calibration["ratio"]))
    if ratios:
        ratios.sort()
        data["calibration"][backend] = round(
            ratios[len(ratios) // 2], 4)
    out = {"comment": "frozen per-target static-peak budgets (bytes, "
                      "bucketed via analysis.report.bucket_bytes) + "
                      "model_drift calibration ratios per backend — "
                      "written by graft_lint --memory --write-baseline; "
                      "review changes like code (docs/STATIC_ANALYSIS.md)",
           "budgets": {k: dict(sorted(v.items()))
                       for k, v in sorted(data["budgets"].items())},
           "calibration": dict(sorted(data["calibration"].items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(out, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"memory baseline: froze {len(mem_reports)} budget(s) to "
          f"{path}")


if __name__ == "__main__":
    sys.exit(main())
