#!/bin/sh
# Reproducible non-test source LoC count (advisor r2: state the exact
# command). Counts Python/C++ under the package + native + CLIs + drivers.
cd "$(dirname "$0")/.."
find deepspeed_tpu csrc bin examples -name '*.py' -o -name '*.cpp' -o -name 'dstpu*' \
  | grep -v __pycache__ | sort | xargs wc -l | tail -1
wc -l bench.py __graft_entry__.py | tail -1
