"""On-chip config sweep for bench.py tuning. Not part of the test suite.

Usage: python tools/bench_sweep.py '{"remat_policy": "none", "loss_tiles": 8}' ...
Each JSON arg is a variant of overrides; prints tokens/s per variant.
Override keys: batch, gas, seq, remat_policy, loss_tiles, scan_unroll,
zero_stage, model.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_variant(ov: dict) -> float:
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel import topology
    from deepspeed_tpu.models import get_model_config

    topology._GLOBAL_TOPOLOGY = None
    batch_size = ov.get("batch", 8)
    gas = ov.get("gas", 8)
    seq = ov.get("seq", 1024)
    model_kw = {}
    if ov.get("loss_tiles"):
        model_kw["loss_tiles"] = ov["loss_tiles"]
    if ov.get("scan_unroll"):
        model_kw["scan_unroll"] = ov["scan_unroll"]
    model = get_model_config(ov.get("model", "gpt2-350m"), max_seq_len=seq,
                             **model_kw)
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": ov.get("zero_stage", 1)},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "activation_checkpointing": {
            "remat_policy": ov.get("remat_policy", "dots_saveable")},
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rows = batch_size * gas
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    for _ in range(3):
        loss = engine.train_batch(batch)
    float(np.asarray(loss))
    steps = 8
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    float(np.asarray(loss))
    dt = time.perf_counter() - t0
    tps = steps * rows * seq / dt
    return tps


def main():
    for arg in sys.argv[1:]:
        ov = json.loads(arg)
        try:
            tps = run_variant(ov)
            print(f"RESULT {json.dumps(ov)} -> {tps:,.1f} tok/s", flush=True)
        except Exception as e:
            print(f"RESULT {json.dumps(ov)} -> FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
